"""``analyze`` command: corpus / model distribution analyses.

Productizes the reference's analysis notebooks (SURVEY.md §1 "Research
notebooks"):

* ``--what features`` — de-normalized pitch/energy/duration distributions
  over a split, with the notebook's IQR outlier rule for durations
  (reference: notebooks/variance_control_distbn.ipynb, corpus half).
* ``--what predictions`` — free-running forward over the split, predicted
  pitch/energy/duration distributions side-by-side with the corpus truth
  plus a histogram-overlap score (reference:
  notebooks/variance_control_distbn.ipynb, prediction half).
* ``--what style`` — reference-encoder γ/β statistics per utterance and
  the learned FiLM gate values s_gamma/s_beta by site (reference:
  notebooks/ref_encoder.ipynb).

Text tables + ASCII histograms by default; ``--json PATH`` dumps the raw
numbers for external plotting.
"""

import argparse
import json
import os

import numpy as np

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument("--what", choices=("features", "predictions", "style"),
                        default="features")
    parser.add_argument("--split", default="val.txt",
                        help="metadata file inside the preprocessed dir")
    parser.add_argument("--restore_step", type=int, default=-1,
                        help="checkpoint for predictions/style (-1 latest; "
                        "if none found, random init with a warning)")
    parser.add_argument("--max_batches", type=int, default=50)
    parser.add_argument("--json", default=None,
                        help="also dump raw stats to this path")
    return parser


def _ascii_hist(values, bins=24, width=46, label=""):
    lines = []
    hist, edges = np.histogram(values, bins=bins)
    top = hist.max() or 1
    for h, lo, hi in zip(hist, edges[:-1], edges[1:]):
        bar = "#" * int(round(width * h / top))
        lines.append(f"  {lo:9.3f}..{hi:9.3f} |{bar}")
    return "\n".join([f"  [{label}]"] + lines)


def _summary(values):
    values = np.asarray(values, np.float64)
    if values.size == 0:
        return {"count": 0}
    return {
        "count": int(values.size),
        "mean": float(values.mean()),
        "std": float(values.std()),
        "p5": float(np.percentile(values, 5)),
        "p50": float(np.percentile(values, 50)),
        "p95": float(np.percentile(values, 95)),
        "min": float(values.min()),
        "max": float(values.max()),
    }


def _remove_outlier(values, k=3.0):
    """The notebook's IQR rule (variance_control_distbn.ipynb), with a
    guard for degenerate (zero-IQR) distributions the strict <> would
    empty out."""
    values = np.asarray(values)
    if values.size == 0:
        return values
    p25, p75 = np.percentile(values, 25), np.percentile(values, 75)
    if p75 == p25:
        return values
    keep = (values > p25 - k * (p75 - p25)) & (values < p75 + k * (p75 - p25))
    return values[keep]


def _split_entries(cfg, split):
    """[(basename, speaker)] from the metadata file — the one canonical
    source of feature-file names (data/dataset.py's
    ``{speaker}-{kind}-{basename}.npy`` convention)."""
    root = cfg.preprocess.path.preprocessed_path
    entries = []
    with open(os.path.join(root, split)) as f:
        for ln in f:
            if not ln.strip():
                continue
            parts = ln.split("|")
            entries.append((parts[0], parts[1]))
    return entries, root


def _corpus_features(cfg, split, denormalize=True):
    """``denormalize=False`` keeps pitch/energy in the on-disk z-normalized
    space — required when comparing against model predictions, which live
    there too."""
    entries, root = _split_entries(cfg, split)
    with open(os.path.join(root, "stats.json")) as f:
        stats = json.load(f)
    out = {"pitch": [], "energy": [], "duration": []}
    for kind in out:
        for base, spk in entries:
            path = os.path.join(root, kind, f"{spk}-{kind}-{base}.npy")
            if not os.path.exists(path):
                continue
            v = np.load(path).astype(np.float64)
            if (
                denormalize
                and kind in ("pitch", "energy")
                and len(stats.get(kind, [])) >= 4
            ):
                # de-normalize: stats.json rows are [min max mean std]
                v = v * stats[kind][3] + stats[kind][2]
            out[kind].extend(v.tolist())
    out["duration"] = _remove_outlier(out["duration"]).tolist()
    return out, stats


def _histogram_overlap(a, b, bins=50):
    lo = min(np.min(a), np.min(b))
    hi = max(np.max(a), np.max(b))
    ha, _ = np.histogram(a, bins=bins, range=(lo, hi), density=True)
    hb, _ = np.histogram(b, bins=bins, range=(lo, hi), density=True)
    ha, hb = ha / (ha.sum() or 1), hb / (hb.sum() or 1)
    return float(np.minimum(ha, hb).sum())


def _restored_state(cfg, model, restore_step):
    import jax

    from speakingstyle_tpu.models.factory import init_variables
    from speakingstyle_tpu.training.checkpoint import CheckpointManager
    from speakingstyle_tpu.training.optim import make_optimizer
    from speakingstyle_tpu.training.state import TrainState

    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    state = TrainState.create(variables, make_optimizer(cfg.train))
    try:
        ckpt = CheckpointManager(cfg.train.path.ckpt_path)
        state = ckpt.restore(
            state, step=restore_step if restore_step > 0 else None
        )
        ckpt.close()
        print(f"restored checkpoint @ step {int(state.step)}")
    except FileNotFoundError:
        print("warning: no checkpoint found — analyzing a random init")
    return state


def _predictions(cfg, split, restore_step, max_batches):
    from speakingstyle_tpu.data import BucketedBatcher, SpeechDataset
    from speakingstyle_tpu.models.factory import build_model
    from speakingstyle_tpu.parallel.registry import jit_program

    model = build_model(cfg)
    state = _restored_state(cfg, model, restore_step)

    ds = SpeechDataset(split, cfg, sort=False, drop_last=False)
    batcher = BucketedBatcher(
        ds, max_src=cfg.model.max_seq_len, max_mel=cfg.model.max_seq_len
    )

    @jit_program
    def fwd(params, batch_stats, arrays):
        return model.apply(
            {"params": params, "batch_stats": batch_stats},
            speakers=arrays["speakers"],
            texts=arrays["texts"],
            src_lens=arrays["src_lens"],
            mels=arrays["mels"],       # style reference (mandatory)
            mel_lens=arrays["mel_lens"],
            max_mel_len=arrays["mels"].shape[1],
            deterministic=True,
        )

    # pitch/energy predictions are phoneme- or frame-shaped depending on
    # the corpus config (configs/config.py feature levels) — pick the
    # matching pad mask for each
    p_level = cfg.preprocess.preprocessing.pitch.feature
    e_level = cfg.preprocess.preprocessing.energy.feature

    pitch, energy, durations = [], [], []
    for n, batch in enumerate(batcher.epoch(shuffle=False)):
        if n >= max_batches:
            break
        out = fwd(state.params, state.batch_stats, batch.arrays())
        keep_src = ~np.asarray(out["src_pad_mask"])
        keep_mel = ~np.asarray(out["mel_pad_mask"])
        keep_p = keep_src if p_level == "phoneme_level" else keep_mel
        keep_e = keep_src if e_level == "phoneme_level" else keep_mel
        pitch.extend(np.asarray(out["pitch_prediction"])[keep_p].tolist())
        energy.extend(np.asarray(out["energy_prediction"])[keep_e].tolist())
        durations.extend(np.asarray(out["durations"])[keep_src].tolist())
    return pitch, energy, durations


def _style(cfg, split, restore_step, max_batches):
    from flax.traverse_util import flatten_dict

    from speakingstyle_tpu.data import BucketedBatcher, SpeechDataset
    from speakingstyle_tpu.models.factory import build_model

    model = build_model(cfg)
    state = _restored_state(cfg, model, restore_step)

    gates = {
        "/".join(k): float(np.asarray(v).reshape(-1)[0])
        for k, v in flatten_dict(state.params).items()
        if k[-1] in ("s_gamma", "s_beta")
    }

    ds = SpeechDataset(split, cfg, sort=False, drop_last=False)
    batcher = BucketedBatcher(
        ds, max_src=cfg.model.max_seq_len, max_mel=cfg.model.max_seq_len
    )

    # only the style branch is needed — apply the ReferenceEncoder
    # submodule directly on its params subtree (same construction as
    # models/fastspeech2.py), jitted, instead of the whole acoustic model
    from speakingstyle_tpu.models.factory import reference_encoder_from_config
    from speakingstyle_tpu.ops.masking import length_to_mask
    from speakingstyle_tpu.parallel.registry import jit_program

    enc = reference_encoder_from_config(cfg)

    @jit_program
    def style_fwd(ref_params, mels, mel_lens):
        pad = length_to_mask(mel_lens, mels.shape[1])
        return enc.apply({"params": ref_params}, mels, pad, deterministic=True)

    ref_params = state.params["reference_encoder"]
    gammas_all, betas_all = [], []
    for n, batch in enumerate(batcher.epoch(shuffle=False)):
        if n >= max_batches:
            break
        arrays = batch.arrays()
        g, b = style_fwd(ref_params, arrays["mels"], arrays["mel_lens"])
        gammas_all.append(np.asarray(g)[:, 0, :])
        betas_all.append(np.asarray(b)[:, 0, :])
    gammas = np.concatenate(gammas_all) if gammas_all else np.zeros((0, 1))
    betas = np.concatenate(betas_all) if betas_all else np.zeros((0, 1))
    return gammas, betas, gates


def main(args):
    cfg = config_from_args(args)
    report = {"what": args.what, "split": args.split}

    if args.what == "features":
        feats, stats = _corpus_features(cfg, args.split)
        for kind, vals in feats.items():
            report[kind] = _summary(vals)
            print(f"== {kind} (de-normalized, {len(vals)} values)")
            for k, v in report[kind].items():
                print(f"  {k:>6}: {v:.4f}" if isinstance(v, float) else f"  {k:>6}: {v}")
            if len(vals):
                print(_ascii_hist(np.asarray(vals), label=kind))

    elif args.what == "predictions":
        # predictions live in the on-disk NORMALIZED space for pitch/energy
        # (and raw hop counts for durations) — load the truth in that same
        # space so the summaries and the overlap are comparable.
        feats, _ = _corpus_features(cfg, args.split, denormalize=False)
        pitch, energy, durations = _predictions(
            cfg, args.split, args.restore_step, args.max_batches
        )
        durations = _remove_outlier(durations).tolist()
        for kind, pred in (("pitch", pitch), ("energy", energy),
                           ("duration", durations)):
            true = feats[kind]
            report[kind] = {
                "true": _summary(true),
                "pred": _summary(pred),
            }
            if len(pred) and len(true):
                report[kind]["hist_overlap"] = _histogram_overlap(true, pred)
            print(f"== {kind}: true vs predicted")
            print(f"  true: {report[kind]['true']}")
            print(f"  pred: {report[kind]['pred']}")
            if "hist_overlap" in report[kind]:
                print(f"  histogram overlap: {report[kind]['hist_overlap']:.3f}")

    else:  # style
        gammas, betas, gates = _style(
            cfg, args.split, args.restore_step, args.max_batches
        )
        report["n_utts"] = int(gammas.shape[0])
        report["gamma"] = {
            "per_utt_norm": _summary(np.linalg.norm(gammas, axis=1)),
            "per_dim_std_mean": float(gammas.std(axis=0).mean()),
        }
        report["beta"] = {
            "per_utt_norm": _summary(np.linalg.norm(betas, axis=1)),
            "per_dim_std_mean": float(betas.std(axis=0).mean()),
        }
        report["film_gates"] = gates
        print(f"== style vectors over {report['n_utts']} utterances")
        print(f"  |gamma| {report['gamma']['per_utt_norm']}")
        print(f"  |beta|  {report['beta']['per_utt_norm']}")
        print(f"  per-dim std (gamma): {report['gamma']['per_dim_std_mean']:.4f}")
        print("  FiLM gates (s_gamma/s_beta by site):")
        for site, val in sorted(gates.items()):
            print(f"    {site}: {val:+.4f}")

    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
        print(f"raw stats -> {args.json}")
    return report


if __name__ == "__main__":
    main(build_parser().parse_args())
