"""``serve`` command: the continuous-batching text->wav HTTP server.

Starts the AOT-precompiled synthesis engine (serving/engine.py) over the
checkpoint named by ``--restore_step``, precompiles the full shape-bucket
lattice (``serve.*`` config block), then serves:

  POST /synthesize  {"text": ..., "speaker_id"?, "pitch_control"?,
                     "energy_control"?, "duration_control"?, "style_id"?,
                     "ref_audio"? (serve.style.ref_dir-confined path),
                     "priority"? (SLO class)}
                    -> audio/wav (429 + Retry-After under backpressure)
  POST /styles      upload a reference wav -> {"style_id": sha256, ...};
                    content-addressed and cached, so a repeat style skips
                    the reference encoder entirely (serving/style.py)
  GET  /styles      -> resident embedding-cache entries
  POST /synthesize/stream -> chunked audio/wav: overlap-trimmed windows
                       emitted as they are vocoded (serving/streaming.py)
                       — time-to-first-audio is the first-window bound
  POST /synthesize/longform -> chapter-length chunked audio/wav
                       (serving/longform.py): sentence-boundary chunking
                       + crossfade stitching through the batcher, or one
                       seq-sharded ring-attention program per chapter
                       when serve.longform.mesh_seq > 1
  GET  /healthz     -> engine/batcher stats (compile counter must stay at
                       its post-startup value: steady state never
                       compiles); 503 with per-replica lifecycle states
                       until at least one replica finished precompile
  GET  /metrics     -> Prometheus text: the same registry snapshot
                       (compile counters, queue depth, per-bucket dispatch
                       latency histograms, program FLOPs/peak-bytes gauges,
                       achieved-FLOP/s histograms, TTFA + replica-state
                       gauges, process RSS/uptime)
  GET  /debug/programs -> one ProgramCard JSON per compiled XLA program
                       (per-lattice-point FLOPs + memory accounting)
  POST /debug/profile?seconds=N -> pull a jax.profiler trace from the
                       live process (serve.debug_profile gates it)

``--replicas N`` (or ``serve.fleet.replicas``) > 1 serves through the
fleet router (serving/fleet.py): N replica engines warm up on background
threads (cheap under the persistent compile cache), requests carry
priority classes dispatched earliest-deadline-first, and queue-depth
watermarks shed load with 429s before latency collapses. SIGTERM drains
in-flight streams before the process exits.

No reference counterpart: the reference's synthesize.py is one-shot and
pays a fresh CUDA/compile warmup per invocation.
"""

import argparse
import signal
import threading

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument("--restore_step", type=int, required=True)
    parser.add_argument(
        "--ref_audio", type=str, default=None,
        help="default style-reference wav used when a request carries none",
    )
    parser.add_argument(
        "--vocoder_ckpt", type=str, default=None,
        help="HiFi-GAN generator checkpoint (.pth.tar or .msgpack)",
    )
    parser.add_argument(
        "--griffin_lim", action="store_true",
        help="no neural vocoder: /synthesize returns the mel as JSON",
    )
    parser.add_argument("--host", type=str, default=None,
                        help="override serve.host")
    parser.add_argument("--port", type=int, default=None,
                        help="override serve.port")
    parser.add_argument(
        "--replicas", type=int, default=None,
        help="override serve.fleet.replicas: >1 serves through the fleet "
             "router (per-replica engines, EDF dispatch, load shedding)",
    )
    parser.add_argument(
        "--ref_dir", type=str, default=None,
        help="override serve.style.ref_dir: the allowlist directory for "
             'request "ref_audio" paths (unset = uploads via POST /styles '
             "only)",
    )
    parser.add_argument(
        "--cluster", action="store_true",
        help="serve through the distributed control plane (overrides "
             "serve.cluster.enabled): each replica is a separate PROCESS "
             "spawned via `speakingstyle-tpu replica`, registered over "
             "HTTP with heartbeat leases, dispatched with hedged retries "
             "(fleet mode only — needs --replicas > 1)",
    )
    parser.add_argument(
        "--enable_rollout", action="store_true",
        help="enable POST /admin/rollout (canary-gated rolling model "
             "upgrade; fleet mode only — overrides serve.rollout.enabled)",
    )
    return parser


def load_engine_parts(cfg, restore_step: int, vocoder_ckpt=None,
                      griffin_lim=False, strict=False, fault_plan=None,
                      events=None, registry=None):
    """Restore the acoustic checkpoint + vocoder ONCE; returns the
    (variables, vocoder, lattice, model, info) quintuple every replica
    engine shares — fleet replicas differ only in their compiled
    programs, so the host-side weights are loaded a single time.
    ``info`` pins the model identity ({step, weights_digest}) for the
    /healthz model block and X-Model-Version. ``strict=True`` refuses
    manifest-less checkpoints (the rollout verify gate)."""
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.serving.lattice import BucketLattice
    from speakingstyle_tpu.synthesis import get_vocoder
    from speakingstyle_tpu.training.checkpoint import CheckpointManager
    from speakingstyle_tpu.training.optim import make_optimizer
    from speakingstyle_tpu.training.state import TrainState

    lattice = BucketLattice.from_config(cfg.serve)
    n_position = max(lattice.max_mel, lattice.max_src, cfg.model.max_seq_len) + 1
    model = build_model(cfg, n_position=n_position)
    variables = init_variables(model, cfg, jax.random.PRNGKey(cfg.train.seed))
    state = TrainState.create(variables, make_optimizer(cfg.train))
    ckpt = CheckpointManager(
        cfg.train.path.ckpt_path, fault_plan=fault_plan, events=events,
        registry=registry,
    )
    try:
        state = ckpt.restore(
            state,
            step=restore_step if restore_step > 0 else None,
            ignore_layers=cfg.train.ignore_layers,
            strict=strict,
        )
        info = {
            "step": ckpt.last_restored_step,
            "weights_digest": ckpt.last_weights_digest,
        }
    finally:
        ckpt.close()
    vocoder = None if griffin_lim else get_vocoder(cfg, vocoder_ckpt)
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    return variables, vocoder, lattice, model, info


def model_version_string(info) -> str:
    """``<step>:<digest prefix>`` — the X-Model-Version wire format."""
    digest = info.get("weights_digest") or "unverified"
    return f"{info.get('step')}:{digest[:12]}"


def load_engine(cfg, restore_step: int, vocoder_ckpt=None, griffin_lim=False,
                registry=None, fault_plan=None):
    """Restore the acoustic checkpoint + vocoder and build one engine.

    Shared by ``serve`` and ``synthesize`` so the CLI one-shot path and
    the server execute the identical padded-dispatch code.
    """
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    variables, vocoder, lattice, model, _ = load_engine_parts(
        cfg, restore_step, vocoder_ckpt=vocoder_ckpt, griffin_lim=griffin_lim,
        fault_plan=fault_plan,
    )
    return SynthesisEngine(
        cfg, variables, vocoder=vocoder, lattice=lattice, model=model,
        registry=registry, fault_plan=fault_plan,
    )


def main(args):
    from speakingstyle_tpu.serving.server import (
        SynthesisServer,
        TextFrontend,
        load_ref_mel,
    )

    cfg = config_from_args(args)
    # fleet observability plane: size the span ring from config and arm
    # (or disarm) span recording before any serving component starts
    from speakingstyle_tpu.obs.trace import (
        configure_span_ring,
        get_span_ring,
        set_tracing_enabled,
    )

    tcfg = cfg.serve.trace
    configure_span_ring(tcfg.ring_capacity, keep_traces=tcfg.keep_traces)
    set_tracing_enabled(tcfg.enabled)
    # ONE deterministic fault plan from SPEAKINGSTYLE_FAULTS, threaded to
    # every serving component — a single shared plan keeps the @N counters
    # exact (building a plan per component would double-fire each entry)
    from speakingstyle_tpu.faults import FaultPlan

    fault_plan = FaultPlan.from_env() or None
    if fault_plan:
        print(f"fault injection armed: {fault_plan.pending()}", flush=True)
    if getattr(args, "ref_dir", None):
        import dataclasses

        cfg = dataclasses.replace(cfg, serve=dataclasses.replace(
            cfg.serve, style=dataclasses.replace(
                cfg.serve.style, ref_dir=args.ref_dir
            )
        ))
    # persistent compile-cache wiring moved into each engine's
    # ProgramRegistry (parallel/registry.py), constructed before the
    # lattice precompile — a warm restart then serves its AOT programs
    # out of the persistent cache instead of XLA
    replicas = (
        args.replicas if args.replicas is not None
        else cfg.serve.fleet.replicas
    )
    default_ref = (
        load_ref_mel(cfg, args.ref_audio) if args.ref_audio else None
    )
    events = None
    if cfg.serve.log_events:
        from speakingstyle_tpu.obs import JsonlEventLog

        events = JsonlEventLog(
            cfg.train.path.log_path,
            max_bytes=cfg.train.obs.events_max_bytes,
            keep=cfg.train.obs.events_keep,
        )
    autoscaler = None
    if replicas > 1:
        # fleet mode: load the checkpoint once, warm replicas on
        # background threads (persistent compile cache makes scale-up
        # cheap) — the server binds immediately and /healthz reports 503
        # until the first replica finishes its precompile
        from speakingstyle_tpu.obs import MetricsRegistry
        from speakingstyle_tpu.serving.engine import SynthesisEngine
        from speakingstyle_tpu.serving.fleet import FleetRouter
        from speakingstyle_tpu.serving.style import StyleService

        registry = MetricsRegistry()
        variables, vocoder, lattice, model, info = load_engine_parts(
            cfg, args.restore_step,
            vocoder_ckpt=args.vocoder_ckpt, griffin_lim=args.griffin_lim,
            fault_plan=fault_plan, events=events, registry=registry,
        )
        # ONE style service across all replicas: one embedding cache,
        # one AOT encoder lattice (the first replica's warm-up compiles
        # it; the rest find it ready)
        style = (
            StyleService(cfg, variables, registry=registry,
                         fault_plan=fault_plan)
            if cfg.model.use_reference_encoder else None
        )

        def factory(reg: "MetricsRegistry") -> "SynthesisEngine":
            return SynthesisEngine(
                cfg, variables, vocoder=vocoder, lattice=lattice,
                model=model, registry=reg, style=style,
                fault_plan=fault_plan,
            )

        cluster_mode = args.cluster or cfg.serve.cluster.enabled
        if cluster_mode:
            # distributed control plane: replicas are separate processes
            # spawned as `speakingstyle-tpu replica`, each restoring the
            # same checkpoint and precompiling its own lattice.  The
            # parent keeps the checkpoint load above only for the model
            # identity + the shared style service (style vectors resolve
            # router-side and ship over the wire as gamma/beta)
            import subprocess
            import sys

            from speakingstyle_tpu.serving.cluster import ClusterRouter

            def spawn(replica_id, router_addr, extra):
                cmd = [
                    sys.executable, "-m", "speakingstyle_tpu", "replica",
                    "--replica_id", replica_id, "--router", router_addr,
                    "--restore_step",
                    str((extra or {}).get("restore_step",
                                          args.restore_step)),
                ]
                if args.preset:
                    cmd += ["--preset", args.preset]
                for flag, val in (("-p", args.preprocess_config),
                                  ("-m", args.model_config),
                                  ("-t", args.train_config)):
                    if val:
                        cmd += [flag, val]
                if args.vocoder_ckpt:
                    cmd += ["--vocoder_ckpt", args.vocoder_ckpt]
                if args.griffin_lim:
                    cmd += ["--griffin_lim"]
                return subprocess.Popen(cmd)

            router = ClusterRouter(
                spawn, cfg, replicas=replicas,
                registry=registry, events=events, style=style,
                fault_plan=fault_plan,
            )
            print(
                f"cluster control plane on "
                f"http://{router.control_addr} (lease ttl "
                f"{cfg.serve.cluster.lease_ttl_s:g}s, quorum "
                f"{cfg.serve.cluster.quorum})", flush=True,
            )
        else:
            router = FleetRouter(
                factory, cfg, replicas=replicas,
                registry=registry, events=events, style=style,
                fault_plan=fault_plan,
            )
        router.set_model_version(
            model_version_string(info), info.get("step"),
            info.get("weights_digest"),
        )
        print(
            f"warming {replicas} replicas x {len(router.lattice)} lattice "
            "points in the background (healthz: 503 until ready) ...",
            flush=True,
        )
        if cfg.serve.autoscale.enabled:
            from speakingstyle_tpu.serving.autoscale import Autoscaler

            acfg = cfg.serve.autoscale
            autoscaler = Autoscaler(router, acfg)
            print(
                f"autoscaler armed: [{acfg.min_replicas}, "
                f"{acfg.max_replicas}] replicas, tick {acfg.interval_s}s "
                f"(serve_autoscale_target tracks decisions)", flush=True,
            )
        lifecycle = None
        if args.enable_rollout or cfg.serve.rollout.enabled:
            from speakingstyle_tpu.serving.lifecycle import RolloutManager

            def verify_and_build(step: int):
                # the rollout verify gate: strict manifest-checked
                # restore — corrupt/manifest-less candidates abort here,
                # before any replica is touched
                v2, voc2, lat2, mdl2, info2 = load_engine_parts(
                    cfg, step, vocoder_ckpt=args.vocoder_ckpt,
                    griffin_lim=args.griffin_lim, strict=True,
                    fault_plan=fault_plan, events=events, registry=registry,
                )
                if cluster_mode:
                    # canary = a remote replica process restoring the
                    # candidate step; the strict load above stays the
                    # verify gate (corrupt candidates abort here)
                    return (
                        router.remote_factory({"restore_step": step}),
                        model_version_string(info2), info2,
                    )

                def factory2(reg):
                    return SynthesisEngine(
                        cfg, v2, vocoder=voc2, lattice=lat2, model=mdl2,
                        registry=reg, style=style, fault_plan=fault_plan,
                    )

                return factory2, model_version_string(info2), info2

            lifecycle = RolloutManager(router, verify_and_build,
                                       autoscaler=autoscaler, events=events)
            print("rollout enabled: POST /admin/rollout {\"step\": N}",
                  flush=True)
        server = SynthesisServer(
            frontend=TextFrontend(cfg, default_ref),
            host=args.host,
            port=args.port,
            events=events,
            router=router,
            lifecycle=lifecycle,
        )
    else:
        if args.enable_rollout:
            print("warning: --enable_rollout needs fleet mode "
                  "(--replicas > 1); ignoring", flush=True)
        if args.cluster:
            print("warning: --cluster needs fleet mode "
                  "(--replicas > 1); ignoring", flush=True)
        from speakingstyle_tpu.serving.engine import SynthesisEngine

        variables, vocoder, lattice, model, info = load_engine_parts(
            cfg, args.restore_step,
            vocoder_ckpt=args.vocoder_ckpt, griffin_lim=args.griffin_lim,
            fault_plan=fault_plan, events=events,
        )
        engine = SynthesisEngine(
            cfg, variables, vocoder=vocoder, lattice=lattice, model=model,
            fault_plan=fault_plan,
        )
        has_style = engine.style is not None
        style_points = len(engine.style.lattice) if has_style else 0
        print(
            f"precompiling {len(engine.lattice)} lattice points "
            f"+ {style_points} style-encoder points ...", flush=True,
        )
        secs = engine.precompile()
        style_n = engine.style.compile_count if has_style else 0
        print(
            f"precompiled {engine.compile_count} synthesis + {style_n} "
            f"style programs in {secs:.1f}s; steady-state serving "
            "performs zero compiles", flush=True,
        )
        server = SynthesisServer(
            engine,
            TextFrontend(cfg, default_ref),
            host=args.host,
            port=args.port,
            events=events,
            model_info=dict(info, version=model_version_string(info)),
        )
        if cfg.serve.longform.mesh_seq > 1:
            # ring tier: the chapter-length free-run as ONE seq-sharded
            # program set, compiled now (startup, not request path) and
            # attached to the server's auto-built LongformService so both
            # tiers share the one batcher/engine. Fleet mode serves the
            # chunked tier only — a ring tier would need its own
            # per-replica seq mesh, and the chunked tier already rides
            # the replicas.
            from speakingstyle_tpu.serving.longform import RingTier

            ring = RingTier(cfg, variables, engine)
            print(
                f"precompiling {len(ring.lattice)} ring-attention "
                f"long-form points (seq mesh of "
                f"{cfg.serve.longform.mesh_seq}) ...", flush=True,
            )
            ring_secs = ring.precompile()
            print(f"ring tier ready in {ring_secs:.1f}s", flush=True)
            server.longform.ring = ring

    # SLO burn-rate engine (obs/slo.py): multi-window burn rates per
    # traffic class against serve.slo.objectives, published as
    # serve_slo_burn_rate gauges + slo_alert events + /healthz slo block
    slo = None
    if cfg.serve.slo.enabled:
        from speakingstyle_tpu.obs.slo import SloEngine

        slo = SloEngine(server.registry, cfg.serve.slo, events=events,
                        trace_ring=get_span_ring())
        server.slo = slo
        print(
            f"SLO engine armed: objectives "
            f"{dict(cfg.serve.slo.objectives)}, windows "
            f"{cfg.serve.slo.fast_window_s:g}s/"
            f"{cfg.serve.slo.slow_window_s:g}s", flush=True,
        )

    # SIGTERM contract: stop accepting, drain in-flight streams (up to
    # serve.fleet.drain_timeout_s), flush admitted requests, exit.
    # shutdown() must run off the serve_forever thread.
    def _sigterm(signum, frame):
        print("SIGTERM: draining in-flight streams ...", flush=True)
        threading.Thread(
            target=server.shutdown, name="server-shutdown", daemon=True
        ).start()

    signal.signal(signal.SIGTERM, _sigterm)

    host, port = server.address[:2]
    print(
        f"latency pipeline: frontend_workers={cfg.serve.frontend_workers} "
        f"(0 = inline G2P), stream_depth={cfg.serve.fleet.stream_depth} "
        "(1 = sequential vocode)", flush=True,
    )
    print(f"serving on http://{host}:{port} "
          "(POST /synthesize, POST /synthesize/stream, "
          "POST /synthesize/longform, POST /styles, GET /styles, "
          "GET /healthz, GET /metrics, GET /debug/programs, "
          "POST /debug/profile?seconds=N)", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("shutting down (flushing admitted requests) ...", flush=True)
    finally:
        # stop the policy loop before the drain: a scale decision
        # landing mid-shutdown would race the router's own teardown
        if autoscaler is not None:
            autoscaler.close()
        if slo is not None:
            slo.close()
        server.shutdown()
        if events is not None:
            events.close()


if __name__ == "__main__":
    main(build_parser().parse_args())
