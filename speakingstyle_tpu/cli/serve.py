"""``serve`` command: the continuous-batching text->wav HTTP server.

Starts the AOT-precompiled synthesis engine (serving/engine.py) over the
checkpoint named by ``--restore_step``, precompiles the full shape-bucket
lattice (``serve.*`` config block), then serves:

  POST /synthesize  {"text": ..., "speaker_id"?, "pitch_control"?,
                     "energy_control"?, "duration_control"?, "ref_audio"?}
                    -> audio/wav
  GET  /healthz     -> engine/batcher stats (compile counter must stay at
                       its post-startup value: steady state never compiles)
  GET  /metrics     -> Prometheus text: the same registry snapshot
                       (compile counters, queue depth, per-bucket dispatch
                       latency histograms, program FLOPs/peak-bytes gauges,
                       achieved-FLOP/s histograms, process RSS/uptime)
  GET  /debug/programs -> one ProgramCard JSON per compiled XLA program
                       (per-lattice-point FLOPs + memory accounting)
  POST /debug/profile?seconds=N -> pull a jax.profiler trace from the
                       live process (serve.debug_profile gates it)

No reference counterpart: the reference's synthesize.py is one-shot and
pays a fresh CUDA/compile warmup per invocation.
"""

import argparse

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument("--restore_step", type=int, required=True)
    parser.add_argument(
        "--ref_audio", type=str, default=None,
        help="default style-reference wav used when a request carries none",
    )
    parser.add_argument(
        "--vocoder_ckpt", type=str, default=None,
        help="HiFi-GAN generator checkpoint (.pth.tar or .msgpack)",
    )
    parser.add_argument(
        "--griffin_lim", action="store_true",
        help="no neural vocoder: /synthesize returns the mel as JSON",
    )
    parser.add_argument("--host", type=str, default=None,
                        help="override serve.host")
    parser.add_argument("--port", type=int, default=None,
                        help="override serve.port")
    return parser


def load_engine(cfg, restore_step: int, vocoder_ckpt=None, griffin_lim=False):
    """Restore the acoustic checkpoint + vocoder and build the engine.

    Shared by ``serve`` and ``synthesize`` so the CLI one-shot path and
    the server execute the identical padded-dispatch code.
    """
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.serving.engine import SynthesisEngine
    from speakingstyle_tpu.serving.lattice import BucketLattice
    from speakingstyle_tpu.synthesis import get_vocoder
    from speakingstyle_tpu.training.checkpoint import CheckpointManager
    from speakingstyle_tpu.training.optim import make_optimizer
    from speakingstyle_tpu.training.state import TrainState

    lattice = BucketLattice.from_config(cfg.serve)
    n_position = max(lattice.max_mel, lattice.max_src, cfg.model.max_seq_len) + 1
    model = build_model(cfg, n_position=n_position)
    variables = init_variables(model, cfg, jax.random.PRNGKey(cfg.train.seed))
    state = TrainState.create(variables, make_optimizer(cfg.train))
    ckpt = CheckpointManager(cfg.train.path.ckpt_path)
    state = ckpt.restore(
        state,
        step=restore_step if restore_step > 0 else None,
        ignore_layers=cfg.train.ignore_layers,
    )
    ckpt.close()
    vocoder = None if griffin_lim else get_vocoder(cfg, vocoder_ckpt)
    return SynthesisEngine(
        cfg,
        {"params": state.params, "batch_stats": state.batch_stats},
        vocoder=vocoder,
        lattice=lattice,
        model=model,
    )


def main(args):
    from speakingstyle_tpu.serving.server import (
        SynthesisServer,
        TextFrontend,
        load_ref_mel,
    )

    cfg = config_from_args(args)
    if cfg.train.obs.compilation_cache_dir:
        # before the lattice precompile: a warm restart then serves its
        # AOT programs out of the persistent cache instead of XLA
        from speakingstyle_tpu.obs import enable_compilation_cache

        enable_compilation_cache(cfg.train.obs.compilation_cache_dir)
    engine = load_engine(
        cfg, args.restore_step,
        vocoder_ckpt=args.vocoder_ckpt, griffin_lim=args.griffin_lim,
    )
    print(f"precompiling {len(engine.lattice)} lattice points ...", flush=True)
    secs = engine.precompile()
    print(
        f"precompiled {engine.compile_count} programs in {secs:.1f}s; "
        "steady-state serving performs zero compiles", flush=True,
    )

    default_ref = (
        load_ref_mel(cfg, args.ref_audio) if args.ref_audio else None
    )
    events = None
    if cfg.serve.log_events:
        from speakingstyle_tpu.obs import JsonlEventLog

        events = JsonlEventLog(
            cfg.train.path.log_path,
            max_bytes=cfg.train.obs.events_max_bytes,
            keep=cfg.train.obs.events_keep,
        )
    server = SynthesisServer(
        engine,
        TextFrontend(cfg, default_ref),
        host=args.host,
        port=args.port,
        events=events,
    )
    host, port = server.address[:2]
    print(f"serving on http://{host}:{port} "
          "(POST /synthesize, GET /healthz, GET /metrics, "
          "GET /debug/programs, POST /debug/profile?seconds=N)", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("shutting down (flushing admitted requests) ...", flush=True)
    finally:
        server.shutdown()
        if events is not None:
            events.close()


if __name__ == "__main__":
    main(build_parser().parse_args())
