"""``synthesize`` command: batch and single-sentence controllable TTS.

Reference: synthesize.py:153-292. Single mode requires ``--ref_audio`` (the
style encoder always needs a reference mel); controls accept either a
scalar for the whole utterance or — beyond the reference CLI, matching its
notebooks' fine-control workflow (notebooks/control.ipynb) — a per-word
list like ``--duration_control 1.0,2.5,1.0``.

Both modes run through the serving engine's shape-bucket lattice and
continuous batcher (serving/): every dispatch is padded to a lattice
point, so the CLI one-shot path and the HTTP server execute the identical
compiled programs — there is exactly one padded-dispatch code path in the
tree. Reference audio routes through the StyleService's content-addressed
embedding cache (serving/style.py): a repeated reference — the same
``--ref_audio`` across a whole batch, or per-item dataset mels with
duplicate content — encodes ONCE and every other request reuses the
cached FiLM (gamma, beta) vectors.
"""

import argparse
import os

import numpy as np

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument("--restore_step", type=int, required=True)
    parser.add_argument(
        "--mode", type=str, choices=["batch", "single"], required=True,
        help="synthesize a whole metadata file or a single sentence",
    )
    parser.add_argument(
        "--source", type=str, default=None,
        help="metadata file (train.txt/val.txt format), batch mode only",
    )
    parser.add_argument(
        "--text", type=str, default=None,
        help="raw text to synthesize, single mode only",
    )
    parser.add_argument(
        "--ref_audio", type=str, default=None,
        help="reference wav for the speaking style: required in single "
             "mode; in batch mode it overrides the per-item dataset mels "
             "(encoded ONCE through the StyleService cache for the whole "
             "batch)",
    )
    parser.add_argument(
        "--speaker_id", type=str, default="0",
        help="numeric id or speaker name from speakers.json (single mode)",
    )
    parser.add_argument(
        "--pitch_control", type=str, default="1.0",
        help="scalar, or comma-separated per-word factors",
    )
    parser.add_argument("--energy_control", type=str, default="1.0")
    parser.add_argument(
        "--duration_control", type=str, default="1.0",
        help="scalar (larger = slower), or comma-separated per-word factors",
    )
    parser.add_argument(
        "--vocoder_ckpt", type=str, default=None,
        help="HiFi-GAN generator checkpoint (.pth.tar or .msgpack)",
    )
    parser.add_argument(
        "--griffin_lim", action="store_true",
        help="skip the neural vocoder; invert mels with Griffin-Lim",
    )
    parser.add_argument("--plot", action="store_true", help="also save mel plots")
    return parser


def _parse_control(spec: str):
    """"1.0" -> scalar; "1.0,2.5,0.9" -> per-word list."""
    parts = [float(x) for x in spec.split(",")]
    return parts[0] if len(parts) == 1 else parts


def _cli_style(engine, cfg, ref_audio):
    """Resolve a --ref_audio wav to cached StyleVectors: content-addressed
    by the file bytes, so repeats (across a batch OR across invocations
    inside one process) hit the embedding cache instead of the encoder."""
    if engine.style is None or ref_audio is None:
        return None
    with open(ref_audio, "rb") as f:
        return engine.style.encode_wav_bytes(f.read())


def _control_value(spec, spans):
    """Scalar passes through; a per-word list becomes a per-phoneme array
    (the engine pads it to the dispatch bucket)."""
    from speakingstyle_tpu.control import expand_word_controls

    if np.isscalar(spec):
        return float(spec)
    if spans is None:
        raise SystemExit("per-word controls need single mode with English text")
    return np.asarray(expand_word_controls(spans, spec), np.float32)


def main(args):
    from speakingstyle_tpu.cli.serve import load_engine
    from speakingstyle_tpu.data.dataset import TextBatcher
    from speakingstyle_tpu.serving.batcher import ContinuousBatcher
    from speakingstyle_tpu.serving.engine import SynthesisRequest
    from speakingstyle_tpu.serving.server import TextFrontend, load_ref_mel
    from speakingstyle_tpu.synthesis import render_result

    if args.mode == "batch":
        assert args.source is not None and args.text is None
    else:
        assert args.source is None and args.text is not None
        if args.ref_audio is None:
            raise SystemExit(
                "--ref_audio is required in single mode: the style encoder "
                "extracts gamma/beta from a reference mel"
            )

    cfg = config_from_args(args)
    pp = cfg.preprocess.preprocessing
    result_dir = os.path.join(cfg.train.path.result_path, str(args.restore_step))
    os.makedirs(result_dir, exist_ok=True)

    # one padded-dispatch code path: the same engine the server runs. The
    # one-shot CLI skips the full-lattice precompile — the engine compiles
    # the buckets this workload actually touches, on miss, under its lock.
    engine = load_engine(
        cfg, args.restore_step,
        vocoder_ckpt=args.vocoder_ckpt, griffin_lim=args.griffin_lim,
    )

    p_c = _parse_control(args.pitch_control)
    e_c = _parse_control(args.energy_control)
    d_c = _parse_control(args.duration_control)

    requests = []
    if args.mode == "single":
        from speakingstyle_tpu.control import english_word_spans, spans_to_sequence
        from speakingstyle_tpu.text.g2p import preprocess_text, read_lexicon

        lang = pp.text.language
        lex_path = cfg.preprocess.path.lexicon_path or None
        spans = None
        if lang == "en":
            spans = english_word_spans(
                args.text, read_lexicon(lex_path) if lex_path else {}
            )
            sequence = spans_to_sequence(spans, pp.text.text_cleaners)
            print("Phoneme sequence:", " ".join(p for _, ps in spans for p in ps))
        else:
            sequence = preprocess_text(
                args.text, lang, lex_path, list(pp.text.text_cleaners)
            )

        # speaker NAME from speakers.json or raw numeric id (the reference
        # crashes on exactly this lookup — synthesize.py:272, SURVEY.md §2.5)
        speaker = 0
        if cfg.model.multi_speaker:
            try:
                speaker = TextFrontend(cfg, None).speaker(args.speaker_id)
            except ValueError as e:
                raise SystemExit(str(e))

        import re as _re

        safe_id = _re.sub(r"[^\w\-]+", "_", args.text[:100]).strip("_")[:60]
        requests.append(SynthesisRequest(
            id=safe_id or "utt",
            sequence=np.asarray(sequence, np.int32),
            style=_cli_style(engine, cfg, args.ref_audio),
            ref_mel=(
                load_ref_mel(cfg, args.ref_audio)
                if engine.style is None else None
            ),
            speaker=speaker,
            raw_text=args.text,
            p_control=_control_value(p_c, spans),
            e_control=_control_value(e_c, spans),
            d_control=_control_value(d_c, spans),
        ))
    else:
        if not np.isscalar(p_c) or not np.isscalar(e_c) or not np.isscalar(d_c):
            raise SystemExit("per-word controls need single mode with English text")
        # an explicit --ref_audio styles the WHOLE batch: one encoder
        # pass through the StyleService cache, every request reuses the
        # cached (gamma, beta) — N utterances, one encode
        shared_style = (
            _cli_style(engine, cfg, args.ref_audio)
            if args.ref_audio is not None else None
        )
        ds = TextBatcher(args.source, cfg)
        for i in range(len(ds)):
            item = ds[i]
            if shared_style is None and item["mel"] is None:
                raise SystemExit(
                    f"no reference mel for {item['id']!r}: the style encoder "
                    "requires one (reference: synthesize.py --ref_audio)"
                )
            requests.append(SynthesisRequest(
                id=item["id"],
                sequence=item["text"],
                style=shared_style,
                ref_mel=None if shared_style is not None else item["mel"],
                speaker=item["speaker"],
                raw_text=item["raw_text"],
                p_control=float(p_c), e_control=float(e_c),
                d_control=float(d_c),
            ))

    with ContinuousBatcher(engine) as batcher:
        futures = [batcher.submit(r) for r in requests]
        results = [f.result() for f in futures]
    for result in results:
        path = render_result(
            result, cfg, result_dir, plot=args.plot,
            vocoder=None,  # griffin_lim fallback inverts host-side
        )
        print("wrote", path)


if __name__ == "__main__":
    main(build_parser().parse_args())
