"""``synthesize`` command: batch and single-sentence controllable TTS.

Reference: synthesize.py:153-292. Single mode requires ``--ref_audio`` (the
style encoder always needs a reference mel); controls accept either a
scalar for the whole utterance or — beyond the reference CLI, matching its
notebooks' fine-control workflow (notebooks/control.ipynb) — a per-word
list like ``--duration_control 1.0,2.5,1.0``.
"""

import argparse
import json
import os

import numpy as np

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument("--restore_step", type=int, required=True)
    parser.add_argument(
        "--mode", type=str, choices=["batch", "single"], required=True,
        help="synthesize a whole metadata file or a single sentence",
    )
    parser.add_argument(
        "--source", type=str, default=None,
        help="metadata file (train.txt/val.txt format), batch mode only",
    )
    parser.add_argument(
        "--text", type=str, default=None,
        help="raw text to synthesize, single mode only",
    )
    parser.add_argument(
        "--ref_audio", type=str, default=None,
        help="reference wav for the speaking style, single mode only (required)",
    )
    parser.add_argument(
        "--speaker_id", type=str, default="0",
        help="numeric id or speaker name from speakers.json (single mode)",
    )
    parser.add_argument(
        "--pitch_control", type=str, default="1.0",
        help="scalar, or comma-separated per-word factors",
    )
    parser.add_argument("--energy_control", type=str, default="1.0")
    parser.add_argument(
        "--duration_control", type=str, default="1.0",
        help="scalar (larger = slower), or comma-separated per-word factors",
    )
    parser.add_argument(
        "--vocoder_ckpt", type=str, default=None,
        help="HiFi-GAN generator checkpoint (.pth.tar or .msgpack)",
    )
    parser.add_argument(
        "--griffin_lim", action="store_true",
        help="skip the neural vocoder; invert mels with Griffin-Lim",
    )
    parser.add_argument("--plot", action="store_true", help="also save mel plots")
    return parser


def _parse_control(spec: str):
    """"1.0" -> scalar; "1.0,2.5,0.9" -> per-word list."""
    parts = [float(x) for x in spec.split(",")]
    return parts[0] if len(parts) == 1 else parts


def _control_array(spec, spans, length):
    """Scalar passes through; a per-word list becomes a [1, length] array."""
    from speakingstyle_tpu.control import expand_word_controls, pad_control

    if np.isscalar(spec):
        return float(spec)
    if spans is None:
        raise SystemExit("per-word controls need single mode with English text")
    return pad_control(expand_word_controls(spans, spec), length)


def main(args):
    import jax

    from speakingstyle_tpu.audio.stft import MelExtractor, get_mel_from_wav
    from speakingstyle_tpu.audio.tools import load_wav
    from speakingstyle_tpu.data.dataset import Batch, TextBatcher, bucket_length
    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.synthesis import get_vocoder, synth_samples
    from speakingstyle_tpu.training.checkpoint import CheckpointManager
    from speakingstyle_tpu.training.optim import make_optimizer
    from speakingstyle_tpu.training.state import TrainState

    if args.mode == "batch":
        assert args.source is not None and args.text is None
    else:
        assert args.source is None and args.text is not None
        if args.ref_audio is None:
            raise SystemExit(
                "--ref_audio is required in single mode: the style encoder "
                "extracts gamma/beta from a reference mel"
            )

    cfg = config_from_args(args)
    pp = cfg.preprocess.preprocessing
    result_dir = os.path.join(cfg.train.path.result_path, str(args.restore_step))
    os.makedirs(result_dir, exist_ok=True)

    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(cfg.train.seed))
    state = TrainState.create(variables, make_optimizer(cfg.train))
    ckpt = CheckpointManager(cfg.train.path.ckpt_path)
    state = ckpt.restore(
        state,
        step=args.restore_step if args.restore_step > 0 else None,
        ignore_layers=cfg.train.ignore_layers,
    )
    ckpt.close()

    vocoder = None if args.griffin_lim else get_vocoder(cfg, args.vocoder_ckpt)

    p_c = _parse_control(args.pitch_control)
    e_c = _parse_control(args.energy_control)
    d_c = _parse_control(args.duration_control)

    spans = None
    if args.mode == "single":
        from speakingstyle_tpu.control import english_word_spans, spans_to_sequence
        from speakingstyle_tpu.text.g2p import preprocess_text, read_lexicon

        lang = pp.text.language
        lex_path = cfg.preprocess.path.lexicon_path or None
        if lang == "en":
            spans = english_word_spans(
                args.text, read_lexicon(lex_path) if lex_path else {}
            )
            sequence = spans_to_sequence(spans, pp.text.text_cleaners)
            print("Phoneme sequence:", " ".join(p for _, ps in spans for p in ps))
        else:
            sequence = preprocess_text(
                args.text, lang, lex_path, list(pp.text.text_cleaners)
            )

        wav, _ = load_wav(args.ref_audio, target_sr=pp.audio.sampling_rate)
        mel, _ = get_mel_from_wav(
            wav,
            MelExtractor(
                pp.stft.filter_length, pp.stft.hop_length, pp.stft.win_length,
                pp.mel.n_mel_channels, pp.audio.sampling_rate,
                pp.mel.mel_fmin, pp.mel.mel_fmax,
            ),
        )
        mel = mel.T  # [T, n_mels]

        speakers_path = os.path.join(
            cfg.preprocess.path.preprocessed_path, "speakers.json"
        )
        speaker = 0
        if cfg.model.multi_speaker:
            # accept a speaker NAME from speakers.json (its keys) or a raw
            # numeric id (the reference crashes on exactly this lookup —
            # synthesize.py:272, SURVEY.md §2.5)
            if os.path.exists(speakers_path):
                with open(speakers_path) as f:
                    speaker_map = json.load(f)
                if args.speaker_id in speaker_map:
                    speaker = speaker_map[args.speaker_id]
                elif args.speaker_id.lstrip("-").isdigit():
                    speaker = int(args.speaker_id)
                else:
                    raise SystemExit(
                        f"unknown speaker {args.speaker_id!r}; known: "
                        f"{sorted(speaker_map)[:10]}..."
                    )
            elif args.speaker_id.lstrip("-").isdigit():
                speaker = int(args.speaker_id)

        L = bucket_length(len(sequence), 16)
        T = bucket_length(mel.shape[0], 64)
        texts = np.zeros((1, L), np.int32)
        texts[0, : len(sequence)] = sequence
        mels = np.zeros((1, T, mel.shape[1]), np.float32)
        mels[0, : mel.shape[0]] = mel
        import re as _re

        safe_id = _re.sub(r"[^\w\-]+", "_", args.text[:100]).strip("_")[:60]
        batches = [
            Batch(
                n_real=1,
                ids=[safe_id or "utt"],
                raw_texts=[args.text],
                speakers=np.asarray([speaker], np.int32),
                texts=texts,
                src_lens=np.asarray([len(sequence)], np.int32),
                mels=mels,
                mel_lens=np.asarray([mel.shape[0]], np.int32),
                pitches=np.zeros((1, L), np.float32),
                energies=np.zeros((1, L), np.float32),
                durations=np.zeros((1, L), np.int32),
            )
        ]
    else:
        batches = TextBatcher(args.source, cfg).epoch()

    for batch in batches:
        L = batch.texts.shape[1]
        out = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            speakers=batch.speakers,
            texts=batch.texts,
            src_lens=batch.src_lens,
            mels=batch.mels,
            mel_lens=batch.mel_lens,
            max_mel_len=int(cfg.model.max_seq_len),
            p_control=_control_array(p_c, spans, L),
            e_control=_control_array(e_c, spans, L),
            d_control=_control_array(d_c, spans, L),
            deterministic=True,
        )
        paths = synth_samples(batch, out, vocoder, cfg, result_dir, plot=args.plot)
        for p in paths:
            print("wrote", p)


if __name__ == "__main__":
    main(build_parser().parse_args())
