"""Command-line entry points.

Argument surface matches the reference scripts (reference: train.py:176-202,
synthesize.py:153-292, preprocess.py, prepare_align.py, evaluate.py:91-122),
plus ``--preset <DATASET>`` as a shorthand for the three YAML paths.

Run as ``python -m speakingstyle_tpu <command> ...`` or via the installed
``speakingstyle-tpu`` console script.
"""

import argparse

from speakingstyle_tpu.configs.config import Config, load_config


def add_config_args(parser: argparse.ArgumentParser, required: bool = False):
    parser.add_argument(
        "-p", "--preprocess_config", type=str, default=None,
        help="path to preprocess.yaml",
    )
    parser.add_argument(
        "-m", "--model_config", type=str, default=None, help="path to model.yaml"
    )
    parser.add_argument(
        "-t", "--train_config", type=str, default=None, help="path to train.yaml"
    )
    parser.add_argument(
        "--preset", type=str, default=None,
        help="named preset (LJSpeech, LJSpeech_paper, LibriTTS, AISHELL3, "
        "BC2013); explicit -p/-m/-t paths override individual files",
    )
    if required:
        # mirror the reference's required -p/-m/-t while allowing --preset
        parser.set_defaults(_config_required=True)


def config_from_args(args) -> Config:
    if getattr(args, "_config_required", False) and not (
        args.preset or (args.preprocess_config and args.model_config and args.train_config)
    ):
        raise SystemExit(
            "config required: pass --preset <DATASET> or all of -p/-m/-t"
        )
    return load_config(
        preprocess=args.preprocess_config,
        model=args.model_config,
        train=args.train_config,
        preset=args.preset,
    )
