"""``distill`` command: train the fast-tier student acoustic model.

Distills the teacher checkpoint under ``train.path.ckpt_path`` into a
halved-depth/width student (training/distill.py), checkpointing under
``<ckpt_path>/student`` as a second model version the tier gates
(serving/tiers.py) canary against the teacher.
"""

import argparse

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument(
        "--max_steps", type=int, default=None,
        help="override total_step for the distill run (smoke tests)",
    )
    parser.add_argument(
        "--batch_size", type=int, default=8,
        help="synthetic distill batch size (static shape: one compile)",
    )
    parser.add_argument(
        "--src_len", type=int, default=None,
        help="phoneme length of the synthetic batches (default: the "
        "golden-set length, min(serve.src_buckets[0], 12))",
    )
    parser.add_argument(
        "--fresh_teacher", action="store_true",
        help="distill against a seeded fresh-init teacher even if a "
        "checkpoint exists (drills/bench: exercises the full loop "
        "without a trained teacher)",
    )
    parser.add_argument(
        "--faults", type=str, default=None,
        help="deterministic fault-injection spec for resilience drills, "
        "e.g. 'nan_grads@120;sigterm@500' (sets SPEAKINGSTYLE_FAULTS; "
        "see training/faults.py for the grammar)",
    )
    return parser


def main(args):
    import os

    if args.faults:
        from speakingstyle_tpu.training.faults import ENV_VAR, FaultPlan

        FaultPlan.parse(args.faults)  # validate the spec before training
        os.environ[ENV_VAR] = args.faults

    cfg = config_from_args(args)
    teacher_variables = None
    if args.fresh_teacher:
        import jax

        from speakingstyle_tpu.models.factory import build_model, init_variables

        teacher_variables = init_variables(
            build_model(cfg), cfg, jax.random.PRNGKey(cfg.train.seed)
        )
    from speakingstyle_tpu.training.distill import run_distillation

    state, _ = run_distillation(
        cfg,
        teacher_variables=teacher_variables,
        max_steps=args.max_steps,
        batch_size=args.batch_size,
        src_len=args.src_len,
    )
    print(f"distillation finished at step {int(state.step)}")


if __name__ == "__main__":
    main(build_parser().parse_args())
