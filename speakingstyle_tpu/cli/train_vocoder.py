"""``train_vocoder`` command: HiFi-GAN GAN training
(reference: hifigan/train.py:226-267 — with the discriminators the
reference's vendored copy is missing)."""

import argparse

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument(
        "--input_wavs_dir", type=str, required=True,
        help="directory tree of training wavs",
    )
    parser.add_argument("--checkpoint_path", type=str, default="./output/vocoder")
    parser.add_argument("--training_steps", type=int, default=400000)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument(
        "--fine_tune_mel_dir", type=str, default=None,
        help="acoustic-model mel dir: fine-tune on predicted mels",
    )
    parser.add_argument(
        "--warm_start", type=str, default=None,
        help="generator checkpoint (.pth.tar or .msgpack) to fine-tune from",
    )
    parser.add_argument(
        "--restore", type=str, default=None,
        help="full-state vocoder checkpoint (.msgpack) to resume from",
    )
    parser.add_argument("--data_parallel", type=int, default=None)
    return parser


def main(args):
    import jax

    from speakingstyle_tpu.data.mel_dataset import scan_wavs
    from speakingstyle_tpu.parallel.mesh import make_mesh
    from speakingstyle_tpu.training.vocoder_trainer import (
        VocoderHParams,
        train_vocoder,
    )

    cfg = config_from_args(args)
    gen_params = None
    if args.warm_start:
        from speakingstyle_tpu.synthesis import get_vocoder

        _, gen_params = get_vocoder(cfg, args.warm_start)
    n_dev = args.data_parallel or len(jax.devices())
    mesh = make_mesh(data=n_dev, model=1) if n_dev > 1 else None
    wavs = scan_wavs(args.input_wavs_dir)
    print(f"training vocoder on {len(wavs)} wavs")
    train_vocoder(
        cfg,
        wavs,
        hp=VocoderHParams(),
        max_steps=args.training_steps,
        batch_size=args.batch_size,
        mesh=mesh,
        ckpt_path=args.checkpoint_path,
        fine_tune_mel_dir=args.fine_tune_mel_dir,
        gen_params=gen_params,
        restore_path=args.restore,
    )


if __name__ == "__main__":
    main(build_parser().parse_args())
