"""``replica`` command: one cluster replica process.

The worker half of the distributed control plane (serving/cluster.py):
restores the checkpoint, AOT-precompiles the full shape-bucket lattice
(exactly the engine ``serve`` builds — replicas differ from the
single-process tier only in who routes to them), then registers with a
``ClusterRouter``'s control server and serves

  POST /dispatch   one coalesced batch over the wire (idempotency-keyed:
                   a hedge or retry of an executed batch answers from a
                   bounded cache instead of re-running the lattice)
  GET  /healthz    ready flag + compile/dispatch counters (the router's
                   adoption probe, and the zero-steady-state-compile
                   check for the cluster bench)
  POST /drain      stop admitting, finish in-flight, report not-ready

Liveness is a heartbeat lease: the process beats every
``serve.cluster.heartbeat_interval_s``; missing the miss budget expires
the lease router-side, requeueing any in-flight work there.  A beat
answered 409/410 (stale epoch / lost lease — e.g. after a healed
partition) re-registers with a bumped epoch.

When one replica spans hosts (``serve.parallel`` gives the engine a
multi-host mesh slice), pass ``--coordinator_address`` (+
``--num_processes``/``--process_id``) and the process joins the jax
distributed runtime before any device work — each *replica* is then a
whole jax process group, and the control plane above it is unchanged.

Usually spawned by ``serve --cluster`` or ``bench.py --cluster`` rather
than by hand.
"""

import argparse
import os
import signal
import threading

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument("--restore_step", type=int, required=True)
    parser.add_argument(
        "--replica_id", type=str, required=True,
        help="lease identity assigned by the router (e.g. r3)",
    )
    parser.add_argument(
        "--router", type=str, required=True,
        help="the ClusterRouter control server, host:port",
    )
    parser.add_argument(
        "--vocoder_ckpt", type=str, default=None,
        help="HiFi-GAN generator checkpoint (.pth.tar or .msgpack)",
    )
    parser.add_argument(
        "--griffin_lim", action="store_true",
        help="no neural vocoder: results carry the mel only",
    )
    parser.add_argument("--host", type=str, default="127.0.0.1",
                        help="bind address for the replica's HTTP server")
    parser.add_argument("--port", type=int, default=0,
                        help="bind port (0 = ephemeral)")
    parser.add_argument(
        "--coordinator_address", type=str, default=None,
        help="jax.distributed coordinator (host:port) when this replica "
             "spans hosts; omitted = single-process replica",
    )
    parser.add_argument("--num_processes", type=int, default=None,
                        help="jax.distributed process count (with "
                             "--coordinator_address)")
    parser.add_argument("--process_id", type=int, default=None,
                        help="this process's jax.distributed index (with "
                             "--coordinator_address)")
    return parser


def main(args):
    cfg = config_from_args(args)
    # replica half of the fleet observability plane: size this process's
    # span ring and arm recording from the SAME serve.trace block the
    # router uses, so a fleet-wide trace has every hop recorded
    from speakingstyle_tpu.obs.trace import (
        configure_span_ring,
        set_tracing_enabled,
    )

    configure_span_ring(cfg.serve.trace.ring_capacity,
                        keep_traces=cfg.serve.trace.keep_traces)
    set_tracing_enabled(cfg.serve.trace.enabled)
    if args.coordinator_address:
        # multi-host replica: join the distributed runtime BEFORE any
        # device work so the engine's serve.parallel mesh sees every
        # host's devices
        import jax

        jax.distributed.initialize(
            coordinator_address=args.coordinator_address,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    from speakingstyle_tpu.cli.serve import load_engine, model_version_string
    from speakingstyle_tpu.faults import FaultPlan
    from speakingstyle_tpu.obs import MetricsRegistry
    from speakingstyle_tpu.serving.cluster import ReplicaServer

    fault_plan = FaultPlan.from_env() or None
    if fault_plan:
        print(f"fault injection armed: {fault_plan.pending()}", flush=True)
    registry = MetricsRegistry()
    engine = load_engine(
        cfg, args.restore_step, vocoder_ckpt=args.vocoder_ckpt,
        griffin_lim=args.griffin_lim, registry=registry,
        fault_plan=fault_plan,
    )
    print(
        f"[{args.replica_id}] precompiling {len(engine.lattice)} lattice "
        "points before registering ...", flush=True,
    )
    secs = engine.precompile()
    print(
        f"[{args.replica_id}] {engine.compile_count} programs in "
        f"{secs:.1f}s; registering with {args.router}", flush=True,
    )
    server = ReplicaServer(
        engine, args.replica_id, args.router, cfg.serve.cluster,
        registry=registry, host=args.host, port=args.port, pid=os.getpid(),
    )
    server.start()
    print(
        f"[{args.replica_id}] serving on http://{server.host}:{server.port} "
        f"(lease ttl {cfg.serve.cluster.lease_ttl_s:g}s)", flush=True,
    )

    # SIGTERM contract mirrors serve: stop admitting (heartbeats report
    # not-ready, dispatches answer 503), let in-flight finish, exit.
    def _sigterm(signum, frame):
        print(f"[{args.replica_id}] SIGTERM: draining ...", flush=True)
        server._draining = True

        def _stop():
            threading.Event().wait(cfg.serve.fleet.drain_timeout_s)
            server.close()

        threading.Thread(target=_stop, name="replica-shutdown",
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        server.wait_closed()
    except KeyboardInterrupt:
        server.close()
    return 0


if __name__ == "__main__":
    main(build_parser().parse_args())
