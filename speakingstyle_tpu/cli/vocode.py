"""``vocode`` command: standalone HiFi-GAN inference without the acoustic model.

TPU-native counterpart of the reference's two standalone scripts:
  * mel-npy dir -> wav  (reference: hifigan/inference_e2e.py:36-62)
  * wav dir -> mel -> wav resynthesis quality check
    (reference: hifigan/inference.py:37-68)

Mel inputs may be [T, n_mels] (this framework's preprocessed layout) or
[n_mels, T] (the reference trainer's save layout) — detected by shape.
Inputs are right-padded to a multiple of 64 frames so the jitted generator
compiles once per bucket instead of once per file, then trimmed to the true
length after upsampling.
"""

import argparse
import os

import numpy as np

from speakingstyle_tpu.cli import add_config_args, config_from_args

PAD_FRAMES = 64
LOG_MEL_FLOOR = float(np.log(1e-5))  # dynamic_range_compression clip floor


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser)
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument(
        "--input_mels_dir", type=str, default=None,
        help="directory of mel .npy files to vocode",
    )
    src.add_argument(
        "--input_wavs_dir", type=str, default=None,
        help="directory of .wav files to resynthesize (wav -> mel -> wav)",
    )
    parser.add_argument(
        "--output_dir", type=str, default="generated_files",
        help="where the generated wavs go",
    )
    parser.add_argument(
        "--checkpoint_file", type=str, required=True,
        help="HiFi-GAN generator: torch generator_*.pth.tar or this "
        "framework's *.generator.msgpack",
    )
    parser.add_argument(
        "--hifigan_config", type=str, default=None,
        help="generator config.json (defaults to the vendored LJSpeech "
        "V1 architecture)",
    )
    return parser


def _load_mel(path: str, n_mels: int) -> np.ndarray:
    """.npy -> [T, n_mels], accepting either orientation."""
    mel = np.load(path).astype(np.float32)
    if mel.ndim != 2:
        raise ValueError(f"{path}: expected 2-D mel, got shape {mel.shape}")
    if mel.shape[0] == n_mels and mel.shape[1] != n_mels:
        mel = mel.T
    return mel


def _vocode_one(gen, params, mel: np.ndarray, max_wav_value: float):
    """[T, n_mels] -> int16 wav, padding T to a compile bucket first."""
    from speakingstyle_tpu.models.hifigan import vocoder_infer

    T = mel.shape[0]
    pad_to = -(-T // PAD_FRAMES) * PAD_FRAMES
    mel = np.pad(
        mel, ((0, pad_to - T), (0, 0)), constant_values=LOG_MEL_FLOOR
    )
    return vocoder_infer(
        gen, params, mel[None], lengths=[T], max_wav_value=max_wav_value
    )[0]


def main(args):
    import scipy.io.wavfile

    from speakingstyle_tpu.synthesis import get_vocoder

    cfg = config_from_args(args)
    audio_cfg = cfg.preprocess.preprocessing.audio
    n_mels = cfg.preprocess.preprocessing.mel.n_mel_channels
    gen, params = get_vocoder(
        cfg, ckpt_path=args.checkpoint_file, config_path=args.hifigan_config
    )
    os.makedirs(args.output_dir, exist_ok=True)

    written = []
    if args.input_mels_dir:
        names = sorted(
            f for f in os.listdir(args.input_mels_dir) if f.endswith(".npy")
        )
        for name in names:
            mel = _load_mel(os.path.join(args.input_mels_dir, name), n_mels)
            wav = _vocode_one(gen, params, mel, audio_cfg.max_wav_value)
            out = os.path.join(
                args.output_dir,
                os.path.splitext(name)[0] + "_generated_e2e.wav",
            )
            scipy.io.wavfile.write(out, audio_cfg.sampling_rate, wav)
            print(out)
            written.append(out)
    else:
        from speakingstyle_tpu.audio.stft import MelExtractor, get_mel_from_wav
        from speakingstyle_tpu.audio.tools import load_wav

        stft_cfg = cfg.preprocess.preprocessing.stft
        extractor = MelExtractor(
            filter_length=stft_cfg.filter_length,
            hop_length=stft_cfg.hop_length,
            win_length=stft_cfg.win_length,
            n_mel_channels=n_mels,
            sampling_rate=audio_cfg.sampling_rate,
            mel_fmin=cfg.preprocess.preprocessing.mel.mel_fmin,
            mel_fmax=cfg.preprocess.preprocessing.mel.mel_fmax,
        )
        names = sorted(
            f for f in os.listdir(args.input_wavs_dir) if f.endswith(".wav")
        )
        for name in names:
            audio, _ = load_wav(
                os.path.join(args.input_wavs_dir, name),
                target_sr=audio_cfg.sampling_rate,
            )
            mel, _ = get_mel_from_wav(audio, extractor)  # [n_mels, T]
            wav = _vocode_one(gen, params, mel.T, audio_cfg.max_wav_value)
            out = os.path.join(
                args.output_dir,
                os.path.splitext(name)[0] + "_generated.wav",
            )
            scipy.io.wavfile.write(out, audio_cfg.sampling_rate, wav)
            print(out)
            written.append(out)
    if not written:
        raise SystemExit("no input files found")
    return written


if __name__ == "__main__":
    main(build_parser().parse_args())
