"""``prepare_align`` command: raw corpus -> MFA-ready tree
(reference: prepare_align.py)."""

import argparse

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument("--num_workers", type=int, default=None)
    return parser


def main(args):
    from speakingstyle_tpu.data import corpora

    cfg = config_from_args(args)
    corpora.prepare_align(cfg, num_workers=args.num_workers)


if __name__ == "__main__":
    main(build_parser().parse_args())
