"""``convert`` command: PyTorch reference checkpoints -> this framework.

Converts an acoustic-model checkpoint (reference format: train.py:155-165,
``torch.save({"model": ..., "optimizer": ...})`` as ``<step>.pth.tar``) into
an Orbax checkpoint loadable by ``train``/``evaluate``/``synthesize``, and
optionally runs the teacher-forced **mel-L1 parity gate** (BASELINE.md) over
the validation set. Also converts a HiFi-GAN ``generator_*.pth.tar``
(reference: hifigan/models.py:112-174, weight norm folded) to the
generator-only msgpack sidecar ``synthesis.get_vocoder`` loads.

The released 900k-step LJSpeech checkpoint is not obtainable in this
environment (structural parity is covered by tests/test_reference_parity.py
with a random-weight reference model instead); this CLI is the ready-to-run
gate for when the artifact is available:

    python -m speakingstyle_tpu convert --preset LJSpeech \\
        --ckpt 900000.pth.tar --eval_mel_l1
"""

import argparse
import os
import re

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument("--ckpt", type=str, required=True,
                        help="PyTorch checkpoint (<step>.pth.tar or "
                        "generator_*.pth.tar)")
    parser.add_argument("--kind", choices=("fastspeech2", "hifigan"),
                        default="fastspeech2")
    parser.add_argument("--step", type=int, default=None,
                        help="checkpoint step (default: parsed from the "
                        "filename's leading integer, else 0)")
    parser.add_argument("--out", type=str, default=None,
                        help="output: Orbax ckpt dir for fastspeech2 "
                        "(default train.path.ckpt_path) / .msgpack path for "
                        "hifigan (default <ckpt>.generator.msgpack)")
    parser.add_argument("--eval_mel_l1", action="store_true",
                        help="after converting, run a full teacher-forced "
                        "val pass and print mel-L1 (the BASELINE.md gate)")
    return parser


def _step_from_name(path: str) -> int:
    m = re.match(r"(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else 0


def _convert_hifigan(args):
    from flax import serialization

    from speakingstyle_tpu.compat.torch_convert import (
        convert_hifigan,
        load_torch_state_dict,
    )

    sd = load_torch_state_dict(args.ckpt, key="generator")
    params = convert_hifigan(sd)
    out = args.out or args.ckpt + ".generator.msgpack"
    with open(out, "wb") as f:
        f.write(serialization.to_bytes(params))
    print(f"wrote generator params to {out}")
    return out


def main(args):
    if args.kind == "hifigan":
        return _convert_hifigan(args)

    import jax

    from speakingstyle_tpu.compat.torch_convert import (
        convert_fastspeech2,
        load_torch_state_dict,
    )
    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.training.checkpoint import CheckpointManager
    from speakingstyle_tpu.training.optim import make_optimizer
    from speakingstyle_tpu.training.state import TrainState

    cfg = config_from_args(args)
    sd = load_torch_state_dict(args.ckpt, key="model")
    converted = convert_fastspeech2(sd)

    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    # Fail loudly on any tree/shape mismatch (wrong preset for this
    # checkpoint) before anything is written.
    def _check(init, conv):
        if init.shape != conv.shape:
            raise ValueError(
                f"checkpoint/config mismatch: {init.shape} vs {conv.shape}"
            )

    jax.tree_util.tree_map(_check, variables["params"], converted["params"])

    tx = make_optimizer(cfg.train)
    step = args.step if args.step is not None else _step_from_name(args.ckpt)
    state = TrainState.create(
        {"params": converted["params"], "batch_stats": converted["batch_stats"]},
        tx,
    ).replace(step=step)

    out_dir = args.out or cfg.train.path.ckpt_path
    ckpt = CheckpointManager(out_dir)
    ckpt.save(step, state)
    print(f"converted {args.ckpt} -> {out_dir} @ step {step}")

    if args.eval_mel_l1:
        from speakingstyle_tpu.data import BucketedBatcher, SpeechDataset
        from speakingstyle_tpu.data.prefetch import DevicePrefetcher
        from speakingstyle_tpu.training.trainer import evaluate, make_eval_step

        eval_step = make_eval_step(model, cfg)
        ds = SpeechDataset("val.txt", cfg, sort=False, drop_last=False)
        batcher = BucketedBatcher(
            ds, max_src=cfg.model.max_seq_len, max_mel=cfg.model.max_seq_len
        )
        losses = evaluate(
            eval_step, state, DevicePrefetcher(batcher.epoch(shuffle=False))
        )
        print(f"mel_l1: {losses['mel_loss']:.6f}  "
              f"postnet_mel_l1: {losses['postnet_mel_loss']:.6f}  "
              f"(gate: BASELINE.md mel-L1 parity vs the torch reference)")
    ckpt.close()
    return state


if __name__ == "__main__":
    main(build_parser().parse_args())
