"""``train`` command (reference: train.py:176-202)."""

import argparse

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument(
        "--restore_step", type=int, default=0,
        help="checkpoint step to resume from (0 = fresh start; -1 = latest)",
    )
    parser.add_argument(
        "--max_steps", type=int, default=None,
        help="override total_step (smoke tests)",
    )
    parser.add_argument(
        "--data_parallel", type=int, default=None,
        help="data-axis size for the device mesh; overrides "
        "train.parallel.mesh (default: the train.parallel.* config block, "
        "falling back to the legacy train.sharding derivation)",
    )
    parser.add_argument(
        "--model_parallel", type=int, default=None,
        help="tensor-parallel degree over the mesh's model axis; overrides "
        "train.parallel.mesh (default: the train.parallel.* config block, "
        "falling back to train.sharding.model_axis)",
    )
    parser.add_argument(
        "--synth", action="store_true",
        help="render a GT-vs-predicted validation sample every synth_step",
    )
    parser.add_argument(
        "--vocoder_ckpt", type=str, default=None,
        help="HiFi-GAN checkpoint for --synth audio (Griffin-Lim otherwise)",
    )
    parser.add_argument(
        "--profile_dir", type=str, default=None,
        help="write a jax.profiler trace of steps 10-20 here",
    )
    parser.add_argument(
        "--profile_at", type=int, default=None,
        help="capture a jax.profiler trace over steps [N, N+10) of this "
        "run (relative to the resume point); the trace lands in "
        "--profile_dir, defaulting to <train.path.log_path>/profile",
    )
    parser.add_argument(
        "--faults", type=str, default=None,
        help="deterministic fault-injection spec for resilience drills, "
        "e.g. 'nan_grads@120;sigterm@500' (sets SPEAKINGSTYLE_FAULTS; "
        "see training/faults.py for the grammar)",
    )
    return parser


def main(args):
    import os

    if args.faults:
        from speakingstyle_tpu.training.faults import ENV_VAR, FaultPlan

        FaultPlan.parse(args.faults)  # validate the spec before training
        os.environ[ENV_VAR] = args.faults

    if os.environ.get("SPEAKINGSTYLE_MULTIHOST"):
        # Pod-slice training: every host runs this process; initialize()
        # must precede any other JAX call so the hosts form one global
        # mesh (coordinator discovery is automatic on TPU VMs). See
        # scripts/train_multihost.sh.
        import jax

        jax.distributed.initialize()
    import jax

    from speakingstyle_tpu.parallel.mesh import make_mesh, resolve_mesh
    from speakingstyle_tpu.training.trainer import run_training

    cfg = config_from_args(args)
    # persistent compile-cache wiring moved into the ProgramRegistry that
    # run_training constructs before its first compile (parallel/registry.py)
    par = cfg.train.parallel
    flags_given = args.data_parallel is not None or args.model_parallel is not None
    if not par.is_single() and not flags_given:
        # train.parallel.* is the multichip contract: mesh != [1,1]
        # engages the mesh path; [1,1] leaves mesh=None (the single-chip
        # path, byte-for-byte the old behavior). Batch divisibility and
        # device-count fit are validated at startup (BatchShardingError /
        # ValueError name the fix).
        mesh = resolve_mesh(par)
    else:
        # legacy resolution, unchanged: CLI flags win, then the
        # train.sharding block, then all-device DP
        model_axis = (
            args.model_parallel
            if args.model_parallel is not None
            else cfg.train.sharding.model_axis
        )
        n_total = len(jax.devices())
        if args.data_parallel:
            data_axis = args.data_parallel
        elif cfg.train.sharding.data_axis > 0:
            data_axis = cfg.train.sharding.data_axis
        else:
            data_axis = n_total // model_axis
        n_dev = data_axis * model_axis
        mesh = (
            make_mesh(
                data=data_axis,
                model=model_axis,
                devices=jax.devices()[:n_dev],
            )
            if n_dev > 1
            else None
        )
    vocoder = None
    if args.synth and args.vocoder_ckpt:
        from speakingstyle_tpu.synthesis import get_vocoder

        vocoder = get_vocoder(cfg, args.vocoder_ckpt)
    profile_dir, profile_steps = args.profile_dir, (10, 20)
    if args.profile_at is not None:
        # --profile_at N: pull a trace from steps [N, N+10) without
        # needing to pick a directory (the serve-side twin is
        # POST /debug/profile)
        profile_steps = (args.profile_at, args.profile_at + 10)
        if profile_dir is None:
            profile_dir = os.path.join(cfg.train.path.log_path, "profile")
    state = run_training(
        cfg,
        mesh=mesh,
        restore_step=args.restore_step if args.restore_step != 0 else None,
        max_steps=args.max_steps,
        synth_callback="default" if args.synth else None,
        vocoder=vocoder,
        profile_dir=profile_dir,
        profile_steps=profile_steps,
    )
    print(f"training finished at step {int(state.step)}")


if __name__ == "__main__":
    main(build_parser().parse_args())
