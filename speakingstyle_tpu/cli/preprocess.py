"""``preprocess`` command: TextGrids + wavs -> features
(reference: preprocess.py — including the ctor-arity fix, SURVEY.md §2.5)."""

import argparse

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument("--num_workers", type=int, default=None)
    return parser


def main(args):
    from speakingstyle_tpu.data.preprocessor import Preprocessor

    cfg = config_from_args(args)
    Preprocessor(cfg).build_from_path(num_workers=args.num_workers)


if __name__ == "__main__":
    main(build_parser().parse_args())
