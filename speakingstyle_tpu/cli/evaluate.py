"""``evaluate`` command: full validation pass (reference: evaluate.py:91-122)."""

import argparse

from speakingstyle_tpu.cli import add_config_args, config_from_args


def build_parser(parser=None):
    parser = parser or argparse.ArgumentParser(description=__doc__)
    add_config_args(parser, required=True)
    parser.add_argument("--restore_step", type=int, default=-1)
    return parser


def main(args):
    import jax

    from speakingstyle_tpu.data import BucketedBatcher, SpeechDataset
    from speakingstyle_tpu.data.prefetch import DevicePrefetcher
    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.training.checkpoint import CheckpointManager
    from speakingstyle_tpu.training.optim import make_optimizer
    from speakingstyle_tpu.training.state import TrainState
    from speakingstyle_tpu.training.trainer import evaluate, make_eval_step

    cfg = config_from_args(args)
    model = build_model(cfg)
    variables = init_variables(model, cfg, jax.random.PRNGKey(cfg.train.seed))
    tx = make_optimizer(cfg.train)
    state = TrainState.create(variables, tx)
    ckpt = CheckpointManager(cfg.train.path.ckpt_path)
    state = ckpt.restore(
        state, step=args.restore_step if args.restore_step > 0 else None
    )
    eval_step = make_eval_step(model, cfg)

    ds = SpeechDataset("val.txt", cfg, sort=False, drop_last=False)
    batcher = BucketedBatcher(
        ds, max_src=cfg.model.max_seq_len, max_mel=cfg.model.max_seq_len
    )
    losses = evaluate(
        eval_step, state, DevicePrefetcher(batcher.epoch(shuffle=False))
    )
    msg = ", ".join(f"{k}: {v:.4f}" for k, v in losses.items())
    print(f"Validation at step {int(state.step)}: {msg}")
    ckpt.close()
    return losses


if __name__ == "__main__":
    main(build_parser().parse_args())
