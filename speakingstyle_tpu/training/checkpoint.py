"""Orbax checkpointing: step-named save/restore with partial loading.

Replaces the reference's ``torch.save({"model", "optimizer"})`` every
save_step (reference: train.py:155-165) and its ``ignore_layers`` +
``strict=False`` transfer-learning restore (reference: utils/model.py:15-32,
config/BC2013/train.yaml:1).
"""

import os
import re
from typing import Optional, Sequence

import jax
import orbax.checkpoint as ocp

from speakingstyle_tpu.training.state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: Optional[int] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True, enable_async_checkpointing=False
            ),
        )

    def save(self, step: int, state: TrainState):
        self.manager.save(step, args=ocp.args.StandardSave(state))
        self.manager.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def restore(
        self,
        state: TrainState,
        step: Optional[int] = None,
        ignore_layers: Sequence[str] = (),
    ) -> TrainState:
        """Restore into the shape of `state` (the abstract template).

        ignore_layers: regexes matched against '/'-joined param paths; matching
        leaves keep their freshly-initialized values AND the optimizer state is
        reset (the reference reinitializes the optimizer when transferring).
        """
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, state
        )
        restored = self.manager.restore(step, args=ocp.args.StandardRestore(abstract))
        if ignore_layers:
            patterns = [re.compile(p) for p in ignore_layers]

            def merge(path, fresh, loaded):
                name = "/".join(str(getattr(k, "key", k)) for k in path)
                return fresh if any(p.search(name) for p in patterns) else loaded

            params = jax.tree_util.tree_map_with_path(
                merge, state.params, restored.params
            )
            return state.replace(params=params, batch_stats=restored.batch_stats)
        return restored

    def close(self):
        self.manager.close()
