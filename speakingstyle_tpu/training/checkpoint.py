"""Orbax checkpointing: step-named save/restore with partial loading.

Replaces the reference's ``torch.save({"model", "optimizer"})`` every
save_step (reference: train.py:155-165) and its ``ignore_layers`` +
``strict=False`` transfer-learning restore (reference: utils/model.py:15-32,
config/BC2013/train.yaml:1).

Resilience extensions (ISSUE 2, config: ``train.resilience.*``):

  * **async saves** — ``save()`` snapshots the state to host memory
    synchronously (donation safety: the next step may reuse the device
    buffers) and hands the Orbax write to a background thread, so the
    step loop never blocks on checkpoint I/O. ``wait()`` joins the
    in-flight write and re-raises any write error.
  * **retention** — keep the newest ``max_to_keep`` steps, plus (with
    ``keep_best``) the best-val-loss step, pruned after each save.
  * **robust latest-step restore** — ``restore(step=None)`` walks steps
    newest-first and falls back past a partial/corrupt checkpoint
    directory (crashed mid-write) instead of bricking the resume,
    distinguishing corrupt (``ckpt_corrupt_skipped`` event + counter)
    from merely absent.

Integrity (ISSUE 13): every save writes ``<step>/manifest.json`` — the
per-leaf sha256 table, tree structure, step, an optional config
fingerprint, and the params-wide ``weights_digest`` — via a temp file +
``os.replace`` so the manifest is atomic: it exists iff it is complete.
Restore verifies the manifest BEFORE handing anything to the caller and
raises ``CheckpointCorruptError`` (structured: ``.step``/``.reason``),
which is a different failure than "no checkpoint here". Manifests are
only advisory for pre-manifest checkpoints (``strict=False`` tolerates
their absence); a rollout's verify gate restores with ``strict=True``.
The ``checkpoint_corrupt@N`` / ``manifest_missing@N`` fault kinds
(faults.py) drill both paths deterministically, counted per manager
instance on the 1-based verification counter ``verify_count``.

Sharding awareness / cross-mesh-shape resume (ISSUE 10): the on-disk
format is mesh-agnostic — ``save()``'s device->host snapshot
(``jax.device_get``) assembles full global arrays whatever DP/TP layout
the live state carried — and ``restore()`` builds its abstract template
from the *passed* state, preserving any shardings its leaves carry. Pass
a state already laid out for the TARGET mesh (or
``TrainState.sharded_abstract``) and Orbax materializes each leaf
directly into that layout: save on an 8x1 DP mesh, restore onto 4x2
DP×TP or 1x1 single-chip, bit-identically (tests/test_multichip.py).
"""

import json
import os
import re
import threading
from typing import Dict, List, Optional, Sequence

import jax
import orbax.checkpoint as ocp

from speakingstyle_tpu.obs.buildinfo import array_sha256, weights_digest
from speakingstyle_tpu.training.state import TrainState
from speakingstyle_tpu.obs.locks import make_lock

MANIFEST_NAME = "manifest.json"
MANIFEST_FORMAT = 1


class CheckpointCorruptError(RuntimeError):
    """A checkpoint EXISTS but failed integrity verification — distinct
    from FileNotFoundError (absent). Carries the step and a machine-
    readable reason (``manifest_missing``, ``manifest_malformed``,
    ``leaf_set_mismatch``, ``leaf_hash_mismatch``, ``injected``)."""

    def __init__(self, step: int, reason: str, detail: str = ""):
        self.step = step
        self.reason = reason
        msg = f"checkpoint step {step} is corrupt ({reason})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


def _abstract_leaf(x):
    """Shape/dtype(/sharding) template leaf for StandardRestore."""
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    return ocp.utils.to_shape_dtype_struct(x)


def _leaf_table(tree) -> Dict[str, Dict]:
    """{'/'-joined leaf path: {sha256, shape, dtype}} for a host tree.
    The same naming as the manifest verifier and ``weights_digest`` use,
    so one flattening convention covers save, verify, and identity."""
    import numpy as np

    table: Dict[str, Dict] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        )
        a = np.asarray(leaf)
        table[name] = {
            "sha256": array_sha256(a),
            "shape": list(a.shape),
            "dtype": str(a.dtype),
        }
    return table


class CheckpointManager:
    def __init__(
        self,
        directory: str,
        max_to_keep: Optional[int] = None,
        async_save: bool = False,
        keep_best: bool = False,
        fault_plan=None,
        events=None,
        registry=None,
        config_fingerprint: Optional[str] = None,
        verify: bool = True,
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        # retention is implemented here (max_to_keep + keep-best protection),
        # not by Orbax options — Orbax's max_to_keep cannot pin the best
        # step past the window
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=None, create=True, enable_async_checkpointing=False
            ),
        )
        self.max_to_keep = max_to_keep or None
        self.keep_best = keep_best
        self.async_save = async_save
        self._metrics: Dict[int, float] = {}  # step -> val loss
        self._lock = make_lock("CheckpointManager._lock")
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self.fault_plan = fault_plan
        self.events = events
        self.registry = registry
        self.config_fingerprint = config_fingerprint
        self.verify = verify
        self.verify_count = 0  # 1-based fault-site counter (per instance)
        self.last_restored_step: Optional[int] = None
        self.last_weights_digest: Optional[str] = None

    # -- saving -------------------------------------------------------------

    def save(
        self,
        step: int,
        state,
        val_loss: Optional[float] = None,
        block: bool = False,
    ):
        """Save ``state`` under ``step``. With ``async_save`` the Orbax
        write runs on a background thread and this returns as soon as the
        device->host snapshot is taken; pass ``block=True`` (final/flush
        saves) to wait for the write. ``val_loss`` feeds keep-best
        retention."""
        self.wait()  # one write in flight at a time; surfaces prior errors
        host_state = jax.device_get(state)
        if self.async_save and not block:
            self._thread = threading.Thread(
                target=self._write_guarded,
                args=(step, host_state, val_loss),
                name=f"ckpt-save-{step}",
                daemon=True,
            )
            self._thread.start()
        else:
            self._write(step, host_state, val_loss)

    def _write_guarded(self, step: int, host_state, val_loss):
        try:
            self._write(step, host_state, val_loss)
        except BaseException as e:  # surfaced by the next wait()/save()
            self._error = e

    def _write(self, step: int, host_state, val_loss):
        self.manager.save(step, args=ocp.args.StandardSave(host_state))
        self.manager.wait_until_finished()
        self._write_manifest(step, host_state)
        with self._lock:
            if val_loss is not None:
                self._metrics[step] = float(val_loss)
        self._prune()

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, str(step), MANIFEST_NAME)

    def _write_manifest(self, step: int, host_state):
        """The integrity record, atomic via temp + os.replace: a torn
        write leaves no manifest at all (absent, never malformed)."""
        manifest = {
            "format": MANIFEST_FORMAT,
            "step": int(step),
            "config_fingerprint": self.config_fingerprint,
            "weights_digest": weights_digest(
                getattr(host_state, "params", host_state)
            ),
            "leaves": _leaf_table(host_state),
        }
        path = self._manifest_path(step)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    def save_in_flight(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def wait(self):
        """Join any in-flight async write; re-raise its error, if any."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        err, self._error = self._error, None
        if err is not None:
            raise err

    # -- retention ----------------------------------------------------------

    def best_step(self) -> Optional[int]:
        """Step with the lowest recorded val loss (this process only)."""
        with self._lock:
            if not self._metrics:
                return None
            return min(self._metrics, key=self._metrics.get)

    def _prune(self):
        if not self.max_to_keep:
            return
        steps = sorted(self.manager.all_steps())
        keep = set(steps[-self.max_to_keep:])
        best = self.best_step()
        if self.keep_best and best is not None:
            keep.add(best)
        for s in steps:
            if s not in keep:
                try:
                    self.manager.delete(s)
                except FileNotFoundError:
                    pass  # already gone (e.g. a concurrent manual cleanup)

    # -- reading ------------------------------------------------------------

    def all_steps(self) -> List[int]:
        return sorted(self.manager.all_steps())

    def _load_manifest(self, step: int) -> Optional[Dict]:
        """Parse the step's manifest, or None when absent. Malformed
        JSON is CORRUPT, not absent: the atomic writer never leaves a
        half manifest, so a torn file means the directory was damaged."""
        path = self._manifest_path(step)
        if not os.path.isfile(path):
            return None
        try:
            with open(path, encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                step, "manifest_malformed", f"{type(e).__name__}: {e}"
            ) from e
        if not isinstance(manifest, dict) or "leaves" not in manifest:
            raise CheckpointCorruptError(
                step, "manifest_malformed", "no leaf table"
            )
        return manifest

    def _verify_restored(self, step: int, manifest: Dict, restored):
        """Per-leaf hash comparison of the materialized tree against the
        manifest written at save time."""
        got = _leaf_table(jax.device_get(restored))
        want = manifest["leaves"]
        if set(got) != set(want):
            missing = sorted(set(want) - set(got))[:3]
            extra = sorted(set(got) - set(want))[:3]
            raise CheckpointCorruptError(
                step, "leaf_set_mismatch",
                f"missing={missing} extra={extra}",
            )
        bad = [n for n in want if got[n]["sha256"] != want[n]["sha256"]]
        if bad:
            raise CheckpointCorruptError(
                step, "leaf_hash_mismatch",
                f"{len(bad)} leaves, first: {sorted(bad)[:3]}",
            )

    def _restore_step(self, step: int, abstract, strict: bool = False):
        """Restore one step via a standalone checkpointer aimed straight
        at the step's item directory. The CheckpointManager is NOT used
        here on purpose: a single failed ``manager.restore`` (a corrupt
        step directory) permanently flips its item-handler registry into
        multi-item mode, after which every later restore — including of
        healthy steps — fails. The standalone path is stateless, so the
        newest-first fallback scan can keep probing.

        The manifest is checked BEFORE materializing (a malformed one
        never costs a restore) and the per-leaf hashes after; either
        failure raises CheckpointCorruptError. ``strict`` additionally
        treats a missing manifest as corrupt (rollout verify gates);
        the default tolerates pre-manifest checkpoints."""
        path = os.path.join(self.directory, str(step), "default")
        if not os.path.isdir(path):
            raise FileNotFoundError(f"no checkpoint item at {path}")
        self.verify_count += 1
        n = self.verify_count
        plan = self.fault_plan
        if plan is not None and plan.fire("checkpoint_corrupt", n):
            raise CheckpointCorruptError(step, "injected", "fault drill")
        manifest = None
        if self.verify:
            if plan is not None and plan.fire("manifest_missing", n):
                manifest = None  # drill: behave as if never written
            else:
                manifest = self._load_manifest(step)
            if manifest is None and strict:
                raise CheckpointCorruptError(
                    step, "manifest_missing",
                    "strict restore requires a save-time manifest",
                )
        restored = ocp.StandardCheckpointer().restore(path, abstract)
        if manifest is not None:
            self._verify_restored(step, manifest, restored)
            self.last_weights_digest = manifest.get("weights_digest")
        else:
            # legacy checkpoint: identity computed, not verified
            self.last_weights_digest = weights_digest(
                getattr(restored, "params", restored)
            )
        self.last_restored_step = step
        return restored

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def restore(
        self,
        state,
        step: Optional[int] = None,
        ignore_layers: Sequence[str] = (),
        strict: bool = False,
    ) -> TrainState:
        """Restore into the shape — and SHARDINGS — of ``state`` (concrete
        arrays or a jax.ShapeDtypeStruct template, e.g.
        ``TrainState.abstract()`` / ``TrainState.sharded_abstract()``).
        Cross-mesh resume rides this: the template names the target mesh's
        layout and Orbax materializes straight into it.

        ``step=None`` restores the latest step, falling back past
        partial/corrupt checkpoint directories (newest-first) so one
        crashed write cannot brick a resume — each corrupt (not merely
        absent) step skipped emits a ``ckpt_corrupt_skipped`` event and
        bumps ``ckpt_corrupt_skipped_total``. An explicitly requested
        step fails loudly instead. ``strict=True`` (rollout verify)
        refuses manifest-less checkpoints.

        ignore_layers: regexes matched against '/'-joined param paths;
        matching leaves keep their freshly-initialized values AND the
        optimizer state is reset (the reference reinitializes the
        optimizer when transferring). Requires concrete ``state``.
        """
        self.wait()  # never read around an in-flight write
        abstract = jax.tree_util.tree_map(_abstract_leaf, state)
        candidates = (
            [step] if step is not None else sorted(self.all_steps(), reverse=True)
        )
        if not candidates:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        restored = None
        failures = []
        for s in candidates:
            try:
                restored = self._restore_step(s, abstract, strict=strict)
                break
            except Exception as e:
                if step is not None:
                    raise
                failures.append((s, f"{type(e).__name__}: {e}"))
                # corrupt-vs-absent triage: an absent item directory is a
                # routine hole in the walk; anything else means the step
                # EXISTS and is damaged — observable, never silent
                if not isinstance(e, FileNotFoundError):
                    self._note_corrupt_skip(s, e)
                print(
                    f"[checkpoint] step {s} under {self.directory} is not "
                    f"restorable ({type(e).__name__}); trying the previous step"
                )
        if restored is None:
            raise FileNotFoundError(
                f"no restorable checkpoint under {self.directory}: "
                f"all candidates failed: {failures}"
            )
        if ignore_layers:
            patterns = [re.compile(p) for p in ignore_layers]

            def merge(path, fresh, loaded):
                name = "/".join(str(getattr(k, "key", k)) for k in path)
                return fresh if any(p.search(name) for p in patterns) else loaded

            params = jax.tree_util.tree_map_with_path(
                merge, state.params, restored.params
            )
            return state.replace(params=params, batch_stats=restored.batch_stats)
        return restored

    def _note_corrupt_skip(self, step: int, error: BaseException) -> None:
        reason = getattr(error, "reason", type(error).__name__)
        if self.registry is not None:
            self.registry.counter(
                "ckpt_corrupt_skipped_total",
                help="corrupt (not absent) checkpoints skipped by the "
                     "newest-first restore walk",
            ).inc()
        if self.events is not None:
            self.events.emit(
                "ckpt_corrupt_skipped", step=int(step), reason=str(reason),
                error=f"{type(error).__name__}: {error}",
            )

    def close(self):
        try:
            self.wait()
        except BaseException as e:
            # close() runs in ``finally`` blocks: surface, don't mask
            print(f"[checkpoint] in-flight save failed during close: {e}")
        self.manager.close()
