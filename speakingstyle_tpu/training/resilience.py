"""Fault-tolerance primitives for the training loops.

Four pillars, wired through ``run_training`` and ``train_vocoder``
(config: ``train.resilience.*``, see configs/config.py:ResilienceConfig):

  1. preemption-safe checkpointing — async saves + a SIGTERM/SIGINT
     flush (``GracefulShutdown``), retention in training/checkpoint.py
  2. NaN/divergence sentinel — ``all_finite`` folded into the jitted
     step, ``RollbackGuard`` bounding consecutive rollbacks host-side
  3. data-pipeline retry and quarantine — ``retry_io`` +
     ``Quarantine``, used by data/dataset.py and data/prefetch.py
  4. deterministic fault injection — training/faults.py exercises all
     of the above end-to-end in tier-1 CPU tests

Everything here is host-side plain Python except ``all_finite``, which
is traced into the step (a cheap on-device reduction; the host reads it
only at the existing log boundary, so it adds no extra sync points).
"""

import signal
import threading
import time
from typing import Callable, Dict, Optional, Tuple
from speakingstyle_tpu.obs.locks import make_lock


class TrainingDivergedError(RuntimeError):
    """Raised when consecutive NaN rollbacks exceed train.resilience.max_rollbacks."""


class BadSampleBudgetError(RuntimeError):
    """Raised when distinct quarantined samples exceed train.resilience.bad_sample_budget."""


# ---------------------------------------------------------------------------
# retry + quarantine (data pipeline)
# ---------------------------------------------------------------------------


def retry_io(
    fn: Callable,
    retries: int = 3,
    backoff: float = 0.05,
    exceptions: Tuple = (OSError,),
    sleep: Callable[[float], None] = time.sleep,
    describe: str = "",
):
    """Call ``fn()`` with up to ``retries`` retries on ``exceptions``,
    sleeping ``backoff * 2**(attempt-1)`` between attempts (exponential
    backoff). The final failure propagates unchanged."""
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions as e:
            attempt += 1
            if attempt > retries:
                raise
            # telemetry: retries are the leading indicator of a sick
            # filesystem/interconnect; counted in the default registry
            # (obs imported lazily — this module must stay importable
            # before jax/obs in minimal contexts)
            from speakingstyle_tpu.obs import get_registry

            get_registry().counter(
                "io_retries_total",
                help="transient I/O errors retried (loads + transfers)",
            ).inc()
            print(
                f"[resilience] transient {type(e).__name__} "
                f"{f'({describe}) ' if describe else ''}retry "
                f"{attempt}/{retries}: {e}"
            )
            sleep(backoff * (2 ** (attempt - 1)))


class Quarantine:
    """Per-sample quarantine list: samples that fail to load even after
    retries are logged and skipped instead of killing the worker thread;
    the run fails only past ``budget`` distinct bad samples."""

    def __init__(self, budget: int = 16):
        self.budget = budget
        self.bad: Dict[str, str] = {}  # sample id -> error summary
        self._lock = make_lock("Quarantine._lock")

    def add(self, sample_id: str, err: BaseException):
        with self._lock:
            self.bad[sample_id] = f"{type(err).__name__}: {err}"
            n = len(self.bad)
        from speakingstyle_tpu.obs import get_registry

        get_registry().counter(
            "quarantined_samples_total",
            help="distinct samples quarantined after exhausting retries",
        ).inc()
        print(
            f"[resilience] quarantined sample {sample_id!r} "
            f"({n}/{self.budget} budget): {type(err).__name__}: {err}"
        )
        if n > self.budget:
            raise BadSampleBudgetError(
                f"{n} quarantined samples exceed the bad-sample budget "
                f"({self.budget}); first failures: "
                f"{dict(list(self.bad.items())[:5])}"
            ) from err

    def __len__(self) -> int:
        return len(self.bad)

    def __contains__(self, sample_id: str) -> bool:
        return sample_id in self.bad


# ---------------------------------------------------------------------------
# graceful shutdown (preemption)
# ---------------------------------------------------------------------------


class GracefulShutdown:
    """Context manager: SIGTERM/SIGINT set ``.requested`` instead of
    killing the process, so the step loop can flush a final atomic
    checkpoint and exit cleanly (TPU preemption sends SIGTERM).

    Installing a handler is only legal on the main thread; elsewhere
    (e.g. a loop run inside a worker thread) this degrades to a no-op
    with ``.installed == False`` and the default disposition intact."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, signals=SIGNALS):
        self.signals = signals
        self.requested = False
        self.signame: Optional[str] = None
        self.installed = False
        self._prev: Dict[int, object] = {}

    def _handler(self, signum, frame):
        self.requested = True
        self.signame = signal.Signals(signum).name

    def __enter__(self) -> "GracefulShutdown":
        self.requested = False
        self.signame = None
        for sig in self.signals:
            try:
                self._prev[sig] = signal.signal(sig, self._handler)
                self.installed = True
            except ValueError:
                # not the main thread: signals keep their default
                # disposition; the loop still works, just not preemptible
                print(
                    "[resilience] not on the main thread: "
                    f"{signal.Signals(sig).name} flush handler not installed"
                )
        return self

    def __exit__(self, *exc):
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()
        self.installed = False
        return False


# ---------------------------------------------------------------------------
# NaN sentinel + rollback policy
# ---------------------------------------------------------------------------


def all_finite(*trees):
    """Scalar bool array: every inexact-dtype leaf of every tree is
    finite. Traced into the jitted step, this is a handful of fused
    on-device reductions — the host only reads the single resulting
    scalar at the log boundary, where it already blocks for logging.

    Under a DP mesh the reductions run over ``data``-sharded grads, so
    GSPMD lowers them to cross-device all-reduces; the trainer
    additionally pins the flag fully replicated
    (``with_sharding_constraint``) so the dp-axis reduction is an explicit
    part of the compiled step — one shard's NaN flips the flag on EVERY
    device, and every host reads the same rollback verdict (drilled by
    the shard-local ``nan_grads`` fault, tests/test_multichip.py)."""
    import jax
    import jax.numpy as jnp

    ok = jnp.bool_(True)
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            if jnp.issubdtype(jnp.result_type(leaf), jnp.inexact):
                ok = ok & jnp.all(jnp.isfinite(leaf))
    return ok


class RollbackGuard:
    """Counts CONSECUTIVE rollbacks; a finite check window resets the
    count, so a one-off bad batch costs one rollback while a genuinely
    diverged run aborts after ``max_rollbacks``."""

    def __init__(self, max_rollbacks: int = 3):
        self.max_rollbacks = max_rollbacks
        self.count = 0

    def ok(self):
        self.count = 0

    def trip(self, step: int) -> int:
        """Record a rollback at ``step``; returns the consecutive count
        or raises TrainingDivergedError past the budget."""
        self.count += 1
        if self.count > self.max_rollbacks:
            raise TrainingDivergedError(
                f"non-finite losses/grads persisted through "
                f"{self.max_rollbacks} consecutive rollbacks "
                f"(last trip at step {step}): the run has diverged"
            )
        return self.count
