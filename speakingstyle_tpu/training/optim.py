"""Optimizer: Adam + ramp-then-step-decay schedule, grad clip, accumulation.

Reference semantics (reference: model/optimizer.py:35-44): during the first
``loss.anneal_steps`` steps LR ramps linearly init_lr -> anneal_lr; after
that, LR = anneal_lr scaled by anneal_rate for every optimizer.anneal_steps
milestone passed. The lr for step s uses ``current_step = s + 1``
(step_and_update_lr increments before reading).

Built as an optax chain: clip_by_global_norm(1.0) -> adam(b1=0.9, b2=0.98,
eps=1e-9) -> schedule; grad accumulation via optax.MultiSteps.
"""

import jax.numpy as jnp
import optax

from speakingstyle_tpu.configs.config import TrainConfig


def make_lr_schedule(train_cfg: TrainConfig):
    opt = train_cfg.optimizer
    ramp_steps = train_cfg.loss.anneal_steps
    init_lr = opt.init_lr
    anneal_lr = opt.anneal_lr
    milestones = jnp.asarray(opt.anneal_steps, jnp.float32)
    anneal_rate = opt.anneal_rate

    def schedule(step):
        current = jnp.asarray(step, jnp.float32) + 1.0
        ramp = init_lr + (current / ramp_steps) * (anneal_lr - init_lr)
        n_passed = jnp.sum(current > milestones)
        decayed = anneal_lr * jnp.power(anneal_rate, n_passed)
        return jnp.where(current > ramp_steps, decayed, ramp)

    return schedule


def make_optimizer(train_cfg: TrainConfig) -> optax.GradientTransformation:
    opt = train_cfg.optimizer
    tx = optax.chain(
        optax.clip_by_global_norm(opt.grad_clip_thresh),
        # torch.optim.Adam folds weight decay into the gradient BEFORE the
        # moment estimates (L2, not AdamW) — order matters for parity.
        optax.add_decayed_weights(opt.weight_decay) if opt.weight_decay else optax.identity(),
        optax.scale_by_adam(b1=opt.betas[0], b2=opt.betas[1], eps=opt.eps),
        optax.scale_by_learning_rate(make_lr_schedule(train_cfg)),
    )
    if opt.grad_acc_step > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=opt.grad_acc_step)
    return tx
