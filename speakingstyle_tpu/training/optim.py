"""Optimizer: Adam + ramp-then-step-decay schedule, grad clip, accumulation.

Reference semantics (reference: model/optimizer.py:35-44): during the first
``loss.anneal_steps`` steps LR ramps linearly init_lr -> anneal_lr; after
that, LR = anneal_lr scaled by anneal_rate for every optimizer.anneal_steps
milestone passed. The lr for step s uses ``current_step = s + 1``
(step_and_update_lr increments before reading).

Built as an optax chain: clip_by_global_norm(1.0) -> adam(b1=0.9, b2=0.98,
eps=1e-9) -> schedule; grad accumulation via optax.MultiSteps.

``train.fused_optimizer`` swaps in ``make_fused_optimizer``: the same math
as one fused pass over a single raveled gradient vector. The hypothesis
was that the optax chain's ~200 leaves x 4 stages of per-leaf fusions
(5.4 ms/step at 35M params on v5e, ~1.5 ms of it intrinsic HBM traffic)
could be collapsed — but the measured end-to-end result is NEGATIVE: the
ravel/unravel copies cost more than the chain overhead they remove
(422.6k vs 442.8k frames/s, PERF.md). Kept as an honest A/B knob, off by
default. Update parity with the chain is pinned by
tests/test_training.py::test_fused_optimizer_matches_chain.
"""

from typing import NamedTuple

import chex
import jax
import jax.flatten_util  # registers jax.flatten_util.ravel_pytree
import jax.numpy as jnp
import optax

from speakingstyle_tpu.configs.config import TrainConfig


def make_lr_schedule(train_cfg: TrainConfig):
    opt = train_cfg.optimizer
    ramp_steps = train_cfg.loss.anneal_steps
    init_lr = opt.init_lr
    anneal_lr = opt.anneal_lr
    milestones = jnp.asarray(opt.anneal_steps, jnp.float32)
    anneal_rate = opt.anneal_rate

    def schedule(step):
        current = jnp.asarray(step, jnp.float32) + 1.0
        ramp = init_lr + (current / ramp_steps) * (anneal_lr - init_lr)
        n_passed = jnp.sum(current > milestones)
        decayed = anneal_lr * jnp.power(anneal_rate, n_passed)
        return jnp.where(current > ramp_steps, decayed, ramp)

    return schedule


class FlatAdamState(NamedTuple):
    """Adam moments stored as single flat vectors (not per-leaf trees)."""

    count: chex.Array  # int32 scalar
    mu: chex.Array     # [n_params] f32
    nu: chex.Array     # [n_params] f32


def make_fused_optimizer(train_cfg: TrainConfig) -> optax.GradientTransformation:
    """clip_by_global_norm -> (L2 weight decay) -> Adam -> -lr, computed in
    one fused pass over the raveled gradient vector. Identical update math
    to the optax chain in make_optimizer (same stage order and the same
    step-count semantics: bias correction uses count+1, the schedule is
    evaluated at count)."""
    opt = train_cfg.optimizer
    schedule = make_lr_schedule(train_cfg)
    b1, b2 = opt.betas
    eps, clip, wd = opt.eps, opt.grad_clip_thresh, opt.weight_decay

    def init(params):
        flat, _ = jax.flatten_util.ravel_pytree(params)
        flat = flat.astype(jnp.float32)
        return FlatAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jnp.zeros_like(flat),
            nu=jnp.zeros_like(flat),
        )

    def update(grads, state, params=None):
        g, unravel = jax.flatten_util.ravel_pytree(grads)
        g = g.astype(jnp.float32)
        # optax.clip_by_global_norm: scale only when the norm exceeds clip
        gnorm = jnp.linalg.norm(g)
        g = g * jnp.where(gnorm < clip, 1.0, clip / gnorm)
        if wd:
            if params is None:
                raise ValueError("weight_decay needs params")
            p, _ = jax.flatten_util.ravel_pytree(params)
            g = g + wd * p.astype(jnp.float32)
        count_inc = state.count + 1
        mu = b1 * state.mu + (1.0 - b1) * g
        nu = b2 * state.nu + (1.0 - b2) * jnp.square(g)
        mu_hat = mu / (1.0 - b1 ** count_inc.astype(jnp.float32))
        nu_hat = nu / (1.0 - b2 ** count_inc.astype(jnp.float32))
        lr = schedule(state.count)
        upd = -lr * mu_hat / (jnp.sqrt(nu_hat) + eps)
        return unravel(upd), FlatAdamState(count=count_inc, mu=mu, nu=nu)

    tx = optax.GradientTransformation(init, update)
    if opt.grad_acc_step > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=opt.grad_acc_step)
    return tx


class LeafAdamState(NamedTuple):
    """Adam moments as per-leaf trees (layout matches the param tree)."""

    count: chex.Array  # int32 scalar
    mu: chex.Array     # pytree like params
    nu: chex.Array     # pytree like params


class _Result:
    """Opaque (non-pytree) per-leaf carrier for (upd, mu, nu)."""

    __slots__ = ("upd", "mu", "nu")

    def __init__(self, upd, mu, nu):
        self.upd, self.mu, self.nu = upd, mu, nu


def make_leaf_fused_optimizer(train_cfg: TrainConfig) -> optax.GradientTransformation:
    """clip_by_global_norm -> (L2) -> Adam -> -lr with the whole chain
    written as ONE expression per leaf, so XLA emits ~one fused kernel per
    leaf instead of the optax chain's 4 stages x ~200 leaves with
    materialized intermediate update trees.

    This is the middle ground the r4 "flat" variant missed: no
    ravel/unravel copies (the flat impl's downfall, PERF.md), but also no
    per-stage HBM round trips. Update math is identical to the chain —
    pinned by tests/test_training.py::test_fused_optimizer_matches_chain —
    and the state layout (count + mu/nu trees) mirrors scale_by_adam's, so
    only the optax chain *wrapper* structure differs in checkpoints."""
    opt = train_cfg.optimizer
    schedule = make_lr_schedule(train_cfg)
    b1, b2 = opt.betas
    eps, clip, wd = opt.eps, opt.grad_clip_thresh, opt.weight_decay

    def init(params):
        return LeafAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(jnp.zeros_like, params),
            nu=jax.tree_util.tree_map(jnp.zeros_like, params),
        )

    def update(grads, state, params=None):
        if wd and params is None:
            raise ValueError("weight_decay needs params")
        # the one unavoidable extra pass: the global grad norm
        gnorm = jnp.sqrt(
            sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree_util.tree_leaves(grads)
            )
        )
        scale = jnp.where(gnorm < clip, 1.0, clip / gnorm)
        count_inc = state.count + 1
        c1 = 1.0 - b1 ** count_inc.astype(jnp.float32)
        c2 = 1.0 - b2 ** count_inc.astype(jnp.float32)
        lr = schedule(state.count)

        def leaf(g, mu, nu, p):
            g = g * scale
            if wd:
                g = g + wd * p
            mu2 = b1 * mu + (1.0 - b1) * g
            nu2 = b2 * nu + (1.0 - b2) * jnp.square(g)
            upd = -lr * (mu2 / c1) / (jnp.sqrt(nu2 / c2) + eps)
            # _Result is NOT a registered pytree, so tree_map treats it as
            # a leaf — unambiguous even if the param tree itself contains
            # tuple nodes (a plain 3-tuple here would collide with them)
            return _Result(upd, mu2, nu2)

        fused = jax.tree_util.tree_map(
            leaf, grads, state.mu, state.nu,
            params if params is not None else grads,
        )
        pick = lambda name: jax.tree_util.tree_map(
            lambda r: getattr(r, name), fused
        )
        return pick("upd"), LeafAdamState(
            count=count_inc, mu=pick("mu"), nu=pick("nu")
        )

    tx = optax.GradientTransformation(init, update)
    if opt.grad_acc_step > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=opt.grad_acc_step)
    return tx


def make_optimizer(train_cfg: TrainConfig) -> optax.GradientTransformation:
    impl = train_cfg.fused_optimizer
    if impl not in (False, True, "flat", "leaf"):
        raise ValueError(
            f"fused_optimizer must be False|True|'flat'|'leaf', got {impl!r}"
        )
    if impl == "leaf":
        return make_leaf_fused_optimizer(train_cfg)
    if impl:  # True or "flat"
        return make_fused_optimizer(train_cfg)
    opt = train_cfg.optimizer
    tx = optax.chain(
        optax.clip_by_global_norm(opt.grad_clip_thresh),
        # torch.optim.Adam folds weight decay into the gradient BEFORE the
        # moment estimates (L2, not AdamW) — order matters for parity.
        optax.add_decayed_weights(opt.weight_decay) if opt.weight_decay else optax.identity(),
        optax.scale_by_adam(b1=opt.betas[0], b2=opt.betas[1], eps=opt.eps),
        optax.scale_by_learning_rate(make_lr_schedule(train_cfg)),
    )
    if opt.grad_acc_step > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=opt.grad_acc_step)
    return tx
