"""Training orchestration: sharded jit steps + the step loop.

The reference's loop (reference: train.py:79-173) maps here as:
  nn.DataParallel scatter/gather  ->  batch sharded over the mesh's data
                                      axis; XLA inserts the gradient psum
  backward + clip + custom LR     ->  optax chain (training/optim.py)
  periodic log/val/save           ->  callbacks driven by the step counter

The train step is compiled once per batch-bucket shape (data/dataset.py
bucket grid); state is replicated, donated, and updated in place.
"""

import os
from typing import Dict, Iterator, Optional

import jax
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from speakingstyle_tpu import obs
from speakingstyle_tpu.analysis import contracts
from speakingstyle_tpu.configs.config import Config
from speakingstyle_tpu.models.loss import fastspeech2_loss
from speakingstyle_tpu.parallel.registry import ProgramRegistry, jit_program
from speakingstyle_tpu.training import faults, resilience
from speakingstyle_tpu.training.state import TrainState

# keys in the step's losses dict that are sentinel/bookkeeping, not losses
_INTERNAL_LOSS_KEYS = ("_finite",)


def public_losses(losses: Dict) -> Dict:
    return {k: v for k, v in losses.items() if k not in _INTERNAL_LOSS_KEYS}


def build_train_step_card(train_step, state, arrays, rng,
                          program_registry: Optional[ProgramRegistry] = None):
    """ProgramCard (obs/cost.py) for the jitted train step at the given
    batch geometry: XLA's own FLOP/bytes/memory accounting of the step
    program. The AOT compile goes through the ProgramRegistry (the
    tree's one compile entry point) and does not share jax's in-memory
    jit cache, so this costs ONE extra compile of the step program — a
    persistent-cache hit when ``train.obs.compilation_cache_dir`` is set
    (the registry wires the cache itself).
    Returns None (with a warning) rather than ever failing the run."""
    registry = (
        program_registry if program_registry is not None
        else ProgramRegistry(counter_name="train_compiles_total",
                             prefix="train")
    )
    try:
        compiled = registry.compile(
            train_step, (state, arrays, rng), name="train_step"
        )
    except Exception as e:
        print(
            "warning: train-step program card unavailable "
            f"({type(e).__name__}: {e})"
        )
        return None
    return obs.ProgramCard.from_compiled(compiled, name="train_step")


def _model_kwargs(arrays: Dict, teacher_forced: bool) -> Dict:
    kw = dict(
        speakers=arrays["speakers"],
        texts=arrays["texts"],
        src_lens=arrays["src_lens"],
        mels=arrays["mels"],
        mel_lens=arrays["mel_lens"],
        max_mel_len=arrays["mels"].shape[1],
    )
    if teacher_forced:
        kw.update(
            p_targets=arrays["pitches"],
            e_targets=arrays["energies"],
            d_targets=arrays["durations"],
        )
    return kw


def make_train_step(model, tx, cfg: Config, mesh=None, state_shardings=None):
    """Returns jitted fn(state, arrays, rng) -> (state, losses).

    ``state_shardings`` (a TrainState pytree of NamedShardings, see
    parallel/partition.train_state_shardings) engages tensor parallelism
    over the mesh's ``model`` axis; omitted, the state is replicated
    (pure DP — the reference's only strategy, SURVEY.md §2.4).

    With ``train.resilience.nan_sentinel`` the step also returns
    ``losses["_finite"]`` — an on-device all-finite reduction over losses
    and grads, read host-side only at the log boundary (run_training's
    rollback trigger; stripped from logs by ``public_losses``).
    """
    lambda_f = cfg.train.loss.lambda_f
    p_level = cfg.preprocess.preprocessing.pitch.feature
    e_level = cfg.preprocess.preprocessing.energy.feature
    nan_sentinel = cfg.train.resilience.nan_sentinel

    def step_fn(state: TrainState, arrays: Dict, rng) -> tuple:
        # trace-time contracts: shape/dtype metadata only, so these run
        # (and fail) during tracing and add nothing to the compiled step
        B = arrays["texts"].shape[0]
        contracts.assert_rank(arrays["texts"], 2, "train_step.texts")
        contracts.assert_rank(arrays["mels"], 3, "train_step.mels")
        contracts.assert_shape(arrays["src_lens"], (B,), "train_step.src_lens")
        contracts.assert_shape(arrays["mel_lens"], (B,), "train_step.mel_lens")
        contracts.assert_shape(
            arrays["durations"], arrays["texts"].shape, "train_step.durations"
        )
        rng = jax.random.fold_in(rng, state.step)

        def loss_fn(params):
            out, updates = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                **_model_kwargs(arrays, teacher_forced=True),
                deterministic=False,
                rngs={"dropout": rng},
                mutable=["batch_stats"],
            )
            losses = fastspeech2_loss(
                out,
                arrays["mels"],
                arrays["pitches"],
                arrays["energies"],
                arrays["durations"],
                params,
                lambda_f=lambda_f,
                pitch_feature_level=p_level,
                energy_feature_level=e_level,
            )
            return losses["total_loss"], (losses, updates["batch_stats"])

        (_, (losses, batch_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        if nan_sentinel:  # trace-time flag: compiled in or out, never branched
            losses = dict(losses)
            flag = resilience.all_finite(losses, grads)
            if mesh is not None:
                # explicit dp-axis reduction: pin the flag fully replicated
                # so GSPMD compiles the all-reduce over the data axis into
                # the step itself — every device holds the same verdict and
                # every host reads the same rollback decision (one shard's
                # NaN trips all of them; drilled by the nan_grads DP fault)
                flag = jax.lax.with_sharding_constraint(
                    flag, NamedSharding(mesh, P())
                )
            losses["_finite"] = flag
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
        )
        return new_state, losses

    if mesh is None:
        return jit_program(step_fn, donate_argnums=(0,))
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    if state_shardings is None:
        state_shardings = repl  # pure DP: state fully replicated
    return jit_program(
        step_fn,
        in_shardings=(state_shardings, data, repl),
        out_shardings=(state_shardings, repl),
        donate_argnums=(0,),
    )


def make_eval_step(model, cfg: Config, mesh=None, state_shardings=None):
    """Teacher-forced loss evaluation (reference: evaluate.py:39-58)."""
    lambda_f = cfg.train.loss.lambda_f
    p_level = cfg.preprocess.preprocessing.pitch.feature
    e_level = cfg.preprocess.preprocessing.energy.feature

    def eval_fn(state: TrainState, arrays: Dict) -> Dict:
        out = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            **_model_kwargs(arrays, teacher_forced=True),
            deterministic=True,
        )
        return fastspeech2_loss(
            out,
            arrays["mels"],
            arrays["pitches"],
            arrays["energies"],
            arrays["durations"],
            state.params,
            lambda_f=lambda_f,
            pitch_feature_level=p_level,
            energy_feature_level=e_level,
        )

    if mesh is None:
        return jit_program(eval_fn)
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    if state_shardings is None:
        state_shardings = repl
    return jit_program(
        eval_fn, in_shardings=(state_shardings, data), out_shardings=repl
    )


def make_predict_step(model, cfg: Config, mesh=None):
    """Free-running synthesis step (style mel in, no p/e/d targets)."""

    def predict_fn(
        state: TrainState,
        arrays: Dict,
        max_mel_len: int,
        p_control: float = 1.0,
        e_control: float = 1.0,
        d_control: float = 1.0,
    ):
        return model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            speakers=arrays["speakers"],
            texts=arrays["texts"],
            src_lens=arrays["src_lens"],
            mels=arrays["mels"],
            mel_lens=arrays["mel_lens"],
            max_mel_len=max_mel_len,
            p_control=p_control,
            e_control=e_control,
            d_control=d_control,
            deterministic=True,
        )

    return jit_program(predict_fn, static_argnums=(2,))


def evaluate(eval_step, state, batches: Iterator) -> Dict[str, float]:
    """Batch-size-weighted mean of every loss over a val pass
    (reference: evaluate.py:39-58)."""
    sums: Dict[str, float] = {}
    count = 0
    for batch, arrays in batches:
        losses = eval_step(state, arrays)
        n = batch.n_real
        count += n
        for k, v in losses.items():
            sums[k] = sums.get(k, 0.0) + float(v) * n
    if count == 0:
        return {}
    return {k: v / count for k, v in sums.items()}


# run_training's mesh default: "resolve from cfg.train.parallel". An
# explicit mesh=None pins the single-chip path even when the config block
# names a mesh (the CLI's flag-override contract).
_MESH_FROM_CONFIG = object()


def run_training(
    cfg: Config,
    mesh=_MESH_FROM_CONFIG,
    restore_step: Optional[int] = None,
    max_steps: Optional[int] = None,
    synth_callback=None,
    log: bool = True,
    vocoder=None,
    profile_dir: Optional[str] = None,
    profile_steps: tuple = (10, 20),
    registry: Optional[obs.MetricsRegistry] = None,
):
    """The full training loop (reference: train.py:21-173).

    Returns the final TrainState. `max_steps` overrides total_step (tests);
    `synth_callback(state, batch, arrays, step, model)` runs every
    synth_step — pass "default" for the GT-vs-predicted sample renderer.
    `profile_dir` enables a jax.profiler trace over the step window
    ``profile_steps`` (greenfield vs the reference — SURVEY.md §5).

    Fault tolerance (``cfg.train.resilience``, ARCHITECTURE.md
    "Resilience"): checkpoint saves are async and a final checkpoint is
    always flushed — at loop end and on SIGTERM/SIGINT (preemption);
    non-finite losses/grads at a log boundary roll the run back to the
    last good checkpoint with a diverged data stream, aborting with
    ``TrainingDivergedError`` after ``max_rollbacks`` consecutive trips;
    loader errors are retried then quarantined per sample. Faults from
    ``SPEAKINGSTYLE_FAULTS`` (training/faults.py) are injected to drill
    each of those paths.

    Telemetry (``speakingstyle_tpu/obs``, ARCHITECTURE.md
    "Observability"): the loop records per-step wall time split into
    data-wait (time blocked on the prefetcher) vs step time into
    ``registry`` histograms, wraps the jitted step in
    ``jax.profiler.StepTraceAnnotation`` so on-demand traces label step
    boundaries, and — via TrainLogger — appends structured JSONL events
    (``train_step``/``val``/``checkpoint_save``/``rollback``/
    ``fault_fire``/``preempt_flush``/``quarantine``; schema in
    obs/events.py) to a rotating ``events.jsonl`` under
    ``train.path.log_path`` (``train.obs.*`` knobs). A ``train_start``
    event records the build identity (git SHA, jax versions, backend,
    device count); after the first step compiles, a one-time
    ``program_card`` event records XLA's own cost/memory accounting of
    the step program (obs/cost.py; gated by ``train.obs.program_card``),
    which also feeds the ``train_achieved_flops_per_sec`` histogram and
    the ``device_memory_watermark_bytes`` gauge at log boundaries.
    """
    import time
    import jax.numpy as jnp

    from speakingstyle_tpu.data import (
        BucketedBatcher,
        DevicePrefetcher,
        SpeechDataset,
    )
    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.training.checkpoint import CheckpointManager
    from speakingstyle_tpu.training.optim import make_lr_schedule, make_optimizer

    from speakingstyle_tpu.parallel.mesh import local_batch_size, resolve_mesh

    steps = cfg.train.step
    res = cfg.train.resilience
    total_step = max_steps if max_steps is not None else steps.total_step
    plan = faults.FaultPlan.from_env()

    # train.parallel.* is the multichip contract: mesh=[1,1] resolves to
    # None and this function behaves exactly as the single-chip path; an
    # explicitly passed mesh — including an explicit None — wins (tests,
    # cli flag overrides)
    if mesh is _MESH_FROM_CONFIG:
        mesh = resolve_mesh(cfg.train.parallel)
    if mesh is not None:
        # startup divisibility gate: fails with the two nearest valid
        # batch sizes named, before any compile or transfer
        local_batch_size(cfg.train.optimizer.batch_size, mesh)

    registry = registry if registry is not None else obs.get_registry()
    # one compile entry point for the run: wires the persistent compile
    # cache (train.obs.compilation_cache_dir) BEFORE the first jit-on-call
    # compile and counts/publishes per-program cards for anything compiled
    # through it (the train-step ProgramCard below)
    program_registry = ProgramRegistry(
        registry,
        cache_dir=cfg.train.obs.compilation_cache_dir or None,
        counter_name="train_compiles_total",
        prefix="train",
    )
    step_hist = registry.histogram(
        "train_step_seconds",
        help="per-step wall time excluding data wait (host dispatch; "
             "device-honest at log boundaries where the loop syncs)",
    )
    wait_hist = registry.histogram(
        "train_data_wait_seconds",
        help="per-step time blocked on the prefetcher",
    )
    steps_ctr = registry.counter("train_steps_total", help="optimizer steps run")
    rollback_ctr = registry.counter(
        "train_rollbacks_total", help="NaN-sentinel rollbacks taken"
    )
    save_ctr = registry.counter(
        "checkpoint_saves_total", help="checkpoints enqueued/flushed"
    )
    fault_ctr = registry.counter(
        "faults_fired_total", help="injected faults fired (drills)"
    )
    flops_hist = registry.histogram(
        "train_achieved_flops_per_sec",
        edges=obs.FLOPS_PER_SEC_BUCKETS,
        help="ProgramCard train-step FLOPs / per-step wall time "
             "(host-dispatch-based; device-honest at log boundaries)",
    )
    mem_gauge = registry.gauge(
        "device_memory_watermark_bytes",
        help="device memory watermark: backend memory_stats peak where "
             "available, else ProgramCard argument+temp bytes",
    )

    if cfg.train.fast_prng:
        try:
            jax.config.update("jax_default_prng_impl", "rbg")
        except Exception as e:  # pragma: no cover - only future jax renames
            print(f"warning: fast_prng unavailable ({e}); using default PRNG")

    model = build_model(cfg)
    rng = jax.random.PRNGKey(cfg.train.seed)
    variables = init_variables(model, cfg, rng)
    tx = make_optimizer(cfg.train)
    state = TrainState.create(variables, tx)
    schedule = make_lr_schedule(cfg.train)

    ckpt = CheckpointManager(
        cfg.train.path.ckpt_path,
        max_to_keep=res.max_to_keep or None,
        async_save=res.async_checkpointing,
        keep_best=res.keep_best,
        fault_plan=plan,
        registry=registry,
    )

    state_shardings = None
    tp_rules = None
    if mesh is not None:
        from speakingstyle_tpu.parallel.partition import (
            parse_rule_overrides,
            shard_train_state,
            train_state_shardings,
        )

        if cfg.train.parallel.partition_rules:
            tp_rules = parse_rule_overrides(cfg.train.parallel.partition_rules)
        if mesh.shape.get("model", 1) > 1:
            state_shardings = train_state_shardings(state, mesh, tp_rules)
            state = shard_train_state(state, mesh, tp_rules)
        else:
            state = jax.device_put(state, NamedSharding(mesh, P()))

    if restore_step is not None:
        # cross-mesh-shape resume: the restore runs AFTER sharding, so the
        # state passed in already carries THIS run's (target) mesh layout.
        # CheckpointManager.restore builds its abstract template from those
        # shardings and Orbax materializes the checkpoint directly into the
        # target layout — whatever mesh shape wrote it (save on 8x1,
        # restore onto 4x2 or 1x1).
        state = ckpt.restore(
            state,
            step=restore_step if restore_step > 0 else None,
            ignore_layers=cfg.train.ignore_layers,
        )

    train_step = make_train_step(
        model, tx, cfg, mesh=mesh, state_shardings=state_shardings
    )
    eval_step = make_eval_step(
        model, cfg, mesh=mesh, state_shardings=state_shardings
    )

    max_src = max_mel = cfg.model.max_seq_len
    pad_mult = mesh.shape["data"] if mesh is not None else 1
    train_ds = SpeechDataset(
        "train.txt", cfg, sort=True, drop_last=True,
        retries=res.loader_retries, backoff=res.loader_backoff,
        fault_plan=plan,
    )
    quarantine = resilience.Quarantine(budget=res.bad_sample_budget)

    step = int(state.step)
    start_step = step  # profile window is relative to where this run begins

    def make_stream(retry: int) -> DevicePrefetcher:
        # the data seed folds in the resume point AND the rollback retry
        # counter, so a resumed run doesn't replay the original stream
        # from its beginning and a rolled-back run diverges past the
        # batch window that tripped the sentinel
        batcher = BucketedBatcher(
            train_ds,
            max_src=max_src,
            max_mel=max_mel,
            batch_pad_multiple=pad_mult,
            seed=cfg.train.seed + start_step + 7919 * retry,
            quarantine=quarantine,
        )
        return DevicePrefetcher(
            iter(batcher), mesh=mesh, transfer_retries=res.loader_retries,
            transfer_backoff=res.loader_backoff, registry=registry,
        )

    def fresh_state() -> TrainState:
        # deterministic re-init (same seed): the rollback target when the
        # sentinel trips before any checkpoint exists
        s = TrainState.create(
            init_variables(model, cfg, jax.random.PRNGKey(cfg.train.seed)), tx
        )
        if mesh is not None:
            if state_shardings is not None:
                from speakingstyle_tpu.parallel.partition import shard_train_state

                s = shard_train_state(s, mesh, tp_rules)
            else:
                s = jax.device_put(s, NamedSharding(mesh, P()))
        return s

    prefetch = make_stream(0)
    val_ds = SpeechDataset("val.txt", cfg, sort=False, drop_last=False)
    val_batcher = BucketedBatcher(
        val_ds,
        max_src=max_src,
        max_mel=max_mel,
        batch_pad_multiple=pad_mult,
        seed=0,
    )

    logger = None
    if log:
        events = (
            obs.JsonlEventLog(
                cfg.train.path.log_path,
                max_bytes=cfg.train.obs.events_max_bytes,
                keep=cfg.train.obs.events_keep,
            )
            if cfg.train.obs.events else None
        )
        logger = TrainLogger(
            cfg.train.path.log_path, registry=registry, events=events
        )
    # per-chip observability: gauge labels name each mesh device; on the
    # single-chip path the one label is the default device
    mesh_devices = (
        list(mesh.devices.flat) if mesh is not None else jax.devices()[:1]
    )
    n_mesh_devices = len(mesh_devices)
    device_labels = [f"{d.platform}:{d.id}" for d in mesh_devices]
    if logger:
        # one identity record per run: build + runtime stack + mesh shape,
        # so a log directory is attributable without the shell that
        # launched it
        logger.event(
            "train_start", step=step, total_step=total_step,
            mesh_shape=(dict(mesh.shape) if mesh is not None
                        else {"data": 1, "model": 1}),
            mesh_devices=n_mesh_devices,
            checkpoint_step=ckpt.last_restored_step,
            weights_digest=ckpt.last_weights_digest,
            **obs.build_info(),
        )
    if synth_callback == "default":
        synth_callback = default_synth_callback(cfg, logger, vocoder=vocoder)
    step_rng = jax.random.PRNGKey(cfg.train.seed + 1)
    # the train-step ProgramCard is built once, after the first step has
    # compiled (train.obs.program_card); card_pending makes it one
    # attempt, success or not
    program_card, card_pending = None, cfg.train.obs.program_card

    # template for rollback restores: stays valid after donation consumes
    # the live buffers (see TrainState.abstract)
    abstract_template = state.abstract()
    guard = resilience.RollbackGuard(res.max_rollbacks)
    last_val: Optional[float] = None
    last_saved: Optional[int] = None
    window_t0, window_step0, window_frames = time.perf_counter(), step, 0
    window_wait = window_compute = 0.0
    trace_active = False
    shutdown = resilience.GracefulShutdown()
    try:
        with shutdown:
            while step < total_step and not shutdown.requested:
                t_iter = time.perf_counter()
                try:
                    batch, arrays = next(prefetch)
                except StopIteration:
                    break
                # the data-wait vs device-time split: time blocked on the
                # prefetcher here, the rest of the iteration below
                data_wait = time.perf_counter() - t_iter
                wait_hist.observe(data_wait)
                window_wait += data_wait
                if plan.fire("nan_grads", step + 1):
                    # under a DP mesh the poison is shard-local (one
                    # device's rows only): the harsher drill — the
                    # sentinel's dp-axis reduction must trip everywhere
                    arrays = faults.poison_batch(arrays, mesh=mesh)
                    fault_ctr.inc()
                    if logger:
                        logger.event("fault_fire", kind="nan_grads",
                                     step=step + 1)
                if (
                    profile_dir is not None
                    and not trace_active
                    and profile_steps[0] <= step - start_step < profile_steps[1]
                ):
                    jax.profiler.start_trace(profile_dir)
                    trace_active = True
                # step_fn folds state.step into the key, so passing the same
                # step_rng every iteration yields a fresh per-step stream
                with jax.profiler.StepTraceAnnotation("train", step_num=step):
                    state, losses = train_step(state, arrays, step_rng)  # jaxlint: disable=JL006
                step += 1
                steps_ctr.inc()
                step_time = time.perf_counter() - t_iter - data_wait
                step_hist.observe(step_time)
                window_compute += step_time
                if card_pending:
                    card_pending = False
                    program_card = build_train_step_card(
                        train_step, state, arrays, step_rng,
                        program_registry=program_registry,
                    )
                    if program_card is not None and logger:
                        logger.event("program_card", **program_card.as_dict())
                if program_card is not None and program_card.flops \
                        and step_time > 0:
                    flops_hist.observe(program_card.flops / step_time)
                    # per-device MFU gauges: SPMD splits the step's FLOPs
                    # evenly over the mesh, so each chip's achieved rate is
                    # the program total divided by the device count
                    per_dev = program_card.flops / n_mesh_devices / step_time
                    for dev in device_labels:
                        registry.gauge(
                            "train_achieved_flops_per_sec",
                            labels={"device": dev},
                            help="per-device achieved FLOP/s share of the "
                                 "train step program",
                        ).set(per_dev)
                window_frames += int(batch.mel_lens.sum())  # host-side, no sync
                if trace_active and step - start_step >= profile_steps[1]:
                    jax.block_until_ready(losses["total_loss"])
                    jax.profiler.stop_trace()
                    trace_active = False
                if plan.fire("sigterm", step):
                    fault_ctr.inc()
                    if logger:
                        logger.event("fault_fire", kind="sigterm", step=step)
                    faults.deliver_sigterm()

                if step % steps.log_step == 0:
                    # host boundary: the loop blocks here for logging anyway,
                    # so the sentinel read adds no extra sync point. The
                    # drain time is charged to the window's compute bucket —
                    # it IS device time the async dispatches above deferred.
                    t_sync = time.perf_counter()
                    jax.block_until_ready(losses["total_loss"])
                    window_compute += time.perf_counter() - t_sync
                    if "_finite" in losses and not bool(losses["_finite"]):
                        n = guard.trip(step)  # raises past max_rollbacks
                        ckpt.wait()
                        good = ckpt.latest_step()
                        rollback_ctr.inc()
                        msg = (
                            f"[resilience] non-finite losses/grads at step "
                            f"{step}; rollback {n}/{res.max_rollbacks} to "
                            + (f"checkpoint step {good}" if good is not None
                               else "fresh init (no checkpoint yet)")
                        )
                        print(msg)
                        if logger:
                            logger.note(msg)
                            logger.event(
                                "rollback", step=step, rollback_n=n,
                                restore_step=good,
                            )
                        prefetch.stop()
                        if good is not None:
                            state = ckpt.restore(abstract_template, step=good)
                        else:
                            state = fresh_state()
                        step = int(state.step)  # jaxlint: disable=JL004
                        prefetch = make_stream(guard.count)
                        window_t0, window_step0, window_frames = (
                            time.perf_counter(), step, 0,
                        )
                        window_wait = window_compute = 0.0
                        continue
                    guard.ok()
                    watermark = obs.device_memory_watermark(program_card)
                    if watermark is not None:
                        mem_gauge.set(watermark)
                    for dev, wm in obs.device_memory_watermarks(
                        program_card, devices=mesh_devices
                    ).items():
                        registry.gauge(
                            "device_memory_watermark_bytes",
                            labels={"device": dev},
                            help="per-device memory watermark (backend "
                                 "memory_stats peak, else ProgramCard "
                                 "argument+temp bytes)",
                        ).set(wm)
                    if logger:
                        contracts.assert_tree_finite(
                            public_losses(losses), "train_step.losses"
                        )
                        lr = float(schedule(jnp.asarray(step - 1)))
                        n_window = step - window_step0
                        dt = time.perf_counter() - window_t0
                        timing = None
                        if n_window > 0:
                            timing = {
                                "step_time_s": window_compute / n_window,
                                "data_wait_s": window_wait / n_window,
                            }
                            if dt > 0:
                                timing["steps_per_sec"] = n_window / dt
                                timing["mel_frames_per_sec"] = window_frames / dt
                        logger.log(
                            step,
                            {k: float(v) for k, v in public_losses(losses).items()},
                            lr=lr,
                            timing=timing,
                        )
                        if timing and "steps_per_sec" in timing:
                            logger.log_throughput(
                                step, timing["steps_per_sec"],
                                timing["mel_frames_per_sec"],
                            )
                        window_t0, window_step0, window_frames = (
                            time.perf_counter(), step, 0,
                        )
                        window_wait = window_compute = 0.0
                if synth_callback is not None and step % steps.synth_step == 0:
                    synth_callback(state, batch, arrays, step, model)
                if step % steps.val_step == 0:
                    with DevicePrefetcher(
                        val_batcher.epoch(shuffle=False), mesh=mesh,
                        registry=registry,
                    ) as val_prefetch:
                        val_losses = evaluate(eval_step, state, val_prefetch)
                    # evaluate() already returns host floats
                    last_val = val_losses.get("total_loss", last_val)
                    if logger:
                        logger.log(step, val_losses, prefix="val")
                if step % steps.save_step == 0:
                    ckpt.save(step, state, val_loss=last_val)
                    save_ctr.inc()
                    if logger:
                        logger.event("checkpoint_save", step=step)
                    last_saved = step

            # always flush a final checkpoint: covers total_step not
            # divisible by save_step AND the SIGTERM/SIGINT preemption path
            if step > start_step and last_saved != step:
                ckpt.save(step, state, val_loss=last_val, block=True)
                save_ctr.inc()
                if logger:
                    logger.event("checkpoint_save", step=step, final=True)
                last_saved = step
            if shutdown.requested:
                msg = (
                    f"[resilience] {shutdown.signame}: checkpoint flushed at "
                    f"step {step}; exiting"
                )
                print(msg)
                if logger:
                    logger.note(msg)
                    logger.event(
                        "preempt_flush", signal=shutdown.signame, step=step
                    )
    finally:
        if trace_active:
            jax.profiler.stop_trace()  # run ended inside the profile window
        prefetch.stop()
        if quarantine.bad and logger:
            logger.note(
                f"[resilience] {len(quarantine.bad)} quarantined sample(s): "
                f"{sorted(quarantine.bad)}"
            )
            logger.event("quarantine", samples=sorted(quarantine.bad))
        if logger:
            logger.close()
        ckpt.close()
    return state


class TrainLogger:
    """TensorBoard scalars/figures/audio + append-only log.txt (reference:
    train.py:53-61, utils/tools.py:82-107). tensorboardX is optional; the
    text log always works.

    With ``registry``/``events`` attached (obs/), every ``log()`` call
    also updates the metric gauges and appends one structured JSONL
    record (``train_step``/``val`` — schema in obs/events.py), so the
    human-readable log and the machine-readable telemetry cannot drift:
    they are written by the same call from the same values.
    """

    def __init__(self, log_dir: str, use_tensorboard: bool = True,
                 registry: Optional[obs.MetricsRegistry] = None,
                 events: Optional[obs.JsonlEventLog] = None):
        os.makedirs(log_dir, exist_ok=True)
        self.txt = open(os.path.join(log_dir, "log.txt"), "a")
        self.registry = registry
        self.events = events
        self.tb = None
        if use_tensorboard:
            try:
                from tensorboardX import SummaryWriter

                self.tb = SummaryWriter(log_dir)
            except ImportError:
                pass

    def log(self, step: int, losses: Dict[str, float],
            lr: Optional[float] = None, prefix: str = "train",
            timing: Optional[Dict[str, float]] = None):
        msg = f"[{prefix}] Step {step}, " + ", ".join(
            f"{k}: {float(v):.4f}" for k, v in losses.items()
        )
        if lr is not None:
            msg += f", lr: {lr:.6f}"
        self.txt.write(msg + "\n")
        self.txt.flush()
        if self.tb is not None:
            for k, v in losses.items():
                self.tb.add_scalar(f"{prefix}/{k}", float(v), step)
            if lr is not None:
                self.tb.add_scalar(f"{prefix}/lr", lr, step)
        if self.registry is not None:
            self.registry.gauge("train_step", help="last logged step").set(step)
            for k, v in losses.items():
                # values arrive as host floats (the caller converts at the
                # log boundary); Gauge.set coerces, no device sync here
                self.registry.gauge(
                    "train_loss", labels={"loss": k, "split": prefix}
                ).set(v)
        self.event(
            "train_step" if prefix == "train" else prefix,
            step=step,
            **{k: float(v) for k, v in losses.items()},
            **({"lr": lr} if lr is not None else {}),
            **(timing or {}),
        )

    def event(self, name: str, /, **fields):
        """Append one structured record to events.jsonl (no-op without an
        event log attached). ``name`` is positional-only so records may
        themselves carry a ``name`` field (program cards do)."""
        if self.events is not None:
            self.events.emit(name, **fields)

    def note(self, msg: str):
        """Raw line into log.txt (resilience events: rollbacks, SIGTERM
        flushes, quarantine summaries) — greppable next to the step log."""
        self.txt.write(msg + "\n")
        self.txt.flush()
        self.event("note", msg=msg)

    def log_throughput(self, step: int, steps_per_sec: float, frames_per_sec: float):
        self.txt.write(
            f"[perf] Step {step}, steps/s: {steps_per_sec:.2f}, "
            f"mel-frames/s: {frames_per_sec:.0f}\n"
        )
        self.txt.flush()
        if self.tb is not None:
            self.tb.add_scalar("perf/steps_per_sec", steps_per_sec, step)
            self.tb.add_scalar("perf/mel_frames_per_sec", frames_per_sec, step)

    def log_figure(self, step: int, tag: str, fig):
        if self.tb is not None:
            self.tb.add_figure(tag, fig, step)

    def log_audio(self, step: int, tag: str, wav, sampling_rate: int,
                  max_wav_value: float = 32768.0):
        if self.tb is not None:
            import numpy as np

            wav = np.asarray(wav, np.float32) / max_wav_value
            try:
                self.tb.add_audio(tag, wav[None], step, sample_rate=sampling_rate)
            except ModuleNotFoundError:
                pass  # tensorboardX audio needs soundfile; scalars/figures still log

    def close(self):
        self.txt.close()
        if self.events is not None:
            self.events.close()
        if self.tb is not None:
            self.tb.close()


def default_synth_callback(cfg: Config, logger: Optional[TrainLogger], vocoder=None):
    """Periodic validation-sample rendering (reference: train.py:117-144):
    plot GT-vs-predicted mel and log both vocoded wavs to TensorBoard."""

    def callback(state, batch, arrays, step, model):
        from speakingstyle_tpu.synthesis import synth_one_sample

        out = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            **_model_kwargs(arrays, teacher_forced=True),
            deterministic=True,
        )
        fig, wav_recon, wav_pred, basename = synth_one_sample(
            batch, out, vocoder, cfg
        )
        if logger is not None:
            sr = cfg.preprocess.preprocessing.audio.sampling_rate
            mw = cfg.preprocess.preprocessing.audio.max_wav_value
            logger.log_figure(step, f"Training/{basename}", fig)
            logger.log_audio(
                step, f"Training/{basename}_reconstructed", wav_recon, sr, mw
            )
            logger.log_audio(
                step, f"Training/{basename}_synthesized", wav_pred, sr, mw
            )
        import matplotlib.pyplot as plt

        plt.close(fig)

    return callback
