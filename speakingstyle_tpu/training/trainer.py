"""Training orchestration: sharded jit steps + the step loop.

The reference's loop (reference: train.py:79-173) maps here as:
  nn.DataParallel scatter/gather  ->  batch sharded over the mesh's data
                                      axis; XLA inserts the gradient psum
  backward + clip + custom LR     ->  optax chain (training/optim.py)
  periodic log/val/save           ->  callbacks driven by the step counter

The train step is compiled once per batch-bucket shape (data/dataset.py
bucket grid); state is replicated, donated, and updated in place.
"""

import os
from typing import Dict, Iterator, Optional

import jax
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from speakingstyle_tpu.analysis import contracts
from speakingstyle_tpu.configs.config import Config
from speakingstyle_tpu.models.loss import fastspeech2_loss
from speakingstyle_tpu.training.state import TrainState


def _model_kwargs(arrays: Dict, teacher_forced: bool) -> Dict:
    kw = dict(
        speakers=arrays["speakers"],
        texts=arrays["texts"],
        src_lens=arrays["src_lens"],
        mels=arrays["mels"],
        mel_lens=arrays["mel_lens"],
        max_mel_len=arrays["mels"].shape[1],
    )
    if teacher_forced:
        kw.update(
            p_targets=arrays["pitches"],
            e_targets=arrays["energies"],
            d_targets=arrays["durations"],
        )
    return kw


def make_train_step(model, tx, cfg: Config, mesh=None, state_shardings=None):
    """Returns jitted fn(state, arrays, rng) -> (state, losses).

    ``state_shardings`` (a TrainState pytree of NamedShardings, see
    parallel/partition.train_state_shardings) engages tensor parallelism
    over the mesh's ``model`` axis; omitted, the state is replicated
    (pure DP — the reference's only strategy, SURVEY.md §2.4).
    """
    lambda_f = cfg.train.loss.lambda_f
    p_level = cfg.preprocess.preprocessing.pitch.feature
    e_level = cfg.preprocess.preprocessing.energy.feature

    def step_fn(state: TrainState, arrays: Dict, rng) -> tuple:
        # trace-time contracts: shape/dtype metadata only, so these run
        # (and fail) during tracing and add nothing to the compiled step
        B = arrays["texts"].shape[0]
        contracts.assert_rank(arrays["texts"], 2, "train_step.texts")
        contracts.assert_rank(arrays["mels"], 3, "train_step.mels")
        contracts.assert_shape(arrays["src_lens"], (B,), "train_step.src_lens")
        contracts.assert_shape(arrays["mel_lens"], (B,), "train_step.mel_lens")
        contracts.assert_shape(
            arrays["durations"], arrays["texts"].shape, "train_step.durations"
        )
        rng = jax.random.fold_in(rng, state.step)

        def loss_fn(params):
            out, updates = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                **_model_kwargs(arrays, teacher_forced=True),
                deterministic=False,
                rngs={"dropout": rng},
                mutable=["batch_stats"],
            )
            losses = fastspeech2_loss(
                out,
                arrays["mels"],
                arrays["pitches"],
                arrays["energies"],
                arrays["durations"],
                params,
                lambda_f=lambda_f,
                pitch_feature_level=p_level,
                energy_feature_level=e_level,
            )
            return losses["total_loss"], (losses, updates["batch_stats"])

        (_, (losses, batch_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
        )
        return new_state, losses

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=(0,))
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    if state_shardings is None:
        state_shardings = repl  # pure DP: state fully replicated
    return jax.jit(
        step_fn,
        in_shardings=(state_shardings, data, repl),
        out_shardings=(state_shardings, repl),
        donate_argnums=(0,),
    )


def make_eval_step(model, cfg: Config, mesh=None, state_shardings=None):
    """Teacher-forced loss evaluation (reference: evaluate.py:39-58)."""
    lambda_f = cfg.train.loss.lambda_f
    p_level = cfg.preprocess.preprocessing.pitch.feature
    e_level = cfg.preprocess.preprocessing.energy.feature

    def eval_fn(state: TrainState, arrays: Dict) -> Dict:
        out = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            **_model_kwargs(arrays, teacher_forced=True),
            deterministic=True,
        )
        return fastspeech2_loss(
            out,
            arrays["mels"],
            arrays["pitches"],
            arrays["energies"],
            arrays["durations"],
            state.params,
            lambda_f=lambda_f,
            pitch_feature_level=p_level,
            energy_feature_level=e_level,
        )

    if mesh is None:
        return jax.jit(eval_fn)
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    if state_shardings is None:
        state_shardings = repl
    return jax.jit(
        eval_fn, in_shardings=(state_shardings, data), out_shardings=repl
    )


def make_predict_step(model, cfg: Config, mesh=None):
    """Free-running synthesis step (style mel in, no p/e/d targets)."""

    def predict_fn(
        state: TrainState,
        arrays: Dict,
        max_mel_len: int,
        p_control: float = 1.0,
        e_control: float = 1.0,
        d_control: float = 1.0,
    ):
        return model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            speakers=arrays["speakers"],
            texts=arrays["texts"],
            src_lens=arrays["src_lens"],
            mels=arrays["mels"],
            mel_lens=arrays["mel_lens"],
            max_mel_len=max_mel_len,
            p_control=p_control,
            e_control=e_control,
            d_control=d_control,
            deterministic=True,
        )

    return jax.jit(predict_fn, static_argnums=(2,))


def evaluate(eval_step, state, batches: Iterator) -> Dict[str, float]:
    """Batch-size-weighted mean of every loss over a val pass
    (reference: evaluate.py:39-58)."""
    sums: Dict[str, float] = {}
    count = 0
    for batch, arrays in batches:
        losses = eval_step(state, arrays)
        n = batch.n_real
        count += n
        for k, v in losses.items():
            sums[k] = sums.get(k, 0.0) + float(v) * n
    if count == 0:
        return {}
    return {k: v / count for k, v in sums.items()}


def run_training(
    cfg: Config,
    mesh=None,
    restore_step: Optional[int] = None,
    max_steps: Optional[int] = None,
    synth_callback=None,
    log: bool = True,
    vocoder=None,
    profile_dir: Optional[str] = None,
    profile_steps: tuple = (10, 20),
):
    """The full training loop (reference: train.py:21-173).

    Returns the final TrainState. `max_steps` overrides total_step (tests);
    `synth_callback(state, batch, arrays, step, model)` runs every
    synth_step — pass "default" for the GT-vs-predicted sample renderer.
    `profile_dir` enables a jax.profiler trace over the step window
    ``profile_steps`` (greenfield vs the reference — SURVEY.md §5).
    """
    import time
    import jax.numpy as jnp

    from speakingstyle_tpu.data import (
        BucketedBatcher,
        DevicePrefetcher,
        SpeechDataset,
    )
    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.training.checkpoint import CheckpointManager
    from speakingstyle_tpu.training.optim import make_lr_schedule, make_optimizer

    steps = cfg.train.step
    total_step = max_steps if max_steps is not None else steps.total_step

    if cfg.train.fast_prng:
        try:
            jax.config.update("jax_default_prng_impl", "rbg")
        except Exception as e:  # pragma: no cover - only future jax renames
            print(f"warning: fast_prng unavailable ({e}); using default PRNG")

    model = build_model(cfg)
    rng = jax.random.PRNGKey(cfg.train.seed)
    variables = init_variables(model, cfg, rng)
    tx = make_optimizer(cfg.train)
    state = TrainState.create(variables, tx)
    schedule = make_lr_schedule(cfg.train)

    ckpt = CheckpointManager(cfg.train.path.ckpt_path)
    if restore_step is not None:
        state = ckpt.restore(
            state,
            step=restore_step if restore_step > 0 else None,
            ignore_layers=cfg.train.ignore_layers,
        )

    state_shardings = None
    if mesh is not None:
        if mesh.shape.get("model", 1) > 1:
            from speakingstyle_tpu.parallel.partition import (
                shard_train_state,
                train_state_shardings,
            )

            state_shardings = train_state_shardings(state, mesh)
            state = shard_train_state(state, mesh)
        else:
            state = jax.device_put(state, NamedSharding(mesh, P()))

    train_step = make_train_step(
        model, tx, cfg, mesh=mesh, state_shardings=state_shardings
    )
    eval_step = make_eval_step(
        model, cfg, mesh=mesh, state_shardings=state_shardings
    )

    max_src = max_mel = cfg.model.max_seq_len
    pad_mult = mesh.shape["data"] if mesh is not None else 1
    train_ds = SpeechDataset("train.txt", cfg, sort=True, drop_last=True)
    batcher = BucketedBatcher(
        train_ds,
        max_src=max_src,
        max_mel=max_mel,
        batch_pad_multiple=pad_mult,
        seed=cfg.train.seed,
    )
    prefetch = DevicePrefetcher(iter(batcher), mesh=mesh)
    val_ds = SpeechDataset("val.txt", cfg, sort=False, drop_last=False)
    val_batcher = BucketedBatcher(
        val_ds,
        max_src=max_src,
        max_mel=max_mel,
        batch_pad_multiple=pad_mult,
        seed=0,
    )

    logger = TrainLogger(cfg.train.path.log_path) if log else None
    if synth_callback == "default":
        synth_callback = default_synth_callback(cfg, logger, vocoder=vocoder)
    step_rng = jax.random.PRNGKey(cfg.train.seed + 1)

    step = int(state.step)
    start_step = step  # profile window is relative to where this run begins
    window_t0, window_step0, window_frames = time.perf_counter(), step, 0
    trace_active = False
    try:
        for batch, arrays in prefetch:
            if step >= total_step:
                break
            if (
                profile_dir is not None
                and not trace_active
                and profile_steps[0] <= step - start_step < profile_steps[1]
            ):
                jax.profiler.start_trace(profile_dir)
                trace_active = True
            # step_fn folds state.step into the key, so passing the same
            # step_rng every iteration yields a fresh per-step stream
            state, losses = train_step(state, arrays, step_rng)  # jaxlint: disable=JL006
            step += 1
            window_frames += int(batch.mel_lens.sum())  # host-side, no sync
            if trace_active and step - start_step >= profile_steps[1]:
                jax.block_until_ready(losses["total_loss"])
                jax.profiler.stop_trace()
                trace_active = False

            if logger and step % steps.log_step == 0:
                jax.block_until_ready(losses["total_loss"])
                # host boundary: losses are materialized for logging anyway
                contracts.assert_tree_finite(losses, "train_step.losses")
                lr = float(schedule(jnp.asarray(step - 1)))
                logger.log(step, {k: float(v) for k, v in losses.items()}, lr=lr)
                dt = time.perf_counter() - window_t0
                if dt > 0 and step > window_step0:
                    logger.log_throughput(
                        step, (step - window_step0) / dt, window_frames / dt
                    )
                window_t0, window_step0, window_frames = (
                    time.perf_counter(), step, 0,
                )
            if synth_callback is not None and step % steps.synth_step == 0:
                synth_callback(state, batch, arrays, step, model)
            if step % steps.val_step == 0:
                val_losses = evaluate(
                    eval_step,
                    state,
                    DevicePrefetcher(val_batcher.epoch(shuffle=False), mesh=mesh),
                )
                if logger:
                    logger.log(step, val_losses, prefix="val")
            if step % steps.save_step == 0:
                ckpt.save(step, jax.device_get(state))
    finally:
        if trace_active:
            jax.profiler.stop_trace()  # run ended inside the profile window
        prefetch.stop()
        if logger:
            logger.close()
        ckpt.close()
    return state


class TrainLogger:
    """TensorBoard scalars/figures/audio + append-only log.txt (reference:
    train.py:53-61, utils/tools.py:82-107). tensorboardX is optional; the
    text log always works."""

    def __init__(self, log_dir: str, use_tensorboard: bool = True):
        os.makedirs(log_dir, exist_ok=True)
        self.txt = open(os.path.join(log_dir, "log.txt"), "a")
        self.tb = None
        if use_tensorboard:
            try:
                from tensorboardX import SummaryWriter

                self.tb = SummaryWriter(log_dir)
            except ImportError:
                pass

    def log(self, step: int, losses: Dict[str, float], lr: Optional[float] = None, prefix: str = "train"):
        msg = f"[{prefix}] Step {step}, " + ", ".join(
            f"{k}: {float(v):.4f}" for k, v in losses.items()
        )
        if lr is not None:
            msg += f", lr: {lr:.6f}"
        self.txt.write(msg + "\n")
        self.txt.flush()
        if self.tb is not None:
            for k, v in losses.items():
                self.tb.add_scalar(f"{prefix}/{k}", float(v), step)
            if lr is not None:
                self.tb.add_scalar(f"{prefix}/lr", lr, step)

    def log_throughput(self, step: int, steps_per_sec: float, frames_per_sec: float):
        self.txt.write(
            f"[perf] Step {step}, steps/s: {steps_per_sec:.2f}, "
            f"mel-frames/s: {frames_per_sec:.0f}\n"
        )
        self.txt.flush()
        if self.tb is not None:
            self.tb.add_scalar("perf/steps_per_sec", steps_per_sec, step)
            self.tb.add_scalar("perf/mel_frames_per_sec", frames_per_sec, step)

    def log_figure(self, step: int, tag: str, fig):
        if self.tb is not None:
            self.tb.add_figure(tag, fig, step)

    def log_audio(self, step: int, tag: str, wav, sampling_rate: int,
                  max_wav_value: float = 32768.0):
        if self.tb is not None:
            import numpy as np

            wav = np.asarray(wav, np.float32) / max_wav_value
            try:
                self.tb.add_audio(tag, wav[None], step, sample_rate=sampling_rate)
            except ModuleNotFoundError:
                pass  # tensorboardX audio needs soundfile; scalars/figures still log

    def close(self):
        self.txt.close()
        if self.tb is not None:
            self.tb.close()


def default_synth_callback(cfg: Config, logger: Optional[TrainLogger], vocoder=None):
    """Periodic validation-sample rendering (reference: train.py:117-144):
    plot GT-vs-predicted mel and log both vocoded wavs to TensorBoard."""

    def callback(state, batch, arrays, step, model):
        from speakingstyle_tpu.synthesis import synth_one_sample

        out = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            **_model_kwargs(arrays, teacher_forced=True),
            deterministic=True,
        )
        fig, wav_recon, wav_pred, basename = synth_one_sample(
            batch, out, vocoder, cfg
        )
        if logger is not None:
            sr = cfg.preprocess.preprocessing.audio.sampling_rate
            mw = cfg.preprocess.preprocessing.audio.max_wav_value
            logger.log_figure(step, f"Training/{basename}", fig)
            logger.log_audio(
                step, f"Training/{basename}_reconstructed", wav_recon, sr, mw
            )
            logger.log_audio(
                step, f"Training/{basename}_synthesized", wav_pred, sr, mw
            )
        import matplotlib.pyplot as plt

        plt.close(fig)

    return callback
