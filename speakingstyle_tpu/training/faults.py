"""Training-side fault injection.

The ``FaultPlan``/``SPEAKINGSTYLE_FAULTS`` core moved to the shared
``speakingstyle_tpu.faults`` module when serving grew its own fault
points (PR 9); this module re-exports it so every training call site —
trainer, vocoder trainer, ``cli/train.py --faults``, the resilience
drills — keeps importing from here, and keeps the two faults whose
*implementation* is training-specific: NaN batch poisoning and real
SIGTERM delivery.

See ``speakingstyle_tpu/faults.py`` for the spec grammar and the full
counter-semantics table (training and serving kinds).
"""

import os
import signal

from speakingstyle_tpu.faults import (  # noqa: F401  (re-export)
    ENV_VAR,
    KINDS,
    SERVING_KINDS,
    TRAINING_KINDS,
    FaultPlan,
    _Fault,
)


def poison_batch(arrays: dict) -> dict:
    """NaN-poison a training batch (the ``nan_grads`` fault): multiplying
    the mel targets by NaN drives every loss and every gradient non-finite
    through the real loss/grad path, exactly like a diverged model or a
    corrupt feature file would."""
    import jax.numpy as jnp

    out = dict(arrays)
    out["mels"] = out["mels"] * jnp.float32(jnp.nan)
    return out


def deliver_sigterm():
    """Deliver a real SIGTERM to this process (the ``sigterm`` fault), so
    the actual installed handler — not a shortcut — is exercised."""
    os.kill(os.getpid(), signal.SIGTERM)
