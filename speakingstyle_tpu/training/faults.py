"""Deterministic fault injection for the resilience layer.

Every recovery path in training/resilience.py is exercised end-to-end by
injecting the fault it guards against at an exact, named point.  The
``SPEAKINGSTYLE_FAULTS`` environment variable holds a spec like

    loader_ioerror@7;nan_grads@12;sigterm@20

meaning: the 7th feature load raises a (transient) IOError once, the
batch feeding train step 12 is NaN-poisoned once, and SIGTERM is
delivered to the process once, right after step 20 completes.  Each
entry fires exactly once — a retried load or a replayed step after
rollback does NOT re-trip the same entry, which is what makes recovery
observable.  Duplicate entries are allowed (``nan_grads@3;nan_grads@3``
poisons the replay too — how the consecutive-rollback abort is tested).

Counter semantics per kind:

  ``loader_ioerror@N``  Nth call of ``SpeechDataset._feature`` (1-based,
                        counted per dataset instance)
  ``nan_grads@N``       the batch consumed by the train step whose
                        post-increment step counter is N
  ``sigterm@N``         delivered after step N completes

The plan is plain Python state constructed per run (``FaultPlan.from_env``)
and threaded explicitly into the sites — no module globals, so tests can
run many faulted loops in one process.
"""

import dataclasses
import os
import signal
from typing import List, Sequence, Tuple

ENV_VAR = "SPEAKINGSTYLE_FAULTS"

KINDS = ("loader_ioerror", "nan_grads", "sigterm")


@dataclasses.dataclass
class _Fault:
    kind: str
    at: int
    fired: bool = False


class FaultPlan:
    """A parsed fault spec; each entry fires at most once."""

    def __init__(self, faults: Sequence[_Fault] = ()):
        self._faults: List[_Fault] = list(faults)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        faults = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            kind, sep, at = part.partition("@")
            kind = kind.strip()
            if not sep or kind not in KINDS:
                raise ValueError(
                    f"bad fault spec entry {part!r}: expected <kind>@<step> "
                    f"with kind in {KINDS}"
                )
            try:
                step = int(at)  # jaxlint: disable=JL004
            except ValueError:
                raise ValueError(
                    f"bad fault spec entry {part!r}: step {at!r} is not an int"
                ) from None
            faults.append(_Fault(kind, step))
        return cls(faults)

    @classmethod
    def from_env(cls) -> "FaultPlan":
        return cls.parse(os.environ.get(ENV_VAR, ""))

    def __bool__(self) -> bool:
        return bool(self._faults)

    def fire(self, kind: str, at: int) -> bool:
        """True exactly once per matching entry when the site's counter
        hits the named value; False forever after."""
        for f in self._faults:
            if f.kind == kind and f.at == at and not f.fired:
                f.fired = True
                return True
        return False

    def pending(self) -> List[Tuple[str, int]]:
        return [(f.kind, f.at) for f in self._faults if not f.fired]


def poison_batch(arrays: dict) -> dict:
    """NaN-poison a training batch (the ``nan_grads`` fault): multiplying
    the mel targets by NaN drives every loss and every gradient non-finite
    through the real loss/grad path, exactly like a diverged model or a
    corrupt feature file would."""
    import jax.numpy as jnp

    out = dict(arrays)
    out["mels"] = out["mels"] * jnp.float32(jnp.nan)
    return out


def deliver_sigterm():
    """Deliver a real SIGTERM to this process (the ``sigterm`` fault), so
    the actual installed handler — not a shortcut — is exercised."""
    os.kill(os.getpid(), signal.SIGTERM)
