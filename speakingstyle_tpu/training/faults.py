"""Training-side fault injection.

The ``FaultPlan``/``SPEAKINGSTYLE_FAULTS`` core moved to the shared
``speakingstyle_tpu.faults`` module when serving grew its own fault
points (PR 9); this module re-exports it so every training call site —
trainer, vocoder trainer, ``cli/train.py --faults``, the resilience
drills — keeps importing from here, and keeps the two faults whose
*implementation* is training-specific: NaN batch poisoning and real
SIGTERM delivery.

See ``speakingstyle_tpu/faults.py`` for the spec grammar and the full
counter-semantics table (training and serving kinds).
"""

import os
import signal

from speakingstyle_tpu.faults import (  # noqa: F401  (re-export)
    ENV_VAR,
    KINDS,
    SERVING_KINDS,
    TRAINING_KINDS,
    FaultPlan,
    _Fault,
    dp_poison_rows,
)


def poison_batch(arrays: dict, mesh=None) -> dict:
    """NaN-poison a training batch (the ``nan_grads`` fault): multiplying
    the mel targets by NaN drives every loss and every gradient non-finite
    through the real loss/grad path, exactly like a diverged model or a
    corrupt feature file would.

    Under a DP mesh the poison is SHARD-LOCAL (``dp_poison_rows`` — the
    first data shard's rows only): the adversarial drill that proves the
    sentinel's dp-axis reduction, since only an all-reduced ``_finite``
    flag makes every device roll back on one shard's NaN."""
    import jax
    import jax.numpy as jnp

    out = dict(arrays)
    mels = out["mels"]
    dp = mesh.shape.get("data", 1) if mesh is not None else 1
    rows = dp_poison_rows(mels.shape[0], dp)
    if rows < mels.shape[0]:
        poisoned = jnp.asarray(mels).at[:rows].multiply(jnp.float32(jnp.nan))
    else:
        poisoned = mels * jnp.float32(jnp.nan)
    # eager .at updates may drop the batch sharding; pin it back so the
    # poisoned batch enters the jitted step with the layout it came with
    sharding = getattr(mels, "sharding", None)
    if mesh is not None and sharding is not None:
        poisoned = jax.device_put(poisoned, sharding)
    out["mels"] = poisoned
    return out


def deliver_sigterm():
    """Deliver a real SIGTERM to this process (the ``sigterm`` fault), so
    the actual installed handler — not a shortcut — is exercised."""
    os.kill(os.getpid(), signal.SIGTERM)
