"""Train state: the replicated pytree carried across steps.

Unlike the reference's (module, optimizer) object pair
(reference: utils/model.py:11-45), state is one pure pytree — params,
PostNet batch_stats, optax state, and the step counter — so it jits,
shards, donates, and checkpoints as a unit.
"""

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray            # [] int32
    params: Any
    batch_stats: Any
    opt_state: Any

    @classmethod
    def create(cls, variables, tx: optax.GradientTransformation):
        params = variables["params"]
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(params),
        )

    def abstract(self) -> "TrainState":
        """Shape/dtype/sharding template of this state (no buffers).

        Captured before the loop donates the concrete buffers, it stays
        valid as a restore target forever — the NaN-rollback path in
        run_training restores checkpoints into it after the live state
        has been donated away."""

        def to_sds(x):
            sharding = getattr(x, "sharding", None)
            return jax.ShapeDtypeStruct(
                jnp.shape(x), jnp.result_type(x), sharding=sharding
            )

        return jax.tree_util.tree_map(to_sds, self)

    def sharded_abstract(self, shardings) -> "TrainState":
        """Abstract template carrying EXPLICIT target shardings — the
        cross-mesh-shape resume spelling.

        ``shardings`` is a matching TrainState pytree of shardings (e.g.
        ``parallel.partition.train_state_shardings`` over the TARGET mesh,
        or a replicated tree for pure DP). Restoring a checkpoint against
        this template materializes it directly into the target layout,
        regardless of the mesh shape that wrote it — no host round-trip
        through the source layout."""

        def to_sds(x, s):
            return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x),
                                        sharding=s)

        return jax.tree_util.tree_map(to_sds, self, shardings)
