"""Train state: the replicated pytree carried across steps.

Unlike the reference's (module, optimizer) object pair
(reference: utils/model.py:11-45), state is one pure pytree — params,
PostNet batch_stats, optax state, and the step counter — so it jits,
shards, donates, and checkpoints as a unit.
"""

from typing import Any

import flax.struct
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray            # [] int32
    params: Any
    batch_stats: Any
    opt_state: Any

    @classmethod
    def create(cls, variables, tx: optax.GradientTransformation):
        params = variables["params"]
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(params),
        )
