"""Training: optax optimizer chain, sharded step functions, loop,
checkpointing, and the fault-tolerance layer (resilience + faults)."""

from speakingstyle_tpu.training.checkpoint import CheckpointManager
from speakingstyle_tpu.training.faults import FaultPlan
from speakingstyle_tpu.training.optim import make_lr_schedule, make_optimizer
from speakingstyle_tpu.training.resilience import (
    BadSampleBudgetError,
    GracefulShutdown,
    Quarantine,
    RollbackGuard,
    TrainingDivergedError,
    retry_io,
)
from speakingstyle_tpu.training.state import TrainState
from speakingstyle_tpu.training.trainer import (
    TrainLogger,
    evaluate,
    make_eval_step,
    make_predict_step,
    make_train_step,
    run_training,
)

__all__ = [
    "CheckpointManager",
    "FaultPlan",
    "BadSampleBudgetError",
    "GracefulShutdown",
    "Quarantine",
    "RollbackGuard",
    "TrainingDivergedError",
    "retry_io",
    "make_lr_schedule",
    "make_optimizer",
    "TrainState",
    "TrainLogger",
    "evaluate",
    "make_eval_step",
    "make_predict_step",
    "make_train_step",
    "run_training",
]
