"""Teacher->student distillation of the acoustic model (ROADMAP item 2b).

The fast tier's weights: a student FastSpeech2 with HALVED encoder /
decoder depth and width (the existing ModelConfig knobs — no new model
code) trained to match the frozen teacher's outputs. Distillation here
is data-free: both models free-run/teacher-force over seeded synthetic
phoneme batches, so the student learns the teacher's *function* —
including its duration/pitch/energy predictors — without touching the
preprocessed dataset (RedApt's faster-and-smaller regime, PAPERS.md,
driven purely by the teacher's mels as targets).

One jitted step (through ``jit_program``, the sanctioned constructor):

  1. the frozen teacher free-runs the batch (``stop_gradient``-frozen by
     construction — its variables enter as a non-differentiated arg),
     emitting mel/duration/pitch/energy targets;
  2. the student runs TEACHER-FORCED on the teacher's durations (so both
     mels align frame-for-frame) and ``fastspeech2_loss`` scores it
     against the teacher's postnet mel — the same masked L1/MSE stack
     training uses, with the dataset targets swapped for teacher
     predictions.

FiLM conditioning is sampled per batch (``style_scale``-scaled gaussian
gamma/beta vectors): the student learns the teacher's response across
the conditioning space it will serve behind the shared StyleService,
without running any reference encoder in the loop.

The resilience stack rides along unchanged: ``SPEAKINGSTYLE_FAULTS``
(``nan_grads`` poisons the FiLM inputs — the analogue of poisoning mel
targets, which a data-free loop doesn't have; ``sigterm`` delivers a
real signal), the NaN sentinel + RollbackGuard roll back to the last
good student checkpoint, and checkpoints land under
``<ckpt_path>/student`` through the manifest-verified CheckpointManager
— the student IS a second model version the PR-13 rollout/tier gates
can verify-and-build like any other.
"""

import os
import time
from typing import Dict, Optional, Tuple

import numpy as np

from speakingstyle_tpu.configs.config import Config

__all__ = [
    "STUDENT_SUBDIR",
    "make_distill_batch",
    "make_distill_step",
    "run_distillation",
    "student_config",
]

# where the student checkpoints live relative to train.path.ckpt_path —
# a sibling model version, not a new step range of the teacher's
STUDENT_SUBDIR = "student"


def student_config(cfg: Config) -> Config:
    """The student's Config: encoder/decoder DEPTH (layers) and WIDTH
    (FFN filter, postnet dim/layers) halved, floored at 1. The model dim
    (``encoder_hidden``/``decoder_hidden``) and the variance-predictor
    filter stay: FiLM broadcasts ``[B, 1, d_model]`` gamma/beta straight
    onto the residual stream AND the predictors' conv streams, so those
    widths ARE the style interface — the student must keep them to
    consume the same conditioning vectors the teacher does (and to share
    one StyleService at serve time). The FFN inner width carries ~4x the
    hidden dim's parameters per layer, so halving depth + FFN + postnet
    still cuts the FLOP bill roughly in half without severing that
    interface."""
    import dataclasses

    tf = cfg.model.transformer

    def half(n: int) -> int:
        return max(1, n // 2)

    student_tf = dataclasses.replace(
        tf,
        encoder_layer=half(tf.encoder_layer),
        decoder_layer=half(tf.decoder_layer),
        conv_filter_size=half(tf.conv_filter_size),
    )
    model = dataclasses.replace(
        cfg.model,
        transformer=student_tf,
        postnet_embedding_dim=half(cfg.model.postnet_embedding_dim),
        # floor 2: a 1-layer postnet degenerates to one mel->mel conv,
        # which is WIDER (80->80 channels) than two narrow layers
        postnet_layers=max(2, cfg.model.postnet_layers // 2),
    )
    return dataclasses.replace(cfg, model=model)


def make_distill_batch(cfg: Config, rng: np.random.Generator,
                       batch_size: int, src_len: int,
                       style_scale: float = 0.1) -> Dict[str, np.ndarray]:
    """One seeded synthetic batch: random phoneme ids, full-length rows,
    and gaussian FiLM vectors. Shapes are constant across steps, so the
    whole run compiles exactly one step program."""
    d = cfg.model.reference_encoder.encoder_hidden
    return {
        "speakers": np.zeros((batch_size,), np.int32),
        "texts": rng.integers(
            1, 300, (batch_size, src_len)).astype(np.int32),
        "src_lens": np.full((batch_size,), src_len, np.int32),
        "gammas": (style_scale * rng.standard_normal(
            (batch_size, 1, d))).astype(np.float32),
        "betas": (style_scale * rng.standard_normal(
            (batch_size, 1, d))).astype(np.float32),
    }


def poison_distill_batch(arrays: Dict) -> Dict:
    """The ``nan_grads`` drill for the data-free loop: NaN the FiLM
    inputs (there are no mel targets to poison — the teacher computes
    them in-step), driving every loss and gradient non-finite through
    the real forward/backward path."""
    import jax.numpy as jnp

    out = dict(arrays)
    out["gammas"] = jnp.asarray(out["gammas"]) * jnp.float32(jnp.nan)
    return out


def make_distill_step(student_model, teacher_model, teacher_variables,
                      tx, cfg: Config, max_mel_len: int):
    """jitted ``fn(state, arrays, rng) -> (state, losses)``.

    The teacher forward runs INSIDE the step (frozen: its variables are
    closed over, never differentiated), so teacher targets never round-
    trip through the host and the whole distill iteration is one XLA
    program. Losses carry the ``_finite`` sentinel when
    ``train.resilience.nan_sentinel`` is on, read at log boundaries
    exactly like the main trainer's.
    """
    import jax
    import jax.numpy as jnp

    from speakingstyle_tpu.models.loss import fastspeech2_loss
    from speakingstyle_tpu.parallel.registry import jit_program
    from speakingstyle_tpu.training import resilience

    lambda_f = cfg.train.loss.lambda_f
    p_level = cfg.preprocess.preprocessing.pitch.feature
    e_level = cfg.preprocess.preprocessing.energy.feature
    nan_sentinel = cfg.train.resilience.nan_sentinel
    use_style = cfg.model.use_reference_encoder

    def step_fn(state, arrays: Dict, rng):
        rng = jax.random.fold_in(rng, state.step)
        gammas = arrays["gammas"] if use_style else None
        betas = arrays["betas"] if use_style else None
        t_out = teacher_model.apply(
            teacher_variables,
            speakers=arrays["speakers"],
            texts=arrays["texts"],
            src_lens=arrays["src_lens"],
            mels=None,
            mel_lens=None,
            max_mel_len=max_mel_len,
            gammas=gammas,
            betas=betas,
            deterministic=True,
        )
        t_out = jax.lax.stop_gradient(t_out)

        def loss_fn(params):
            s_out, updates = student_model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                speakers=arrays["speakers"],
                texts=arrays["texts"],
                src_lens=arrays["src_lens"],
                mels=None,
                mel_lens=t_out["mel_lens"],
                max_mel_len=max_mel_len,
                # teacher-forced on the TEACHER's predictions: the
                # student's mel aligns frame-for-frame with its target,
                # and its variance predictors regress onto the teacher's
                p_targets=t_out["pitch_prediction"],
                e_targets=t_out["energy_prediction"],
                d_targets=t_out["durations"],
                gammas=gammas,
                betas=betas,
                deterministic=False,
                rngs={"dropout": rng},
                mutable=["batch_stats"],
            )
            losses = fastspeech2_loss(
                s_out,
                t_out["mel_postnet"],
                t_out["pitch_prediction"],
                t_out["energy_prediction"],
                t_out["durations"],
                params,
                lambda_f=lambda_f,
                pitch_feature_level=p_level,
                energy_feature_level=e_level,
            )
            return losses["total_loss"], (losses, updates["batch_stats"])

        (_, (losses, batch_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        if nan_sentinel:
            losses = dict(losses)
            losses["_finite"] = resilience.all_finite(losses, grads)
        import optax

        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
        )
        return new_state, losses

    return jit_program(step_fn, donate_argnums=(0,))


def run_distillation(
    cfg: Config,
    teacher_variables: Optional[Dict] = None,
    max_steps: Optional[int] = None,
    batch_size: int = 8,
    src_len: Optional[int] = None,
    log: bool = True,
    registry=None,
    ckpt_dir: Optional[str] = None,
) -> Tuple[object, Config]:
    """The distillation loop; returns ``(student_state, student_cfg)``.

    ``teacher_variables=None`` restores the latest teacher checkpoint
    from ``train.path.ckpt_path`` (manifest-verified), falling back to a
    seeded fresh init when none exists (the smoke/drill mode — the
    mechanics are identical, only the teacher is untrained). Student
    checkpoints land under ``ckpt_dir`` (default
    ``<ckpt_path>/student``) with per-leaf manifests, so the student is
    restorable as a second model version by the same strict path the
    rollout verify gate uses.
    """
    import jax

    from speakingstyle_tpu import obs
    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.parallel.registry import ProgramRegistry
    from speakingstyle_tpu.training import faults, resilience
    from speakingstyle_tpu.training.checkpoint import CheckpointManager
    from speakingstyle_tpu.training.optim import make_optimizer
    from speakingstyle_tpu.training.state import TrainState
    from speakingstyle_tpu.training.trainer import (
        TrainLogger,
        public_losses,
    )

    res = cfg.train.resilience
    steps_cfg = cfg.train.step
    total_step = (
        max_steps if max_steps is not None else steps_cfg.total_step
    )
    plan = faults.FaultPlan.from_env()
    registry = registry if registry is not None else obs.get_registry()
    # same choke-point discipline as run_training: wires the persistent
    # cache before the first compile and counts distill compiles
    ProgramRegistry(
        registry,
        cache_dir=cfg.train.obs.compilation_cache_dir or None,
        counter_name="train_compiles_total",
        prefix="train",
    )

    rng = jax.random.PRNGKey(cfg.train.seed)
    teacher_model = build_model(cfg)
    if teacher_variables is None:
        fresh = init_variables(teacher_model, cfg, rng)
        try:
            teacher_ckpt = CheckpointManager(
                cfg.train.path.ckpt_path, registry=registry
            )
            t_state = teacher_ckpt.restore(
                TrainState.create(fresh, make_optimizer(cfg.train))
            )
            teacher_variables = {
                "params": t_state.params,
                "batch_stats": t_state.batch_stats,
            }
            teacher_ckpt.close()
        except FileNotFoundError:
            print(
                "warning: no teacher checkpoint under "
                f"{cfg.train.path.ckpt_path}; distilling against a "
                "seeded fresh teacher (smoke mode)"
            )
            teacher_variables = fresh

    s_cfg = student_config(cfg)
    student_model = build_model(s_cfg)

    def fresh_student_variables():
        """Seeded student init WITH the teacher's reference encoder
        grafted in: the distill loop conditions on sampled FiLM vectors,
        so the student's own style encoder receives zero gradient and
        would serve untrained garbage. The encoder config is
        deliberately un-halved (same d_model), so the teacher's params
        drop in — teacher and student then share one style front-end,
        and a style vector encoded once serves both tiers."""
        sv = init_variables(
            student_model, s_cfg, jax.random.PRNGKey(cfg.train.seed + 2)
        )
        if cfg.model.use_reference_encoder:
            t_ref = teacher_variables["params"].get("reference_encoder")
            if t_ref is not None:
                sp = dict(sv["params"])
                # COPY, never alias: the jitted step donates the student
                # state, and donated teacher buffers would be deleted
                # out from under the caller's teacher_variables
                sp["reference_encoder"] = jax.tree_util.tree_map(
                    lambda x: np.array(x), t_ref
                )
                sv = dict(sv)
                sv["params"] = sp
        return sv

    tx = make_optimizer(s_cfg.train)
    state = TrainState.create(fresh_student_variables(), tx)

    src = src_len if src_len is not None else min(
        cfg.serve.src_buckets[0], 12
    )
    t_mel = min(
        src * cfg.serve.frames_per_phoneme, cfg.model.max_seq_len
    )
    distill_step = make_distill_step(
        student_model, teacher_model, teacher_variables, tx, cfg, t_mel
    )

    ckpt = CheckpointManager(
        ckpt_dir or os.path.join(cfg.train.path.ckpt_path, STUDENT_SUBDIR),
        max_to_keep=res.max_to_keep or None,
        async_save=res.async_checkpointing,
        keep_best=res.keep_best,
        fault_plan=plan,
        registry=registry,
    )
    guard = resilience.RollbackGuard(res.max_rollbacks)
    abstract_template = state.abstract()
    logger = None
    if log:
        logger = TrainLogger(
            cfg.train.path.log_path, registry=registry
        )
        logger.event(
            "distill_start", total_step=total_step, batch_size=batch_size,
            src_len=src, max_mel_len=t_mel, teacher_subdir="",
            student_subdir=STUDENT_SUBDIR,
        )
    steps_ctr = registry.counter(
        "distill_steps_total", help="student optimizer steps run"
    )
    rollback_ctr = registry.counter(
        "train_rollbacks_total", help="NaN-sentinel rollbacks taken"
    )
    step_hist = registry.histogram(
        "distill_step_seconds", help="per-step wall time of the distill step"
    )

    batch_rng = np.random.default_rng(cfg.train.seed + 3)
    step_rng = jax.random.PRNGKey(cfg.train.seed + 4)
    step = int(state.step)
    last_loss: Optional[float] = None
    shutdown = resilience.GracefulShutdown()
    try:
        with shutdown:
            while step < total_step and not shutdown.requested:
                arrays = make_distill_batch(cfg, batch_rng, batch_size, src)
                if plan.fire("nan_grads", step + 1):
                    arrays = poison_distill_batch(arrays)
                    if logger:
                        logger.note(f"[fault] nan_grads fired at step "
                                    f"{step + 1} (FiLM inputs poisoned)")
                        logger.event("fault_fire", kind="nan_grads",
                                     step=step + 1)
                t0 = time.perf_counter()
                state, losses = distill_step(state, arrays, step_rng)  # jaxlint: disable=JL006
                step += 1
                steps_ctr.inc()
                step_hist.observe(time.perf_counter() - t0)
                if plan.fire("sigterm", step):
                    if logger:
                        logger.event("fault_fire", kind="sigterm", step=step)
                    faults.deliver_sigterm()
                if step % steps_cfg.log_step == 0 or step >= total_step:
                    jax.block_until_ready(losses["total_loss"])
                    if "_finite" in losses and not bool(losses["_finite"]):
                        n = guard.trip(step)  # raises past max_rollbacks
                        ckpt.wait()
                        good = ckpt.latest_step()
                        rollback_ctr.inc()
                        if logger:
                            logger.note(
                                f"[resilience] non-finite loss/grads at step "
                                f"{step}; rollback {n}/{res.max_rollbacks} "
                                f"to step {good}"
                            )
                            logger.event("rollback", step=step, rollback_n=n,
                                         restore_step=good)
                        if good is not None:
                            state = ckpt.restore(abstract_template, step=good)
                        else:
                            # no good checkpoint yet: deterministic
                            # re-init (same seed, same graft)
                            state = TrainState.create(
                                fresh_student_variables(), tx
                            )
                        step = int(state.step)  # jaxlint: disable=JL004
                        continue
                    guard.ok()
                    last_loss = float(losses["total_loss"])
                    if logger:
                        logger.log(
                            step,
                            {k: float(v)
                             for k, v in public_losses(losses).items()},
                            prefix="distill",
                        )
                if step % steps_cfg.save_step == 0:
                    ckpt.save(step, state, val_loss=last_loss)
    finally:
        # the student checkpoint is the artifact: always flush a final
        # manifest-verified save (preemption included), like run_training
        ckpt.save(step, state, val_loss=last_loss, block=True)
        if logger:
            logger.event("distill_end", step=step, loss=last_loss)
            logger.close()
        ckpt.close()
    return state, s_cfg
