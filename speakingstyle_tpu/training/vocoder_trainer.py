"""HiFi-GAN vocoder training: alternating gen/disc steps under one jit.

Reference: hifigan/train.py:24-267 — AdamW(0.8, 0.99) + per-epoch
ExponentialLR(0.999), discriminator step then generator step
(adv + 2×feature-matching + 45×mel-L1), NCCL DDP across GPUs.

TPU redesign: both updates run inside a single jitted, mesh-sharded step
(batch split over the data axis; XLA inserts the gradient psums that DDP's
allreduce did). The differentiable mel loss reuses the framework's own
STFT (audio/stft.py), so generator gradients flow through the log-mel.
"""

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import serialization
from jax.sharding import NamedSharding, PartitionSpec as P

from speakingstyle_tpu.audio.mel import mel_filterbank
from speakingstyle_tpu.configs.config import Config
from speakingstyle_tpu.models.hifigan import Generator
from speakingstyle_tpu.models.hifigan_disc import (
    MultiPeriodDiscriminator,
    MultiScaleDiscriminator,
    discriminator_loss,
    feature_matching_loss,
    generator_adversarial_loss,
)
from speakingstyle_tpu.parallel.registry import jit_program


class VocoderHParams(NamedTuple):
    """Training hyperparameters (reference: hifigan/config.json:2-13)."""

    learning_rate: float = 2e-4
    adam_b1: float = 0.8
    adam_b2: float = 0.99
    lr_decay: float = 0.999
    lr_decay_steps: int = 1000  # decay interval in steps (torch decays per epoch)
    segment_size: int = 8192
    mel_loss_weight: float = 45.0


class VocoderState(NamedTuple):
    step: jnp.ndarray
    gen_params: Dict
    mpd_params: Dict
    msd_params: Dict
    # spectral-norm power-iteration state (u, sigma) of the first MSD
    # scale — non-trainable, updated on each discriminator pass
    msd_stats: Dict
    gen_opt: optax.OptState
    disc_opt: optax.OptState


def differentiable_mel(cfg: Config):
    """wav [B, T] -> log-mel [B, n_frames, n_mels], differentiable, jit-safe.

    Built directly on audio/stft.py's ``stft_magnitude`` +
    ``dynamic_range_compression`` — the SAME transform the preprocessor and
    MelExtractor use — so the vocoder's training target never diverges from
    the acoustic model's features (the reference had two subtly different
    mel pipelines, audio/stft.py vs hifigan/meldataset.py).
    """
    from speakingstyle_tpu.audio.stft import (
        dynamic_range_compression,
        stft_magnitude,
    )

    pp = cfg.preprocess.preprocessing
    fb = jnp.asarray(
        mel_filterbank(
            pp.audio.sampling_rate, pp.stft.filter_length,
            pp.mel.n_mel_channels, pp.mel.mel_fmin, pp.mel.mel_fmax,
        )
    )

    def mel_fn(wav):
        mag = stft_magnitude(
            wav, pp.stft.filter_length, pp.stft.hop_length, pp.stft.win_length
        )  # [B, F, T]
        mel = jnp.einsum("mf,bft->btm", fb, mag)
        return dynamic_range_compression(mel)

    return mel_fn


def init_vocoder_state(
    cfg: Config, hp: VocoderHParams, rng, gen_params: Optional[Dict] = None,
    gen: Optional[Generator] = None,
    mpd: Optional[MultiPeriodDiscriminator] = None,
    msd: Optional[MultiScaleDiscriminator] = None,
) -> Tuple[VocoderState, Generator, MultiPeriodDiscriminator, MultiScaleDiscriminator, optax.GradientTransformation, optax.GradientTransformation]:
    """Build models + optimizers; ``gen_params`` warm-starts the generator
    (fine-tuning a converted checkpoint). Pass ``gen`` (e.g. from
    ``hifigan.generator_from_config`` on the checkpoint's config.json) when
    fine-tuning a non-default topology — V3/ResBlock2, different upsample
    rates — so the module matches the warm-start params. ``mpd``/``msd``
    likewise override the discriminator topology (fewer periods/scales for
    cheap experiments; the defaults are the reference recipe)."""
    n_mels = cfg.preprocess.preprocessing.mel.n_mel_channels
    gen = gen if gen is not None else Generator()
    mpd = mpd if mpd is not None else MultiPeriodDiscriminator()
    msd = msd if msd is not None else MultiScaleDiscriminator()
    k1, k2, k3 = jax.random.split(rng, 3)
    seg = hp.segment_size
    hop = cfg.preprocess.preprocessing.stft.hop_length
    if gen_params is None:
        gen_params = gen.init(k1, jnp.zeros((1, seg // hop, n_mels)))["params"]
    wav0 = jnp.zeros((1, seg))
    mpd_params = mpd.init(k2, wav0, wav0)["params"]
    msd_vars = msd.init(k3, wav0, wav0)
    msd_params = msd_vars["params"]
    msd_stats = msd_vars["batch_stats"]

    schedule = optax.exponential_decay(
        hp.learning_rate, hp.lr_decay_steps, hp.lr_decay, staircase=True
    )
    # weight_decay pinned to torch AdamW's default (0.01): optax.adamw
    # defaults to 1e-4, which would silently diverge from the reference's
    # HiFi-GAN recipe (hifigan/train.py AdamW with torch defaults).
    mk_opt = lambda: optax.adamw(
        schedule, b1=hp.adam_b1, b2=hp.adam_b2, weight_decay=0.01
    )
    gen_tx, disc_tx = mk_opt(), mk_opt()
    state = VocoderState(
        step=jnp.zeros((), jnp.int32),
        gen_params=gen_params,
        mpd_params=mpd_params,
        msd_params=msd_params,
        msd_stats=msd_stats,
        gen_opt=gen_tx.init(gen_params),
        disc_opt=disc_tx.init({"mpd": mpd_params, "msd": msd_params}),
    )
    return state, gen, mpd, msd, gen_tx, disc_tx


def make_vocoder_train_step(cfg: Config, hp: VocoderHParams, gen, mpd, msd,
                            gen_tx, disc_tx, mesh=None):
    """jitted fn(state, wavs [B,S], mels [B,S/hop,M]) -> (state, metrics)."""
    mel_fn = differentiable_mel(cfg)

    def step_fn(state: VocoderState, wavs, mels):
        y_hat = gen.apply({"params": state.gen_params}, mels)
        y_hat = y_hat[:, : wavs.shape[1]]

        # --- discriminator step (y_hat detached via stop_gradient) ---
        y_hat_d = jax.lax.stop_gradient(y_hat)

        def disc_loss_fn(dparams):
            pr, pg, _, _ = mpd.apply({"params": dparams["mpd"]}, wavs, y_hat_d)
            # power-iteration update (torch spectral_norm updates u on
            # every train-mode forward); u/sigma are non-trainable, so
            # they ride out of the grad as aux
            (sr_, sg, _, _), new_stats = msd.apply(
                {"params": dparams["msd"], "batch_stats": state.msd_stats},
                wavs, y_hat_d, update_stats=True, mutable=["batch_stats"],
            )
            loss = discriminator_loss(pr, pg) + discriminator_loss(sr_, sg)
            return loss, new_stats["batch_stats"]

        dparams = {"mpd": state.mpd_params, "msd": state.msd_params}
        (d_loss, msd_stats), d_grads = jax.value_and_grad(
            disc_loss_fn, has_aux=True
        )(dparams)
        d_updates, disc_opt = disc_tx.update(d_grads, state.disc_opt, dparams)
        dparams = optax.apply_updates(dparams, d_updates)

        # --- generator step (against the UPDATED discriminators, matching
        # the reference's sequential optimizer ordering) ---
        def gen_loss_fn(gparams):
            y_g = gen.apply({"params": gparams}, mels)[:, : wavs.shape[1]]
            mel_g = mel_fn(y_g)
            mel_r = mel_fn(wavs)
            T = min(mel_g.shape[1], mels.shape[1])
            loss_mel = jnp.mean(jnp.abs(mel_r[:, :T] - mel_g[:, :T]))
            _, pg, pf_r, pf_g = mpd.apply({"params": dparams["mpd"]}, wavs, y_g)
            # update_stats=True, like torch: spectral_norm recomputes sigma
            # (and steps u) on EVERY train-mode forward, including the
            # generator pass — and the MSD params just changed in the
            # discriminator optimizer step, so stale sigma would normalize
            # W_new by sigma(W_old)
            (_, sg, sf_r, sf_g), new_stats = msd.apply(
                {"params": dparams["msd"], "batch_stats": msd_stats},
                wavs, y_g, update_stats=True, mutable=["batch_stats"],
            )
            loss_adv = generator_adversarial_loss(pg) + generator_adversarial_loss(sg)
            loss_fm = feature_matching_loss(pf_r, pf_g) + feature_matching_loss(
                sf_r, sf_g
            )
            total = loss_adv + loss_fm + hp.mel_loss_weight * loss_mel
            return total, (loss_mel, loss_adv, loss_fm,
                           new_stats["batch_stats"])

        (g_loss, (loss_mel, loss_adv, loss_fm, msd_stats)), g_grads = (
            jax.value_and_grad(gen_loss_fn, has_aux=True)(state.gen_params)
        )
        g_updates, gen_opt = gen_tx.update(
            g_grads, state.gen_opt, state.gen_params
        )
        gen_params = optax.apply_updates(state.gen_params, g_updates)

        new_state = VocoderState(
            step=state.step + 1,
            gen_params=gen_params,
            mpd_params=dparams["mpd"],
            msd_params=dparams["msd"],
            msd_stats=msd_stats,
            gen_opt=gen_opt,
            disc_opt=disc_opt,
        )
        metrics = {
            "disc_loss": d_loss,
            "gen_loss": g_loss,
            "mel_l1": loss_mel,
            "adv_loss": loss_adv,
            "fm_loss": loss_fm,
        }
        return new_state, metrics

    if mesh is None:
        return jit_program(step_fn, donate_argnums=(0,))
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    return jit_program(
        step_fn,
        in_shardings=(repl, data, data),
        out_shardings=(repl, repl),
        donate_argnums=(0,),
    )


def save_vocoder(path: str, state: VocoderState):
    """g_/do_-style checkpoint: generator params + full GAN state
    (reference: hifigan/train.py:158-176)."""
    import os

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(jax.device_get(state)))
    gen_path = path + ".generator.msgpack"
    with open(gen_path, "wb") as f:
        f.write(serialization.to_bytes(jax.device_get(state.gen_params)))
    return gen_path


def restore_vocoder(path: str, state: VocoderState) -> VocoderState:
    """Restore a full GAN state checkpoint into ``state``'s structure.

    Tolerant of exactly ONE kind of structure drift: checkpoints saved
    before the r4 spectral-norm addition, recognized by ``msd_stats``
    being absent from the raw msgpack dict (their first MSD scale's param
    subtree also differs). For those, the MSD-side fields fall back to
    their freshly-initialized values with a warning naming each failed
    field and its underlying error. Any other structural mismatch (e.g. a
    checkpoint from a different discriminator topology) is a hard error —
    silently training a fresh discriminator against a restored generator
    under a restored step counter would masquerade as a resume."""
    with open(path, "rb") as f:
        data = f.read()
    try:
        return serialization.from_bytes(state, data)
    except (ValueError, KeyError):
        raw = serialization.msgpack_restore(data)
        # the actual pre-r4 signature, not just "something didn't match"
        pre_r4 = "msd_stats" not in raw
        tolerated = {"msd_stats", "msd_params", "disc_opt"} if pre_r4 else set()
        restored, kept_fresh = {}, []
        for name in state._fields:
            fresh = getattr(state, name)
            try:
                restored[name] = serialization.from_state_dict(
                    fresh, raw[name]
                )
            except (ValueError, KeyError) as e:
                if name not in tolerated:
                    raise ValueError(
                        f"checkpoint {path} does not match the current "
                        f"VocoderState layout: field {name!r} failed to "
                        f"restore ({type(e).__name__}: {e}). This is not a "
                        "pre-r4 checkpoint (msd_stats "
                        f"{'missing' if pre_r4 else 'present'}), so no "
                        "tolerant fallback applies."
                    ) from e
                restored[name] = fresh
                kept_fresh.append((name, f"{type(e).__name__}: {e}"))
        for name, err in kept_fresh:
            print(
                f"[restore_vocoder] {path}: field {name!r} kept "
                f"freshly-initialized ({err})"
            )
        print(
            f"[restore_vocoder] checkpoint {path} predates the r4 MSD "
            f"spectral-norm state; kept fresh: {[n for n, _ in kept_fresh]}"
        )
        return VocoderState(**restored)


def train_vocoder(
    cfg: Config,
    wav_paths,
    hp: VocoderHParams = VocoderHParams(),
    max_steps: int = 1000,
    batch_size: int = 16,
    mesh=None,
    ckpt_path: Optional[str] = None,
    save_every: int = 1000,
    log_every: int = 100,
    fine_tune_mel_dir: Optional[str] = None,
    gen_params: Optional[Dict] = None,
    seed: int = 1234,
    restore_path: Optional[str] = None,
    gen: Optional[Generator] = None,
    mpd: Optional[MultiPeriodDiscriminator] = None,
    msd: Optional[MultiScaleDiscriminator] = None,
):
    """The full vocoder GAN loop (reference: hifigan/train.py:24-267).

    ``restore_path`` resumes a previous run from a full-state checkpoint
    (save_vocoder's .msgpack); the loop continues from the restored
    ``state.step`` up to ``max_steps`` total.

    Shares run_training's fault-tolerance layer (training/resilience.py,
    config ``cfg.train.resilience``): SIGTERM/SIGINT flush a final
    checkpoint, a final save always lands at loop end, non-finite metrics
    at a log boundary roll back to the last saved .msgpack with a
    diverged segment stream (abort past ``max_rollbacks`` consecutive
    trips), and ``SPEAKINGSTYLE_FAULTS`` injects nan_grads/sigterm drills
    (training/faults.py)."""
    from speakingstyle_tpu.data.mel_dataset import MelWavDataset
    from speakingstyle_tpu.training import faults, resilience

    res = cfg.train.resilience
    plan = faults.FaultPlan.from_env()

    state, gen, mpd, msd, gen_tx, disc_tx = init_vocoder_state(
        cfg, hp, jax.random.PRNGKey(seed), gen_params=gen_params,
        gen=gen, mpd=mpd, msd=msd,
    )
    if restore_path:
        state = restore_vocoder(restore_path, state)
        print(f"[vocoder] restored step {int(state.step)} from {restore_path}")
    # host-side structural template: stays valid after donation consumes
    # the live device buffers (rollback restores re-use its structure)
    template = jax.device_get(state)
    if mesh is not None:
        state = jax.device_put(state, NamedSharding(mesh, P()))
    train_step = make_vocoder_train_step(
        cfg, hp, gen, mpd, msd, gen_tx, disc_tx, mesh=mesh
    )

    def make_stream(retry: int):
        # fold the restored step AND the rollback retry counter into the
        # dataset seed: a resumed run draws a fresh batch/segment stream
        # instead of replaying the original run's sequence, and a rolled-
        # back run diverges past the window that tripped the sentinel
        return iter(MelWavDataset(
            wav_paths, cfg, segment_size=hp.segment_size,
            batch_size=batch_size, fine_tune_mel_dir=fine_tune_mel_dir,
            seed=seed + int(state.step) + 7919 * retry,
        ))

    stream = make_stream(0)
    guard = resilience.RollbackGuard(res.max_rollbacks)
    last_ckpt_file = restore_path
    last_saved_step = int(state.step) if restore_path else None
    step = int(state.step)
    metrics = {}
    with resilience.GracefulShutdown() as shutdown:
        while step < max_steps and not shutdown.requested:
            try:
                wavs, mels = next(stream)
            except StopIteration:
                break
            wavs = jnp.asarray(wavs)
            if plan.fire("nan_grads", step + 1):
                wavs = wavs * jnp.float32(jnp.nan)
            state, metrics = train_step(state, wavs, jnp.asarray(mels))
            step += 1
            if plan.fire("sigterm", step):
                faults.deliver_sigterm()
            if step % log_every == 0:
                # host boundary: metrics materialize for logging anyway
                vals = {k: float(v) for k, v in metrics.items()}
                if res.nan_sentinel and not all(
                    np.isfinite(v) for v in vals.values()
                ):
                    n = guard.trip(step)  # raises past max_rollbacks
                    print(
                        f"[vocoder] non-finite metrics at step {step}; "
                        f"rollback {n}/{res.max_rollbacks} to "
                        + (last_ckpt_file or "fresh init (no checkpoint yet)")
                    )
                    if last_ckpt_file:
                        state = restore_vocoder(last_ckpt_file, template)
                    else:
                        state = jax.device_put(template)
                    if mesh is not None:
                        state = jax.device_put(state, NamedSharding(mesh, P()))
                    step = int(state.step)  # jaxlint: disable=JL004
                    stream = make_stream(guard.count)
                    continue
                guard.ok()
                msg = ", ".join(f"{k}: {v:.4f}" for k, v in vals.items())
                print(f"[vocoder] step {step}: {msg}")
            if ckpt_path and step % save_every == 0:
                last_ckpt_file = f"{ckpt_path}/vocoder_{step:08d}.msgpack"
                save_vocoder(last_ckpt_file, state)
                last_saved_step = step
        # always flush a final checkpoint: tail steps (max_steps not
        # divisible by save_every) and the SIGTERM/SIGINT preemption path
        if ckpt_path and step > 0 and last_saved_step != step:
            last_ckpt_file = f"{ckpt_path}/vocoder_{step:08d}.msgpack"
            save_vocoder(last_ckpt_file, state)
            last_saved_step = step
        if shutdown.requested:
            print(
                f"[vocoder] {shutdown.signame}: checkpoint flushed at step "
                f"{step} ({last_ckpt_file or 'no ckpt_path set'}); exiting"
            )
    return state, metrics
