"""Typed, validated configuration system.

Replaces the reference's raw-YAML-triple plumbing (reference:
train.py:176-200 passes three untyped dicts positionally) with frozen
dataclasses. The three-file split (preprocess/model/train) and per-dataset
presets are preserved so reference configs remain readable, but every key is
schema-checked at load time — the config-drift crashes catalogued in
SURVEY.md §2.5 become load-time errors here.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import yaml

PRESET_DIR = os.path.join(os.path.dirname(__file__), "presets")


def _build(cls, data: Dict[str, Any], path: str = ""):
    """Recursively build a dataclass from a nested dict, rejecting unknown keys."""
    if data is None:
        data = {}
    import typing

    hints = typing.get_type_hints(cls)
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(data) - names
    if unknown:
        raise ValueError(f"Unknown config keys at {path or cls.__name__}: {sorted(unknown)}")
    kwargs = {}
    for name in names:
        if name not in data:
            continue
        value = data[name]
        ftype = hints.get(name)
        if dataclasses.is_dataclass(ftype) and isinstance(value, dict):
            value = _build(ftype, value, f"{path}.{name}" if path else name)
        kwargs[name] = value
    return cls(**kwargs)


# ---------------------------------------------------------------------------
# preprocess.yaml
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class PathConfig:
    corpus_path: str = ""
    lexicon_path: str = ""
    raw_path: str = ""
    preprocessed_path: str = ""


@dataclass(frozen=True)
class TextConfig:
    text_cleaners: List[str] = field(default_factory=lambda: ["english_cleaners"])
    language: str = "en"


@dataclass(frozen=True)
class AudioConfig:
    sampling_rate: int = 22050
    max_wav_value: float = 32768.0


@dataclass(frozen=True)
class STFTConfig:
    filter_length: int = 1024
    hop_length: int = 256
    win_length: int = 1024


@dataclass(frozen=True)
class MelConfig:
    n_mel_channels: int = 80
    mel_fmin: float = 0.0
    mel_fmax: Optional[float] = 8000.0


@dataclass(frozen=True)
class VarianceFeatureConfig:
    feature: str = "phoneme_level"  # or "frame_level"
    normalization: bool = True

    def __post_init__(self):
        if self.feature not in ("phoneme_level", "frame_level"):
            raise ValueError(f"feature must be phoneme_level|frame_level, got {self.feature}")


@dataclass(frozen=True)
class PreprocessingConfig:
    val_size: int = 512
    text: TextConfig = field(default_factory=TextConfig)
    audio: AudioConfig = field(default_factory=AudioConfig)
    stft: STFTConfig = field(default_factory=STFTConfig)
    mel: MelConfig = field(default_factory=MelConfig)
    pitch: VarianceFeatureConfig = field(default_factory=VarianceFeatureConfig)
    energy: VarianceFeatureConfig = field(default_factory=VarianceFeatureConfig)


@dataclass(frozen=True)
class PreprocessConfig:
    dataset: str = "LJSpeech"
    path: PathConfig = field(default_factory=PathConfig)
    preprocessing: PreprocessingConfig = field(default_factory=PreprocessingConfig)


# ---------------------------------------------------------------------------
# model.yaml
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TransformerConfig:
    encoder_layer: int = 4
    encoder_head: int = 2
    encoder_hidden: int = 256
    decoder_layer: int = 6
    decoder_head: int = 2
    decoder_hidden: int = 256
    conv_filter_size: int = 1024
    conv_kernel_size: Tuple[int, int] = (9, 1)
    encoder_dropout: float = 0.2
    decoder_dropout: float = 0.2


@dataclass(frozen=True)
class ReferenceEncoderConfig:
    encoder_layer: int = 4
    encoder_head: int = 8
    encoder_hidden: int = 256
    conv_layer: int = 3
    conv_filter_size: int = 1024
    conv_kernel_size: int = 3
    dropout: float = 0.1


@dataclass(frozen=True)
class VariancePredictorConfig:
    filter_size: int = 256
    kernel_size: int = 3
    dropout: float = 0.5


@dataclass(frozen=True)
class VarianceEmbeddingConfig:
    pitch_quantization: str = "linear"  # "linear" | "log"
    energy_quantization: str = "linear"
    n_bins: int = 256

    def __post_init__(self):
        for q in (self.pitch_quantization, self.energy_quantization):
            if q not in ("linear", "log"):
                raise ValueError(f"quantization must be linear|log, got {q}")


@dataclass(frozen=True)
class VocoderConfig:
    model: str = "HiFi-GAN"
    speaker: str = "LJSpeech"


@dataclass(frozen=True)
class ModelConfig:
    transformer: TransformerConfig = field(default_factory=TransformerConfig)
    reference_encoder: ReferenceEncoderConfig = field(default_factory=ReferenceEncoderConfig)
    variance_predictor: VariancePredictorConfig = field(default_factory=VariancePredictorConfig)
    variance_embedding: VarianceEmbeddingConfig = field(default_factory=VarianceEmbeddingConfig)
    multi_speaker: bool = False
    max_seq_len: int = 1000
    vocoder: VocoderConfig = field(default_factory=VocoderConfig)
    # postnet topology (reference hardcodes 512/5/5 — model/modules.py);
    # exposed so scaled-down configs (tests, the CPU serve bench) shrink
    # the whole model, not all-but-the-postnet
    postnet_embedding_dim: int = 512
    postnet_kernel_size: int = 5
    postnet_layers: int = 5
    # TPU-specific knobs (no reference counterpart):
    compute_dtype: str = "bfloat16"  # activations/matmul dtype under jit
    # conv1d lowering for the FLOP-dominant conv stacks (ops/conv.py):
    # "xla" = lax.conv emitter, "unfold" = im2col GEMM (one large MXU
    # matmul per conv), "pallas" = fused conv+bias+ReLU(+LN) kernel
    # (ops/pallas_conv.py). Param trees are identical — switchable on a
    # restored checkpoint. Default set by the r4 on-chip A/B (PERF.md):
    # the XLA conv emitter measured fastest end-to-end on v5e (325k
    # frames/s vs unfold's 265k — the im2col operand's extra HBM traffic
    # costs more than the cleaner GEMM tiling saves on these shapes).
    conv_impl: str = "xla"
    # softmax accumulation dtype in attention: "float32" (reference-parity
    # default) or "bfloat16" (A/B candidate; attention is <1% of step
    # FLOPs so this mostly saves VPU/memory traffic)
    attention_softmax_dtype: str = "float32"
    use_reference_encoder: bool = True
    # attention lowering for the dense path: "fused" (default —
    # ops/pallas_attention.py: one VMEM pass per (batch, head), f32
    # softmax in-register; measured ~1.7x faster fwd+bwd at paper shapes)
    # or "einsum" (XLA, materializes [B, H, L, L] scores in HBM — the
    # literal transcription of the reference math). "fused" engages only
    # on TPU hardware with L <= 1024 / head_dim <= 128 and falls back to
    # einsum elsewhere (CPU tests and parity runs always exercise einsum
    # numerics). Parameter-free, so switchable on a restored checkpoint.
    # Sharding: the kernel carries a custom_partitioning batch rule —
    # without it GSPMD ALL-GATHERS the operands of a custom call.
    # Validated: zero all-gathers + batch-sharded grads in the
    # 8-device-mesh HLO
    # (tests/test_parallel.py::test_fused_attention_batch_partitioned_*),
    # loss parity with einsum under the data-sharded train step, and
    # hardware execution on the 1-chip mesh (PERF.md).
    attention_kernel: str = "fused"
    # "dense" or "ring": ring engages sequence-parallel exact attention
    # (parallel/ring_attention.py) in the encoder/decoder FFT stacks for
    # inference beyond max_seq_len — build the model with a seq mesh
    # (models/factory.build_model(..., seq_mesh=...)); sequence lengths
    # must divide by the mesh's seq axis.
    attention_impl: str = "dense"
    # dropout mask generation (ops/dropout.py): "hash" (default — salted
    # murmur3 counter hash, pure elementwise so XLA fuses it into the
    # consumer; zero RNG-bit HBM traffic; measured -71..-286 us/site vs
    # bernoulli on v5e, scripts/exp_dropout_r5.py), "bernoulli"
    # (jax.random, what nn.Dropout does — the reference-parity RNG
    # stream), or "bits16" (raw 16-bit threshold compare; measured worse
    # than bernoulli — the bitcast defeats fusion; kept as the recorded
    # negative). Mask distribution is identical across impls (inverted
    # dropout, P(keep)=1-rate); only the PRNG stream differs, so this is
    # switchable on a restored checkpoint.
    dropout_impl: str = "hash"

    def __post_init__(self):
        if self.attention_impl not in ("dense", "ring"):
            raise ValueError(
                f"attention_impl must be dense|ring, got {self.attention_impl}"
            )
        if self.dropout_impl not in ("bernoulli", "bits16", "hash"):
            raise ValueError(
                f"dropout_impl must be bernoulli|bits16|hash, "
                f"got {self.dropout_impl}"
            )
        if self.conv_impl not in ("xla", "unfold", "pallas"):
            raise ValueError(
                f"conv_impl must be xla|unfold|pallas, got {self.conv_impl}"
            )
        if self.attention_kernel not in ("einsum", "fused"):
            raise ValueError(
                f"attention_kernel must be einsum|fused, got {self.attention_kernel}"
            )
        if self.attention_softmax_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                "attention_softmax_dtype must be float32|bfloat16, "
                f"got {self.attention_softmax_dtype}"
            )
        if self.attention_impl == "ring" and self.attention_softmax_dtype != "float32":
            # the ring path accumulates its running softmax in f32 by design
            # (parallel/ring_attention.py); a bf16 label would misreport A/Bs
            raise ValueError(
                'attention_impl="ring" supports only '
                'attention_softmax_dtype="float32"'
            )


# ---------------------------------------------------------------------------
# train.yaml
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class OptimizerConfig:
    batch_size: int = 16
    betas: Tuple[float, float] = (0.9, 0.98)
    eps: float = 1e-9
    weight_decay: float = 0.0
    grad_clip_thresh: float = 1.0
    grad_acc_step: int = 1
    warm_up_step: int = 4000  # vestigial in the reference; kept for config parity
    anneal_steps: List[int] = field(default_factory=lambda: [300000, 400000, 500000])
    anneal_rate: float = 0.3
    init_lr: float = 1e-4
    anneal_lr: float = 1e-3


@dataclass(frozen=True)
class StepConfig:
    total_step: int = 900000
    log_step: int = 100
    synth_step: int = 1000
    val_step: int = 1000
    save_step: int = 1000


@dataclass(frozen=True)
class LossConfig:
    lambda_f: float = 0.0  # FiLM-gate L2 weight (reference: model/loss.py:20,84-89)
    anneal_steps: int = 10000  # LR ramp length (reference: model/optimizer.py:17,37-44)


@dataclass(frozen=True)
class TrainPathConfig:
    ckpt_path: str = "./output/ckpt"
    log_path: str = "./output/log"
    result_path: str = "./output/result"


@dataclass(frozen=True)
class ShardingConfig:
    """TPU mesh layout (no reference counterpart; replaces nn.DataParallel).

    Legacy block: ``train.parallel`` (ParallelConfig) is the multichip
    contract now; this survives for old YAML and the
    ``--data_parallel``/``--model_parallel`` CLI flags, which map onto the
    same mesh resolution in ``cli/train.py``."""

    data_axis: int = -1  # -1: all devices on the data axis
    model_axis: int = 1  # tensor-parallel degree (1 = pure DP)
    remat: bool = False  # jax.checkpoint the FFT stacks


@dataclass(frozen=True)
class ParallelConfig:
    """Multichip mesh layout (``parallel/mesh.py`` /
    ``parallel/partition.py`` — ARCHITECTURE.md "Multichip training").
    Used twice: ``train.parallel`` shapes the trainer's mesh,
    ``serve.parallel`` shapes one serving replica's mesh slice.

    ``mesh = [dp, tp]`` names the 2-D device mesh: batches shard over the
    ``data`` axis (dp-way), parameters shard over the ``model`` axis
    (tp-way, Megatron-style column/row rules). The default ``[1, 1]`` is
    the single-chip path — ``resolve_mesh`` returns ``None`` and the
    trainer behaves exactly as before. ``dp = -1`` consumes all devices
    not claimed by ``tp``.
    """

    # [dp, tp]: data-parallel x tensor-parallel degree. [1, 1] = single
    # chip (mesh path disengaged); dp = -1 = all remaining devices
    mesh: List[int] = field(default_factory=lambda: [1, 1])
    # sequence-parallel axis for ring attention (long-context training);
    # 1 = off. Engages attention_impl="ring" semantics; the mesh then
    # needs dp*tp*seq devices.
    seq: int = 1
    # partition-rule overrides PREPENDED to DEFAULT_TP_RULES (first match
    # wins): each entry is [path_regex, axes] where axes is a
    # comma-separated per-dim list of mesh axis names or "none", e.g.
    # ["encoder_emb/embedding$", "none,model"] -> P(None, "model")
    partition_rules: List[List[str]] = field(default_factory=list)

    def __post_init__(self):
        if len(self.mesh) != 2:
            raise ValueError(
                f"parallel.mesh must be [dp, tp], got {self.mesh}"
            )
        dp, tp = self.mesh
        if tp < 1:
            raise ValueError(f"parallel.mesh tp must be >= 1, got {tp}")
        if dp < 1 and dp != -1:
            raise ValueError(
                f"parallel.mesh dp must be >= 1 (or -1 for all "
                f"remaining devices), got {dp}"
            )
        if self.seq < 1:
            raise ValueError(f"parallel.seq must be >= 1, got {self.seq}")
        import re as _re

        for rule in self.partition_rules:
            if len(rule) != 2 or not all(isinstance(s, str) for s in rule):
                raise ValueError(
                    "parallel.partition_rules entries must be "
                    f"[path_regex, axes] string pairs, got {rule!r}"
                )
            pattern, axes = rule
            try:
                _re.compile(pattern)
            except _re.error as e:
                raise ValueError(
                    f"parallel.partition_rules regex {pattern!r}: {e}"
                )
            for tok in axes.split(","):
                if tok.strip().lower() not in ("", "none", "data", "model", "seq"):
                    raise ValueError(
                        f"parallel.partition_rules axes token {tok!r} "
                        "must be one of none|data|model|seq"
                    )

    @property
    def dp(self) -> int:
        return self.mesh[0]

    @property
    def tp(self) -> int:
        return self.mesh[1]

    def is_single(self) -> bool:
        """True iff this config keeps the single-chip train path."""
        return tuple(self.mesh) == (1, 1) and self.seq == 1


@dataclass(frozen=True)
class ResilienceConfig:
    """Fault-tolerance knobs (training/resilience.py — no reference
    counterpart; the reference loop dies on the first bad sample and
    loses up to save_step steps on preemption).

    See the "Resilience" section of ARCHITECTURE.md for the fault model
    and the ``SPEAKINGSTYLE_FAULTS`` injection spec grammar."""

    # checkpoint saves run on a background thread (the step loop never
    # blocks on Orbax I/O); the device->host snapshot is still taken
    # synchronously so buffer donation cannot invalidate an in-flight save
    async_checkpointing: bool = True
    # retain the newest N step checkpoints; 0 keeps everything
    max_to_keep: int = 5
    # never prune the best-val-loss step, even past max_to_keep
    keep_best: bool = True
    # fold an all-finite reduction over losses+grads into the jitted step
    # and check it host-side at the log boundary; on trip, roll back to
    # the last good checkpoint with a diverged data stream
    nan_sentinel: bool = True
    # abort with TrainingDivergedError after this many CONSECUTIVE
    # rollbacks (a finite check window resets the counter)
    max_rollbacks: int = 3
    # feature-loader retry-with-exponential-backoff on transient I/O errors
    loader_retries: int = 3
    loader_backoff: float = 0.05  # seconds; doubles per attempt
    # samples that still fail after retries are quarantined (logged +
    # skipped); the run fails only past this many distinct bad samples
    bad_sample_budget: int = 16

    def __post_init__(self):
        if self.max_to_keep < 0:
            raise ValueError(f"max_to_keep must be >= 0, got {self.max_to_keep}")
        if self.max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be >= 0, got {self.max_rollbacks}"
            )
        if self.loader_retries < 0:
            raise ValueError(
                f"loader_retries must be >= 0, got {self.loader_retries}"
            )
        if self.bad_sample_budget < 0:
            raise ValueError(
                f"bad_sample_budget must be >= 0, got {self.bad_sample_budget}"
            )


@dataclass(frozen=True)
class ObsConfig:
    """Telemetry knobs (speakingstyle_tpu/obs/ — ARCHITECTURE.md
    "Observability"). The metrics registry itself is always on (it is
    just in-memory counters); these control the export surfaces."""

    # rotating JSONL event log under train.path.log_path (obs/events.py
    # documents the schema; read it with `python -m speakingstyle_tpu.obs.cli`)
    events: bool = True
    # rotation: shift events.jsonl -> .1 past this size, keep N rotated files
    events_max_bytes: int = 8_000_000
    events_keep: int = 3
    # persistent XLA compilation cache directory ("" = disabled): wired
    # by the ProgramRegistry (parallel/registry.py) each consumer —
    # trainer, serve replicas, style, bench — constructs, so every one
    # of them gets the warm restart uniformly; the jaxmon bridge counts
    # cache hits vs requests per-registry
    # (jax_persistent_cache_{hits,requests}_total) so /metrics
    # distinguishes a warm start from a cold one
    compilation_cache_dir: str = ""
    # build a ProgramCard for the jitted train step after its first
    # compile (obs/cost.py): emits a one-time `program_card` JSONL event
    # and feeds the achieved-FLOP/s histogram + device-memory watermark.
    # Costs ONE extra compile of the step program at startup (a
    # persistent-cache hit when compilation_cache_dir is set); disable on
    # compile-budget-critical runs
    program_card: bool = True

    def __post_init__(self):
        if self.events_max_bytes <= 0:
            raise ValueError(
                f"events_max_bytes must be > 0, got {self.events_max_bytes}"
            )
        if self.events_keep < 1:
            raise ValueError(f"events_keep must be >= 1, got {self.events_keep}")


@dataclass(frozen=True)
class TrainConfig:
    path: TrainPathConfig = field(default_factory=TrainPathConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    step: StepConfig = field(default_factory=StepConfig)
    loss: LossConfig = field(default_factory=LossConfig)
    sharding: ShardingConfig = field(default_factory=ShardingConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    ignore_layers: List[str] = field(default_factory=list)
    seed: int = 1234
    # Use XLA's native RBG bit generator for dropout masks instead of
    # threefry: measured 15% step-time win on v5e (dropout masks over
    # [B,600,1024] tensors dominate threefry's generation cost). No
    # reference counterpart (torch RNG is cuRAND); disable for bit-stable
    # dropout streams across hardware.
    fast_prng: bool = True
    # Run clip+Adam+LR as one fused pass over a single raveled parameter
    # vector (training/optim.py make_fused_optimizer) instead of the
    # per-leaf optax chain: mathematically identical update (parity test
    # in tests/test_training.py), different opt_state layout (flat mu/nu),
    # so checkpoints are not interchangeable with the unfused optimizer.
    # A recorded NEGATIVE result on v5e at 35M params: the ravel/unravel
    # copies cost more than the chain overhead they remove (422.6k vs
    # 442.8k frames/s — see PERF.md), so this stays off by default and is
    # kept as an honest A/B knob.
    # r5 adds "leaf" (training/optim.make_leaf_fused_optimizer): the whole
    # clip+L2+Adam+lr chain as ONE fused expression per param leaf — no
    # ravel copies, no per-stage intermediate trees. True == "flat" for
    # back-compat. All three impls produce bit-identical updates (parity
    # test); opt_state layouts differ, so optimizer checkpoints are not
    # interchangeable across impls.
    fused_optimizer: object = False  # False | True | "flat" | "leaf"

    def __post_init__(self):
        if self.fused_optimizer not in (False, True, "flat", "leaf"):
            raise ValueError(
                "fused_optimizer must be False|True|'flat'|'leaf', "
                f"got {self.fused_optimizer!r}"
            )


# ---------------------------------------------------------------------------
# serve.* — the synthesis server (serving/; no reference counterpart)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FleetConfig:
    """Multi-replica fleet serving knobs (serving/fleet.py,
    serving/streaming.py — ARCHITECTURE.md "Fleet serving & streaming").

    The fleet router runs N replica engines behind one SLO-aware
    admission queue: requests carry a priority class, the queue orders by
    earliest SLO deadline (EDF), and queue-depth watermarks shed load
    with HTTP 429 + Retry-After well before the queue hard-fills —
    distinct from shutdown rejection (``serve_shed_total`` vs
    ``serve_rejected_total``).
    """

    # replica engines behind the router (one per device, or N on one
    # device for the CPU proxy); `cli serve --replicas N` overrides
    replicas: int = 1
    # bounded pending heap the router admits into (EDF-ordered); all
    # serving queues are bounded — backpressure is meaningless otherwise
    # (jaxlint JL011 enforces this structurally for queue.Queue)
    queue_depth: int = 256
    # load-shedding hysteresis as fractions of queue_depth: shedding
    # starts when pending >= high * depth and stops once it drains to
    # <= low * depth (two watermarks so the 429 boundary cannot flap
    # request-by-request)
    shed_high_watermark: float = 0.9
    shed_low_watermark: float = 0.5
    # Retry-After seconds advertised on a 429 shed response
    shed_retry_after_s: float = 1.0
    # priority classes: request "priority" -> SLO completion budget (ms);
    # the router's EDF heap orders by arrival + this budget
    class_deadline_ms: Dict[str, float] = field(
        default_factory=lambda: {"interactive": 250.0, "batch": 2000.0}
    )
    default_class: str = "interactive"
    # chunked streaming synthesis: emit wav in windows of this many mel
    # frames (POST /synthesize/stream); windows ride the precompiled
    # vocoder lattice buckets, never ad-hoc shapes
    stream_window: int = 64
    # mel-frame context vocoded on each side of a window and trimmed
    # from the emitted wav; 0 = derive from the vocoder's receptive
    # field (streaming.receptive_field_frames), which is the smallest
    # overlap that keeps chunk seams bit-exact
    stream_overlap: int = 0
    # vocoder windows in flight per stream: window k+1 is dispatched
    # before window k is collected (JAX async dispatch), so steady-state
    # chunk cadence is max(device window, host trim+emit) instead of
    # their sum; 1 = strictly sequential (the pre-pipeline behavior,
    # bit-identical output)
    stream_depth: int = 2
    # SIGTERM/shutdown waits this long for in-flight streams to finish
    drain_timeout_s: float = 10.0
    # --- resilience (serving/resilience.py, ARCHITECTURE.md "Serving
    # resilience") ---
    # a READY replica whose dispatch has been on-device longer than this
    # is declared hung: the supervisor fails it, requeues its in-flight
    # requests and re-warms it; 0 disables the watchdog
    hang_watchdog_s: float = 10.0
    # per-class retry budget for transient replica failures: a request
    # requeued off a failed replica is retried at most this many times
    # before resolving as ReplicaError (503); classes absent from the
    # map get no retries — streams continuations are never retried
    retry_budget: Dict[str, int] = field(
        default_factory=lambda: {"interactive": 1, "batch": 2}
    )
    # circuit-breaker re-warm backoff: first re-warm after this many
    # seconds, doubling per consecutive failure, capped at the max
    rewarm_backoff_s: float = 0.5
    rewarm_backoff_max_s: float = 30.0
    # grace added on top of the class deadline budget when the HTTP
    # layer bounds future.result(timeout=...) — the deadline is enforced
    # in the router; the grace covers result readback + response writing
    deadline_grace_ms: float = 500.0
    # ceiling for per-request deadline overrides: a request may carry its
    # own deadline_ms (a long-form chapter group's budget scales with its
    # chunk count instead of inheriting the flat class budget); the
    # router clamps any override into (0, max_deadline_ms] so a client
    # cannot park an entry in the EDF heap forever. 0.0 (the default)
    # derives max(120000.0, largest class deadline); an explicit value
    # must be >= every class deadline
    max_deadline_ms: float = 0.0

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError(f"fleet.replicas must be >= 1, got {self.replicas}")
        if self.queue_depth <= 0:
            raise ValueError(
                f"fleet.queue_depth must be > 0, got {self.queue_depth}"
            )
        if not (0.0 < self.shed_low_watermark <= self.shed_high_watermark <= 1.0):
            raise ValueError(
                "fleet watermarks must satisfy 0 < low <= high <= 1, got "
                f"low={self.shed_low_watermark} high={self.shed_high_watermark}"
            )
        if not self.class_deadline_ms:
            raise ValueError("fleet.class_deadline_ms must be non-empty")
        for name, ms in self.class_deadline_ms.items():
            if ms <= 0:
                raise ValueError(
                    f"fleet.class_deadline_ms[{name!r}] must be > 0, got {ms}"
                )
        if self.default_class not in self.class_deadline_ms:
            raise ValueError(
                f"fleet.default_class {self.default_class!r} is not a key of "
                f"class_deadline_ms {sorted(self.class_deadline_ms)}"
            )
        if self.stream_window <= 0:
            raise ValueError(
                f"fleet.stream_window must be > 0, got {self.stream_window}"
            )
        if self.stream_overlap < 0:
            raise ValueError(
                f"fleet.stream_overlap must be >= 0, got {self.stream_overlap}"
            )
        if self.stream_depth < 1:
            raise ValueError(
                f"fleet.stream_depth must be >= 1, got {self.stream_depth}"
            )
        if self.drain_timeout_s < 0:
            raise ValueError(
                f"fleet.drain_timeout_s must be >= 0, got {self.drain_timeout_s}"
            )
        if self.hang_watchdog_s < 0:
            raise ValueError(
                f"fleet.hang_watchdog_s must be >= 0 (0 disables), got "
                f"{self.hang_watchdog_s}"
            )
        for name, n in self.retry_budget.items():
            if n < 0:
                raise ValueError(
                    f"fleet.retry_budget[{name!r}] must be >= 0, got {n}"
                )
        if self.rewarm_backoff_s <= 0:
            raise ValueError(
                f"fleet.rewarm_backoff_s must be > 0, got {self.rewarm_backoff_s}"
            )
        if self.rewarm_backoff_max_s < self.rewarm_backoff_s:
            raise ValueError(
                "fleet.rewarm_backoff_max_s must be >= rewarm_backoff_s, got "
                f"{self.rewarm_backoff_max_s} < {self.rewarm_backoff_s}"
            )
        if self.deadline_grace_ms < 0:
            raise ValueError(
                f"fleet.deadline_grace_ms must be >= 0, got "
                f"{self.deadline_grace_ms}"
            )
        if self.max_deadline_ms < 0:
            raise ValueError(
                f"fleet.max_deadline_ms must be >= 0 (0 = derive), got "
                f"{self.max_deadline_ms}"
            )
        if self.max_deadline_ms == 0.0:
            object.__setattr__(
                self, "max_deadline_ms",
                max(120000.0, max(self.class_deadline_ms.values())),
            )
        elif self.max_deadline_ms < max(self.class_deadline_ms.values()):
            raise ValueError(
                "fleet.max_deadline_ms must be >= every class deadline "
                f"(it is the override ceiling), got {self.max_deadline_ms} "
                f"< max of {self.class_deadline_ms}"
            )


@dataclass(frozen=True)
class AutoscaleConfig:
    """Closed-loop fleet autoscaler knobs (serving/autoscale.py —
    ARCHITECTURE.md "Autoscaling & traffic model").

    Disabled by default: with ``enabled: false`` nothing changes — the
    replica count stays wherever ``scale_to()`` last put it. Enabled, a
    policy thread watches the signals the router already exports
    (pending-heap depth vs the shed watermarks, shed/deadline-miss
    rates, per-replica dispatch occupancy) and drives ``scale_to()``
    inside ``[min_replicas, max_replicas]`` with hysteresis and
    cooldowns. The scale-up cost model is MEASURED, not assumed: the
    ``serve_replica_warmup_seconds`` histogram (sampled from actual
    replica warm-ups through the persistent compile cache) stretches
    both the post-scale-up cooldown and the calm window required before
    shedding capacity again.
    """

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 4
    # policy tick period; the loop is a stop-aware Event.wait, never a
    # bare time.sleep (jaxlint JL016), so drain/shutdown is not blocked
    interval_s: float = 0.25
    # -- scale-up triggers (any one fires) --
    # pending-heap depth as a fraction of fleet.queue_depth; sits below
    # shed_high_watermark on purpose — capacity should grow BEFORE the
    # router starts shedding
    up_queue_fraction: float = 0.5
    # instantaneous busy fraction of READY replicas; only fires with a
    # backlog at least one-deep per live replica (floor 2) SUSTAINED
    # for a full tick — a single mid-dispatch snapshot is not pressure
    up_occupancy: float = 0.9
    # shed + deadline-miss events per second over the last tick
    up_pressure_rate: float = 1.0
    # -- scale-down (all must hold, sustained) --
    down_queue_fraction: float = 0.05
    down_occupancy: float = 0.5
    # calm must persist this long (stretched by the measured warm-up
    # cost, see warmup_cost_factor) before one replica is drained
    down_stable_s: float = 5.0
    # -- hysteresis / bounds --
    cooldown_up_s: float = 2.0
    cooldown_down_s: float = 10.0
    # replicas added per scale-up decision at extreme pressure (depth
    # past twice the up watermark); ordinary pressure adds one
    max_step: int = 2
    # cost model: assumed warm-up seconds until the first measured
    # sample lands in serve_replica_warmup_seconds
    assumed_warmup_s: float = 10.0
    # the calm window before a scale-down is max(down_stable_s,
    # warmup_cost_factor * measured-warmup): capacity that was expensive
    # to warm is held longer against oscillating load
    warmup_cost_factor: float = 1.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"autoscale.min_replicas must be >= 1, got {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                "autoscale.max_replicas must be >= min_replicas, got "
                f"{self.max_replicas} < {self.min_replicas}"
            )
        if self.interval_s <= 0:
            raise ValueError(
                f"autoscale.interval_s must be > 0, got {self.interval_s}"
            )
        if not (0.0 < self.up_queue_fraction <= 1.0):
            raise ValueError(
                "autoscale.up_queue_fraction must be in (0, 1], got "
                f"{self.up_queue_fraction}"
            )
        if not (0.0 <= self.down_queue_fraction < self.up_queue_fraction):
            raise ValueError(
                "autoscale.down_queue_fraction must satisfy 0 <= down < "
                f"up_queue_fraction, got {self.down_queue_fraction}"
            )
        if not (0.0 < self.up_occupancy <= 1.0):
            raise ValueError(
                "autoscale.up_occupancy must be in (0, 1], got "
                f"{self.up_occupancy}"
            )
        if not (0.0 <= self.down_occupancy < self.up_occupancy):
            raise ValueError(
                "autoscale.down_occupancy must satisfy 0 <= down < "
                f"up_occupancy, got {self.down_occupancy}"
            )
        if self.up_pressure_rate < 0:
            raise ValueError(
                "autoscale.up_pressure_rate must be >= 0, got "
                f"{self.up_pressure_rate}"
            )
        for name in ("down_stable_s", "cooldown_up_s", "cooldown_down_s",
                     "warmup_cost_factor"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"autoscale.{name} must be >= 0, got {getattr(self, name)}"
                )
        if self.max_step < 1:
            raise ValueError(
                f"autoscale.max_step must be >= 1, got {self.max_step}"
            )
        if self.assumed_warmup_s <= 0:
            raise ValueError(
                "autoscale.assumed_warmup_s must be > 0, got "
                f"{self.assumed_warmup_s}"
            )


@dataclass(frozen=True)
class StyleConfig:
    """Style-service knobs (serving/style.py — ARCHITECTURE.md "Style
    service").

    The reference encoder runs as its own AOT-precompiled subsystem over
    a ``(batch, ref_len)`` bucket lattice, fronted by a content-addressed
    LRU cache (sha256 of the reference bytes -> FiLM ``(gamma, beta)``
    vectors) so repeat styles never touch the encoder. Decoupling the
    reference length from the synthesis lattice's ``T_mel`` axis is the
    point: a long reference no longer inflates the output bucket.
    """

    # padded reference-mel lengths the style encoder compiles for (the
    # top bucket caps the longest admissible reference)
    ref_buckets: List[int] = field(default_factory=lambda: [256, 512, 1000])
    # encode batch sizes; empty = inherit serve.batch_buckets
    batch_buckets: List[int] = field(default_factory=list)
    # content-addressed LRU entries retained (gamma+beta vectors are a
    # few KB each; bounded by jaxlint JL012's no-unbounded-caches rule)
    cache_capacity: int = 512
    # allowlist directory for server-side "ref_audio" request paths; ""
    # (the default) refuses path-based references entirely — uploads go
    # through POST /styles instead
    ref_dir: str = ""

    def __post_init__(self):
        for name in ("ref_buckets", "batch_buckets"):
            vals = getattr(self, name)
            if name == "ref_buckets" and not vals:
                raise ValueError("serve.style.ref_buckets must be non-empty")
            if any(v <= 0 for v in vals):
                raise ValueError(
                    f"serve.style.{name} must be positive, got {vals}"
                )
            if sorted(vals) != list(vals) or len(set(vals)) != len(vals):
                raise ValueError(
                    f"serve.style.{name} must be strictly ascending, "
                    f"got {vals}"
                )
        if self.cache_capacity <= 0:
            raise ValueError(
                f"serve.style.cache_capacity must be > 0, "
                f"got {self.cache_capacity}"
            )


@dataclass(frozen=True)
class RolloutConfig:
    """Canary-gated rolling model rollout knobs (serving/lifecycle.py —
    ARCHITECTURE.md "Model lifecycle").

    A rollout verifies the new checkpoint's manifest, warms ONE canary
    replica on the new weights, replays a seeded golden set through the
    canary's AOT lattice (all-finite + mean-|Δmel| parity against the
    live version), and only then drain-replaces the remaining replicas
    one at a time. Any failure before commit aborts with the fleet
    untouched.
    """

    # gate POST /admin/rollout (and the RolloutManager wiring) — OFF by
    # default: a mutating admin surface must be opted into
    enabled: bool = False
    # golden-set size replayed through BOTH versions at the canary gate
    golden_set_size: int = 4
    # rng seed for the generated golden set (deterministic across runs)
    canary_seed: int = 0
    # mean |new_mel - old_mel| bound per golden request; generous by
    # default — the gate is against BROKEN weights (NaN, wrong tree,
    # garbage), not against intended retraining deltas
    canary_tolerance: float = 1e3
    # per-replica warm/drain wait during canary + roll phases
    replica_timeout_s: float = 600.0

    def __post_init__(self):
        if self.golden_set_size <= 0:
            raise ValueError(
                "serve.rollout.golden_set_size must be > 0, "
                f"got {self.golden_set_size}"
            )
        if self.canary_tolerance < 0:
            raise ValueError(
                "serve.rollout.canary_tolerance must be >= 0, "
                f"got {self.canary_tolerance}"
            )
        if self.replica_timeout_s <= 0:
            raise ValueError(
                "serve.rollout.replica_timeout_s must be > 0, "
                f"got {self.replica_timeout_s}"
            )


@dataclass(frozen=True)
class LongformConfig:
    """Long-form (chapter-length) synthesis knobs (serving/longform.py —
    ARCHITECTURE.md "Long-form synthesis").

    Two tiers behind ``POST /synthesize/longform``. **Chunked** (always
    available): the chapter is split at sentence boundaries into
    utterances that each fit the interactive lattice, synthesized as a
    deadline-sharing group of ``long_form``-class requests through the
    existing batcher/fleet, and stitched with prosodic continuity —
    per-chunk duration/pitch/energy controls carried across the seam
    plus an equal-power crossfade — streamed chunk-by-chunk (bounded
    memory, jaxlint JL019). **Ring** (``mesh_seq > 1``): one coherent
    chapter-length utterance compiled as a single ring-attention program
    over a ``seq``-axis mesh at the ``longform`` buckets below, with
    tier-b→tier-a degradation on ring failure decided at admission.
    """

    # seq-axis mesh size for the ring tier: devices the chapter-length
    # free-run shards its attention over (parallel/ring_attention.py);
    # 0 or 1 = chunked tier only (no ring programs compiled)
    mesh_seq: int = 0
    # padded text lengths the ring tier compiles for — the long-form
    # lattice ABOVE serve.src_buckets[-1]; every value must be divisible
    # by mesh_seq (ring shards the length axis evenly)
    src_buckets: List[int] = field(default_factory=lambda: [512, 1024])
    # padded mel lengths for the ring free-run output buffer (defaults
    # pair with src_buckets at serve.frames_per_phoneme=12); same
    # divisibility contract as src_buckets
    mel_buckets: List[int] = field(default_factory=lambda: [6144, 12288])
    # mel frames of equal-power crossfade at each chunk seam (chunked
    # tier); converted to wav samples via the vocoder hop
    crossfade_frames: int = 8
    # admission cap on chapter size (chunks after sentence packing)
    max_chunks: int = 64
    # chunked-tier in-flight bound: at most this many chunk requests are
    # submitted ahead of the stitch point, so resident memory is
    # O(group_depth) chunk wavs — never the whole chapter (jaxlint JL019
    # polices the concatenate-the-chapter failure mode)
    group_depth: int = 4
    # per-chunk share of the chapter group's deadline budget: the group
    # budget is n_chunks * this, clamped to fleet.max_deadline_ms
    deadline_ms_per_chunk: float = 2000.0
    # tier selection at admission: "auto" rings when the ring tier is up
    # and the chapter fits a ring bucket, else chunks; "chunked"/"ring"
    # force a tier ("ring" still degrades to chunked on failure)
    tier: str = "auto"

    def __post_init__(self):
        if self.mesh_seq < 0:
            raise ValueError(
                f"serve.longform.mesh_seq must be >= 0, got {self.mesh_seq}"
            )
        for name in ("src_buckets", "mel_buckets"):
            vals = getattr(self, name)
            if not vals:
                raise ValueError(f"serve.longform.{name} must be non-empty")
            if any(v <= 0 for v in vals):
                raise ValueError(
                    f"serve.longform.{name} must be positive, got {vals}"
                )
            if sorted(vals) != list(vals) or len(set(vals)) != len(vals):
                raise ValueError(
                    f"serve.longform.{name} must be strictly ascending, "
                    f"got {vals}"
                )
            if self.mesh_seq > 1 and any(v % self.mesh_seq for v in vals):
                raise ValueError(
                    f"serve.longform.{name} must be divisible by "
                    f"mesh_seq={self.mesh_seq} (ring shards the length "
                    f"axis evenly), got {vals}"
                )
        if self.crossfade_frames < 0:
            raise ValueError(
                f"serve.longform.crossfade_frames must be >= 0, "
                f"got {self.crossfade_frames}"
            )
        if self.max_chunks <= 0:
            raise ValueError(
                f"serve.longform.max_chunks must be > 0, got {self.max_chunks}"
            )
        if self.group_depth < 1:
            raise ValueError(
                f"serve.longform.group_depth must be >= 1, "
                f"got {self.group_depth}"
            )
        if self.deadline_ms_per_chunk <= 0:
            raise ValueError(
                f"serve.longform.deadline_ms_per_chunk must be > 0, "
                f"got {self.deadline_ms_per_chunk}"
            )
        if self.tier not in ("auto", "chunked", "ring"):
            raise ValueError(
                "serve.longform.tier must be 'auto'|'chunked'|'ring', "
                f"got {self.tier!r}"
            )


@dataclass(frozen=True)
class ClusterConfig:
    """Distributed control plane knobs (serving/cluster.py —
    ARCHITECTURE.md "Distributed control plane").

    Disabled by default: with ``enabled: false`` the fleet router keeps
    its in-process replica engines and nothing here applies. Enabled,
    every replica is a separate *process* (cli/replica.py) that owns a
    full AOT engine and registers with the router over HTTP; liveness is
    heartbeat leases, dispatch is hedged with per-class timeouts, and
    the autoscaler's scale_to() spawns/drains real processes.
    """

    enabled: bool = False
    # control-plane bind address for the router's /register + /heartbeat
    # endpoints (port 0 = ephemeral, the bound port is advertised to
    # spawned replicas via --router)
    control_host: str = "127.0.0.1"
    control_port: int = 0
    # replica -> router heartbeat cadence; a lease is granted for
    # heartbeat_interval_s * (lease_miss_budget + 1) and renewed on every
    # beat, so a replica may miss `lease_miss_budget` consecutive beats
    # before the lease expires and the router fails it
    heartbeat_interval_s: float = 0.5
    lease_miss_budget: int = 3
    # hedged dispatch: a second request goes to a different host once the
    # first has been outstanding longer than this quantile of the class's
    # observed wire latency (serve_wire_latency_seconds), clamped into
    # [hedge_min_ms, hedge_max_ms]; first response wins, the loser's
    # connection is torn down. 0 quantile disables hedging.
    hedge_quantile: float = 0.95
    hedge_min_ms: float = 50.0
    hedge_max_ms: float = 2000.0
    # TCP connect timeout for every control + dispatch connection; the
    # per-attempt read timeout derives from the request's class deadline
    # (never unbounded — jaxlint JL024 enforces this structurally)
    connect_timeout_s: float = 2.0
    # a spawned replica process must register within this budget or the
    # spawn is declared failed (covers engine AOT warmup; the measured
    # serve_replica_warmup_seconds histogram still feeds the autoscaler)
    spawn_grace_s: float = 120.0
    # /healthz readiness quorum: the server answers 503 until at least
    # this many replicas hold live leases and are READY
    quorum: int = 1
    # bounded per-replica idempotency cache (keys of executed dispatch
    # batches -> cached wire response), so a hedge or wire retry of an
    # already-executed batch never re-runs the lattice
    idempotency_cache: int = 256

    def __post_init__(self):
        if self.heartbeat_interval_s <= 0:
            raise ValueError(
                f"serve.cluster.heartbeat_interval_s must be > 0, "
                f"got {self.heartbeat_interval_s}"
            )
        if self.lease_miss_budget < 1:
            raise ValueError(
                f"serve.cluster.lease_miss_budget must be >= 1, "
                f"got {self.lease_miss_budget}"
            )
        if not (0.0 <= self.hedge_quantile < 1.0):
            raise ValueError(
                f"serve.cluster.hedge_quantile must be in [0, 1) "
                f"(0 disables hedging), got {self.hedge_quantile}"
            )
        if self.hedge_min_ms < 0:
            raise ValueError(
                f"serve.cluster.hedge_min_ms must be >= 0, "
                f"got {self.hedge_min_ms}"
            )
        if self.hedge_max_ms < self.hedge_min_ms:
            raise ValueError(
                "serve.cluster.hedge_max_ms must be >= hedge_min_ms, got "
                f"{self.hedge_max_ms} < {self.hedge_min_ms}"
            )
        if self.connect_timeout_s <= 0:
            raise ValueError(
                f"serve.cluster.connect_timeout_s must be > 0, "
                f"got {self.connect_timeout_s}"
            )
        if self.spawn_grace_s <= 0:
            raise ValueError(
                f"serve.cluster.spawn_grace_s must be > 0, "
                f"got {self.spawn_grace_s}"
            )
        if self.quorum < 1:
            raise ValueError(
                f"serve.cluster.quorum must be >= 1, got {self.quorum}"
            )
        if self.idempotency_cache < 1:
            raise ValueError(
                f"serve.cluster.idempotency_cache must be >= 1, "
                f"got {self.idempotency_cache}"
            )

    @property
    def lease_ttl_s(self) -> float:
        """Lease duration granted per heartbeat: the replica may miss
        ``lease_miss_budget`` consecutive beats before expiry."""
        return self.heartbeat_interval_s * (self.lease_miss_budget + 1)


@dataclass(frozen=True)
class TiersConfig:
    """Quality-tiered serving (serving/tiers.py): precision variants of
    the acoustic lattice plus an optional distilled student model,
    canary-gated against the teacher and routed by traffic class.

    A tier name is ``<model>-<precision>`` (``teacher-f32``,
    ``teacher-bf16``, ``student-int8``): the model half picks the param
    tree (teacher checkpoint vs the distilled student registered as a
    second model version), the precision half picks the lattice's
    precision axis. A tier only ships if its golden-set mel-L2 against
    the teacher-f32 engine holds under ``tier_tolerance``; a failed gate
    falls back to ``default_tier`` so routing never loses requests.
    """

    enabled: bool = False
    # precision tiers the lattice compiles (registry.PRECISIONS subset;
    # the first entry is the default precision for untagged requests)
    precisions: List[str] = field(default_factory=lambda: ["f32"])
    # traffic class -> tier name; classes absent here ride default_tier
    class_tier: Dict[str, str] = field(default_factory=dict)
    # the always-shipped reference tier (the quality anchor; its gate is
    # identity so it can never fail)
    default_tier: str = "teacher-f32"
    # golden-set mel-L2 ceiling vs the teacher-f32 engine for a tier to
    # ship (same spirit as rollout.canary_tolerance; loose default for
    # tiny CI configs — production presets tighten it)
    tier_tolerance: float = 1e3
    # golden probe set (reuses lifecycle.make_golden_set)
    golden_set_size: int = 4
    golden_seed: int = 0
    # the distilled student checkpoint (training/distill.py output);
    # empty = no student tiers available
    student_ckpt_path: str = ""

    def __post_init__(self):
        allowed = ("f32", "bf16", "int8")
        if not self.precisions:
            raise ValueError("serve.tiers.precisions must be non-empty")
        for p in self.precisions:
            if p not in allowed:
                raise ValueError(
                    f"serve.tiers.precisions entries must be in {allowed}, "
                    f"got {p!r}"
                )
        if len(set(self.precisions)) != len(self.precisions):
            raise ValueError(
                f"serve.tiers.precisions must be unique, got {self.precisions}"
            )
        names = [self.default_tier, *self.class_tier.values()]
        for name in names:
            model, sep, prec = name.partition("-")
            if not sep or model not in ("teacher", "student") \
                    or prec not in allowed:
                raise ValueError(
                    "tier names must be '<model>-<precision>' with model in "
                    f"(teacher, student) and precision in {allowed}, "
                    f"got {name!r}"
                )
        if self.tier_tolerance <= 0:
            raise ValueError(
                f"serve.tiers.tier_tolerance must be > 0, "
                f"got {self.tier_tolerance}"
            )
        if self.golden_set_size <= 0:
            raise ValueError(
                f"serve.tiers.golden_set_size must be > 0, "
                f"got {self.golden_set_size}"
            )


@dataclass(frozen=True)
class TraceConfig:
    """Distributed-tracing knobs (obs/trace.py — ARCHITECTURE.md
    "Fleet observability plane").

    Context propagation is always on (three strings riding each
    request); these knobs govern span *recording*: the bounded
    per-process ring served at ``GET /debug/spans``, and the tail
    sampler's healthy-traffic keep rate.  Every shed/504/hedge-won/
    deadline-miss trace is kept regardless of ``sample_rate`` — tail
    sampling only thins the healthy majority.
    """

    enabled: bool = True
    # bounded per-process finished-span ring (oldest evicted first)
    ring_capacity: int = 4096
    # bounded keep-store of pinned (tail-sampled) traces
    keep_traces: int = 256
    # deterministic keep probability for *healthy* traces; interesting
    # traces (error ladder, hedge winner, deadline miss) always keep
    sample_rate: float = 0.1

    def __post_init__(self):
        if self.ring_capacity < 1:
            raise ValueError(
                f"serve.trace.ring_capacity must be >= 1, "
                f"got {self.ring_capacity}"
            )
        if self.keep_traces < 1:
            raise ValueError(
                f"serve.trace.keep_traces must be >= 1, "
                f"got {self.keep_traces}"
            )
        if not (0.0 <= self.sample_rate <= 1.0):
            raise ValueError(
                f"serve.trace.sample_rate must be in [0, 1], "
                f"got {self.sample_rate}"
            )


@dataclass(frozen=True)
class SloConfig:
    """Multi-window burn-rate SLO accounting (obs/slo.py).

    Per traffic class, ``objectives`` states the availability target —
    the fraction of admitted requests that must resolve inside their
    deadline (neither shed after admission, nor 504ed, nor served past
    their SLO stamp). The engine differentiates the fleet's cumulative
    miss/shed/request counters into two sliding windows and publishes

        burn_rate = (bad / total) / (1 - objective)

    per (class, window) as ``serve_slo_burn_rate`` gauges: burn 1.0
    consumes the error budget exactly at sustainable rate. An alert
    (``slo_alert`` JSONL event) fires only when BOTH windows burn past
    their thresholds — the standard multi-window rule: the fast window
    catches the page-worthy spike, the slow window keeps one transient
    blip from paging.
    """

    enabled: bool = True
    # traffic class -> availability objective (fraction of requests that
    # must meet their deadline); classes absent here are not tracked
    objectives: Dict[str, float] = field(
        default_factory=lambda: {"interactive": 0.999, "batch": 0.99}
    )
    # sliding windows the cumulative counters are differentiated over
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    # burn-rate thresholds per window (SRE handbook pairing: 14.4x burns
    # a 30-day budget in 2 days; 6x in 5 days)
    fast_burn_threshold: float = 14.4
    slow_burn_threshold: float = 6.0
    # evaluation cadence of the stop-aware policy loop
    tick_s: float = 5.0
    # traffic class -> audio-quality objective: the fraction of
    # validated wavs (obs/quality.py choke point) that must pass.
    # A separate stream from availability — the probe class exists
    # ONLY here (probe traffic is excluded from the latency SLO)
    quality_objectives: Dict[str, float] = field(
        default_factory=lambda: {
            "interactive": 0.99, "batch": 0.99, "probe": 0.99,
        }
    )

    def __post_init__(self):
        for klass, obj in self.objectives.items():
            if not (0.0 < obj < 1.0):
                raise ValueError(
                    f"serve.slo.objectives[{klass!r}] must be in (0, 1), "
                    f"got {obj}"
                )
        for klass, obj in self.quality_objectives.items():
            if not (0.0 < obj < 1.0):
                raise ValueError(
                    f"serve.slo.quality_objectives[{klass!r}] must be in "
                    f"(0, 1), got {obj}"
                )
        if self.fast_window_s <= 0:
            raise ValueError(
                f"serve.slo.fast_window_s must be > 0, "
                f"got {self.fast_window_s}"
            )
        if self.slow_window_s <= self.fast_window_s:
            raise ValueError(
                "serve.slo.slow_window_s must be > fast_window_s, got "
                f"{self.slow_window_s} <= {self.fast_window_s}"
            )
        if self.fast_burn_threshold <= 0 or self.slow_burn_threshold <= 0:
            raise ValueError(
                "serve.slo burn thresholds must be > 0, got "
                f"{self.fast_burn_threshold}/{self.slow_burn_threshold}"
            )
        if self.tick_s <= 0:
            raise ValueError(
                f"serve.slo.tick_s must be > 0, got {self.tick_s}"
            )


@dataclass(frozen=True)
class QualityConfig:
    """Audio-quality observability plane (obs/quality.py validators +
    serving/probes.py golden prober).

    Validator thresholds apply to every wav leaving the process
    (engine batch path, streaming windows, longform stitcher); probe
    knobs drive the background golden replays through the live fleet
    on their own traffic class — excluded from autoscaler pressure
    signals and the latency SLO, visible only to the quality SLO
    stream (``serve.slo.quality_objectives``).
    """

    enabled: bool = True
    # fraction of samples at >= 99.9% full scale before a wav fails
    clip_fraction_max: float = 0.5
    # longest exact-zero run (digital silence) a wav may carry
    silence_run_ms_max: float = 500.0
    # |mean| of the normalized wav (full scale = 1.0)
    dc_offset_max: float = 0.5
    # spectral flatness above this is a stuck/degenerate signal
    # (constant -> ~1.0; white noise -> ~0.56; speech far below)
    flatness_max: float = 0.9
    # skip the flatness check below this many samples (no spectrum)
    flatness_min_samples: int = 256
    # traffic class golden probes ride on; must not collide with
    # tenant classes — the fleet admits it with probe_deadline_ms and
    # keeps it out of shed/pressure/latency-SLO accounting
    probe_class: str = "probe"
    probe_deadline_ms: float = 30_000.0
    # cadence of the background prober's rounds
    probe_interval_s: float = 30.0
    # RMS mel-L2 drift vs the pinned anchor before the prober pages
    # (healthy drift is ~0: same lattice, same seeds, same weights)
    probe_mel_tolerance: float = 10.0
    # RMS FiLM (gamma, beta) drift vs the pinned style baseline
    probe_style_tolerance: float = 10.0
    # where pinned anchors live ("" = alongside train.path.log_path)
    anchor_dir: str = ""

    def __post_init__(self):
        for name in ("clip_fraction_max", "flatness_max"):
            v = getattr(self, name)
            if not (0.0 < v <= 1.0):
                raise ValueError(
                    f"serve.quality.{name} must be in (0, 1], got {v}"
                )
        for name in (
            "silence_run_ms_max", "dc_offset_max", "probe_deadline_ms",
            "probe_interval_s", "probe_mel_tolerance",
            "probe_style_tolerance",
        ):
            v = getattr(self, name)
            if v <= 0:
                raise ValueError(
                    f"serve.quality.{name} must be > 0, got {v}"
                )
        if self.flatness_min_samples < 2:
            raise ValueError(
                "serve.quality.flatness_min_samples must be >= 2, got "
                f"{self.flatness_min_samples}"
            )
        if not self.probe_class:
            raise ValueError("serve.quality.probe_class must be non-empty")


@dataclass(frozen=True)
class ServeConfig:
    """Continuous-batching synthesis server knobs (serving/engine.py,
    serving/batcher.py).

    The three bucket lists span the AOT-precompiled shape lattice: every
    served dispatch runs at some ``(batch, L_src, T_mel)`` drawn from
    their cross product, compiled once at server start. ``T_mel`` bounds
    the free-run output buffer (``max_mel_len``); the style-reference
    mel rides its own ``serve.style.ref_buckets`` axis (serving/style.py)
    so reference length never inflates the output bucket.
    """

    # batch sizes the engine compiles for; a dispatch of n requests runs
    # at the smallest bucket >= n
    batch_buckets: List[int] = field(default_factory=lambda: [1, 2, 4, 8])
    # padded text lengths (multiples of the dataset src bucket work well;
    # the top bucket caps the longest admissible utterance)
    src_buckets: List[int] = field(default_factory=lambda: [32, 64, 128, 256])
    # padded mel lengths: reference-mel input AND free-run output buffer
    mel_buckets: List[int] = field(default_factory=lambda: [256, 512, 1000])
    # admission deadline: a request is dispatched at most this long after
    # arrival (sooner when a full batch_buckets[-1] coalesces first)
    max_wait_ms: float = 10.0
    # bounded admission queue depth; submit blocks (stop-aware) when full
    queue_depth: int = 64
    # output-buffer sizing bound: a request with n phonemes needs
    # T_mel >= n * frames_per_phoneme (predictions past the buffer are
    # truncated, matching the reference's max_seq_len clamp)
    frames_per_phoneme: int = 12
    # donate request buffers into the compiled programs (XLA reuses the
    # padded input HBM for outputs; ignored with a warning on CPU)
    donate_buffers: bool = True
    # host->device transfer retry-with-backoff (DevicePrefetcher discipline)
    transfer_retries: int = 0
    transfer_backoff: float = 0.05
    host: str = "127.0.0.1"
    port: int = 8400
    # POST /debug/profile?seconds=N pulls a jax.profiler trace from the
    # live server (written under <log_path>/serve_profile); disable on
    # exposed deployments
    debug_profile: bool = True
    # emit serve_dispatch / http_request JSONL events (obs/events.py
    # schema) under train.path.log_path — req_id joins the two streams
    log_events: bool = False
    # host frontend worker pool: text normalization/G2P/phoneme encoding
    # runs off the dispatch path on this many threads, so frontend work
    # for request k+1 overlaps device dispatch of request k (requests
    # enter the queue with a resolved-or-pending frontend handle);
    # 0 = inline frontend on the HTTP handler thread (the pre-pipeline
    # behavior)
    frontend_workers: int = 2
    # fleet serving: multi-replica router, SLO admission, streaming
    fleet: FleetConfig = field(default_factory=FleetConfig)
    # distributed control plane: replica processes with heartbeat leases
    # and hedged dispatch (disabled by default — in-process replicas)
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    # closed-loop autoscaler over the fleet (disabled by default)
    autoscale: AutoscaleConfig = field(default_factory=AutoscaleConfig)
    # style service: AOT reference-encoder lattice + embedding cache
    style: StyleConfig = field(default_factory=StyleConfig)
    # canary-gated rolling model rollout (disabled by default)
    rollout: RolloutConfig = field(default_factory=RolloutConfig)
    # long-form (chapter-length) synthesis: chunk+stitch tier always on,
    # ring-attention tier when longform.mesh_seq > 1
    longform: LongformConfig = field(default_factory=LongformConfig)
    # quality tiers: precision lattice axis + distilled fast tier,
    # canary-gated and routed by class (disabled by default — one
    # teacher-f32 tier, byte-identical to the pre-tier engine)
    tiers: TiersConfig = field(default_factory=TiersConfig)
    # mesh geometry of ONE replica (parallel/mesh.py resolve_mesh — the
    # same resolution path as train.parallel): [1, 1] keeps the
    # single-device engine byte-for-byte; [dp, tp] makes every replica a
    # dp x tp mesh slice whose lattice programs compile with the batch
    # axis sharded over ``data`` (buckets divisible by dp) and outputs
    # replicated for host readback. Weights replicate unless
    # partition_rules opt into tensor parallelism — replicated weights
    # keep a mesh replica bit-identical to the 1x1 one from the same
    # checkpoint (the cross-mesh serving contract).
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    # distributed tracing: span ring sizing + tail-sampling keep rate
    trace: TraceConfig = field(default_factory=TraceConfig)
    # multi-window SLO burn-rate accounting per traffic class
    slo: SloConfig = field(default_factory=SloConfig)
    # audio-quality plane: output validators + live golden probes
    quality: QualityConfig = field(default_factory=QualityConfig)

    def __post_init__(self):
        for name in ("batch_buckets", "src_buckets", "mel_buckets"):
            vals = getattr(self, name)
            if not vals:
                raise ValueError(f"serve.{name} must be non-empty")
            if any(v <= 0 for v in vals):
                raise ValueError(f"serve.{name} must be positive, got {vals}")
            if sorted(vals) != list(vals) or len(set(vals)) != len(vals):
                raise ValueError(
                    f"serve.{name} must be strictly ascending, got {vals}"
                )
        if self.max_wait_ms < 0:
            raise ValueError(f"serve.max_wait_ms must be >= 0, got {self.max_wait_ms}")
        if self.queue_depth <= 0:
            raise ValueError(f"serve.queue_depth must be > 0, got {self.queue_depth}")
        if self.frames_per_phoneme <= 0:
            raise ValueError(
                f"serve.frames_per_phoneme must be > 0, got {self.frames_per_phoneme}"
            )
        if self.frontend_workers < 0:
            raise ValueError(
                f"serve.frontend_workers must be >= 0 (0 = inline), "
                f"got {self.frontend_workers}"
            )


@dataclass(frozen=True)
class Config:
    """The full (preprocess, model, train) triple, plus the serve block."""

    preprocess: PreprocessConfig = field(default_factory=PreprocessConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)


def load_yaml(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return yaml.safe_load(f) or {}


def load_config(
    preprocess: Optional[str] = None,
    model: Optional[str] = None,
    train: Optional[str] = None,
    preset: Optional[str] = None,
) -> Config:
    """Load a Config from explicit YAML paths and/or a named preset."""
    if preset is not None:
        base = os.path.join(PRESET_DIR, preset)
        if not os.path.isdir(base):
            raise ValueError(
                f"Unknown preset {preset!r}; available: {sorted(os.listdir(PRESET_DIR))}"
            )
        preprocess = preprocess or os.path.join(base, "preprocess.yaml")
        model = model or os.path.join(base, "model.yaml")
        train = train or os.path.join(base, "train.yaml")
    pc = _build(PreprocessConfig, load_yaml(preprocess)) if preprocess else PreprocessConfig()
    mc = _build(ModelConfig, load_yaml(model)) if model else ModelConfig()
    # the serve.* block rides in train.yaml (a fourth file for a handful of
    # server knobs would be ceremony); absent -> defaults
    train_data = load_yaml(train) if train else {}
    serve_data = train_data.pop("serve", None) if isinstance(train_data, dict) else None
    tc = _build(TrainConfig, train_data) if train else TrainConfig()
    sc = _build(ServeConfig, serve_data, "serve") if serve_data else ServeConfig()
    return Config(preprocess=pc, model=mc, train=tc, serve=sc)


def load_stats(preprocessed_path: str) -> Dict[str, List[float]]:
    """stats.json: {"pitch": [min, max, mean, std], "energy": [...]}."""
    with open(os.path.join(preprocessed_path, "stats.json")) as f:
        return json.load(f)


def asdict(cfg) -> Dict[str, Any]:
    return dataclasses.asdict(cfg)
