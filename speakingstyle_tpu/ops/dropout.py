"""Dropout with selectable mask generation, tuned for TPU.

The reference applies standard inverted dropout everywhere (reference:
transformer/SubLayers.py:55-57, model/modules.py:383-384); the math here
is identical — ``where(keep_mask, x / keep_prob, 0)`` with
``P(keep) = 1 - rate`` — but mask *generation* is the knob. The r4
breakdown measured the train-step's dropout cost at 5.0 ms (PERF.md), most
of it RNG-bit materialization traffic, so:

* ``"bernoulli"`` — ``jax.random.bernoulli`` (what ``nn.Dropout`` does):
  32 random bits per element, converted to f32 uniforms, compared.
* ``"bits16"`` — 16 raw random bits per element (one u32 generates two
  masks), integer threshold compare, no float conversion. Halves the RNG
  traffic; quantizes the keep probability to 1/65536 steps (≤8e-6
  absolute, vs f32 uniforms' own 2^-24 granularity — negligible).
* ``"hash"`` — zero RNG materialization: a murmur3-finalizer
  (fmix32) counter hash of the flat element index, salted per call from
  the PRNG key. Pure elementwise arithmetic on an iota — XLA fuses it
  into the consumer, so no random bits ever touch HBM. fmix32 has full
  avalanche (every input bit flips every output bit with p≈0.5), which
  is far more than dropout masks need; the keep probability quantizes to
  1/2^32. NOT a cryptographic stream and deliberately so.

All impls draw from the module's "dropout" RNG collection and differ only
in mask bits; tests/test_ops.py::test_dropout_impls checks keep-rate
statistics, scaling, and determinism per impl.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

DROPOUT_IMPLS = ("bernoulli", "bits16", "hash")


def _u32(v: int):
    return jnp.uint32(v & 0xFFFFFFFF)


def _fmix32(h):
    """murmur3 32-bit finalizer: 6 fused elementwise ops, full avalanche."""
    h = h ^ (h >> 16)
    h = h * _u32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * _u32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def keep_mask(rng, rate: float, shape, impl: str = "bernoulli"):
    """Boolean keep mask with P(True) = 1 - rate."""
    if impl not in DROPOUT_IMPLS:
        raise ValueError(
            f"dropout impl must be one of {DROPOUT_IMPLS}, got {impl!r}"
        )
    if rate >= 1.0:
        # drop everything, exactly: the bits16/hash thresholds clamp at
        # 0xFFFF/0xFFFFFFFF and would otherwise keep a ~2^-16/2^-32 sliver
        # of elements (which dropout() would then scale by 1/(1-rate) = inf)
        return jnp.zeros(shape, jnp.bool_)
    if impl == "bernoulli":
        return jax.random.bernoulli(rng, 1.0 - rate, shape)
    n = 1
    for d in shape:
        n *= d
    if impl == "bits16":
        n32 = (n + 1) // 2
        # the three rng consumers live in mutually exclusive impl branches
        # — exactly one draw happens per call
        bits32 = jax.random.bits(rng, (n32,), jnp.uint32)  # jaxlint: disable=JL006
        bits16 = jax.lax.bitcast_convert_type(bits32, jnp.uint16).reshape(-1)
        thresh = min(0xFFFF, int(round(rate * 65536)))
        return (bits16[:n] >= jnp.uint16(thresh)).reshape(shape)
    salt = jax.random.bits(rng, (), jnp.uint32)
    idx = jax.lax.iota(jnp.uint32, n)
    h = _fmix32((idx * _u32(0x9E3779B9)) ^ salt)
    thresh = min(0xFFFFFFFF, int(round(rate * 2**32)))
    return (h >= _u32(thresh)).reshape(shape)


def dropout(x, rate: float, rng, impl: str = "bernoulli"):
    """Inverted dropout: zero with probability ``rate``, scale survivors by
    1/(1-rate). Identical math to flax ``nn.Dropout``; only the mask bits'
    provenance differs by ``impl``."""
    if rate == 0.0:
        return x
    if rate >= 1.0:
        # nn.Dropout semantics: drop everything, exactly (keep_mask also
        # guards this case; returning here just skips the dead where())
        return jnp.zeros_like(x)
    mask = keep_mask(rng, rate, x.shape, impl)
    return jnp.where(mask, x / (1.0 - rate), jnp.zeros_like(x))


class Dropout(nn.Module):
    """Drop-in replacement for ``nn.Dropout`` with a selectable mask impl
    (``ModelConfig.dropout_impl``). Reads the same "dropout" RNG
    collection, so switching impls changes no call-site wiring."""

    rate: float
    impl: str = "bernoulli"

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        if deterministic or self.rate == 0.0:
            return x
        return dropout(x, self.rate, self.make_rng("dropout"), self.impl)
