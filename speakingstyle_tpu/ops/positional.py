"""Sinusoidal position encoding (reference: transformer/Models.py:10-30).

Computed once in numpy at module-construction time and baked into the
compiled program as a constant — never recomputed on host at step time
(the reference recomputes it per call for long sequences,
transformer/Models.py:82-87; we size the table up front instead).
"""

import jax.numpy as jnp
import numpy as np


def add_position_encoding(x, n_position: int):
    """Add the sinusoid table to [B, L, H] features; L must fit the table."""
    L, d = x.shape[1], x.shape[2]
    if L > n_position:
        raise ValueError(
            f"sequence length {L} exceeds position table {n_position}; "
            "enlarge max_seq_len / n_position for long inference"
        )
    pe = sinusoid_position_table(n_position, d)[:L]
    return x + jnp.asarray(pe, x.dtype)[None, :, :]


def sinusoid_position_table(n_position: int, d_hid: int) -> np.ndarray:
    """[n_position, d_hid] float32 table; even dims sin, odd dims cos."""
    positions = np.arange(n_position, dtype=np.float64)[:, None]
    dim_idx = np.arange(d_hid, dtype=np.float64)[None, :]
    angle_rates = 1.0 / np.power(10000.0, 2.0 * (np.floor(dim_idx / 2.0)) / d_hid)
    angles = positions * angle_rates
    table = np.empty((n_position, d_hid), dtype=np.float64)
    table[:, 0::2] = np.sin(angles[:, 0::2])
    table[:, 1::2] = np.cos(angles[:, 1::2])
    return table.astype(np.float32)
