"""Pitch/energy bucketization (reference: model/modules.py:85-103).

``torch.bucketize(v, bins)`` (right=False) == ``searchsorted(bins, v,
side='left')`` — verified empirically; note this is NOT ``jnp.digitize``,
which uses side='right'. Bins are ``n_bins - 1`` boundaries, linear or log
spaced from stats.json min/max.
"""

import jax.numpy as jnp
import numpy as np


def make_bins(vmin: float, vmax: float, n_bins: int, quantization: str) -> np.ndarray:
    """[n_bins - 1] boundaries; log spacing only valid for unnormalized stats."""
    if quantization == "log":
        if vmin <= 0:
            raise ValueError(
                f"log quantization needs positive stats, got min={vmin}; "
                "z-normalized features require 'linear' (see config comment)"
            )
        return np.exp(
            np.linspace(np.log(vmin), np.log(vmax), n_bins - 1, dtype=np.float64)
        ).astype(np.float32)
    return np.linspace(vmin, vmax, n_bins - 1, dtype=np.float32)


def bucketize(values, bins):
    """Map continuous values to bucket ids in [0, len(bins)]."""
    return jnp.searchsorted(jnp.asarray(bins), values, side="left").astype(jnp.int32)
