"""Fused conv1d (+bias +ReLU +LayerNorm) Pallas TPU kernel.

The hot conv patterns of the model (SURVEY.md §2.1):
  * reference-encoder stack: conv k=3 @1024ch -> ReLU -> LayerNorm
    (reference: model/modules.py:361-379)
  * conv-FFN first half: conv k=9 256->1024 -> ReLU
    (reference: transformer/SubLayers.py:60-93)

One kernel serves both: a K-tap matmul accumulation in f32 over a VMEM
tile of the time axis, with the elementwise epilogue (bias, ReLU, and the
channel LayerNorm) applied in-register before the single HBM write-back.
Versus the unfold GEMM (ops/conv.py) this saves the im2col materialization
and the separate LN read-modify-write passes; versus XLA's conv emitter it
guarantees every FLOP is an MXU matmul.

The input rides in HBM/ANY and each grid step DMAs its (tile + halo) slice
into VMEM scratch — overlapping windows are not expressible as a blocked
``BlockSpec``. Weights/bias/affine are small enough to sit in VMEM whole
(max: k=9, 256->1024 bf16 = 4.7 MB).

Differentiation: ``fused_conv1d`` / ``fused_conv_relu_ln`` carry a
``jax.custom_vjp`` with an **analytic backward** (the r5 fix for why
conv=pallas lost the r4 training A/B — its old backward recomputed the
whole forward through the im2col reference path, itself 19% slower than
the conv emitter):

* epilogue backward (LayerNorm + ReLU) runs in plain jnp from a saved
  post-ReLU residual (the kernel's second output when ``ln`` is on;
  the primal output itself when only ReLU is on — ``y > 0`` IS the
  ReLU mask) — all elementwise/reduction work XLA fuses;
* dx/dw/db come from ``jax.vjp`` of the *linear* ``lax.conv`` — conv is
  linear in (x, w), so this stores nothing and recomputes nothing; XLA
  lowers the transposed convs with the same emitter the "xla" impl uses
  (93–140 TF/s measured, PERF.md).

Gradient parity vs the composed reference:
tests/test_ops.py::test_conv1d_impl_parity,
::test_fused_conv_relu_ln_matches_composed. Pass ``bwd_mode="recompute"``
to the public functions (or set the ``BWD_MODE`` module-global default
before tracing) to A/B the old recompute path.

Set ``interpret=True`` (or run on a non-TPU backend, which forces it) to
emulate the kernel — CPU tests use this.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fail on builds without the TPU plugin; interpret-only then
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAVE_PLTPU = False

LN_EPS = 1e-5


def _reference_fused_parts(x, kernel, bias, ln_scale, ln_bias, dilation,
                           relu):
    """Pure-jnp spec of the fused op. Returns (y, act) where act is the
    post-ReLU / pre-LayerNorm intermediate (== y when there is no LN) —
    the residual the analytic backward needs."""
    from speakingstyle_tpu.ops.conv import conv1d_unfold

    y = conv1d_unfold(x, kernel, bias, dilation=dilation)
    if relu:
        y = jnp.maximum(y, 0.0)
    act = y
    if ln_scale is not None:
        yf = y.astype(jnp.float32)
        mean = yf.mean(axis=-1, keepdims=True)
        var = yf.var(axis=-1, keepdims=True)
        yf = (yf - mean) * jax.lax.rsqrt(var + LN_EPS)
        y = (yf * ln_scale + ln_bias).astype(y.dtype)
    return y, act


def _reference_fused(x, kernel, bias, ln_scale, ln_bias, dilation, relu):
    """Pure-jnp spec of the fused op (also the recompute-mode backward)."""
    return _reference_fused_parts(
        x, kernel, bias, ln_scale, ln_bias, dilation, relu
    )[0]


def _kernel(x_hbm, w_ref, b_ref, s_ref, sb_ref, *refs,
            tile, copy_len, taps, dilation, relu, ln, want_act):
    if want_act:
        out_ref, act_ref, x_vmem, sem = refs
    else:
        out_ref, x_vmem, sem = refs
        act_ref = None
    b = pl.program_id(0)
    t = pl.program_id(1)
    # copy_len is (tile + span - 1) rounded up to the sublane tiling (8):
    # Mosaic requires DMA slice shapes aligned to the memref tiling. The
    # rows past tile+span-1 are junk halo and never read by the taps.
    copy = pltpu.make_async_copy(
        x_hbm.at[b, pl.ds(t * tile, copy_len), :], x_vmem, sem
    )
    copy.start()
    copy.wait()
    acc = jnp.zeros(out_ref.shape[1:], jnp.float32)
    for j in range(taps):  # static unroll: one MXU matmul per tap
        acc += jnp.dot(
            x_vmem[j * dilation : j * dilation + tile, :],
            w_ref[j],
            preferred_element_type=jnp.float32,
        )
    acc += b_ref[0]
    if relu:
        acc = jnp.maximum(acc, 0.0)
    if ln:
        # round to the storage dtype BEFORE the LN stats: this is exactly
        # what the unfused reference does (bf16 ReLU output -> f32 LN), and
        # it makes the backward's stats (recomputed from the saved act)
        # bit-consistent with the forward's
        acc = acc.astype(out_ref.dtype).astype(jnp.float32)
    if want_act:
        # post-ReLU / pre-LN residual for the analytic backward
        act_ref[0] = acc.astype(act_ref.dtype)
    if ln:
        mean = acc.mean(axis=-1, keepdims=True)
        var = ((acc - mean) ** 2).mean(axis=-1, keepdims=True)
        acc = (acc - mean) * jax.lax.rsqrt(var + LN_EPS)
        acc = acc * s_ref[0] + sb_ref[0]
    out_ref[0] = acc.astype(out_ref.dtype)


LANE = 128  # Mosaic lane tiling: channel dims in DMA slices must align


def _fused_fwd_pallas(x, kernel, bias, ln_scale, ln_bias, dilation, relu,
                      tile, interpret, want_act=False):
    B, T, cin = x.shape
    K, _, cout = kernel.shape
    span = (K - 1) * dilation + 1
    pad_lo = (span - 1) // 2
    n_t = pl.cdiv(T, tile)
    t_pad = n_t * tile
    # DMA slices must be sublane(8)-aligned in length; round the halo copy up
    copy_len = -(-(tile + span - 1) // 8) * 8
    # SAME padding plus right-fill so the last tile's copy_len DMA is in range
    right = (t_pad - tile + copy_len) - T - pad_lo
    xp = jnp.pad(x, ((0, 0), (pad_lo, right), (0, 0)))
    # Channel dims must be lane(128)-aligned for the manual HBM slice (cin)
    # and the output block (cout): zero-pad both — zeros contribute nothing
    # to the taps' dot products, and padded output columns are sliced off.
    # (The ln=True call sites are the 1024-channel ref-encoder stack, always
    # aligned; _fused falls back to the reference impl for unaligned-ln.)
    cin_p = -(-cin // LANE) * LANE
    cout_p = -(-cout // LANE) * LANE
    if cin_p != cin:
        xp = jnp.pad(xp, ((0, 0), (0, 0), (0, cin_p - cin)))
        kernel = jnp.pad(kernel, ((0, 0), (0, cin_p - cin), (0, 0)))
    if cout_p != cout:
        kernel = jnp.pad(kernel, ((0, 0), (0, 0), (0, cout_p - cout)))
        if bias is not None:
            bias = jnp.pad(bias, (0, cout_p - cout))
        if ln_scale is not None:
            ln_scale = jnp.pad(ln_scale, (0, cout_p - cout))
            ln_bias = jnp.pad(ln_bias, (0, cout_p - cout))
    cout_orig = cout
    cin, cout = cin_p, cout_p

    if bias is None:
        bias = jnp.zeros((cout,), x.dtype)
    ln = ln_scale is not None
    if not ln:
        ln_scale = jnp.zeros((cout,), x.dtype)
        ln_bias = jnp.zeros((cout,), x.dtype)

    # the act residual only differs from the output when LN runs after it
    want_act = want_act and ln
    kern = functools.partial(
        _kernel, tile=tile, copy_len=copy_len, taps=K, dilation=dilation,
        relu=relu, ln=ln, want_act=want_act,
    )
    vec = lambda v: v.reshape(1, cout)
    block = pl.BlockSpec((1, tile, cout), lambda b, t: (b, t, 0))
    shape = jax.ShapeDtypeStruct((B, t_pad, cout), x.dtype)
    out = pl.pallas_call(
        kern,
        grid=(B, n_t),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # x: manual halo DMA
            pl.BlockSpec((K, cin, cout), lambda b, t: (0, 0, 0)),
            pl.BlockSpec((1, cout), lambda b, t: (0, 0)),
            pl.BlockSpec((1, cout), lambda b, t: (0, 0)),
            pl.BlockSpec((1, cout), lambda b, t: (0, 0)),
        ],
        out_specs=[block, block] if want_act else block,
        out_shape=[shape, shape] if want_act else shape,
        scratch_shapes=[
            pltpu.VMEM((copy_len, cin), x.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(xp, kernel, vec(bias), vec(ln_scale), vec(ln_bias))
    if want_act:
        return tuple(o[:, :T, :cout_orig] for o in out)
    return out[:, :T, :cout_orig]


def _pick_tile(tile: int, T: int) -> int:
    """Clamp the time tile to the sequence and round up to the sublane
    tiling (8): Mosaic requires both block shapes and tile offsets
    (t * tile) to be 8-divisible on the second-minor dimension."""
    return min(-(-tile // 8) * 8, max(8, -(-T // 8) * 8))


def _use_interpret() -> bool:
    """Compile for real only on TPU hardware; emulate elsewhere (CPU tests).

    The tunneled-TPU platform registers as "axon" with TPU device kinds, so
    check the device kind too, not just the platform string.
    """
    if not _HAVE_PLTPU:
        return True
    try:
        dev = jax.devices()[0]
    except Exception:  # pragma: no cover - backend init failure
        return True
    kind = (getattr(dev, "device_kind", "") or "").lower()
    return not ("tpu" in dev.platform.lower() or "tpu" in kind)


def _use_reference(ln_scale, kernel) -> bool:
    """Fall back to the pure-jnp reference when there is no pallas-TPU
    module at all (even the interpreter path uses its DMA/scratch
    primitives), or for an in-kernel LayerNorm over a non-lane-aligned
    channel count (the kernel's mean/var would average the alignment
    padding). Single source of truth for BOTH the primal and the vjp fwd
    rule — they must agree or grad-time and inference-time forwards drift."""
    return not _HAVE_PLTPU or (
        ln_scale is not None and kernel.shape[-1] % LANE != 0
    )


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9)
)
def _fused(x, kernel, bias, ln_scale, ln_bias, dilation, relu, tile,
           interpret, bwd_mode):
    if _use_reference(ln_scale, kernel):
        return _reference_fused(
            x, kernel, bias, ln_scale, ln_bias, dilation, relu
        )
    return _fused_fwd_pallas(
        x, kernel, bias, ln_scale, ln_bias, dilation, relu, tile, interpret
    )


# "analytic" (default): epilogue backward from the saved post-ReLU
# residual + linear-conv vjp for dx/dw. "recompute": the pre-r5 behavior
# (full forward recompute through the im2col reference) — kept for A/B.
# The module global is only the DEFAULT, resolved when the public
# functions are called (i.e. at trace time); pass ``bwd_mode=`` explicitly
# when A/B-ing so the mode is part of the traced function — flipping the
# global after a callable is jitted does NOT retrace it.
BWD_MODE = "analytic"


def _fused_fwd(x, kernel, bias, ln_scale, ln_bias, dilation, relu, tile,
               interpret, bwd_mode):
    if bwd_mode != "analytic":
        y = _fused(x, kernel, bias, ln_scale, ln_bias, dilation, relu,
                   tile, interpret, bwd_mode)
        return y, (x, kernel, bias, ln_scale, ln_bias, None)
    if _use_reference(ln_scale, kernel):
        y, act = _reference_fused_parts(
            x, kernel, bias, ln_scale, ln_bias, dilation, relu
        )
    elif ln_scale is not None:
        y, act = _fused_fwd_pallas(
            x, kernel, bias, ln_scale, ln_bias, dilation, relu, tile,
            interpret, want_act=True,
        )
    else:
        # without LN the primal output itself is the residual: y > 0 IS
        # the ReLU mask (and with no ReLU either, no residual is read)
        y = _fused_fwd_pallas(
            x, kernel, bias, ln_scale, ln_bias, dilation, relu, tile,
            interpret,
        )
        act = y
    return y, (x, kernel, bias, ln_scale, ln_bias, act)


def _fused_bwd(dilation, relu, tile, interpret, bwd_mode, res, g):
    x, kernel, bias, ln_scale, ln_bias, act = res
    if bwd_mode != "analytic":
        wrt = (x, kernel, bias, ln_scale, ln_bias)

        def f(x_, k_, b_, s_, sb_):
            return _reference_fused(x_, k_, b_, s_, sb_, dilation, relu)

        _, vjp = jax.vjp(f, *wrt)
        grads = vjp(g)
        if ln_scale is None:
            grads = grads[:3] + (None, None)
        return grads

    gf = g.astype(jnp.float32)
    if ln_scale is not None:
        # LayerNorm backward from the saved pre-LN input (stats recomputed
        # — two cheap fused reductions, no conv recompute)
        af = act.astype(jnp.float32)
        mean = af.mean(axis=-1, keepdims=True)
        var = af.var(axis=-1, keepdims=True)
        rstd = jax.lax.rsqrt(var + LN_EPS)
        norm = (af - mean) * rstd
        d_scale = (gf * norm).sum(axis=(0, 1)).astype(ln_scale.dtype)
        d_lnbias = gf.sum(axis=(0, 1)).astype(ln_bias.dtype)
        dnorm = gf * ln_scale.astype(jnp.float32)
        da = (
            dnorm
            - dnorm.mean(axis=-1, keepdims=True)
            - norm * (dnorm * norm).mean(axis=-1, keepdims=True)
        ) * rstd
    else:
        d_scale = d_lnbias = None
        da = gf
    if relu:
        # ReLU mask from the residual stored in x.dtype. The threshold is
        # the stored dtype's smallest positive NORMAL (finfo.tiny), not a
        # literal 0: accumulator values that round to a stored 0 or
        # subnormal (possible in bf16, where recompute mode would keep
        # their gradient) are cut off at a bound that is explicit in the
        # stored dtype rather than implicit in its rounding — and XLA
        # flushes subnormals to zero anyway, so a subnormal threshold
        # constant would itself collapse to 0 (observed on CPU). Every
        # normal positive stored value passes, so f32 parity with the old
        # ``act > 0`` mask is exact; see the bf16 parity test for the
        # low-precision tolerance note.
        if jnp.issubdtype(act.dtype, jnp.floating):
            relu_thresh = float(jnp.finfo(act.dtype).tiny)
            da = da * (act.astype(jnp.float32) >= relu_thresh)
        else:
            da = da * (act > 0)
    dz = da.astype(x.dtype)
    db = None if bias is None else da.sum(axis=(0, 1)).astype(bias.dtype)

    # conv is linear in (x, w): vjp through it stores nothing and
    # recomputes nothing; XLA emits the transposed convs directly.
    def conv_lin(x_, k_):
        return jax.lax.conv_general_dilated(
            x_, k_, window_strides=(1,), padding="SAME",
            rhs_dilation=(dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"),
        )

    _, vjp = jax.vjp(conv_lin, x, kernel)
    dx, dw = vjp(dz)
    return dx, dw, db, d_scale, d_lnbias


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_conv1d(
    x,
    kernel,
    bias=None,
    *,
    dilation: int = 1,
    relu: bool = False,
    tile: int = 256,
    interpret: Optional[bool] = None,
    bwd_mode: Optional[str] = None,
):
    """SAME conv1d (+optional ReLU) via the fused kernel.

    x [B,T,Cin], kernel [K,Cin,Cout], bias [Cout]. Differentiable.
    """
    interpret = _use_interpret() if interpret is None else interpret
    tile = _pick_tile(tile, x.shape[1])
    return _fused(x, kernel, bias, None, None, dilation, relu, tile,
                  interpret, bwd_mode or BWD_MODE)


def fused_conv_relu_ln(
    x,
    kernel,
    bias,
    ln_scale,
    ln_bias,
    *,
    dilation: int = 1,
    tile: int = 256,
    interpret: Optional[bool] = None,
    bwd_mode: Optional[str] = None,
):
    """conv1d -> ReLU -> LayerNorm in one pass (the reference-encoder conv
    stack pattern, reference: model/modules.py:361-379). Differentiable."""
    interpret = _use_interpret() if interpret is None else interpret
    tile = _pick_tile(tile, x.shape[1])
    return _fused(x, kernel, bias, ln_scale, ln_bias, dilation, True, tile,
                  interpret, bwd_mode or BWD_MODE)
