"""Fused multi-head self-attention Pallas TPU kernel for short sequences.

The model's attention shapes (reference: transformer/SubLayers.py:8-57 at
the paper geometry) are tiny by flash-attention standards: T <= 1000
frames, head dims 32 (reference encoder, 8 heads) and 128 (en/decoder,
2 heads). The stock flash kernel is mistuned for this regime — measured
3.3x SLOWER than einsum attention at [48, 8, 600, 32] fwd+bwd, because its
online-softmax tiling and backward recomputation are built for sequences
that cannot fit in VMEM. Here they CAN: per (batch, head), the whole
[T, T] score matrix in f32 plus q/k/v is under 5 MB for T <= 1024.

So this kernel does the simplest possible thing: one grid step per
(batch, head), full K/V resident in VMEM, one-pass f32 softmax
in-register, no score materialization in HBM. The einsum path's HBM
traffic for the probability tensor ([B, H, T, T] written + read in fwd,
re-read twice in bwd — ~1 GB per reference-encoder layer at bench shapes)
disappears entirely; measured fwd+bwd at bench shapes: 3.4 ms vs 5.9 ms
(ref-encoder, 8 heads d32), 1.65 ms vs 2.3 ms (decoder, 2 heads d128).

Layout: everything rides as [B, H, D, T] — T on the lane (128) dimension,
D on sublanes (8) — so every Mosaic tiling constraint is satisfied for
D in {8, 16, ..., 128} without padding the head dimension. The host-side
transposes are fused by XLA into the surrounding projections.

Numerics match the einsum path with ``attention_softmax_dtype="float32"``
exactly in structure: f32 logits + additive finite mask bias + f32
softmax, probabilities cast to the compute dtype for the PV matmul.
The backward recomputes the probabilities in-kernel (same
rematerialization cost profile as flash attention) and computes exact
gradients for q, k, v.

Differentiation note: unlike ops/pallas_conv.py (whose backward re-runs
the jnp reference), both directions here are Pallas kernels — the
backward's score recomputation is the whole point, since materializing
probabilities for the VJP would reintroduce the HBM traffic being
eliminated.
"""

import functools
import inspect
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec

try:  # pltpu imports fail on builds without the TPU plugin; fallback then
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    _HAVE_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAVE_PLTPU = False

LANE = 128
# VMEM budget guard: f32 scores are Tp*Tp*4 bytes (+ ~3 same-size f32
# temporaries in bwd); 1024 keeps the worst case ~12 MB.
MAX_T = 1024


def _softmax_rows(scores, sm_dtype):
    """Row softmax entirely in VMEM registers. ``sm_dtype`` is the
    exp/normalize dtype: f32 for reference parity, bf16 saves ~24% of the
    kernel's forward (the VPU exp over [T, T] is a large share of its
    time; the matmuls are small). The f32->bf16 cast happens after the
    scale+bias so the mask bias keeps its full magnitude."""
    scores = scores.astype(sm_dtype)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    return p / jnp.sum(p, axis=-1, keepdims=True)


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, out_ref, *, sm_scale,
                sm_dtype):
    q = q_ref[0, 0]  # [D, T]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    # scores[q, t] = sum_d q[d, q] * k[d, t]
    scores = jax.lax.dot_general(
        q, k, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    scores = scores * sm_scale + bias_ref[0, 0][None, :]
    p = _softmax_rows(scores, sm_dtype).astype(v.dtype)
    # outT[d, q] = sum_t v[d, t] * p[q, t]
    out_ref[0, 0] = jax.lax.dot_general(
        v, p, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(out_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref,
                dq_ref, dk_ref, dv_ref, *, sm_scale, sm_dtype):
    q = q_ref[0, 0]   # [D, T]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]  # [D, T] cotangent of outT
    scores = jax.lax.dot_general(
        q, k, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    scores = scores * sm_scale + bias_ref[0, 0][None, :]
    p = _softmax_rows(scores, sm_dtype).astype(jnp.float32)  # [Tq, Tk]
    p_lo = p.astype(v.dtype)
    # dv[d, t] = sum_q do[d, q] * p[q, t]
    dv_ref[0, 0] = jax.lax.dot_general(
        do, p_lo, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dv_ref.dtype)
    # dp[q, t] = sum_d do[d, q] * v[d, t]
    dp = jax.lax.dot_general(
        do, v, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    # softmax vjp: ds = p * (dp - rowsum(dp * p)), with the sm_scale factor
    ds = (p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True)) * sm_scale
          ).astype(q.dtype)
    # dq[d, q] = sum_t k[d, t] * ds[q, t]
    dq_ref[0, 0] = jax.lax.dot_general(
        k, ds, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dq_ref.dtype)
    # dk[d, t] = sum_q q[d, q] * ds[q, t]
    dk_ref[0, 0] = jax.lax.dot_general(
        q, ds, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dk_ref.dtype)


def _bh_specs(D, Tp, n: int):
    # one (batch, head) per grid step: measured faster than a grid-over-
    # batch variant with the head loop unrolled in-kernel (2.2 ms vs
    # 1.65 ms for the 2-head d128 layers) — the deeper grid pipelines
    # DMA against compute better
    return [
        pl.BlockSpec((1, 1, D, Tp), lambda b, h: (b, h, 0, 0)) for _ in range(n)
    ]


def _bias_spec(Tp):
    # [B, 1, Tp] with block (1, 1, Tp): the middle axis keeps the block's
    # second-minor dim equal to the array dim (a Mosaic block-shape
    # requirement for dims < 8)
    return pl.BlockSpec((1, 1, Tp), lambda b, h: (b, 0, 0))


def _pallas_fwd(qT, kT, vT, bias, sm_scale, sm_dtype, interpret):
    B, H, D, Tp = qT.shape
    return pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, sm_dtype=sm_dtype),
        grid=(B, H),
        in_specs=_bh_specs(D, Tp, 3) + [_bias_spec(Tp)],
        out_specs=_bh_specs(D, Tp, 1)[0],
        out_shape=jax.ShapeDtypeStruct((B, H, D, Tp), qT.dtype),
        interpret=interpret,
    )(qT, kT, vT, bias)


def _pallas_bwd(qT, kT, vT, bias, doT, sm_scale, sm_dtype, interpret):
    B, H, D, Tp = qT.shape
    shape = jax.ShapeDtypeStruct((B, H, D, Tp), qT.dtype)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, sm_scale=sm_scale, sm_dtype=sm_dtype),
        grid=(B, H),
        in_specs=_bh_specs(D, Tp, 3) + [_bias_spec(Tp)] + _bh_specs(D, Tp, 1),
        out_specs=tuple(_bh_specs(D, Tp, 3)),
        out_shape=(shape, shape, shape),
        interpret=interpret,
    )(qT, kT, vT, bias, doT)


def _batch_partitioned(fn, rule: str):
    """Wrap a per-batch-independent pallas entry in custom_partitioning so
    GSPMD shards it along the batch dim instead of all-gathering the
    operands (which it does for unannotated custom calls — verified in
    HLO). ``rule`` is a Shardy einsum-like sharding rule whose only shared
    factor is the batch dim ``b``; the partition callback forces every
    operand/result to batch-only sharding (replicated on H/D/T — the
    kernel needs whole sequences) and lowers the same pallas call on the
    shard's batch slice. Falls back to full replication when the batch
    axis doesn't divide the shard count."""

    cp = custom_partitioning(fn, static_argnums=())

    def _batch_axis(mesh, arg_infos):
        spec = getattr(arg_infos[0].sharding, "spec", None)
        b = spec[0] if spec and len(spec) > 0 else None
        if b is None:
            return None
        axes = (b,) if isinstance(b, str) else tuple(b)
        n = 1
        for a in axes:
            n *= mesh.shape[a]
        return b if arg_infos[0].shape[0] % n == 0 else None

    def _batch_only(mesh, b, infos):
        return tuple(
            NamedSharding(mesh, PartitionSpec(b, *(None,) * (len(i.shape) - 1)))
            for i in infos
        )

    def partition(mesh, arg_infos, result_infos):
        b = _batch_axis(mesh, arg_infos)
        arg_sh = _batch_only(mesh, b, arg_infos)
        if isinstance(result_infos, (list, tuple)):
            out_sh = _batch_only(mesh, b, result_infos)
        else:
            out_sh = _batch_only(mesh, b, (result_infos,))[0]
        return mesh, fn, out_sh, arg_sh

    def infer_sharding(mesh, arg_infos, result_infos):
        b = _batch_axis(mesh, arg_infos)
        if isinstance(result_infos, (list, tuple)):
            return _batch_only(mesh, b, result_infos)
        return _batch_only(mesh, b, (result_infos,))[0]

    # ``sharding_rule`` (a Shardy einsum rule) exists from jax 0.4.(late)/0.5
    # onward; older releases take the GSPMD ``infer_sharding_from_operands``
    # callback instead — same batch-only policy either way.
    if "sharding_rule" in inspect.signature(
        custom_partitioning.def_partition
    ).parameters:
        cp.def_partition(partition=partition, sharding_rule=rule)
    else:
        cp.def_partition(
            partition=partition, infer_sharding_from_operands=infer_sharding
        )
    return cp


_FWD_RULE = "b h d t, b h d t, b h d t, b i t -> b h d t"
_BWD_RULE = (
    "b h d t, b h d t, b h d t, b i t, b h d t "
    "-> b h d t, b h d t, b h d t"
)


def _call_fwd(qT, kT, vT, bias, sm_scale, sm_dtype, interpret):
    # custom_partitioning requires a purely positional callee
    def fn(qT, kT, vT, bias):
        return _pallas_fwd(qT, kT, vT, bias, sm_scale, sm_dtype, interpret)

    return _batch_partitioned(fn, _FWD_RULE)(qT, kT, vT, bias)


def _call_bwd(qT, kT, vT, bias, doT, sm_scale, sm_dtype, interpret):
    def fn(qT, kT, vT, bias, doT):
        return _pallas_bwd(qT, kT, vT, bias, doT, sm_scale, sm_dtype,
                           interpret)

    return _batch_partitioned(fn, _BWD_RULE)(qT, kT, vT, bias, doT)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused(qT, kT, vT, bias, sm_scale, sm_dtype, interpret):
    return _call_fwd(qT, kT, vT, bias, sm_scale, sm_dtype, interpret)


def _fused_fwd(qT, kT, vT, bias, sm_scale, sm_dtype, interpret):
    out = _call_fwd(qT, kT, vT, bias, sm_scale, sm_dtype, interpret)
    return out, (qT, kT, vT, bias)


def _fused_bwd(sm_scale, sm_dtype, interpret, res, doT):
    qT, kT, vT, bias = res
    dq, dk, dv = _call_bwd(qT, kT, vT, bias, doT, sm_scale, sm_dtype,
                           interpret)
    return dq, dk, dv, None


_fused.defvjp(_fused_fwd, _fused_bwd)


def _reference_mha(q, k, v, pad_mask, sm_scale, softmax_dtype):
    """The einsum path (models/layers.py dense attention), used off-TPU."""
    from speakingstyle_tpu.ops.masking import attention_bias

    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * jnp.asarray(
        sm_scale, q.dtype
    )
    logits = logits.astype(softmax_dtype) + attention_bias(
        pad_mask, softmax_dtype
    )
    attn = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", attn, v)


# Test hook: when True, the auto path runs the kernel in interpret mode
# even off-TPU, so sharded-mesh CPU tests can exercise the pallas code
# path (tests/test_parallel.py::test_fused_attention_under_sharded_mesh)
# instead of silently falling back to einsum.
FORCE_INTERPRET = False


def _on_tpu() -> bool:
    if not _HAVE_PLTPU:
        return False
    try:
        dev = jax.devices()[0]
    except Exception:  # pragma: no cover - backend init failure
        return False
    kind = (getattr(dev, "device_kind", "") or "").lower()
    return "tpu" in dev.platform.lower() or "tpu" in kind


def supported(T: int, D: int, dtype=jnp.float32) -> bool:
    """Shapes this kernel handles; callers fall back to einsum otherwise.

    D rides on sublanes in the kernel's [B, H, D, T] layout, so it must be
    a multiple of the dtype's sublane tiling: 8 for 4-byte dtypes, 16 for
    bf16/f16, 32 for 1-byte dtypes (Mosaic packs 4/itemsize rows per
    sublane — a D of 8/24/40 in bf16 would pass an %8 gate yet fail
    lowering on real hardware)."""
    sublane = max(8, 32 // jnp.dtype(dtype).itemsize)
    return D % sublane == 0 and D <= LANE and -(-T // LANE) * LANE <= MAX_T


def fused_mha(
    q,
    k,
    v,
    pad_mask,
    sm_scale: Optional[float] = None,
    softmax_dtype=jnp.float32,
    interpret: Optional[bool] = None,
):
    """Fused self-attention. q/k/v: [B, L, H, D] (the layout the model's
    QKV projections produce); pad_mask: [B, L] True at padding. Returns
    [B, L, H, D]. Falls back to the einsum reference off-TPU or for
    unsupported shapes; ``interpret=True`` forces kernel emulation (CPU
    parity tests)."""
    B, L, H, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)
    # interpret=None: auto (real kernel on TPU, einsum fallback elsewhere,
    # emulated kernel if FORCE_INTERPRET); interpret=True: force kernel
    # emulation (CPU tests); interpret=False: force the compiled kernel
    # (raises off-TPU).
    if interpret is None and FORCE_INTERPRET:
        interpret = True
    use_kernel = _on_tpu() if interpret is None else True
    if not use_kernel or not supported(L, D, q.dtype):
        return _reference_mha(q, k, v, pad_mask, sm_scale, softmax_dtype)

    Tp = -(-L // LANE) * LANE
    pad_t = Tp - L
    # [B, L, H, D] -> [B, H, D, Tp]: T on lanes, D on sublanes
    def to_t(x):
        x = x.transpose(0, 2, 3, 1)
        return jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, pad_t)))

    qT, kT, vT = to_t(q), to_t(k), to_t(v)
    neg = jnp.asarray(jnp.finfo(jnp.float32).min / 2, jnp.float32)
    key_pad = jnp.pad(pad_mask, ((0, 0), (0, pad_t)), constant_values=True)
    # [B, 1, Tp]: the middle axis keeps the block's second-minor dim equal
    # to the array dim (a Mosaic block-shape requirement for dims < 8)
    bias = jnp.where(key_pad, neg, jnp.zeros((), jnp.float32))[:, None, :]

    outT = _fused(qT, kT, vT, bias, float(sm_scale), jnp.dtype(softmax_dtype),
                  bool(interpret) if interpret is not None else False)
    # [B, H, D, Tp] -> [B, L, H, D]
    return outT[..., :L].transpose(0, 3, 1, 2)
