"""Conv1d implementations tuned for the MXU.

The step-FLOP budget of the flagship model is ~90% 1-D convolutions
(reference-encoder 1024-channel k=3 stack, decoder k=9 conv-FFN, postnet
k=5 — reference: model/modules.py:307-406, transformer/SubLayers.py:60-93,
transformer/Layers.py:78-148). How those lower onto the TPU matrix unit is
therefore THE performance lever of the whole framework. Three
param-compatible implementations, selected by ``ModelConfig.conv_impl``:

* ``"xla"`` — ``lax.conv_general_dilated`` (flax nn.Conv's path): XLA's
  spatial conv emitter. Baseline.
* ``"unfold"`` — im2col reformulation: stack the K shifted input views and
  contract with one ``[K*Cin, Cout]`` GEMM. Every FLOP lands on the MXU as
  a single large matmul (e.g. the 1024-ch ref-encoder conv becomes
  [B*T, 3072] @ [3072, 1024]); the backward pass autodiffs to two more
  clean GEMMs. Costs K× activation reads — irrelevant while compute-bound.
* ``"pallas"`` — the hand-written fused kernel (ops/pallas_conv.py):
  conv + bias + ReLU (+ LayerNorm) in one VMEM pass, K-tap accumulation
  in f32 without materializing the im2col buffer.

All three produce identical math (tests/test_ops.py::test_conv1d_impl_parity
in the fast CI gate; the full model-level A/B is
tests/test_models.py::test_conv_impls_identical_tree_and_outputs)
and the identical ``{"kernel": [K, Cin, Cout], "bias": [Cout]}`` param
entry, so ``conv_impl`` can change per run — including on a restored
checkpoint — without any conversion.

Exception to the dispatch: **K=1 convs lower as an einsum matmul** for
the "xla" and "unfold" impls (they are not spatial convolutions, and the
einsum measures ~19% faster than the conv emitter at model shapes); the
"pallas" impl keeps its fused kernel so conv+ReLU stays one VMEM pass.
The "xla"-vs-"unfold" A/B therefore compares lowerings of the K>1 convs
only.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

CONV_IMPLS = ("xla", "unfold", "pallas")


def conv1d_unfold(x, kernel, bias=None, dilation: int = 1):
    """SAME-padded 1-D conv as one GEMM. x [B,T,Cin], kernel [K,Cin,Cout]."""
    K = kernel.shape[0]
    if K == 1 and dilation == 1:
        y = jnp.einsum("btc,co->bto", x, kernel[0])
    else:
        span = (K - 1) * dilation + 1
        pad = (span - 1) // 2
        T = x.shape[1]
        xp = jnp.pad(x, ((0, 0), (pad, span - 1 - pad), (0, 0)))
        cols = jnp.stack(
            [
                jax.lax.dynamic_slice_in_dim(xp, j * dilation, T, axis=1)
                for j in range(K)
            ],
            axis=2,
        )  # [B, T, K, Cin] — XLA fuses the stack into the GEMM operand
        y = jnp.einsum("btkc,kco->bto", cols, kernel)
    if bias is not None:
        y = y + bias
    return y


class Conv1d(nn.Module):
    """Drop-in replacement for ``nn.Conv`` (1-D, SAME, channel-last) with a
    selectable lowering. The param entry ({kernel [K,Cin,Cout], bias}) is
    created by this module for every impl, so the tree is identical no
    matter which lowering runs. ``activation="relu"`` fuses the ReLU into
    the pallas kernel (elsewhere it is a separate — XLA-fused — op)."""

    features: int
    kernel_size: int
    impl: str = "xla"
    dilation: int = 1
    use_bias: bool = True
    activation: Optional[str] = None  # None | "relu"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        if self.impl not in CONV_IMPLS:
            raise ValueError(f"conv_impl must be one of {CONV_IMPLS}, got {self.impl!r}")
        if self.activation not in (None, "relu"):
            raise ValueError(f"activation must be None|relu, got {self.activation!r}")
        cin = x.shape[-1]
        # same initializers/layout as nn.Conv for checkpoint parity
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (self.kernel_size, cin, self.features),
        )
        bias = (
            self.param("bias", nn.initializers.zeros, (self.features,))
            if self.use_bias
            else None
        )
        x, kernel, bias = nn.dtypes.promote_dtype(
            x, kernel, bias, dtype=self.dtype
        )
        if self.kernel_size == 1 and self.impl != "pallas":
            # K=1 is mathematically a matmul, lowered as einsum (module
            # docstring "Exception"): ~19% faster fwd+bwd than the conv
            # emitter at model shapes ([48,600,1024]->256: 1.05 vs
            # 1.29 ms), ~14 such convs per step (FFN second halves).
            # The pallas impl keeps its own path so its fused ReLU
            # epilogue stays in one kernel.
            y = conv1d_unfold(x, kernel, bias, dilation=self.dilation)
            if self.activation == "relu":
                y = jnp.maximum(y, 0.0)
            return y
        if self.impl == "pallas":
            from speakingstyle_tpu.ops.pallas_conv import fused_conv1d

            return fused_conv1d(
                x, kernel, bias,
                dilation=self.dilation,
                relu=self.activation == "relu",
            )
        if self.impl == "unfold":
            y = conv1d_unfold(x, kernel, bias, dilation=self.dilation)
        else:
            y = jax.lax.conv_general_dilated(
                x,
                kernel,
                window_strides=(1,),
                padding="SAME",
                rhs_dilation=(self.dilation,),
                dimension_numbers=("NWC", "WIO", "NWC"),
            )
            if bias is not None:
                y = y + bias
        if self.activation == "relu":
            y = jnp.maximum(y, 0.0)
        return y


class ConvParams(nn.Module):
    """Param-only twin of Conv1d ({kernel, bias}) for call sites that hand
    the weights to a fused kernel (e.g. the reference-encoder
    conv+ReLU+LN stack) instead of calling the conv op here."""

    features: int
    kernel_size: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, cin: int):
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (self.kernel_size, cin, self.features),
        )
        bias = (
            self.param("bias", nn.initializers.zeros, (self.features,))
            if self.use_bias
            else None
        )
        return kernel, bias


class AffineParams(nn.Module):
    """Param holder matching ``nn.LayerNorm``'s tree ({scale, bias}) for
    call sites that consume the affine inside a fused kernel instead of a
    separate LayerNorm op."""

    features: int

    @nn.compact
    def __call__(self):
        return (
            self.param("scale", nn.initializers.ones, (self.features,)),
            self.param("bias", nn.initializers.zeros, (self.features,)),
        )
