"""Mask construction helpers.

Convention matches the reference (reference: utils/tools.py:110-118):
masks are True at PADDING positions. All shapes are static under jit;
lengths are traced values.
"""

import jax.numpy as jnp


def length_to_mask(lengths, max_len):
    """[B] lengths -> [B, max_len] bool mask, True where position >= length."""
    ids = jnp.arange(max_len, dtype=lengths.dtype)[None, :]
    return ids >= lengths[:, None]


def attention_bias(pad_mask, dtype=jnp.float32):
    """[B, L] padding mask -> [B, 1, 1, L] additive bias for attention logits.

    Padded keys get a large negative bias (not -inf: on padded *query* rows
    every key would be -inf and softmax would produce NaNs; the reference
    relies on downstream masked_fill to hide those NaN rows, we keep the
    whole graph finite instead).
    """
    neg = jnp.asarray(jnp.finfo(dtype).min / 2, dtype)
    return jnp.where(pad_mask[:, None, None, :], neg, jnp.zeros((), dtype))


def mask_fill(x, pad_mask, value=0.0):
    """Zero (or fill) padded time steps. x: [B, L, H], pad_mask: [B, L]."""
    return jnp.where(pad_mask[..., None], jnp.asarray(value, x.dtype), x)


def masked_mean(values, keep_mask):
    """Mean of `values` over positions where keep_mask is True.

    Equivalent to the reference's ``masked_select(...).mean()`` pattern
    (reference: model/loss.py:55-82) but jit-friendly.
    """
    keep = keep_mask.astype(values.dtype)
    total = jnp.sum(values * keep)
    count = jnp.maximum(jnp.sum(keep), 1.0)
    return total / count
