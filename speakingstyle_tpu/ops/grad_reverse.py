"""Gradient reversal: identity forward, -alpha-scaled gradient backward.

Counterpart of the reference's GradientReversalLayer/RevGrad
(reference: model/blocks.py:7-40) — unused on the reference's main
training path but part of its public surface (adversarial
speaker/style disentanglement experiments). JAX-native as a
``custom_vjp`` pure function; compose it inside any module:

    x = grad_reverse(x, alpha=0.5)
"""

from functools import partial

import jax


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def grad_reverse(x, alpha: float = 1.0):
    return x


def _fwd(x, alpha):
    return x, None


def _bwd(alpha, _, g):
    return (jax.tree_util.tree_map(lambda t: -alpha * t, g),)


grad_reverse.defvjp(_fwd, _bwd)
