"""Jit-traceable length regulation (phoneme -> frame expansion).

The reference expands each phoneme vector `duration[i]` times with a
per-batch-item, per-phoneme Python loop of ``Tensor.expand`` + ``torch.cat``
(reference: model/modules.py:168-201) — host-bound and untraceable. Here the
expansion is a single batched gather:

    ends[i]      = cumsum(durations)[i]           (frame index where phone i ends)
    frame_to_ph  = searchsorted(ends, t, 'right') (phone owning frame t)
    out[t]       = x[frame_to_ph[t]]

All shapes static; frames beyond sum(durations) are masked out. This is the
single most important TPU-side design change (SURVEY.md §7 step 4).
"""

import jax
import jax.numpy as jnp

from speakingstyle_tpu.analysis import contracts
from speakingstyle_tpu.ops.masking import length_to_mask


def length_regulate(x, durations, max_mel_len):
    """Expand phoneme-level features to frame level.

    Args:
      x: [B, L_src, H] phoneme-level features.
      durations: [B, L_src] integer frame counts (>= 0).
      max_mel_len: static output length (frames past the true length are 0).

    Returns:
      (frames [B, max_mel_len, H], mel_lens [B], mel_pad_mask [B, max_mel_len])
    """
    contracts.assert_rank(x, 3, "length_regulate.x")
    contracts.assert_shape(
        durations, x.shape[:2], "length_regulate.durations"
    )
    durations = durations.astype(jnp.int32)
    ends = jnp.cumsum(durations, axis=1)  # [B, L_src]
    mel_lens = ends[:, -1]
    frame_idx = jnp.arange(max_mel_len, dtype=jnp.int32)

    # frame t belongs to the first phone whose end is > t
    frame_to_ph = jax.vmap(
        lambda e: jnp.searchsorted(e, frame_idx, side="right")
    )(ends).astype(jnp.int32)
    frame_to_ph = jnp.minimum(frame_to_ph, x.shape[1] - 1)

    frames = jnp.take_along_axis(x, frame_to_ph[..., None], axis=1)
    mel_lens = jnp.minimum(mel_lens, max_mel_len)
    pad_mask = length_to_mask(mel_lens, max_mel_len)
    frames = jnp.where(pad_mask[..., None], 0.0, frames)
    return frames, mel_lens, pad_mask


def predicted_durations(log_duration_pred, src_pad_mask, d_control=1.0):
    """Free-running durations: round(exp(logd) - 1) * control, clamped at 0.

    Matches reference: model/modules.py:137-144 (note the reference rounds
    *before* scaling by d_control and clamps after; we reproduce that order).
    Padded source positions get duration 0.
    """
    d = jnp.round(jnp.exp(log_duration_pred) - 1.0) * d_control
    d = jnp.clip(d, 0.0, None)
    d = jnp.where(src_pad_mask, 0.0, d)
    return d.astype(jnp.int32)
