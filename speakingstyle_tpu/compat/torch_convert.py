"""PyTorch checkpoint -> Flax parameter conversion.

Loads reference-framework checkpoints for the parity gate (SURVEY.md §6):
the HiFi-GAN generator (``generator_*.pth.tar`` with weight-normed convs,
reference: hifigan/models.py:112-174) and, via `convert_fastspeech2`, the
acoustic-model checkpoints (reference: train.py:155-165 format —
``{"model": state_dict, "optimizer": ...}``).

All functions take a plain ``dict[str, np.ndarray]`` state_dict, so torch is
only needed by the caller that unpickles the file (`load_torch_state_dict`).
"""

from typing import Dict

import numpy as np


def load_torch_state_dict(path: str, key: str = None) -> Dict[str, np.ndarray]:
    """Unpickle a torch checkpoint to numpy (CPU). torch required here only."""
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if key is not None:
        obj = obj[key]
    return {k: v.detach().cpu().numpy() for k, v in obj.items()}


def fold_weight_norm(sd: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Collapse every (weight_g, weight_v) pair into a plain weight.

    torch weight_norm default dim=0: ||v|| is computed over all dims except
    the first (reference inference calls remove_weight_norm,
    hifigan/models.py:167-174 — this is its numpy equivalent).
    """
    out = {}
    for k, v in sd.items():
        if k.endswith("weight_g"):
            base = k[: -len("weight_g")]
            vv = sd[base + "weight_v"]
            axes = tuple(range(1, vv.ndim))
            norm = np.sqrt((vv**2).sum(axis=axes, keepdims=True))
            out[base + "weight"] = (v * vv / np.maximum(norm, 1e-12)).astype(vv.dtype)
        elif k.endswith("weight_v"):
            continue
        else:
            out[k] = v
    return out


def _conv1d(sd, prefix):
    """torch Conv1d [out, in, k] -> flax {kernel [k, in, out], bias}."""
    entry = {"kernel": sd[prefix + ".weight"].transpose(2, 1, 0)}
    if prefix + ".bias" in sd:
        entry["bias"] = sd[prefix + ".bias"]
    return entry


def _dense(sd, prefix):
    """torch Linear [out, in] -> flax {kernel [in, out], bias}."""
    entry = {"kernel": sd[prefix + ".weight"].T}
    if prefix + ".bias" in sd:
        entry["bias"] = sd[prefix + ".bias"]
    return entry


def _ln(sd, prefix):
    """torch LayerNorm/BatchNorm affine -> flax {scale, bias}."""
    return {"scale": sd[prefix + ".weight"], "bias": sd[prefix + ".bias"]}


def _embed(sd, prefix):
    return {"embedding": sd[prefix + ".weight"]}


def _film(sd, prefix):
    return {"s_gamma": sd[prefix + ".s_gamma"], "s_beta": sd[prefix + ".s_beta"]}


def _fft_block(sd, prefix):
    """FFTBlock (reference: transformer/Layers.py:11-37) -> models/layers.py
    FFTBlock params. The optional per-block FiLM maps only when present."""
    block = {
        "slf_attn": {
            "w_qs": _dense(sd, prefix + ".slf_attn.w_qs"),
            "w_ks": _dense(sd, prefix + ".slf_attn.w_ks"),
            "w_vs": _dense(sd, prefix + ".slf_attn.w_vs"),
            "fc": _dense(sd, prefix + ".slf_attn.fc"),
            "layer_norm": _ln(sd, prefix + ".slf_attn.layer_norm"),
        },
        "pos_ffn": {
            "w_1": _conv1d(sd, prefix + ".pos_ffn.w_1"),
            "w_2": _conv1d(sd, prefix + ".pos_ffn.w_2"),
            "layer_norm": _ln(sd, prefix + ".pos_ffn.layer_norm"),
        },
    }
    if prefix + ".film.s_gamma" in sd:
        block["film"] = _film(sd, prefix + ".film")
    return block


def _fft_stack(sd, prefix):
    """ModuleList of FFTBlocks -> FFTStack {layer_i: ...}."""
    stack = {}
    i = 0
    while f"{prefix}.{i}.slf_attn.w_qs.weight" in sd:
        stack[f"layer_{i}"] = _fft_block(sd, f"{prefix}.{i}")
        i += 1
    return stack


def _variance_predictor(sd, prefix, film: bool):
    """reference: model/modules.py:204-259. `film` selects whether the
    predictor's FiLM gates are live in our graph (duration predictor only —
    the torch ckpt carries unused film params for pitch/energy which our
    pitch/energy predictors never instantiate, model/modules.py:122-131)."""
    vp = {
        "conv1d_1": _conv1d(sd, prefix + ".conv_layer.conv1d_1.conv"),
        "layer_norm_1": _ln(sd, prefix + ".conv_layer.layer_norm_1"),
        "conv1d_2": _conv1d(sd, prefix + ".conv_layer.conv1d_2.conv"),
        "layer_norm_2": _ln(sd, prefix + ".conv_layer.layer_norm_2"),
        "linear_layer": _dense(sd, prefix + ".linear_layer"),
    }
    if film and prefix + ".film.s_gamma" in sd:
        # absent in vanilla ming024-style FastSpeech2 checkpoints
        vp["film"] = _film(sd, prefix + ".film")
    return vp


def convert_fastspeech2(sd: Dict[str, np.ndarray]) -> Dict:
    """Acoustic-model state_dict (``torch.load(...)["model"]``, reference:
    train.py:155-165) -> {"params", "batch_stats"} for models/fastspeech2.py.

    Non-trainable buffers that our graph bakes in as constants are skipped:
    ``*.position_enc`` (sinusoid PE recomputed at trace time) and
    ``variance_adaptor.{pitch,energy}_bins`` (compile-time constants from
    stats.json). PostNet BatchNorm running stats land in batch_stats.
    """
    # DataParallel checkpoints prefix every key with "module."
    sd = {k.removeprefix("module."): v for k, v in sd.items()}

    params: Dict = {
        "encoder": {
            "src_word_emb": _embed(sd, "encoder.src_word_emb"),
            "layer_stack": _fft_stack(sd, "encoder.layer_stack"),
        },
        "decoder": {
            "layer_stack": _fft_stack(sd, "decoder.layer_stack"),
        },
        "mel_linear": _dense(sd, "mel_linear"),
    }
    if "speaker_emb.weight" in sd:
        params["speaker_emb"] = _embed(sd, "speaker_emb")

    va = {
        "duration_predictor": _variance_predictor(
            sd, "variance_adaptor.duration_predictor", film=True
        ),
        "pitch_predictor": _variance_predictor(
            sd, "variance_adaptor.pitch_predictor", film=False
        ),
        "energy_predictor": _variance_predictor(
            sd, "variance_adaptor.energy_predictor", film=False
        ),
        "pitch_embedding": _embed(sd, "variance_adaptor.pitch_embedding"),
        "energy_embedding": _embed(sd, "variance_adaptor.energy_embedding"),
    }
    params["variance_adaptor"] = va

    if "reference_encoder.fftb_linear.linear.weight" in sd:
        re: Dict = {}
        i = 0
        while f"reference_encoder.layer_stack.{i}.0.conv.weight" in sd:
            re[f"conv_{i}"] = {
                "conv": _conv1d(sd, f"reference_encoder.layer_stack.{i}.0.conv")
            }
            re[f"ln_{i}"] = _ln(sd, f"reference_encoder.layer_stack.{i}.2")
            i += 1
        re["fftb_linear"] = {
            "linear": _dense(sd, "reference_encoder.fftb_linear.linear")
        }
        j = 0
        while f"reference_encoder.fftb_stack.{j}.slf_attn.w_qs.weight" in sd:
            re[f"fftb_{j}"] = _fft_block(sd, f"reference_encoder.fftb_stack.{j}")
            j += 1
        re["feature_wise_affine"] = {
            "linear": _dense(sd, "reference_encoder.feature_wise_affine.linear")
        }
        params["reference_encoder"] = re

    postnet: Dict = {}
    postnet_stats: Dict = {}
    i = 0
    while f"postnet.convolutions.{i}.0.conv.weight" in sd:
        postnet[f"conv_{i}"] = _conv1d(sd, f"postnet.convolutions.{i}.0.conv")
        postnet[f"bn_{i}"] = _ln(sd, f"postnet.convolutions.{i}.1")
        postnet_stats[f"bn_{i}"] = {
            "mean": sd[f"postnet.convolutions.{i}.1.running_mean"],
            "var": sd[f"postnet.convolutions.{i}.1.running_var"],
        }
        i += 1
    params["postnet"] = postnet

    return {"params": params, "batch_stats": {"postnet": postnet_stats}}


def convert_hifigan(sd: Dict[str, np.ndarray]) -> Dict:
    """Generator state_dict -> params tree for models/hifigan.py.

    Our TorchConvTranspose1d stores its kernel in torch's native
    [in, out, k] layout, so ups_* weights pass through untransposed.
    """
    sd = fold_weight_norm(sd)
    params: Dict = {}
    params["conv_pre"] = {"conv": _conv1d(sd, "conv_pre")}
    params["conv_post"] = {"conv": _conv1d(sd, "conv_post")}

    n_ups = len([k for k in sd if k.startswith("ups.") and k.endswith(".weight")])
    for i in range(n_ups):
        params[f"ups_{i}"] = {
            "kernel": sd[f"ups.{i}.weight"],
            "bias": sd[f"ups.{i}.bias"],
        }

    n_res = len(
        {k.split(".")[1] for k in sd if k.startswith("resblocks.")}
    )
    for n in range(n_res):
        block: Dict = {}
        # ResBlock1 stores dilated+plain conv pairs as convs1/convs2;
        # ResBlock2 (the public V3 config) stores a single "convs" list
        for branch in ("convs1", "convs2", "convs"):
            j = 0
            while f"resblocks.{n}.{branch}.{j}.weight" in sd:
                block[f"{branch}_{j}"] = {
                    "conv": _conv1d(sd, f"resblocks.{n}.{branch}.{j}")
                }
                j += 1
        params[f"resblocks_{n}"] = block
    return params


def convert_melgan(sd: Dict[str, np.ndarray]) -> Dict:
    """descript MelGAN generator state_dict -> params for models/melgan.py.

    The hub module is one big ``nn.Sequential`` named ``model`` (reference
    usage: utils/model.py:64-74; architecture: descriptinc/melgan-neurips
    mel2wav/modules.py), so keys are positional: ``model.<i>.weight`` for
    the plain convs / transposed convs and ``model.<i>.{block.2,block.4,
    shortcut}.weight`` inside ResnetBlocks. Conversion walks the indices in
    order and classifies by position: first plain conv = conv_pre, then
    per upsample stage one transposed conv + n residual blocks, final
    plain conv = conv_post. Weight norm is folded first.
    """
    sd = {k.removeprefix("mel2wav."): v for k, v in sd.items()}
    sd = fold_weight_norm(sd)

    idxs = sorted(
        {int(k.split(".")[1]) for k in sd if k.startswith("model.")}
    )
    plain = [i for i in idxs if f"model.{i}.weight" in sd]
    res = [i for i in idxs if f"model.{i}.block.2.weight" in sd]
    if len(plain) < 3:
        raise ValueError("not a MelGAN generator state_dict")

    def _reflect_conv(i):
        return {"conv": _conv1d(sd, f"model.{i}")}

    params: Dict = {"conv_pre": _reflect_conv(plain[0]),
                    "conv_post": _reflect_conv(plain[-1])}

    ups = plain[1:-1]  # transposed convs, in encounter order
    n_res_per_stage = len(res) // max(len(ups), 1)
    for s, i in enumerate(ups):
        # torch ConvTranspose1d weight [in, out, k] passes through
        # untransposed (TorchConvTranspose1d stores torch's native layout)
        params[f"ups_{s}"] = {
            "kernel": sd[f"model.{i}.weight"],
            "bias": sd[f"model.{i}.bias"],
        }
    for n, i in enumerate(res):
        s, j = divmod(n, n_res_per_stage)
        params[f"res_{s}_{j}"] = {
            "conv1": {"conv": _conv1d(sd, f"model.{i}.block.2")},
            "conv2": {"conv": _conv1d(sd, f"model.{i}.block.4")},
            "shortcut": {"conv": _conv1d(sd, f"model.{i}.shortcut")},
        }
    return params
