"""PyTorch checkpoint -> Flax parameter conversion.

Loads reference-framework checkpoints for the parity gate (SURVEY.md §6):
the HiFi-GAN generator (``generator_*.pth.tar`` with weight-normed convs,
reference: hifigan/models.py:112-174) and, via `convert_fastspeech2`, the
acoustic-model checkpoints (reference: train.py:155-165 format —
``{"model": state_dict, "optimizer": ...}``).

All functions take a plain ``dict[str, np.ndarray]`` state_dict, so torch is
only needed by the caller that unpickles the file (`load_torch_state_dict`).
"""

from typing import Dict

import numpy as np


def load_torch_state_dict(path: str, key: str = None) -> Dict[str, np.ndarray]:
    """Unpickle a torch checkpoint to numpy (CPU). torch required here only."""
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if key is not None:
        obj = obj[key]
    return {k: v.detach().cpu().numpy() for k, v in obj.items()}


def fold_weight_norm(sd: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Collapse every (weight_g, weight_v) pair into a plain weight.

    torch weight_norm default dim=0: ||v|| is computed over all dims except
    the first (reference inference calls remove_weight_norm,
    hifigan/models.py:167-174 — this is its numpy equivalent).
    """
    out = {}
    for k, v in sd.items():
        if k.endswith("weight_g"):
            base = k[: -len("weight_g")]
            vv = sd[base + "weight_v"]
            axes = tuple(range(1, vv.ndim))
            norm = np.sqrt((vv**2).sum(axis=axes, keepdims=True))
            out[base + "weight"] = (v * vv / np.maximum(norm, 1e-12)).astype(vv.dtype)
        elif k.endswith("weight_v"):
            continue
        else:
            out[k] = v
    return out


def _conv1d(sd, prefix):
    """torch Conv1d [out, in, k] -> flax {kernel [k, in, out], bias}."""
    entry = {"kernel": sd[prefix + ".weight"].transpose(2, 1, 0)}
    if prefix + ".bias" in sd:
        entry["bias"] = sd[prefix + ".bias"]
    return entry


def convert_hifigan(sd: Dict[str, np.ndarray]) -> Dict:
    """Generator state_dict -> params tree for models/hifigan.py.

    Our TorchConvTranspose1d stores its kernel in torch's native
    [in, out, k] layout, so ups_* weights pass through untransposed.
    """
    sd = fold_weight_norm(sd)
    params: Dict = {}
    params["conv_pre"] = {"conv": _conv1d(sd, "conv_pre")}
    params["conv_post"] = {"conv": _conv1d(sd, "conv_post")}

    n_ups = len([k for k in sd if k.startswith("ups.") and k.endswith(".weight")])
    for i in range(n_ups):
        params[f"ups_{i}"] = {
            "kernel": sd[f"ups.{i}.weight"],
            "bias": sd[f"ups.{i}.bias"],
        }

    n_res = len(
        {k.split(".")[1] for k in sd if k.startswith("resblocks.")}
    )
    for n in range(n_res):
        block: Dict = {}
        for branch in ("convs1", "convs2"):
            j = 0
            while f"resblocks.{n}.{branch}.{j}.weight" in sd:
                block[f"{branch}_{j}"] = {
                    "conv": _conv1d(sd, f"resblocks.{n}.{branch}.{j}")
                }
                j += 1
        params[f"resblocks_{n}"] = block
    return params
