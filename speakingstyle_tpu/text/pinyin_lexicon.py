"""Generator for the Mandarin pinyin MFA lexicon (pinyin-lexicon-r.txt).

The reference vendors this dictionary as a static data file
(reference: lexicon/pinyin-lexicon-r.txt, 4120 entries) — AISHELL3
preprocessing (MFA alignment) and pinyin g2p at synthesis time both
consume it, and its phone inventory must line up one-to-one with
``text/phonesets.py`` or embedding rows stop matching checkpoints.

Instead of vendoring an opaque table we REGENERATE it from standard
pinyin phonology: each syllable-with-tone decomposes into
``initial final+tone [rr]`` where

  * zh/ch/sh/r + "i" use the retroflex final ``iii``; z/c/s + "i" the
    apical ``ii``
  * j/q/x(+y) neutralize u -> ümlaut: u->v, ue->ve, uan->van, un->vn
  * the contracted finals expand: iu->iou, ui->uei, un->uen
  * pseudo-initials y/w keep their letter and expand to the full
    i-/u- series final (yi -> y i, wen -> w uen; weng merges to uen,
    yo/you both to iou — quirks preserved for row parity)
  * erhua (-r) appends the standalone ``rr`` phone

``write_lexicon(path)`` emits the file: all plain syllable entries
sorted by (syllable, tone), then all erhua entries. Run
``python -m speakingstyle_tpu.text.pinyin_lexicon --out lexicon/pinyin-lexicon-r.txt``.

Content parity: the generated file is LINE-SET IDENTICAL to the
reference's data file (4120 entries; verified by
tests/test_text.py::test_pinyin_lexicon_generator). The only raw
diff is line ORDER for 60 lines: the reference file was hand-edited, with
``r1..r5`` spliced in before ``er*`` and the ``lve*``/``nve*`` spelling
variants spliced immediately after ``lue*``/``nue*`` instead of in sorted
position. Lexicon lookup (MFA and ``text/g2p.py``) is order-independent,
so we keep deterministic sorted order rather than reproducing the manual
insertion points.
"""

import argparse

# The standard Mandarin syllabary (412 pinyin syllables as used by the
# AISHELL3 corpus' MFA dictionary; includes the interjection/colloquial
# forms lo, me, yo, den, dia, rua, tei, kei, zhei, shei, nou and the
# standalone retroflex "r").
PLAIN_SYLLABLES = """
a ai an ang ao ba bai ban bang bao bei ben beng bi bian biao bie bin
bing bo bu ca cai can cang cao ce cen ceng cha chai chan chang chao che
chen cheng chi chong chou chu chuai chuan chuang chui chun chuo ci cong
cou cu cuan cui cun cuo da dai dan dang dao de dei den deng di dia dian
diao die ding diu dong dou du duan dui dun duo e ei en eng er fa fan
fang fei fen feng fo fou fu ga gai gan gang gao ge gei gen geng gong
gou gu gua guai guan guang gui gun guo ha hai han hang hao he hei hen
heng hong hou hu hua huai huan huang hui hun huo ji jia jian jiang jiao
jie jin jing jiong jiu ju juan jue jun ka kai kan kang kao ke kei ken
keng kong kou ku kua kuai kuan kuang kui kun kuo la lai lan lang lao le
lei leng li lia lian liang liao lie lin ling liu lo long lou lu luan
lue lun luo lv lve ma mai man mang mao me mei men meng mi mian miao mie
min ming miu mo mou mu na nai nan nang nao ne nei nen neng ni nian
niang niao nie nin ning niu nong nou nu nuan nue nuo nv nve o ou pa
pai pan pang pao pei pen peng pi pian piao pie pin ping po pou pu qi
qia qian qiang qiao qie qin qing qiong qiu qu quan que qun r ran rang
rao re ren reng ri rong rou ru rua ruan rui run ruo sa sai san sang
sao se sen seng sha shai shan shang shao she shei shen sheng shi shou
shu shua shuai shuan shuang shui shun shuo si song sou su suan sui sun
suo ta tai tan tang tao te tei teng ti tian tiao tie ting tong tou tu
tuan tui tun tuo wa wai wan wang wei wen weng wo wu xi xia xian xiang
xiao xie xin xing xiong xiu xu xuan xue xun ya yan yang yao ye yi yin
ying yo yong you yu yuan yue yun za zai zan zang zao ze zei zen zeng
zha zhai zhan zhang zhao zhe zhei zhen zheng zhi zhong zhou zhu zhua
zhuai zhuan zhuang zhui zhun zhuo zi zong zou zu zuan zui zun zuo
""".split()

ZERO_INITIAL = {"a", "ai", "an", "ang", "ao", "e", "ei", "en", "eng",
                "er", "o", "ou"}
_INITIALS = ("zh", "ch", "sh", "b", "p", "m", "f", "d", "t", "n", "l",
             "g", "k", "h", "j", "q", "x", "r", "z", "c", "s")
_V_SERIES = {"u": "v", "ue": "ve", "uan": "van", "un": "vn"}
_CONTRACTED = {"iu": "iou", "ui": "uei", "un": "uen", "ue": "ve"}
TONES = "12345"


def decompose(syllable: str):
    """Base pinyin syllable (no tone, no erhua) -> (initial|None, final)."""
    s = syllable
    if s in ZERO_INITIAL:
        return None, s
    if s == "r":  # standalone retroflex syllable, e.g. 儿 in casual text
        return None, "er"
    if s[0] == "y":
        rest = s[1:]
        if rest.startswith("u"):  # yu-series neutralizes to v
            return "y", _V_SERIES.get(rest, "v" + rest[1:])
        if s == "yo" or s == "you":
            return "y", "iou"
        return "y", rest if rest.startswith("i") else "i" + rest
    if s[0] == "w":
        rest = s[1:]
        if s == "weng":  # merged with uen in this phone set
            return "w", "uen"
        return "w", rest if rest.startswith("u") else "u" + rest
    for ini in _INITIALS:
        if s.startswith(ini) and len(s) > len(ini):
            rest = s[len(ini):]
            if rest == "i" and ini in ("zh", "ch", "sh", "r"):
                return ini, "iii"
            if rest == "i" and ini in ("z", "c", "s"):
                return ini, "ii"
            if ini in ("j", "q", "x") and rest in _V_SERIES:
                return ini, _V_SERIES[rest]
            return ini, _CONTRACTED.get(rest, rest)
    raise ValueError(f"cannot decompose pinyin syllable {syllable!r}")


def entries():
    """Yield (key, [phones]) in the file's order: plain block, then erhua."""
    for s in sorted(PLAIN_SYLLABLES):
        ini, fin = decompose(s)
        for t in TONES:
            phones = ([ini] if ini else []) + [fin + t]
            yield f"{s}{t}", phones
    for s in sorted(PLAIN_SYLLABLES):
        if s in ("r", "er"):  # already end in r: no -r erhua key of their own
            continue
        ini, fin = decompose(s)
        for t in TONES:
            phones = ([ini] if ini else []) + [fin + t, "rr"]
            yield f"{s}r{t}", phones


def write_lexicon(path: str) -> int:
    n = 0
    with open(path, "w", encoding="utf-8") as f:
        for key, phones in entries():
            f.write(f"{key} {' '.join(phones)}\n")
            n += 1
    return n


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="lexicon/pinyin-lexicon-r.txt")
    args = ap.parse_args(argv)
    import os

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    n = write_lexicon(args.out)
    print(f"wrote {n} entries to {args.out}")


if __name__ == "__main__":
    main()
