"""Phone-set inventories for the grapheme/phoneme vocabulary.

The ARPAbet set (84 entries, incl. stress-marked vowels) and the pinyin
initial/final set (209 entries, incl. tone-marked finals) reproduce the
reference inventories (reference: text/cmudict.py:6-92, text/pinyin.py) so
that symbol ids — and therefore embedding rows — line up one-to-one.
"""

ARPABET_SYMBOLS = [
    "AA", "AA0", "AA1", "AA2", "AE", "AE0", "AE1", "AE2",
    "AH", "AH0", "AH1", "AH2", "AO", "AO0", "AO1", "AO2",
    "AW", "AW0", "AW1", "AW2", "AY", "AY0", "AY1", "AY2",
    "B", "CH", "D", "DH", "EH", "EH0", "EH1", "EH2",
    "ER", "ER0", "ER1", "ER2", "EY", "EY0", "EY1", "EY2",
    "F", "G", "HH", "IH", "IH0", "IH1", "IH2", "IY",
    "IY0", "IY1", "IY2", "JH", "K", "L", "M", "N",
    "NG", "OW", "OW0", "OW1", "OW2", "OY", "OY0", "OY1",
    "OY2", "P", "R", "S", "SH", "T", "TH", "UH",
    "UH0", "UH1", "UH2", "UW", "UW0", "UW1", "UW2", "V",
    "W", "Y", "Z", "ZH",
]

PINYIN_SYMBOLS = [
    "b", "c", "ch", "d", "f", "g", "h", "j",
    "k", "l", "m", "n", "p", "q", "r", "s",
    "sh", "t", "w", "x", "y", "z", "zh", "a1",
    "a2", "a3", "a4", "a5", "ai1", "ai2", "ai3", "ai4",
    "ai5", "an1", "an2", "an3", "an4", "an5", "ang1", "ang2",
    "ang3", "ang4", "ang5", "ao1", "ao2", "ao3", "ao4", "ao5",
    "e1", "e2", "e3", "e4", "e5", "ei1", "ei2", "ei3",
    "ei4", "ei5", "en1", "en2", "en3", "en4", "en5", "eng1",
    "eng2", "eng3", "eng4", "eng5", "er1", "er2", "er3", "er4",
    "er5", "i1", "i2", "i3", "i4", "i5", "ia1", "ia2",
    "ia3", "ia4", "ia5", "ian1", "ian2", "ian3", "ian4", "ian5",
    "iang1", "iang2", "iang3", "iang4", "iang5", "iao1", "iao2", "iao3",
    "iao4", "iao5", "ie1", "ie2", "ie3", "ie4", "ie5", "ii1",
    "ii2", "ii3", "ii4", "ii5", "iii1", "iii2", "iii3", "iii4",
    "iii5", "in1", "in2", "in3", "in4", "in5", "ing1", "ing2",
    "ing3", "ing4", "ing5", "iong1", "iong2", "iong3", "iong4", "iong5",
    "iou1", "iou2", "iou3", "iou4", "iou5", "o1", "o2", "o3",
    "o4", "o5", "ong1", "ong2", "ong3", "ong4", "ong5", "ou1",
    "ou2", "ou3", "ou4", "ou5", "u1", "u2", "u3", "u4",
    "u5", "ua1", "ua2", "ua3", "ua4", "ua5", "uai1", "uai2",
    "uai3", "uai4", "uai5", "uan1", "uan2", "uan3", "uan4", "uan5",
    "uang1", "uang2", "uang3", "uang4", "uang5", "uei1", "uei2", "uei3",
    "uei4", "uei5", "uen1", "uen2", "uen3", "uen4", "uen5", "uo1",
    "uo2", "uo3", "uo4", "uo5", "v1", "v2", "v3", "v4",
    "v5", "van1", "van2", "van3", "van4", "van5", "ve1", "ve2",
    "ve3", "ve4", "ve5", "vn1", "vn2", "vn3", "vn4", "vn5",
    "rr",
]
