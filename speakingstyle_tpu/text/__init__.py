"""Text frontend: symbol vocabulary, cleaners, sequence conversion.

API mirrors the reference frontend (reference: text/__init__.py:15-76):
``{...}``-braced phone strings bypass the cleaners and map to "@"-prefixed
phone symbols; everything else is cleaned then mapped character-wise.
"""

import re

from speakingstyle_tpu.text.cleaners import clean_text
from speakingstyle_tpu.text.symbols import (
    ID_TO_SYMBOL,
    PAD_ID,
    SYMBOL_TO_ID,
    VOCAB_SIZE,
    symbols,
)

_curly_re = re.compile(r"(.*?)\{(.+?)\}(.*)")


def _keep(symbol):
    return symbol in SYMBOL_TO_ID and symbol not in ("_", "~")


def _symbols_to_ids(syms):
    return [SYMBOL_TO_ID[s] for s in syms if _keep(s)]


def _phones_to_ids(phone_text):
    return _symbols_to_ids(["@" + s for s in phone_text.split()])


def text_to_sequence(text, cleaner_names):
    """Convert text (with optional {PH ON E} spans) to a list of symbol ids."""
    sequence = []
    while text:
        m = _curly_re.match(text)
        if not m:
            sequence += _symbols_to_ids(clean_text(text, cleaner_names))
            break
        sequence += _symbols_to_ids(clean_text(m.group(1), cleaner_names))
        sequence += _phones_to_ids(m.group(2))
        text = m.group(3)
    return sequence


def sequence_to_text(sequence):
    """Inverse of text_to_sequence; phone symbols are re-braced."""
    out = []
    for sid in sequence:
        s = ID_TO_SYMBOL.get(int(sid))
        if s is None:
            continue
        if len(s) > 1 and s[0] == "@":
            s = "{%s}" % s[1:]
        out.append(s)
    return "".join(out).replace("}{", " ")


__all__ = [
    "text_to_sequence",
    "sequence_to_text",
    "symbols",
    "SYMBOL_TO_ID",
    "ID_TO_SYMBOL",
    "PAD_ID",
    "VOCAB_SIZE",
]
