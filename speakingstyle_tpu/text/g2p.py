"""Synthesis-time grapheme-to-phoneme frontends.

Reference: synthesize.py:26-90. English goes through a pronouncing lexicon
with a ``g2p_en`` fallback for OOV words; Mandarin goes through ``pypinyin``
TONE3 pinyin and a pinyin→initial/final lexicon with OOV mapped to "sp".
Both external packages are optional: without them, lexicon hits still work
and OOV handling degrades gracefully (letters-as-graphemes / "sp").
"""

import re
from string import punctuation
from typing import Dict, List, Optional, Tuple

import numpy as np

from speakingstyle_tpu.text import text_to_sequence

_WORD_SPLIT_RE = re.compile(r"([,;.\-\?\!\s+])")


def read_lexicon(path: str) -> Dict[str, List[str]]:
    """word -> phone list; first pronunciation wins (reference:
    synthesize.py:26-35).

    The pinyin lexicon is self-hosting: if ``path`` names the standard
    ``pinyin-lexicon-r.txt`` and the file does not exist yet, it is
    generated in place from ``text/pinyin_lexicon.py`` (the reference
    vendors it as opaque data; we derive it from pinyin phonology).
    """
    import os

    if not os.path.exists(path) and os.path.basename(path) == "pinyin-lexicon-r.txt":
        from speakingstyle_tpu.text.pinyin_lexicon import write_lexicon

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        write_lexicon(path)
    lexicon: Dict[str, List[str]] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = re.split(r"\s+", line.strip("\n"))
            if len(parts) < 2:
                continue
            word, phones = parts[0].lower(), parts[1:]
            lexicon.setdefault(word, phones)
    return lexicon


def _g2p_en_fallback():
    try:
        from g2p_en import G2p  # optional

        return G2p()
    except ImportError:
        return None


def english_word_spans(
    text: str, lexicon: Dict[str, List[str]], g2p=None
) -> List[Tuple[str, List[str]]]:
    """English text -> [(word, [phones])] keeping word→phone alignment.

    Lexicon lookup per word; OOV words go to g2p_en when available, else to
    the "spn" unknown marker (MFA convention); punctuation tokens become
    "sp" pauses (reference: synthesize.py:44-52). The spans feed both plain
    synthesis (joined) and per-word prosody control (control.py).
    """
    text = text.rstrip(punctuation)
    if g2p is None:
        g2p = _g2p_en_fallback()
    spans: List[Tuple[str, List[str]]] = []
    for w in _WORD_SPLIT_RE.split(text):
        if not w or w.isspace():
            continue
        lw = w.lower()
        if not re.match(r"[\w\d]", w):
            phones = ["sp"]  # punctuation -> short pause
        elif lw in lexicon:
            phones = list(lexicon[lw])
        elif g2p is not None:
            phones = [p for p in g2p(w) if p != " "]
        else:
            phones = ["spn"]
        # g2p can emit punctuation-ish phones; map those to pauses too
        phones = ["sp" if not re.match(r"[\w\d]", p) else p for p in phones]
        spans.append((w, phones))
    return spans


def english_to_phones(
    text: str, lexicon: Dict[str, List[str]], g2p=None
) -> str:
    """English text -> "{PH ON E ...}" phone string."""
    spans = english_word_spans(text, lexicon, g2p=g2p)
    return "{" + " ".join(p for _, ps in spans for p in ps) + "}"


def mandarin_to_phones(text: str, lexicon: Dict[str, List[str]]) -> str:
    """Mandarin text -> phone string via TONE3 pinyin + lexicon
    (reference: synthesize.py:65-81)."""
    try:
        from pypinyin import Style, pinyin  # optional

        pinyins = [
            p[0]
            for p in pinyin(
                text, style=Style.TONE3, strict=False, neutral_tone_with_five=True
            )
        ]
    except ImportError:
        pinyins = text.split()  # assume pre-converted pinyin tokens
    phones: List[str] = []
    for p in pinyins:
        phones += lexicon.get(p, ["sp"])
    return "{" + " ".join(phones) + "}"


def preprocess_text(
    text: str,
    language: str,
    lexicon_path: Optional[str],
    cleaners: List[str],
    g2p=None,
) -> np.ndarray:
    """Raw text -> int32 symbol-id array (reference: synthesize.py:38-90)."""
    lexicon = read_lexicon(lexicon_path) if lexicon_path else {}
    if language == "zh":
        phones = mandarin_to_phones(text, lexicon)
    else:
        phones = english_to_phones(text, lexicon, g2p=g2p)
    return np.asarray(text_to_sequence(phones, cleaners), np.int32)
