"""Symbol vocabulary for text input.

Layout (order matters — ids must match the reference checkpoints, see
reference: text/symbols.py:10-29): pad, "-", punctuation, ASCII letters,
"@"-prefixed ARPAbet, "@"-prefixed pinyin, silence marks. 360 symbols total;
the embedding table is sized ``len(symbols) + 1`` (vocab 361).
"""

from speakingstyle_tpu.text.phonesets import ARPABET_SYMBOLS, PINYIN_SYMBOLS

PAD = "_"
SPECIAL = "-"
PUNCTUATION = "!'(),.:;? "
LETTERS = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"
SILENCES = ["@sp", "@spn", "@sil"]

symbols = (
    [PAD]
    + list(SPECIAL)
    + list(PUNCTUATION)
    + list(LETTERS)
    + ["@" + s for s in ARPABET_SYMBOLS]
    + ["@" + s for s in PINYIN_SYMBOLS]
    + SILENCES
)

PAD_ID = 0
VOCAB_SIZE = len(symbols) + 1

SYMBOL_TO_ID = {s: i for i, s in enumerate(symbols)}
ID_TO_SYMBOL = {i: s for i, s in enumerate(symbols)}
