"""English number normalization (dependency-free).

Behavioral equivalent of the reference's inflect-based normalizer
(reference: text/numbers.py:7-73): commas stripped, currency expanded,
decimals read as "point", ordinals spelled out, years grouped in digit
pairs, everything else read as cardinal words without "and".
"""

import re

_UNITS = [
    "zero", "one", "two", "three", "four", "five", "six", "seven", "eight",
    "nine", "ten", "eleven", "twelve", "thirteen", "fourteen", "fifteen",
    "sixteen", "seventeen", "eighteen", "nineteen",
]
_TENS = [
    "", "", "twenty", "thirty", "forty", "fifty", "sixty", "seventy",
    "eighty", "ninety",
]
_SCALE_NAMES = ["", "thousand", "million", "billion", "trillion", "quadrillion"]

_ORDINAL_IRREGULAR = {
    "one": "first", "two": "second", "three": "third", "five": "fifth",
    "eight": "eighth", "nine": "ninth", "twelve": "twelfth",
}

_comma_number_re = re.compile(r"([0-9][0-9\,]+[0-9])")
_decimal_number_re = re.compile(r"([0-9]+\.[0-9]+)")
_pounds_re = re.compile(r"£([0-9\,]*[0-9]+)")
_dollars_re = re.compile(r"\$([0-9\.\,]*[0-9]+)")
_ordinal_re = re.compile(r"[0-9]+(st|nd|rd|th)")
_number_re = re.compile(r"[0-9]+")


def _small_to_words(n):
    """Words for 0 <= n < 100."""
    if n < 20:
        return _UNITS[n]
    tens, unit = divmod(n, 10)
    if unit:
        return _TENS[tens] + "-" + _UNITS[unit]
    return _TENS[tens]


def _group_to_words(n, andword):
    """Words for 0 < n < 1000: "X hundred[ <andword>] YZ" (inflect style)."""
    hundreds, rest = divmod(n, 100)
    parts = []
    if hundreds:
        parts.append(_UNITS[hundreds] + " hundred")
    if rest:
        if hundreds and andword:
            parts.append(andword)
        parts.append(_small_to_words(rest))
    return " ".join(parts)


def number_to_words(n, andword=""):
    """Cardinal words matching inflect's format: scale groups joined with
    ", " and an optional andword between hundreds and tens (the reference
    calls inflect with andword="" for cardinals, text/numbers.py:63).
    e.g. 3456 -> "three thousand, four hundred fifty-six".
    """
    if n < 0:
        return "minus " + number_to_words(-n, andword)
    if n == 0:
        return "zero"
    groups = []  # (scale_index, 3-digit value), most significant first
    scale = 0
    while n:
        n, g = divmod(n, 1000)
        if g:
            groups.append((scale, g))
        scale += 1
    words = []
    for scale, g in reversed(groups):
        w = _group_to_words(g, andword)
        if scale:
            w += " " + _SCALE_NAMES[scale]
        words.append(w)
    return ", ".join(words)


def ordinal_to_words(n):
    """Ordinal words, inflect-style with "and": 101 -> "one hundred and first"."""
    words = number_to_words(n, andword="and")
    for sep in ("-", " "):
        head, found, last = words.rpartition(sep)
        if found:
            break
    if last in _ORDINAL_IRREGULAR:
        last = _ORDINAL_IRREGULAR[last]
    elif last.endswith("y"):
        last = last[:-1] + "ieth"
    else:
        last = last + "th"
    return head + found + last if found else last


def _year_to_words(n):
    """Digit-pair year reading: 1999 -> "nineteen ninety-nine"."""
    if n == 2000:
        return "two thousand"
    if 2000 < n < 2010:
        return "two thousand " + _UNITS[n % 100]
    if n % 100 == 0:
        return number_to_words(n // 100) + " hundred"
    high, low = divmod(n, 100)
    low_words = "oh " + _UNITS[low] if low < 10 else _small_to_words(low)
    return _small_to_words(high) + " " + low_words


def _remove_commas(m):
    return m.group(1).replace(",", "")


def _expand_decimal_point(m):
    integer, frac = m.group(1).split(".")
    return integer + " point " + frac


def _expand_dollars(m):
    match = m.group(1)
    parts = match.split(".")
    if len(parts) > 2:
        return match + " dollars"
    dollars = int(parts[0]) if parts[0] else 0
    cents = int(parts[1]) if len(parts) > 1 and parts[1] else 0
    if dollars and cents:
        dollar_unit = "dollar" if dollars == 1 else "dollars"
        cent_unit = "cent" if cents == 1 else "cents"
        return "%s %s, %s %s" % (dollars, dollar_unit, cents, cent_unit)
    if dollars:
        return "%s %s" % (dollars, "dollar" if dollars == 1 else "dollars")
    if cents:
        return "%s %s" % (cents, "cent" if cents == 1 else "cents")
    return "zero dollars"


def _expand_ordinal(m):
    return ordinal_to_words(int(m.group(0)[:-2]))


def _expand_number(m):
    num = int(m.group(0))
    if 1000 < num < 3000:
        return _year_to_words(num)
    return number_to_words(num)


def normalize_numbers(text):
    text = re.sub(_comma_number_re, _remove_commas, text)
    text = re.sub(_pounds_re, r"\1 pounds", text)
    text = re.sub(_dollars_re, _expand_dollars, text)
    text = re.sub(_decimal_number_re, _expand_decimal_point, text)
    text = re.sub(_ordinal_re, _expand_ordinal, text)
    text = re.sub(_number_re, _expand_number, text)
    return text
