"""Text cleaners (reference: text/cleaners.py).

Same three pipelines as the reference — ``english_cleaners``,
``basic_cleaners``, ``transliteration_cleaners`` — with ASCII
transliteration done via ``unidecode`` when available and a
``unicodedata``-based fallback otherwise (the reference hard-depends on
unidecode).
"""

import re
import unicodedata

try:
    from unidecode import unidecode as _to_ascii
except ImportError:  # pragma: no cover - exercised only without unidecode
    def _to_ascii(text):
        decomposed = unicodedata.normalize("NFKD", text)
        return decomposed.encode("ascii", "ignore").decode("ascii")

_whitespace_re = re.compile(r"\s+")

_abbreviations = [
    (re.compile(r"\b%s\." % abbr, re.IGNORECASE), expansion)
    for abbr, expansion in [
        ("mrs", "misess"),
        ("mr", "mister"),
        ("dr", "doctor"),
        ("st", "saint"),
        ("co", "company"),
        ("jr", "junior"),
        ("maj", "major"),
        ("gen", "general"),
        ("drs", "doctors"),
        ("rev", "reverend"),
        ("lt", "lieutenant"),
        ("hon", "honorable"),
        ("sgt", "sergeant"),
        ("capt", "captain"),
        ("esq", "esquire"),
        ("ltd", "limited"),
        ("col", "colonel"),
        ("ft", "fort"),
    ]
]

from speakingstyle_tpu.text.numbers import normalize_numbers


def expand_abbreviations(text):
    for regex, replacement in _abbreviations:
        text = re.sub(regex, replacement, text)
    return text


def lowercase(text):
    return text.lower()


def collapse_whitespace(text):
    return re.sub(_whitespace_re, " ", text)


def convert_to_ascii(text):
    return _to_ascii(text)


def basic_cleaners(text):
    """Lowercase + collapse whitespace, no transliteration."""
    return collapse_whitespace(lowercase(text))


def transliteration_cleaners(text):
    """ASCII transliteration for non-English text."""
    return collapse_whitespace(lowercase(convert_to_ascii(text)))


def english_cleaners(text):
    """Full English pipeline: ascii, lowercase, numbers, abbreviations."""
    text = convert_to_ascii(text)
    text = lowercase(text)
    text = normalize_numbers(text)
    text = expand_abbreviations(text)
    text = collapse_whitespace(text)
    return text


CLEANERS = {
    "basic_cleaners": basic_cleaners,
    "transliteration_cleaners": transliteration_cleaners,
    "english_cleaners": english_cleaners,
}


def clean_text(text, cleaner_names):
    for name in cleaner_names:
        if name not in CLEANERS:
            raise ValueError("Unknown cleaner: %s" % name)
        text = CLEANERS[name](text)
    return text
