"""jaxlint driver: file walking, suppression handling, baseline compare.

The linter's contract with CI (tests/test_analysis.py makes it tier-1):

  * ``lint_paths(paths)`` -> findings, with per-line
    ``# jaxlint: disable=JL001[,JL004]`` (or bare ``disable``) and
    file-level ``# jaxlint: skip-file`` suppressions already applied.
  * Findings fingerprint as ``rule:path:context:detail`` — deliberately
    line-number-free, so unrelated edits don't churn the baseline.
  * ``compare_to_baseline`` is bidirectional: NEW findings fail, and
    STALE baseline entries (fixed code, unfixed baseline) also fail, so
    the committed baseline can never silently rot.
"""

import collections
import io
import json
import os
import time
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from speakingstyle_tpu.analysis.rules import RULES, Finding, ModuleInfo

import ast

_SKIP_DIRS = {
    "__pycache__", ".git", ".jax_cache", "artifacts", "node_modules",
    ".pytest_cache",
}

DEFAULT_BASELINE_NAME = "baseline.json"


def repo_root() -> str:
    """The directory containing the ``speakingstyle_tpu`` package."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def default_baseline_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)), DEFAULT_BASELINE_NAME
    )


def default_lockorder_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "lockorder.json"
    )


def default_lint_paths() -> List[str]:
    root = repo_root()
    out = []
    for rel in ("speakingstyle_tpu", "scripts", "tests", "bench.py"):
        p = os.path.join(root, rel)
        if os.path.exists(p):
            out.append(p)
    return out


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------


def _directives(source: str) -> Tuple[bool, Dict[int, Optional[set]]]:
    """Parse jaxlint comments. Returns (skip_file, {line: rules-or-None}).

    ``None`` as the rule set means "disable everything on this line".
    Uses the tokenizer so string literals containing 'jaxlint:' are not
    misread as directives.

    A directive that is the only thing on its line applies to the NEXT
    line instead — so long ``reason=`` clauses don't force overlong
    code lines.
    """
    skip_file = False
    per_line: Dict[int, Optional[set]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            text = tok.string.lstrip("#").strip()
            if not text.startswith("jaxlint:"):
                continue
            body = text[len("jaxlint:"):].strip()
            target = tok.start[0]
            if tok.line.lstrip().startswith("#"):
                target += 1   # standalone comment: guards the next line
            if body == "skip-file":
                skip_file = True
            elif body == "disable":
                per_line[target] = None
            elif body.startswith("disable="):
                spec = body[len("disable="):]
                # an optional trailing reason clause documents WHY a
                # deliberate pattern is suppressed:
                #   # jaxlint: disable=JL020 reason=single-reader stamp
                # (the concurrency rules require one; the reason text is
                # free-form and ends at end-of-comment)
                if " reason=" in spec:
                    spec = spec.split(" reason=", 1)[0]
                rules = {
                    r.strip().upper()
                    for r in spec.split(",")
                    if r.strip()
                }
                existing = per_line.get(target, set())
                per_line[target] = (
                    None if existing is None else existing | rules
                )
    except tokenize.TokenError:
        pass  # malformed tail; directives seen so far still apply
    return skip_file, per_line


def _suppressed(finding: Finding, per_line: Dict[int, Optional[set]]) -> bool:
    rules = per_line.get(finding.line, set())
    return rules is None or (rules and finding.rule in rules)


# ---------------------------------------------------------------------------
# linting
# ---------------------------------------------------------------------------


def lint_source(
    source: str,
    path: str = "<string>",
    select: Optional[Iterable[str]] = None,
    profile: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """Lint one source string; ``path`` is used for reporting/fingerprints
    and for path-scoped rules (JL004 looks for ``training/``).

    ``profile``, if given, accumulates per-rule wall seconds
    (``--profile`` in the CLI).
    """
    skip_file, per_line = _directives(source)
    if skip_file:
        return []
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [
            Finding(
                rule="JL000",
                path=path,
                line=e.lineno or 0,
                context="<module>",
                detail="syntax error",
                message=f"could not parse: {e.msg}",
            )
        ]
    mod = ModuleInfo(path, source, tree)
    wanted = set(select) if select else set(RULES)
    findings: List[Finding] = []
    for code, rule in sorted(RULES.items()):
        if code not in wanted:
            continue
        t0 = time.perf_counter() if profile is not None else 0.0
        for f in rule(mod):
            if not _suppressed(f, per_line):
                findings.append(f)
        if profile is not None:
            profile[code] = (
                profile.get(code, 0.0) + time.perf_counter() - t0
            )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def lint_paths(
    paths: Optional[Sequence[str]] = None,
    select: Optional[Iterable[str]] = None,
    root: Optional[str] = None,
    profile: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """Lint files/trees; paths in findings are repo-root-relative."""
    root = root or repo_root()
    paths = list(paths) if paths else default_lint_paths()
    findings: List[Finding] = []
    for fpath in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(fpath), root).replace(
            os.sep, "/"
        )
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError):
            continue
        findings.extend(
            lint_source(source, rel, select=select, profile=profile)
        )
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def findings_counter(findings: Iterable[Finding]) -> "collections.Counter":
    return collections.Counter(f.fingerprint for f in findings)


def load_baseline(path: Optional[str] = None) -> "collections.Counter":
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return collections.Counter()
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return collections.Counter(
        {entry["fingerprint"]: entry["count"] for entry in data["findings"]}
    )


def save_baseline(findings: Iterable[Finding], path: Optional[str] = None):
    path = path or default_baseline_path()
    counter = findings_counter(findings)
    data = {
        "comment": (
            "jaxlint tracked-but-allowed findings. Entries here are known "
            "hazards that are deliberate (rate-gated syncs, bucketed "
            "retraces) or pre-existing. Regenerate with "
            "`python scripts/lint_jax.py --update-baseline` and review the "
            "diff like code."
        ),
        "version": 1,
        "findings": [
            {"fingerprint": fp, "count": n}
            for fp, n in sorted(counter.items())
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2)
        fh.write("\n")


def compare_to_baseline(
    findings: Iterable[Finding], baseline: "collections.Counter"
) -> Tuple[Dict[str, int], Dict[str, int]]:
    """-> (new findings over baseline, stale baseline entries), both as
    {fingerprint: count-delta}."""
    current = findings_counter(findings)
    new = {
        fp: n - baseline.get(fp, 0)
        for fp, n in current.items()
        if n > baseline.get(fp, 0)
    }
    stale = {
        fp: n - current.get(fp, 0)
        for fp, n in baseline.items()
        if n > current.get(fp, 0)
    }
    return new, stale
