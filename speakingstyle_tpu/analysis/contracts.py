"""Runtime shape/dtype contracts (chex-style, zero-cost when disabled).

The static linter (``speakingstyle_tpu.analysis``) catches structural
TPU-safety hazards; this module covers the complementary dynamic class —
wrong shapes/dtypes threaded through the model entry points, and NaN/Inf
trees at host boundaries. Every helper is a no-op unless the environment
variable ``SPEAKINGSTYLE_CHECKS=1`` is set when the process starts, so the
hot path compiles to exactly the same jaxpr in production.

Design rules:
  * Shape/dtype/rank checks read only static metadata (``.shape``,
    ``.dtype``) — they work identically on concrete arrays and tracers,
    and inside ``jax.jit`` they fail at trace time, not run time.
  * ``assert_tree_finite`` needs values, so it silently skips tracers:
    inside a jitted function it is a no-op (no host sync is ever
    introduced); call it at host boundaries (checkpoint save, logging).
  * Failures raise ``ContractError`` (an ``AssertionError`` subclass) with
    the offending name, expected spec, and actual metadata.

Enablement is snapshotted at import (``ENABLED``); tests flip the module
attribute directly instead of re-importing.
"""

import os

ENABLED = os.environ.get("SPEAKINGSTYLE_CHECKS", "") == "1"


class ContractError(AssertionError):
    """A runtime shape/dtype/finiteness contract was violated."""


def checks_enabled() -> bool:
    return ENABLED


def assert_rank(x, rank: int, name: str = "array"):
    """``x.ndim == rank``; None passes (optional inputs)."""
    if not ENABLED or x is None:
        return x
    actual = getattr(x, "ndim", None)
    if actual is None:
        actual = len(getattr(x, "shape", ()))
    if actual != rank:
        raise ContractError(
            f"{name}: expected rank {rank}, got rank {actual} "
            f"(shape {tuple(getattr(x, 'shape', ()))})"
        )
    return x


def assert_shape(x, shape, name: str = "array"):
    """``x.shape`` matches ``shape``; ``None`` entries are wildcards.

    ``assert_shape(x, (None, 80))`` accepts any [B, 80]. None ``x`` passes.
    """
    if not ENABLED or x is None:
        return x
    actual = tuple(getattr(x, "shape", ()))
    ok = len(actual) == len(shape) and all(
        want is None or want == got for want, got in zip(shape, actual)
    )
    if not ok:
        raise ContractError(
            f"{name}: expected shape {tuple(shape)}, got {actual}"
        )
    return x


def assert_dtype(x, dtype, name: str = "array"):
    """``x.dtype`` matches ``dtype``.

    ``dtype`` may be a concrete dtype (``jnp.float32``) or one of the
    category strings ``"integer"`` / ``"floating"`` / ``"bool"``
    (checked via ``jnp.issubdtype``). None ``x`` passes.
    """
    if not ENABLED or x is None:
        return x
    import jax.numpy as jnp

    actual = jnp.dtype(getattr(x, "dtype", type(x)))
    if dtype == "integer":
        ok = jnp.issubdtype(actual, jnp.integer)
    elif dtype == "floating":
        ok = jnp.issubdtype(actual, jnp.floating)
    elif dtype == "bool":
        ok = actual == jnp.bool_
    else:
        ok = actual == jnp.dtype(dtype)
    if not ok:
        raise ContractError(f"{name}: expected dtype {dtype}, got {actual}")
    return x


def assert_tree_finite(tree, name: str = "tree"):
    """Every concrete leaf of ``tree`` is finite (no NaN/Inf).

    Tracer leaves are skipped, so this is safe (and free) inside jitted
    code; use it at host boundaries where values are materialized anyway.
    """
    if not ENABLED or tree is None:
        return tree
    import jax
    import numpy as np

    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if isinstance(leaf, jax.core.Tracer):
            continue
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(arr).all():
            bad.append(jax.tree_util.keystr(path))
    if bad:
        raise ContractError(
            f"{name}: non-finite values in {len(bad)} leaves: "
            f"{bad[:5]}{'...' if len(bad) > 5 else ''}"
        )
    return tree
