"""AST rule implementations for the jaxlint static analyzer.

Every rule is a function ``(module: ModuleInfo) -> Iterator[Finding]``
registered in ``RULES``. Rules are deliberately heuristic: they resolve
names lexically within one file (no imports, no cross-file types), which
is exactly enough for the hazard classes that destroy TPU throughput —
each is a *syntactic* pattern. Conservative over-reporting is handled by
the committed baseline (tracked-but-allowed) and inline
``# jaxlint: disable=JLxxx`` suppressions, never by weakening a rule to
silence.

Rule catalog (docstrings are the user-facing documentation; the CLI's
``--list-rules`` prints them):

JL001  trace-unsafe Python control flow in traced contexts
JL002  numpy applied to JAX arrays (host fallback / implicit transfer)
JL003  missing donation on state-updating jits; unhashable static args
JL004  host-device sync inside training loops
JL005  recompilation hazards in jitted signatures
JL006  PRNG key reuse without split
JL007  swallowed exceptions (broad except with no handling)
JL008  XLA compilation in hot paths (jit/lower().compile() in loops or
       request handlers; precompile/warmup functions exempt)
JL009  wall-clock time.time() used for duration measurement
       (monotonic-clock rule: durations must use time.monotonic() or
       time.perf_counter(); time.time() is for timestamps only)
JL010  jitted-call timing without a sync: monotonic/perf_counter
       subtraction around a jitted call with no block_until_ready or
       device read in the timed region — async dispatch makes such
       timings measure enqueue cost, not execution
JL011  unbounded queues in serving code: queue.Queue()/LifoQueue()/
       PriorityQueue() with no positive maxsize (or SimpleQueue, which
       cannot be bounded) under speakingstyle_tpu/serving/ — an
       unbounded admission queue makes backpressure meaningless: load
       past capacity accumulates as latency instead of shedding
JL012  unbounded caches in serving code: lru_cache(maxsize=None)/
       functools.cache, or a dict literal/dict() assigned to a
       cache-named target, under speakingstyle_tpu/serving/ — a server
       caching per-request content (styles, mels, ...) grows without
       bound under real traffic; use a bounded LRU with an eviction
       counter (serving/style.py) instead
JL013  unbounded blocking waits in serving code: ``.result()`` or a
       zero-argument ``.get()`` with no ``timeout=`` under
       speakingstyle_tpu/serving/ — a handler or worker parked forever
       on a future/queue survives the very replica failure the
       supervision layer exists to detect; every serving wait needs a
       deadline so a fault resolves as a structured 5xx, not a hang
JL014  hard single-device pinning in training/data code:
       ``device_put(x, jax.devices()[0])`` (or ``jax.local_devices()``,
       directly or via a variable) under training/ or data/ — now that
       the trainer runs on a mesh, placement is a sharding contract;
       a pin to device 0 funnels every batch onto one chip of the mesh
       (correct but 1/N throughput). Pass a NamedSharding instead.
JL015  fresh ndarray allocation in the serving hot path: np.zeros/
       np.full/np.pad/np.concatenate in a dispatch loop or request
       handler under speakingstyle_tpu/serving/ — steady-state serving
       is allocation-free by contract (per-bucket BufferPool leases,
       serving/pool.py); a per-request allocation puts malloc and
       page-zeroing jitter straight into the p999
JL016  bare time.sleep() inside a loop under speakingstyle_tpu/serving/
       — supervision/policy loops (the fleet supervisor, the
       autoscaler) must park on a stop-aware Event.wait(timeout) or
       Condition.wait so close()/drain interrupts them immediately; a
       sleeping thread holds shutdown hostage for up to a full tick
JL017  non-atomic persistent writes under training/ or serving/:
       open(path, "w"/"wb") or np.save/np.savez aimed at a
       checkpoint/artifact-shaped path (ckpt, checkpoint, manifest,
       weights, baseline, snapshot, artifact) with no temp-file +
       os.replace in the enclosing scope — a crash mid-write leaves a
       torn file that reads as CORRUPT, not absent; durable artifacts
       must appear atomically (write <name>.tmp, fsync, os.replace)
JL018  XLA compilation outside the program registry: any reference to
       jax.jit/jax.pjit (call, decorator, functools.partial argument,
       bare attribute), a ``from jax import jit/pjit`` import, or a
       .lower().compile() AOT chain anywhere under speakingstyle_tpu/
       (plus bench.py) except parallel/registry.py — the registry is
       the one guarded compile entry point (ProgramRegistry.compile
       for AOT, jit_program for jit-on-call wrappers), which is what
       makes the zero-steady-state-compiles invariant structural;
       precompile/warmup fixtures are exempt. Tree baseline: zero.
JL019  full-utterance accumulation in serving code: a list that is
       ``.append``/``.extend``-ed inside a loop and later passed to
       np.concatenate/jnp.concatenate in the same scope, under
       speakingstyle_tpu/serving/ — the accumulate-then-concat shape
       materializes an entire utterance (or chapter) host-side, which
       is exactly what the bounded-memory streaming contract forbids:
       long-form output must flow window-by-window (serving/
       streaming.py) or seam-by-seam (serving/longform.py), never be
       rebuilt whole. Complements JL015 (which flags the concatenate
       CALL in a loop/handler; JL019 catches the concat-after-loop
       spelling JL015's loop test misses). Tree baseline: zero.
JL020  torn-state race: a class attribute accessed under a lock in one
       method and read/written lock-free in another, in a class whose
       methods run on more than one thread (analysis/concurrency.py
       guarded-by inference: ``with self._lock:`` scope tracking plus
       one level of helper call-through, with replica-style local
       receivers bound to the declaring class). Exempt: Events, queue
       objects, obs.registry metrics, the lock objects themselves, and
       ``# jaxlint: disable=JL020 reason=...``. Tree baseline: zero.
JL021  blocking call under a lock (lock convoy / deadlock feeder):
       future.result, Event.wait, queue get/put (SimpleQueue.put is
       non-blocking and exempt), socket send/recv, subprocess, HTTP,
       time.sleep, or a registry/XLA compile while holding any
       recognized lock. Condition.wait on the lock being held is the
       sanctioned wait idiom and exempt. Tree baseline: zero.
JL022  lock-order cycle: nested ``with self._lock`` acquisitions (plus
       self-method and cross-class call-through) form the static
       lock-order graph; a cycle within one module is an error here,
       and the program-wide acyclic order is the checked-in
       analysis/lockorder.json (``cli lockorder --write``), which the
       runtime TrackedLock witness (obs/locks.py) enforces under
       SPEAKINGSTYLE_CHECKS=1. Tree baseline: zero.
JL023  unsupervised thread: ``threading.Thread(...)`` without a
       ``name=`` (invisible to the watchdog/supervision machinery), or
       a thread-creating class with no close()/stop() path that joins
       the thread or sets a stop Event. Scoped to speakingstyle_tpu/
       (bench/test harness threads are deliberately ad hoc).
       Tree baseline: zero.
JL024  unbounded wire call in serving code: an HTTP/socket client
       construct — http.client.HTTPConnection/HTTPSConnection,
       urllib's urlopen, any requests.<verb>/requests.request, or
       socket.create_connection — without an explicit ``timeout``
       under speakingstyle_tpu/serving/. The distributed control
       plane (serving/cluster.py) makes the serving tier a wire
       *client*: dispatches, heartbeats, registration and adoption
       probes all cross host boundaries, and the OS default for a
       connect/read is minutes-to-forever. A single timeout-less call
       re-introduces exactly the unbounded wait JL013 banned for
       futures/queues — a partitioned peer then parks a worker past
       every lease, breaker, and hedge budget. The socket-module
       default (socket.setdefaulttimeout) is process-global state and
       does NOT count: the bound must be visible at the call site.
       Tree baseline: zero.
JL025  out-of-band weight-tree precision cast: ``<tree>.astype(...)``,
       a ``jnp.float32(<tree>)``-style dtype constructor, or a
       ``tree_map(lambda x: x.astype(...), <tree>)`` over a
       params/variables tree anywhere outside the sanctioned
       ``cast_params`` helper in parallel/registry.py. Precision is a
       lattice axis: the registry cache key, ProgramCard rows, and the
       tier canary gates all key on which precision a param tree
       carries, so an inline cast serves weights no gate approved and
       no card records. Tree baseline: zero.
JL026  label-cardinality bomb at a metric registration site:
       per-request identity (req_id, trace_id, span ids, idempotency
       keys, raw text) flowing into a metric NAME or a label VALUE at
       a ``registry.counter/gauge/histogram`` call under
       speakingstyle_tpu/serving/ or obs/ — every distinct label value
       mints a whole new time series, so a per-request label turns a
       bounded /metrics page (and the fleet federation merge over it)
       into an allocation that grows with traffic forever. Per-request
       identity belongs on trace spans and JSONL events; metric labels
       stay bounded (class, replica, reason, bucket).
       Tree baseline: zero.
JL027  audio bytes leaving serving code without the quality choke
       point: an int16 PCM conversion (``.astype(np.int16)``), a RIFF
       container build (``wav_bytes(...)``), or an audio buffer
       serialization (``wav.tobytes()`` — terminal receiver named
       wav/pcm/audio/chunk/piece) in a function with NO
       ``QualityGate.check``/``check_result``/``validate_wav``/
       injected ``quality_check`` call, under speakingstyle_tpu/
       serving/. Every wav must cross obs/quality.py where it is
       produced or served — an unvalidated emission path is invisible
       to the validators, the quality SLO burn stream, and the
       golden-probe degradation drill. Tree baseline: zero.
"""

import ast
import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# shared model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    context: str  # enclosing function qualname (or "<module>")
    detail: str  # short, line-number-free (stable across edits)
    message: str  # full human-readable text

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.path}:{self.context}:{self.detail}"


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted-name of an expression (``jax.random.split``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return _dotted(node.func)
    return ""


def _names_in(node: ast.AST) -> Set[str]:
    return {
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    }


# calls whose result is a jax array (lexical heuristics)
_ARRAY_PRODUCER_PREFIXES = (
    "jnp.", "jax.numpy.", "jax.nn.", "jax.lax.", "jax.random.",
)
_ARRAY_PRODUCER_SUFFIXES = (".apply", ".init")

# jax transforms whose function argument is traced (jit_program is the
# registry's sanctioned jax.jit alias — parallel/registry.py)
_TRACING_TRANSFORMS = {
    "jax.jit", "jax.grad", "jax.value_and_grad", "jax.vmap", "jax.pmap",
    "jax.checkpoint", "jax.remat", "jit_program",
}

# spellings that construct a jit-on-call wrapper (JL003's call sites)
_JIT_CONSTRUCTORS = {"jax.jit", "jit_program"}

_STATE_PARAM_NAMES = {"state", "variables", "params", "opt_state", "carry"}

_HOST_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}

_CONFIG_PARAM_NAMES = {"cfg", "config", "hp", "hparams", "hyper_params"}

_DICTISH_ANNOTATIONS = {"dict", "Dict", "list", "List", "Mapping", "Any"}

_RNG_DERIVERS = {"jax.random.split", "jax.random.fold_in", "jax.random.clone"}


class ModuleInfo:
    """One parsed file plus the pre-analysis every rule shares."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        # memoized ast.walk: every rule that used to run its own full
        # traversal shares one cached node list per subtree, so linting a
        # file costs one AST pass (plus one per distinct function subtree
        # a rule inspects) instead of one pass per rule
        self._walk_cache: Dict[int, List[ast.AST]] = {}
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in self.walk():
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

        self.functions: List[ast.FunctionDef] = [
            n for n in self.walk()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self._jitted_names = self._collect_jitted_names()
        self._partial_static_params = self._collect_partial_bindings()
        self._traced = {f for f in self.functions if self._is_traced(f)}

    def walk(self, node: Optional[ast.AST] = None) -> List[ast.AST]:
        """``list(ast.walk(node or tree))``, memoized per subtree. The
        cached list preserves ast.walk's exact BFS order, so findings are
        byte-identical to the per-rule-walk implementation."""
        key = -1 if node is None or node is self.tree else id(node)
        cached = self._walk_cache.get(key)
        if cached is None:
            cached = list(ast.walk(self.tree if key == -1 else node))
            self._walk_cache[key] = cached
        return cached

    # -- context helpers ----------------------------------------------------

    def qualname(self, node: ast.AST) -> str:
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_loops(self, node: ast.AST) -> List[ast.AST]:
        out = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                out.append(cur)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            cur = self.parents.get(cur)
        return out

    # -- traced-context detection -------------------------------------------

    def _collect_jitted_names(self) -> Set[str]:
        """Function names that appear as the traced argument of a jax
        transform call anywhere in the file: ``jax.jit(step_fn, ...)``."""
        names: Set[str] = set()
        for node in self.walk():
            if not isinstance(node, ast.Call):
                continue
            callee = _dotted(node.func)
            if callee in _TRACING_TRANSFORMS or (
                callee in ("functools.partial", "partial")
                and node.args
                and _dotted(node.args[0]) in _TRACING_TRANSFORMS
            ):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        names.add(arg.id)
        return names

    def _collect_partial_bindings(self) -> Dict[str, Set[str]]:
        """functools.partial(f, kw=..., pos...) binds those params of ``f``
        statically — they are Python values at trace time, not tracers."""
        out: Dict[str, Set[str]] = {}
        defs = {f.name: f for f in self.functions}
        for node in self.walk():
            if not isinstance(node, ast.Call):
                continue
            if _dotted(node.func) not in ("functools.partial", "partial"):
                continue
            if not node.args or not isinstance(node.args[0], ast.Name):
                continue
            fn = defs.get(node.args[0].id)
            if fn is None:
                continue
            bound = out.setdefault(fn.name, set())
            params = [a.arg for a in fn.args.args]
            for i, _ in enumerate(node.args[1:]):
                if i < len(params):
                    bound.add(params[i])
            for kw in node.keywords:
                if kw.arg:
                    bound.add(kw.arg)
        return out

    def _is_traced(self, fn: ast.FunctionDef) -> bool:
        for dec in fn.decorator_list:
            d = _dotted(dec)
            if d in _TRACING_TRANSFORMS or d in ("nn.compact", "nn.remat"):
                return True
            if isinstance(dec, ast.Call):
                dc = _dotted(dec.func)
                if dc in _TRACING_TRANSFORMS:
                    return True
                if dc in ("functools.partial", "partial") and dec.args and \
                        _dotted(dec.args[0]) in _TRACING_TRANSFORMS:
                    return True
        if fn.name in self._jitted_names:
            return True
        # __call__ / compact methods of nn.Module subclasses
        parent = self.parents.get(fn)
        if isinstance(parent, ast.ClassDef):
            bases = {_dotted(b) for b in parent.bases}
            if any(b.endswith("Module") for b in bases):
                if fn.name == "__call__" or any(
                    _dotted(d) == "nn.compact" for d in fn.decorator_list
                ):
                    return True
        return False

    def is_in_traced_context(self, node: ast.AST) -> bool:
        """True if ``node`` sits inside a traced function (nested defs
        inside a traced function execute at trace time too)."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                    cur in self._traced:
                return True
            cur = self.parents.get(cur)
        return False

    # -- per-function dataflow ----------------------------------------------

    def array_locals(self, fn: ast.FunctionDef) -> Set[str]:
        """Names assigned (anywhere in ``fn``) from expressions that produce
        jax arrays: jnp./jax.lax./..., ``.apply(...)``/``.init(...)`` calls,
        or calls of locally-jitted callables."""
        producers: Set[str] = set()
        jitted_locals = set(self._jitted_names)
        # names bound directly to a jit wrapper: g = jax.jit(...)
        for node in self.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if _dotted(node.value.func) in _TRACING_TRANSFORMS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jitted_locals.add(t.id)
        # locally @jax.jit-decorated defs
        for sub in self.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                    sub in self._traced:
                jitted_locals.add(sub.name)

        def produces_array(value: ast.AST) -> bool:
            if not isinstance(value, ast.Call):
                return False
            callee = _dotted(value.func)
            if callee.startswith(_ARRAY_PRODUCER_PREFIXES):
                return True
            if any(callee.endswith(s) for s in _ARRAY_PRODUCER_SUFFIXES):
                return True
            return callee in jitted_locals

        for node in self.walk(fn):
            if isinstance(node, ast.Assign) and produces_array(node.value):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            producers.add(n.id)
        return producers

    def static_params(self, fn: ast.FunctionDef) -> Set[str]:
        """Params known static at trace time: ``self``, partial-bound
        params, and str/int-annotated ones (shape-like by convention)."""
        static = {"self"}
        static |= self._partial_static_params.get(fn.name, set())
        for a in list(fn.args.args) + list(fn.args.kwonlyargs):
            ann = a.annotation
            if ann is not None:
                t = _dotted(ann)
                if isinstance(ann, ast.Subscript):  # Optional[int] etc.
                    t = f"{_dotted(ann.value)}[{_dotted(ann.slice)}]"
                if t in ("str", "int", "Optional[int]", "Optional[str]"):
                    static.add(a.arg)
        return static


# ---------------------------------------------------------------------------
# JL001 — trace-unsafe Python control flow
# ---------------------------------------------------------------------------

_SAFE_CALLS = {
    "isinstance", "len", "hasattr", "getattr", "callable", "issubclass",
    "jnp.issubdtype", "jax.numpy.issubdtype",
}
_SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}


def _suspicious_names(test: ast.AST, suspects: Set[str]) -> Set[str]:
    """Bare Name loads from ``suspects`` in ``test``, after pruning
    trace-safe subexpressions (identity checks, metadata attrs, string
    comparisons, isinstance/len)."""

    pruned: Set[ast.AST] = set()

    def prune(node: ast.AST):
        for child in ast.walk(node):
            pruned.add(child)

    for node in ast.walk(test):
        if node in pruned:
            continue
        if isinstance(node, ast.Compare):
            ops_safe = all(isinstance(o, (ast.Is, ast.IsNot)) for o in node.ops)
            str_cmp = any(
                isinstance(c, ast.Constant) and isinstance(c.value, (str, bytes))
                for c in [node.left] + list(node.comparators)
            )
            if ops_safe or str_cmp:
                prune(node)
        elif isinstance(node, ast.Call) and _dotted(node.func) in _SAFE_CALLS:
            prune(node)
        elif isinstance(node, ast.Attribute):
            if node.attr in _SAFE_ATTRS:
                prune(node)
            else:
                # attribute access on a name (cfg.multi_speaker, self.rate)
                # reads config, not array truthiness — prune the VALUE name
                # but keep walking anything deeper than a plain name chain
                if isinstance(node.value, ast.Name):
                    pruned.add(node.value)

    out = set()
    for node in ast.walk(test):
        if node in pruned:
            continue
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in suspects:
                out.add(node.id)
    return out


def rule_jl001(mod: ModuleInfo) -> Iterator[Finding]:
    """JL001: Python ``if``/``while``/``assert`` on a potentially traced
    value inside a traced context (@jax.jit functions, functions passed to
    jax transforms, nn.Module ``__call__``/@nn.compact bodies).

    Python branching executes at trace time: on a tracer it raises
    ``TracerBoolConversionError``; on a Python value it silently bakes one
    branch into the compiled program. Parameters of traced functions are
    traced unless marked static (bool flags included — ``donate``/``jit``
    do NOT make bools static), so branch on ``self.*`` config, mark the
    argument static, or use ``jax.lax.cond``/``jnp.where``.
    """
    for fn in mod.functions:
        if fn not in mod._traced:
            continue
        static = mod.static_params(fn)
        params = {
            a.arg
            for a in list(fn.args.args) + list(fn.args.kwonlyargs)
            + ([fn.args.vararg] if fn.args.vararg else [])
            + ([fn.args.kwarg] if fn.args.kwarg else [])
        } - static
        arrays = mod.array_locals(fn)
        suspects = params | arrays
        qual = mod.qualname(fn)
        for node in mod.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
                kind = "if" if isinstance(node, ast.If) else "while"
            elif isinstance(node, ast.Assert):
                test, kind = node.test, "assert"
            else:
                continue
            hits = _suspicious_names(test, suspects)
            # direct jnp./jax. calls in the test are traced values too
            for call in ast.walk(test):
                if isinstance(call, ast.Call) and _dotted(call.func).startswith(
                    _ARRAY_PRODUCER_PREFIXES
                ):
                    hits.add(_dotted(call.func))
            for name in sorted(hits):
                yield Finding(
                    rule="JL001",
                    path=mod.path,
                    line=node.lineno,
                    context=qual,
                    detail=f"{kind} on {name!r}",
                    message=(
                        f"Python `{kind}` on {name!r} inside traced context "
                        f"{qual}: traced values cannot drive Python control "
                        "flow — use jax.lax.cond/jnp.where, mark the "
                        "argument static, or branch on self.* config."
                    ),
                )


# ---------------------------------------------------------------------------
# JL002 — numpy on jax arrays
# ---------------------------------------------------------------------------


def rule_jl002(mod: ModuleInfo) -> Iterator[Finding]:
    """JL002: ``np.*`` applied to a value produced by jax (jnp/jax.lax/
    jax.random calls, ``.apply``/``.init``, or a jitted callable).

    Inside a traced context this is a host fallback that breaks tracing or
    silently constant-folds; outside, it is an implicit device->host
    transfer (a sync point) that belongs at explicit boundaries only.
    Test files are exempt: round-tripping through numpy is the assertion
    idiom there, and np.testing.* transfers on purpose everywhere.
    """
    p = mod.path.replace("\\", "/")
    if "tests/" in p or os.path.basename(p).startswith("test_"):
        return
    for fn in mod.functions:
        arrays = mod.array_locals(fn)
        if not arrays:
            continue
        qual = mod.qualname(fn)
        traced = mod.is_in_traced_context(fn.body[0]) if fn.body else False
        for node in mod.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = _dotted(node.func)
            if not (callee.startswith("np.") or callee.startswith("numpy.")):
                continue
            if callee.startswith("np.testing") or callee.startswith(
                "numpy.testing"
            ):
                continue  # test assertions transfer on purpose
            used = set()
            for arg in list(node.args) + [k.value for k in node.keywords]:
                used |= _names_in(arg) & arrays
            for name in sorted(used):
                where = (
                    "inside a traced context (host fallback breaks tracing)"
                    if traced
                    else "an implicit device->host transfer (sync point)"
                )
                yield Finding(
                    rule="JL002",
                    path=mod.path,
                    line=node.lineno,
                    context=qual,
                    detail=f"{callee} on {name!r}",
                    message=(
                        f"`{callee}` applied to jax array {name!r} in {qual}: "
                        f"{where}. Use jnp.* on device, or jax.device_get at "
                        "an explicit boundary."
                    ),
                )


# ---------------------------------------------------------------------------
# JL003 — donation / static hashability
# ---------------------------------------------------------------------------


def _jit_callsites(mod: ModuleInfo):
    """Yield (call_node, callee_fndef_or_None, jit_kwargs, decorated_fn).

    Covers ``jax.jit(f, **kw)``/``jit_program(f, **kw)`` calls,
    ``@jax.jit``/``@jit_program`` and
    ``@functools.partial(jax.jit, **kw)`` decorations.
    """
    defs = {f.name: f for f in mod.functions}
    for node in mod.walk():
        if isinstance(node, ast.Call) and \
                _dotted(node.func) in _JIT_CONSTRUCTORS:
            target = None
            if node.args and isinstance(node.args[0], ast.Name):
                target = defs.get(node.args[0].id)
            kwargs = {k.arg for k in node.keywords if k.arg}
            yield node, target, kwargs, None
    for fn in mod.functions:
        for dec in fn.decorator_list:
            if _dotted(dec) in _JIT_CONSTRUCTORS:
                yield dec, fn, set(), fn
            elif isinstance(dec, ast.Call):
                dc = _dotted(dec.func)
                if dc in _JIT_CONSTRUCTORS:
                    yield dec, fn, {k.arg for k in dec.keywords if k.arg}, fn
                elif dc in ("functools.partial", "partial") and dec.args and \
                        _dotted(dec.args[0]) in _JIT_CONSTRUCTORS:
                    yield dec, fn, {k.arg for k in dec.keywords if k.arg}, fn


def _is_state_update_shaped(fn: ast.FunctionDef, state_params: Set[str]) -> bool:
    """Does ``fn`` return an updated copy of a state-like parameter?"""

    updated: Set[str] = set()

    def is_update_expr(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):
            callee = _dotted(expr.func)
            head = callee.split(".")[0]
            if callee.endswith(".replace") and head in state_params:
                return True
            if callee in ("optax.apply_updates",):
                return True
            # SomeState(**restored)-style reconstruction mentioning state
            if callee and callee[0].isupper() and "State" in callee:
                return True
        if isinstance(expr, ast.Dict):
            for k, v in zip(expr.keys, expr.values):
                # {**state, ...}: a copied-and-updated state dict
                if k is None and isinstance(v, ast.Name) and \
                        v.id in state_params:
                    return True
        return False

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and is_update_expr(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    updated.add(t.id)

    for node in ast.walk(fn):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        values = (
            list(node.value.elts)
            if isinstance(node.value, ast.Tuple)
            else [node.value]
        )
        for v in values:
            if is_update_expr(v):
                return True
            if isinstance(v, ast.Name) and v.id in updated:
                return True
    return False


def rule_jl003(mod: ModuleInfo) -> Iterator[Finding]:
    """JL003: (a) ``jax.jit`` of a train-step-shaped function (takes a
    state-like argument and returns an updated copy of it) without
    ``donate_argnums``/``donate_argnames`` — without donation every step
    holds two copies of the full state in HBM and pays an extra copy;
    (b) list/dict/set literals passed in ``static_argnums`` positions —
    unhashable statics raise at call time.
    """
    seen: Set[int] = set()
    for node, target, kwargs, _ in _jit_callsites(mod):
        if target is None or id(target) in seen:
            continue
        state_params = {
            a.arg
            for a in target.args.args
            if a.arg in _STATE_PARAM_NAMES or a.arg.endswith("_state")
        }
        if not state_params:
            continue
        if not _is_state_update_shaped(target, state_params):
            continue
        seen.add(id(target))
        if not (kwargs & {"donate_argnums", "donate_argnames"}):
            yield Finding(
                rule="JL003",
                path=mod.path,
                line=node.lineno,
                context=mod.qualname(target),
                detail=f"jit of state-updating {target.name!r} without donation",
                message=(
                    f"jax.jit({target.name}) updates {sorted(state_params)} "
                    "but does not donate it: pass donate_argnums so XLA can "
                    "reuse the input buffers instead of holding two copies "
                    "of the state."
                ),
            )

    # (b) unhashable literals at static positions
    static_of: Dict[str, List[int]] = {}
    for node, target, _, decorated in _jit_callsites(mod):
        call = node if isinstance(node, ast.Call) else None
        if call is None:
            continue
        for k in call.keywords:
            if k.arg == "static_argnums":
                idxs = []
                vals = (
                    k.value.elts
                    if isinstance(k.value, (ast.Tuple, ast.List))
                    else [k.value]
                )
                for v in vals:
                    if isinstance(v, ast.Constant) and isinstance(v.value, int):
                        idxs.append(v.value)
                name = None
                if decorated is not None:
                    name = decorated.name
                else:
                    parent = mod.parents.get(call)
                    if isinstance(parent, ast.Assign):
                        for t in parent.targets:
                            if isinstance(t, ast.Name):
                                name = t.id
                if name and idxs:
                    static_of[name] = idxs
    for node in mod.walk():
        if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Name):
            continue
        idxs = static_of.get(node.func.id)
        if not idxs:
            continue
        for i in idxs:
            if i < len(node.args) and isinstance(
                node.args[i], (ast.List, ast.Dict, ast.Set)
            ):
                kind = type(node.args[i]).__name__.lower()
                yield Finding(
                    rule="JL003",
                    path=mod.path,
                    line=node.lineno,
                    context=mod.qualname(
                        mod.enclosing_function(node) or mod.tree
                    ),
                    detail=f"unhashable {kind} at static arg {i} of "
                           f"{node.func.id!r}",
                    message=(
                        f"call of jitted {node.func.id!r} passes a {kind} "
                        f"literal at static_argnums position {i}: statics "
                        "must be hashable — use a tuple/frozen dataclass."
                    ),
                )


# ---------------------------------------------------------------------------
# JL004 — host sync inside training loops
# ---------------------------------------------------------------------------


def rule_jl004(mod: ModuleInfo) -> Iterator[Finding]:
    """JL004: host-device synchronization inside a loop in ``training/``
    code: ``.item()``, ``float()``/``int()`` on non-constants,
    ``jax.device_get``, ``(jax.)block_until_ready``.

    Each of these drains the dispatch queue: the device goes idle until
    the host catches up, which serializes the step pipeline. Deliberate,
    rate-gated syncs (logging every N steps) belong in the baseline or
    under an inline disable with the gate visible on the same line.
    """
    if "training/" not in mod.path.replace("\\", "/"):
        return
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        if not mod.enclosing_loops(node):
            continue
        callee = _dotted(node.func)
        detail = None
        if callee in _HOST_SYNC_CALLS:
            detail = callee
        elif isinstance(node.func, ast.Attribute) and node.func.attr in (
            "item", "block_until_ready"
        ):
            detail = f".{node.func.attr}()"
        elif isinstance(node.func, ast.Name) and node.func.id in (
            "float", "int"
        ):
            if node.args and not isinstance(node.args[0], ast.Constant):
                arg_callee = _dotted(node.args[0])
                if not arg_callee.startswith(("time.", "len", "os.")):
                    detail = f"{node.func.id}() on device value"
        if detail is None:
            continue
        fn = mod.enclosing_function(node)
        yield Finding(
            rule="JL004",
            path=mod.path,
            line=node.lineno,
            context=mod.qualname(fn or mod.tree),
            detail=f"host sync {detail} in loop",
            message=(
                f"host sync `{detail}` inside a loop in "
                f"{mod.qualname(fn or mod.tree)}: this blocks the dispatch "
                "queue every iteration — hoist it, gate it on a log step, "
                "or keep the value on device."
            ),
        )


# ---------------------------------------------------------------------------
# JL005 — recompilation hazards
# ---------------------------------------------------------------------------


def rule_jl005(mod: ModuleInfo) -> Iterator[Finding]:
    """JL005: recompilation hazards at jit boundaries: (a) dict/list-typed
    parameters in jitted signatures — every distinct key set or leaf shape
    retraces; (b) config-named parameters (cfg/config/hparams/...) —
    thread config by closure, not as a traced argument; (c) Python scalar
    defaults on non-static jitted parameters — weak-type churn retraces on
    the first call that passes a concrete dtype; (d) ``jax.jit`` applied
    inside a loop body — a fresh wrapper (usually over a fresh closure)
    retraces and recompiles every iteration.
    """
    seen: Set[int] = set()
    for node, target, kwargs_, decorated in _jit_callsites(mod):
        if target is None or id(target) in seen:
            continue
        seen.add(id(target))
        qual = mod.qualname(target)
        static: Set[str] = set()
        call = node if isinstance(node, ast.Call) else None
        static_idxs: List[int] = []
        if call is not None:
            for k in call.keywords:
                if k.arg == "static_argnums":
                    vals = (
                        k.value.elts
                        if isinstance(k.value, (ast.Tuple, ast.List))
                        else [k.value]
                    )
                    static_idxs = [
                        v.value
                        for v in vals
                        if isinstance(v, ast.Constant)
                        and isinstance(v.value, int)
                    ]
                if k.arg == "static_argnames":
                    for v in ast.walk(k.value):
                        if isinstance(v, ast.Constant) and isinstance(
                            v.value, str
                        ):
                            static.add(v.value)
        params = list(target.args.args)
        for i in static_idxs:
            if i < len(params):
                static.add(params[i].arg)

        defaults = target.args.defaults
        defaulted = params[len(params) - len(defaults):] if defaults else []
        for a, d in zip(defaulted, defaults):
            if a.arg in static:
                continue
            # bools excluded: flag-shaped defaults are JL001's territory
            if isinstance(d, ast.Constant) and isinstance(
                d.value, (int, float)
            ) and not isinstance(d.value, bool):
                yield Finding(
                    rule="JL005",
                    path=mod.path,
                    line=a.lineno,
                    context=qual,
                    detail=f"python scalar param {a.arg!r} in jitted signature",
                    message=(
                        f"jitted {target.name!r} takes Python scalar "
                        f"{a.arg!r} (default {d.value!r}) as a traced arg: "
                        "weak-type promotion retraces when callers pass "
                        "arrays vs literals — mark it static or pass "
                        "jnp.asarray values."
                    ),
                )
        for a in params:
            if a.arg in static:
                continue
            ann = _dotted(a.annotation) if a.annotation is not None else ""
            if ann in _DICTISH_ANNOTATIONS and ann != "Any":
                yield Finding(
                    rule="JL005",
                    path=mod.path,
                    line=a.lineno,
                    context=qual,
                    detail=f"{ann}-typed param {a.arg!r} in jitted signature",
                    message=(
                        f"jitted {target.name!r} takes {a.arg!r}: {ann} — "
                        "every distinct key set / leaf shape is a retrace. "
                        "Bucketed batches should be deliberate (baseline "
                        "this) and config should not be traced at all."
                    ),
                )
            if a.arg in _CONFIG_PARAM_NAMES:
                yield Finding(
                    rule="JL005",
                    path=mod.path,
                    line=a.lineno,
                    context=qual,
                    detail=f"config param {a.arg!r} in jitted signature",
                    message=(
                        f"jitted {target.name!r} threads config object "
                        f"{a.arg!r} through the traced signature: close "
                        "over it (or pass a hashable static) instead."
                    ),
                )

    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        is_jit = callee == "jax.jit" or (
            callee in ("functools.partial", "partial")
            and node.args
            and _dotted(node.args[0]) == "jax.jit"
        )
        if not is_jit or not mod.enclosing_loops(node):
            continue
        fn = mod.enclosing_function(node)
        yield Finding(
            rule="JL005",
            path=mod.path,
            line=node.lineno,
            context=mod.qualname(fn or mod.tree),
            detail="jax.jit inside loop body",
            message=(
                "jax.jit applied inside a loop: each iteration builds a "
                "fresh wrapper (and usually a fresh closure) — trace + "
                "compile every pass. Hoist the jit out of the loop."
            ),
        )


# ---------------------------------------------------------------------------
# JL006 — PRNG key reuse
# ---------------------------------------------------------------------------


def _is_key_producer(value: ast.AST) -> bool:
    return isinstance(value, ast.Call) and _dotted(value.func) in (
        "jax.random.PRNGKey", "jax.random.key", *_RNG_DERIVERS
    )


def rule_jl006(mod: ModuleInfo) -> Iterator[Finding]:
    """JL006: PRNG key reuse — the same key consumed by more than one
    draw without an intervening ``jax.random.split``/``fold_in``: (a) one
    key passed to two consumer calls (or twice within one call); (b) a key
    defined outside a loop and consumed inside it without per-iteration
    reassignment; (c) ``jax.random.PRNGKey(<constant>)`` created inside a
    traced context — the same stream on every call, compiled in.

    Reused keys give perfectly correlated "random" draws: dropout masks
    identical across layers/steps, initializations that alias, silently
    degraded training.
    """
    # (c) constant PRNGKey in traced context
    for node in mod.walk():
        if isinstance(node, ast.Call) and _dotted(node.func) in (
            "jax.random.PRNGKey", "jax.random.key"
        ):
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    mod.is_in_traced_context(node):
                fn = mod.enclosing_function(node)
                yield Finding(
                    rule="JL006",
                    path=mod.path,
                    line=node.lineno,
                    context=mod.qualname(fn or mod.tree),
                    detail=f"constant PRNGKey({node.args[0].value!r}) in "
                           "traced context",
                    message=(
                        "jax.random.PRNGKey with a constant seed inside a "
                        "traced function: every call replays the identical "
                        "stream (it is baked into the compiled program) — "
                        "thread a key argument in instead."
                    ),
                )

    for fn in mod.functions:
        keys: Set[str] = set()
        for a in list(fn.args.args) + list(fn.args.kwonlyargs):
            n = a.arg
            if n in ("rng", "key", "prng", "prng_key") or \
                    n.endswith(("_rng", "_key")):
                keys.add(n)
        for node in mod.walk(fn):
            if isinstance(node, ast.Assign) and _is_key_producer(node.value):
                for t in node.targets:
                    for nm in ast.walk(t):
                        if isinstance(nm, ast.Name):
                            keys.add(nm.id)
        if not keys:
            continue

        events: List[Tuple[int, str, str, ast.AST]] = []  # (line, kind, key, node)
        for node in mod.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for nm in ast.walk(t):
                        if isinstance(nm, ast.Name) and nm.id in keys:
                            events.append((node.lineno, "assign", nm.id, node))
            elif isinstance(node, ast.Call):
                callee = _dotted(node.func)
                if callee in _RNG_DERIVERS or callee in (
                    "jax.random.PRNGKey", "jax.random.key"
                ):
                    continue
                consumed: List[str] = []
                slots = list(node.args) + [k.value for k in node.keywords]
                # flax .init/.apply fold the collection name into the key,
                # so {"params": rng, "dropout": rng} is safe idiom there —
                # don't count dict values for those callees
                flax_entry = callee.endswith((".init", ".apply"))
                for arg in slots:
                    if isinstance(arg, ast.Name) and arg.id in keys:
                        consumed.append(arg.id)
                    elif isinstance(arg, ast.Dict) and not flax_entry:
                        for v in arg.values:  # rngs={"dropout": rng}
                            if isinstance(v, ast.Name) and v.id in keys:
                                consumed.append(v.id)
                for k in consumed:
                    events.append((node.lineno, "consume", k, node))
                for k in set(consumed):
                    if consumed.count(k) > 1:
                        events.append((node.lineno, "dup", k, node))

        events.sort(key=lambda e: e[0])
        qual = mod.qualname(fn)
        live: Dict[str, int] = {}
        reported: Set[str] = set()
        for line, kind, k, node in events:
            if kind == "assign":
                live[k] = 0
            elif kind == "dup" and f"dup:{k}" not in reported:
                reported.add(f"dup:{k}")
                yield Finding(
                    rule="JL006", path=mod.path, line=line, context=qual,
                    detail=f"key {k!r} passed twice in one call",
                    message=(
                        f"PRNG key {k!r} appears twice in a single call in "
                        f"{qual}: both consumers draw the identical stream "
                        "— jax.random.split it first."
                    ),
                )
            elif kind == "consume":
                loops = mod.enclosing_loops(node)
                in_unrefreshed_loop = False
                for loop in loops:
                    reassigned = any(
                        isinstance(n, ast.Assign)
                        and any(
                            isinstance(t, ast.Name) and t.id == k
                            or (
                                isinstance(t, (ast.Tuple, ast.List))
                                and any(
                                    isinstance(e, ast.Name) and e.id == k
                                    for e in t.elts
                                )
                            )
                            for t in n.targets
                        )
                        for n in mod.walk(loop)
                    )
                    defined_outside = not (
                        loop.lineno <= _first_def_line(fn, k, events)
                        <= _last_line(loop)
                    )
                    if not reassigned and defined_outside:
                        in_unrefreshed_loop = True
                        break
                if in_unrefreshed_loop and f"loop:{k}" not in reported:
                    reported.add(f"loop:{k}")
                    yield Finding(
                        rule="JL006", path=mod.path, line=line, context=qual,
                        detail=f"key {k!r} consumed every loop iteration",
                        message=(
                            f"PRNG key {k!r} is consumed inside a loop in "
                            f"{qual} without per-iteration splitting: every "
                            "iteration draws the identical stream (unless "
                            "the consumer folds in a counter — if it does, "
                            "baseline or suppress this)."
                        ),
                    )
                elif not in_unrefreshed_loop:
                    count = live.get(k, 0)  # params start live at 0 uses
                    live[k] = count + 1
                    if count + 1 == 2 and f"multi:{k}" not in reported:
                        reported.add(f"multi:{k}")
                        yield Finding(
                            rule="JL006", path=mod.path, line=line,
                            context=qual,
                            detail=f"key {k!r} reused by a second consumer",
                            message=(
                                f"PRNG key {k!r} reaches a second consumer "
                                f"in {qual} without jax.random.split: both "
                                "draws are identical."
                            ),
                        )


def _first_def_line(fn: ast.FunctionDef, key: str, events) -> int:
    for line, kind, k, _ in events:
        if kind == "assign" and k == key:
            return line
    return fn.lineno  # parameter


def _last_line(node: ast.AST) -> int:
    return max(
        (getattr(n, "lineno", 0) for n in ast.walk(node)), default=node.lineno
    )


# ---------------------------------------------------------------------------
# JL007 — swallowed exceptions
# ---------------------------------------------------------------------------

_BROAD_EXCEPTION_NAMES = {"Exception", "BaseException"}
_HANDLING_CALL_MARKERS = ("print", "log", "warn", "fail", "record")


def _handler_is_broad(handler: ast.ExceptHandler) -> Optional[str]:
    """The broad type name this handler catches, or None if specific."""
    t = handler.type
    if t is None:
        return "bare except"
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in types:
        name = _dotted(e).split(".")[-1]
        if name in _BROAD_EXCEPTION_NAMES:
            return name
    return None


def _body_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the handler drops the error on the floor: no raise, the
    bound exception name (if any) is never read, and nothing that looks
    like logging/reporting runs."""
    for node in ast.walk(handler):
        if node is handler:
            continue
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.ExceptHandler):
            return False  # nested try/except: too opaque to judge
        if handler.name and isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and node.id == handler.name:
            return False  # the error is used (re-packaged, returned, ...)
        if isinstance(node, ast.Call):
            callee = _dotted(node.func).lower()
            if any(m in callee for m in _HANDLING_CALL_MARKERS):
                return False
    return True


def rule_jl007(mod: ModuleInfo) -> Iterator[Finding]:
    """JL007: swallowed exceptions — an ``except`` catching a broad type
    (bare ``except:``, ``Exception``, ``BaseException``) whose body
    neither re-raises, nor reads the bound error, nor logs: the failure
    silently vanishes.

    In a fault-tolerant training harness every swallowed exception is a
    masked fault: a loader error eaten here bypasses the retry/quarantine
    accounting (training/resilience.py) and surfaces later as a hang or a
    silent data gap. Catch the narrowest type that models the expected
    failure, or route the error through the resilience layer. Scoped to
    the shipped package (``speakingstyle_tpu/``) — tests and one-off
    scripts may probe-and-ignore deliberately.
    """
    p = mod.path.replace("\\", "/")
    if "speakingstyle_tpu/" not in p:
        return
    for node in mod.walk():
        if not isinstance(node, ast.ExceptHandler):
            continue
        broad = _handler_is_broad(node)
        if broad is None or not _body_swallows(node):
            continue
        fn = mod.enclosing_function(node)
        qual = mod.qualname(fn or mod.tree)
        yield Finding(
            rule="JL007",
            path=mod.path,
            line=node.lineno,
            context=qual,
            detail=f"swallowed {broad}",
            message=(
                f"`except {broad}` in {qual} swallows the error (no "
                "re-raise, no use of the exception, no logging): the "
                "failure vanishes. Catch the narrowest expected type, or "
                "log/route it through the resilience layer."
            ),
        )


# ---------------------------------------------------------------------------
# JL008 — compile in hot path
# ---------------------------------------------------------------------------

_JIT_CALL_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit", "jit_program"}
# functions sanctioned to compile in a loop: the AOT startup pattern
# (serving/engine.py precompile) — hoist compiles INTO one of these
_COMPILE_EXEMPT_MARKERS = ("precompile", "warmup", "warm_up")


def _is_handler_name(name: str) -> bool:
    """Request-handler heuristics: http.server's ``do_GET``-style methods,
    and anything named like a handler (``handle_*``, ``*_handler``,
    ``on_request``, ...)."""
    low = name.lower()
    return (name.startswith("do_") and name[3:].isupper()) or \
        "handle" in low or "request" in low


def _is_aot_compile_chain(node: ast.Call) -> bool:
    """``<expr>.lower(...).compile(...)`` — the AOT idiom. Matching the
    full chain (not bare ``.compile()``) keeps re.compile & co. silent."""
    f = node.func
    return (
        isinstance(f, ast.Attribute) and f.attr == "compile"
        and isinstance(f.value, ast.Call)
        and isinstance(f.value.func, ast.Attribute)
        and f.value.func.attr == "lower"
    )


def rule_jl008(mod: ModuleInfo) -> Iterator[Finding]:
    """JL008: XLA compilation in a hot path — ``jax.jit``/``pjit`` or a
    ``.lower(...).compile()`` chain invoked inside a loop, or anywhere in
    a request-handler-shaped function.

    A compile is 10^5-10^7x a dispatch; in a loop it recompiles per
    iteration (a fresh ``jax.jit`` object never shares cache entries with
    the last iteration's), and in a request handler it stalls a live
    request behind XLA. Hoist compilation to startup: build the jits
    once, or AOT-precompile the shape lattice (serving/engine.py). Loops
    inside functions named ``precompile``/``warmup`` are exempt — that IS
    the sanctioned startup pattern.
    """
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        is_jit = _dotted(node.func) in _JIT_CALL_NAMES
        is_aot = _is_aot_compile_chain(node)
        if not (is_jit and not mod.is_in_traced_context(node)) and not is_aot:
            continue
        qual = mod.qualname(node)
        if any(m in qual.lower() for m in _COMPILE_EXEMPT_MARKERS):
            continue
        what = _dotted(node.func) if is_jit else ".lower().compile()"
        fn = mod.enclosing_function(node)
        in_loop = bool(mod.enclosing_loops(node))
        in_handler = fn is not None and _is_handler_name(fn.name)
        if not in_loop and not in_handler:
            continue
        where = "loop" if in_loop else "request handler"
        yield Finding(
            rule="JL008",
            path=mod.path,
            line=node.lineno,
            context=qual,
            detail=f"{what} in {where}",
            message=(
                f"`{what}` inside a {where} ({qual}): compilation in the "
                "hot path — each hit costs an XLA compile (not a cached "
                "dispatch). Build the jit once at startup, or AOT-"
                "precompile the shape lattice (see serving/engine.py); "
                "precompile/warmup-named functions are exempt."
            ),
        )


# ---------------------------------------------------------------------------
# JL009 — wall clock used for durations
# ---------------------------------------------------------------------------


def rule_jl009(mod: ModuleInfo) -> Iterator[Finding]:
    """JL009: ``time.time()`` used for duration measurement — a
    wall-clock value (or a name assigned from one) appearing as an
    operand of a subtraction.

    ``time.time()`` follows the system clock: NTP slews/steps (and leap
    smearing on cloud VMs) make wall-clock deltas lie, occasionally by
    seconds — poison for latency histograms and throughput windows. Use
    ``time.monotonic()`` (or ``time.perf_counter()``) for every
    duration; wall time is for *timestamps* only (event-log ``ts``
    fields), which are never subtracted.
    """
    wall = {"time.time"}
    for node in mod.walk():
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    wall.add(alias.asname or "time")

    def is_wall_call(n: ast.AST) -> bool:
        return isinstance(n, ast.Call) and _dotted(n.func) in wall

    stamps: Set[str] = set()
    for node in mod.walk():
        if isinstance(node, ast.Assign) and is_wall_call(node.value):
            for t in node.targets:
                for nm in ast.walk(t):
                    if isinstance(nm, ast.Name):
                        stamps.add(nm.id)

    for node in mod.walk():
        if not isinstance(node, ast.BinOp) or not isinstance(node.op, ast.Sub):
            continue
        hits = []
        for side in (node.left, node.right):
            if is_wall_call(side):
                hits.append("time.time()")
            elif isinstance(side, ast.Name) and side.id in stamps:
                hits.append(side.id)
        if not hits:
            continue
        fn = mod.enclosing_function(node)
        qual = mod.qualname(fn or mod.tree)
        yield Finding(
            rule="JL009",
            path=mod.path,
            line=node.lineno,
            context=qual,
            detail=f"duration arithmetic on wall clock ({', '.join(hits)})",
            message=(
                f"wall-clock subtraction in {qual} ({', '.join(hits)}): "
                "time.time() follows the (NTP-adjusted) system clock, so "
                "deltas can jump or run backwards — measure durations with "
                "time.monotonic()/time.perf_counter(); keep time.time() "
                "for timestamps only."
            ),
        )


# ---------------------------------------------------------------------------
# JL010 — jitted-call timing without a device sync
# ---------------------------------------------------------------------------

_MONO_CLOCK_CALLS = {"time.monotonic", "time.perf_counter"}
# calls that force the device to catch up (or read a result back) —
# any of these inside the timed region makes the timing device-honest
_SYNC_CALL_NAMES = {
    "jax.block_until_ready", "block_until_ready", "jax.device_get",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
}


def _jl010_jitted_names(mod: ModuleInfo, fn: ast.FunctionDef) -> Set[str]:
    """Names in/visible-to ``fn`` bound to jit-compiled callables: passed
    to a jax transform anywhere in the file, assigned from ``jax.jit(...)``,
    assigned from an AOT ``.lower(...).compile()`` chain, or locally
    ``@jax.jit``-decorated."""
    jitted = set(mod._jitted_names)
    for node in mod.walk():
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _dotted(node.value.func) in _TRACING_TRANSFORMS or \
                    _is_aot_compile_chain(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jitted.add(t.id)
    for sub in mod.walk(fn):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                sub in mod._traced:
            jitted.add(sub.name)
    return jitted


def _jl010_is_sync(node: ast.Call) -> bool:
    callee = _dotted(node.func)
    if callee in _SYNC_CALL_NAMES:
        return True
    if isinstance(node.func, ast.Attribute) and node.func.attr in (
        "item", "block_until_ready"
    ):
        return True
    # float(x)/int(x) on a non-constant is a device->host read when x is
    # a device value — the repo's sanctioned explicit-sync idiom
    if isinstance(node.func, ast.Name) and node.func.id in ("float", "int"):
        return bool(node.args) and not isinstance(node.args[0], ast.Constant)
    return False


def rule_jl010(mod: ModuleInfo) -> Iterator[Finding]:
    """JL010: a monotonic-clock duration (``time.monotonic()``/
    ``time.perf_counter()`` subtraction) measured around a jitted call
    with no device sync in the timed region — no
    ``(jax.)block_until_ready``, no ``.item()``/``float()``/
    ``np.asarray``/``device_get`` read of a result.

    jax dispatch is asynchronous: the call returns once the work is
    *enqueued*, so the subtraction times the host's enqueue cost, not
    the device's execution — such numbers are reproducibly, confidently
    wrong (often 100x). Read a result back or ``block_until_ready``
    inside the region, or time at a boundary that already syncs.
    """
    for fn in mod.functions:
        jitted = _jl010_jitted_names(mod, fn)
        if not jitted:
            continue
        stamp_lines: Dict[str, List[int]] = {}   # name -> clock-assign lines
        jit_lines: List[int] = []
        sync_lines: List[int] = []
        subs: List[Tuple[int, str]] = []         # (line, stamp name)
        for node in mod.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ) and _dotted(node.value.func) in _MONO_CLOCK_CALLS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        stamp_lines.setdefault(t.id, []).append(node.lineno)
            elif isinstance(node, ast.Call):
                if _jl010_is_sync(node):
                    sync_lines.append(node.lineno)
                elif isinstance(node.func, ast.Name) and \
                        node.func.id in jitted:
                    jit_lines.append(node.lineno)
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                for side in (node.left, node.right):
                    if isinstance(side, ast.Name) and side.id in stamp_lines:
                        subs.append((node.lineno, side.id))
        qual = mod.qualname(fn)
        reported: Set[Tuple[int, str]] = set()
        for line, stamp in subs:
            starts = [s for s in stamp_lines[stamp] if s < line]
            if not starts:
                continue
            start = max(starts)  # the stamp assignment this delta closes
            if not any(start < l <= line for l in jit_lines):
                continue
            if any(start < l <= line for l in sync_lines):
                continue
            if (start, stamp) in reported:
                continue
            reported.add((start, stamp))
            yield Finding(
                rule="JL010",
                path=mod.path,
                line=line,
                context=qual,
                detail=f"unsynced jitted-call timing via {stamp!r}",
                message=(
                    f"duration from {stamp!r} in {qual} times a jitted "
                    "call with no sync in the region: async dispatch "
                    "returns at enqueue, so this measures host overhead, "
                    "not execution — block_until_ready (or read a result "
                    "back) before taking the end timestamp."
                ),
            )


# ---------------------------------------------------------------------------
# JL011 — unbounded queues in serving code
# ---------------------------------------------------------------------------

_BOUNDABLE_QUEUES = {
    "queue.Queue", "Queue", "queue.LifoQueue", "LifoQueue",
    "queue.PriorityQueue", "PriorityQueue",
}
_UNBOUNDABLE_QUEUES = {"queue.SimpleQueue", "SimpleQueue"}


def rule_jl011(mod: ModuleInfo) -> Iterator[Finding]:
    """JL011: unbounded queue construction under
    ``speakingstyle_tpu/serving/`` — ``queue.Queue()`` (or LifoQueue/
    PriorityQueue) with no ``maxsize``, a constant ``maxsize <= 0``
    (stdlib semantics: infinite), or ``queue.SimpleQueue`` (which cannot
    be bounded at all).

    Serving backpressure is a *contract*: load-shedding watermarks and
    the 429 path only mean something if every queue between admission
    and the device has a capacity to measure against. An unbounded queue
    silently converts overload into unbounded latency (and memory)
    instead of an honest shed — the exact failure mode the fleet
    router's ``serve_shed_total`` exists to prevent. Bound the queue
    (``queue.Queue(maxsize=...)``) and admit through a stop-aware
    ``bounded_put`` (data/prefetch.py).
    """
    p = mod.path.replace("\\", "/")
    if "speakingstyle_tpu/serving/" not in p:
        return
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        detail = None
        if callee in _UNBOUNDABLE_QUEUES:
            detail = f"{callee} (cannot be bounded)"
        elif callee in _BOUNDABLE_QUEUES:
            size = None
            if node.args:
                size = node.args[0]
            for kw in node.keywords:
                if kw.arg == "maxsize":
                    size = kw.value
            if size is None:
                detail = f"{callee}() with no maxsize"
            elif isinstance(size, ast.Constant) and (
                not isinstance(size.value, int) or size.value <= 0
            ):
                detail = f"{callee}(maxsize={size.value!r})"
        if detail is None:
            continue
        fn = mod.enclosing_function(node)
        qual = mod.qualname(fn or mod.tree)
        yield Finding(
            rule="JL011",
            path=mod.path,
            line=node.lineno,
            context=qual,
            detail=f"unbounded {detail}",
            message=(
                f"unbounded queue `{detail}` in serving code ({qual}): "
                "every serving queue must be bounded or backpressure is "
                "meaningless — overload becomes unbounded latency/memory "
                "instead of an honest 429 shed. Pass a positive maxsize "
                "and enqueue via the stop-aware bounded_put."
            ),
        )


# ---------------------------------------------------------------------------
# JL012 — unbounded caches in serving code
# ---------------------------------------------------------------------------

_LRU_CACHE_NAMES = {"functools.lru_cache", "lru_cache"}
_ALWAYS_UNBOUNDED_CACHES = {"functools.cache", "cache"}


def _target_names(target: ast.AST) -> Iterator[str]:
    """Terminal identifiers of an assignment target: ``self._mel_cache``
    -> ``_mel_cache``; tuple targets yield each element's name."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, ast.Attribute):
        yield target.attr
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            yield from _target_names(e)


def _lru_cache_unbounded(node: ast.Call) -> bool:
    """``lru_cache(maxsize=None)`` / ``lru_cache(None)`` — the bare call
    keeps the stdlib's bounded default of 128, so only an explicit None
    is the hazard."""
    size = node.args[0] if node.args else None
    for kw in node.keywords:
        if kw.arg == "maxsize":
            size = kw.value
    return isinstance(size, ast.Constant) and size.value is None


def rule_jl012(mod: ModuleInfo) -> Iterator[Finding]:
    """JL012: unbounded caches under ``speakingstyle_tpu/serving/`` —
    ``functools.lru_cache(maxsize=None)`` / ``functools.cache`` (which is
    exactly that), or an empty ``{}``/``dict()`` assigned to a target
    whose name contains "cache".

    The JL011 rule for state that *content* fills rather than requests:
    a serving process caching per-request payloads (reference styles,
    mels, parsed uploads) in an unbounded structure converts distinct-
    content traffic into unbounded memory — an OOM kill on a long-lived
    replica, the slowest possible shed. Serving caches must be bounded
    with explicit eviction (the StyleService's content-addressed LRU,
    ``serve.style.cache_capacity`` + ``serve_style_cache_evictions_total``,
    is the house pattern).
    """
    p = mod.path.replace("\\", "/")
    if "speakingstyle_tpu/serving/" not in p:
        return
    # bare @functools.cache / @cache decorators (no call parentheses)
    for fn in mod.functions:
        for dec in fn.decorator_list:
            if not isinstance(dec, ast.Call) and \
                    _dotted(dec) in _ALWAYS_UNBOUNDED_CACHES:
                yield Finding(
                    rule="JL012",
                    path=mod.path,
                    line=dec.lineno,
                    context=mod.qualname(fn),
                    detail=f"unbounded {_dotted(dec)} (never evicts)",
                    message=(
                        f"`@{_dotted(dec)}` in serving code caches every "
                        "distinct call unboundedly — use "
                        "lru_cache(maxsize=N) or a capacity-limited LRU "
                        "(serving/style.py)."
                    ),
                )
    for node in mod.walk():
        if isinstance(node, ast.Call):
            callee = _dotted(node.func)
            detail = None
            if callee in _ALWAYS_UNBOUNDED_CACHES:
                detail = f"{callee} (never evicts)"
            elif callee in _LRU_CACHE_NAMES and _lru_cache_unbounded(node):
                detail = f"{callee}(maxsize=None)"
            if detail is None:
                continue
            fn = mod.enclosing_function(node)
            yield Finding(
                rule="JL012",
                path=mod.path,
                line=node.lineno,
                context=mod.qualname(fn or mod.tree),
                detail=f"unbounded {detail}",
                message=(
                    f"unbounded cache `{detail}` in serving code: per-"
                    "request content accumulates without eviction — bound "
                    "the cache (lru_cache(maxsize=N), or a capacity-"
                    "limited LRU like serving/style.py's) so memory is a "
                    "function of capacity, not traffic history."
                ),
            )
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            is_empty_dict = isinstance(value, ast.Dict) and not value.keys
            is_dict_call = (
                isinstance(value, ast.Call)
                and _dotted(value.func) == "dict" and not value.args
                and not value.keywords
            )
            if not (is_empty_dict or is_dict_call):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                for name in _target_names(t):
                    if "cache" not in name.lower():
                        continue
                    fn = mod.enclosing_function(node)
                    yield Finding(
                        rule="JL012",
                        path=mod.path,
                        line=node.lineno,
                        context=mod.qualname(fn or mod.tree),
                        detail=f"dict cache {name!r} with no bound",
                        message=(
                            f"`{name}` is a plain dict used as a cache in "
                            "serving code: nothing ever evicts, so memory "
                            "grows with distinct request content. Use a "
                            "bounded LRU (OrderedDict + capacity + "
                            "eviction counter — see serving/style.py)."
                        ),
                    )


# ---------------------------------------------------------------------------
# JL013 — unbounded blocking waits in serving code
# ---------------------------------------------------------------------------


def rule_jl013(mod: ModuleInfo) -> Iterator[Finding]:
    """JL013: a blocking wait with no timeout under
    ``speakingstyle_tpu/serving/`` — ``fut.result()`` with no arguments,
    or a zero-argument ``q.get()`` (the ``queue.Queue`` signature; a
    ``dict.get(key)`` carries a positional argument and is not matched)
    — neither carrying a ``timeout=``.

    Serving threads that wait forever undo the resilience contract: the
    supervisor can fail a replica, requeue its batch, and resolve every
    future with a structured error, but a handler parked on a bare
    ``future.result()`` (or a worker on a bare ``queue.get()``) only
    benefits if *someone* resolves/feeds it — a bookkeeping bug or a
    lost wakeup then hangs the connection with no 5xx ever sent. Every
    wait in the serving tree must carry a deadline (the class deadline
    budget + grace for request futures; a poll interval for queues) so
    the worst case is a timely 504, not a stuck thread.
    """
    p = mod.path.replace("\\", "/")
    if "speakingstyle_tpu/serving/" not in p:
        return
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        if func.attr not in ("result", "get"):
            continue
        # zero positional args only: dict.get(key[, default]) and
        # result(timeout) positionally both carry args and are bounded
        # (or at least deliberate); the bare no-arg call is the hazard
        if node.args:
            continue
        if any(kw.arg == "timeout" for kw in node.keywords):
            continue
        fn = mod.enclosing_function(node)
        qual = mod.qualname(fn or mod.tree)
        recv = _dotted(func.value) or "<expr>"
        yield Finding(
            rule="JL013",
            path=mod.path,
            line=node.lineno,
            context=qual,
            detail=f"bare {recv}.{func.attr}() with no timeout",
            message=(
                f"`{recv}.{func.attr}()` in serving code ({qual}) blocks "
                "forever: if the producer dies or a bookkeeping bug drops "
                "the wakeup, this thread hangs with no 5xx ever sent. "
                "Pass timeout= (request futures: the class deadline "
                "budget + grace; queues: a poll interval) and map the "
                "timeout to a structured error."
            ),
        )


# ---------------------------------------------------------------------------
# JL014 — hard single-device pinning in training/data code
# ---------------------------------------------------------------------------


_DEVICE_LIST_CALLS = ("jax.devices", "jax.local_devices")


def _device_pin_spelling(node: ast.AST, pinned_names: Set[str]) -> str:
    """The pinned-device spelling if ``node`` hard-pins one device
    (``jax.devices()[i]`` / ``jax.local_devices()[i]``, or a name
    assigned from one), else ''."""
    if isinstance(node, ast.Subscript):
        base = _dotted(node.value)
        if base in _DEVICE_LIST_CALLS:
            return f"{base}()[...]"
    if isinstance(node, ast.Name) and node.id in pinned_names:
        return node.id
    return ""


def rule_jl014(mod: ModuleInfo) -> Iterator[Finding]:
    """JL014: hard single-device pinning under ``training/`` or ``data/``:
    ``jax.device_put(x, jax.devices()[0])`` — the device argument is a
    subscript of ``jax.devices()``/``jax.local_devices()``, directly or
    through a variable assigned from one.

    Now that the trainer runs on a mesh, placement is a *sharding*
    contract: the prefetcher device_puts against the batch
    NamedSharding, the state is laid out by train_state_shardings, and
    XLA spreads both across the mesh. A device_put pinned to device 0
    silently defeats that — every batch (and the compute consuming it)
    funnels onto one chip of an N-chip mesh, so the run stays correct
    while throughput divides by N. Pass the mesh's NamedSharding
    (``batch_sharding(mesh)``) instead, or omit the device and let jax
    place single-chip transfers by default.
    """
    p = mod.path.replace("\\", "/")
    if "training/" not in p and "data/" not in p:
        return
    # names assigned (lexically, anywhere in the file) from a
    # jax.devices()/jax.local_devices() subscript
    pinned: Set[str] = set()
    for node in mod.walk():
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Subscript
        ):
            if _dotted(node.value.value) in _DEVICE_LIST_CALLS:
                pinned |= {
                    t.id for t in node.targets if isinstance(t, ast.Name)
                }
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        if callee not in ("jax.device_put", "device_put"):
            continue
        dev_args = list(node.args[1:]) + [
            kw.value for kw in node.keywords if kw.arg == "device"
        ]
        for arg in dev_args:
            pin = _device_pin_spelling(arg, pinned)
            if not pin:
                continue
            fn = mod.enclosing_function(node)
            qual = mod.qualname(fn or mod.tree)
            yield Finding(
                rule="JL014",
                path=mod.path,
                line=node.lineno,
                context=qual,
                detail=f"device_put pinned to {pin}",
                message=(
                    f"`device_put(..., {pin})` in {qual} hard-pins the "
                    "transfer to one device: under a mesh this funnels "
                    "every batch onto a single chip (1/N throughput). "
                    "Pass the mesh's NamedSharding "
                    "(batch_sharding(mesh)) or omit the device."
                ),
            )
            break


# ---------------------------------------------------------------------------
# JL015 — fresh ndarray allocation in the serving hot path
# ---------------------------------------------------------------------------


_FRESH_ALLOC_CALLS = {
    "np.zeros", "np.full", "np.pad", "np.concatenate",
    "numpy.zeros", "numpy.full", "numpy.pad", "numpy.concatenate",
}


def _is_dispatch_shaped(name: str) -> bool:
    """Hot-path heuristics for serving code: request handlers (JL008's
    definition) plus dispatch/emit-loop workers (``_dispatch``,
    ``dispatch_loop``, ``stream_wav``-style emitters)."""
    low = name.lower()
    return _is_handler_name(name) or "dispatch" in low or "emit" in low


def rule_jl015(mod: ModuleInfo) -> Iterator[Finding]:
    """JL015: fresh ndarray allocation in the serving hot path —
    ``np.zeros``/``np.full``/``np.pad``/``np.concatenate`` inside a loop,
    or anywhere in a dispatch-/handler-shaped function, under
    ``speakingstyle_tpu/serving/``.

    The steady-state serving claim is *allocation-free*: every padded
    staging buffer is leased from the per-bucket BufferPool
    (serving/pool.py) and written in place, so the dispatch loop's
    allocator traffic is zero after warmup (``serve_pool_allocs_total``
    flat).  A fresh ``np.zeros``/``np.pad`` per request reintroduces
    malloc/free (and page-zeroing) jitter exactly where the p999 is
    made, and ``np.concatenate`` re-materializes whole utterances the
    streaming path deliberately emits window-by-window.  Lease from the
    pool and ``np.copyto``/slice-assign instead.  Functions named
    ``precompile``/``warmup`` are exempt — startup may allocate freely.
    """
    p = mod.path.replace("\\", "/")
    if "speakingstyle_tpu/serving/" not in p:
        return
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        if callee not in _FRESH_ALLOC_CALLS:
            continue
        qual = mod.qualname(node)
        if any(m in qual.lower() for m in _COMPILE_EXEMPT_MARKERS):
            continue
        fn = mod.enclosing_function(node)
        in_loop = bool(mod.enclosing_loops(node))
        in_dispatch = fn is not None and _is_dispatch_shaped(fn.name)
        if not in_loop and not in_dispatch:
            continue
        where = "loop" if in_loop else "dispatch/handler function"
        yield Finding(
            rule="JL015",
            path=mod.path,
            line=node.lineno,
            context=qual,
            detail=f"{callee} in {where}",
            message=(
                f"`{callee}` inside a {where} ({qual}): a fresh ndarray "
                "per request breaks the allocation-free steady state — "
                "malloc + page-zero jitter lands straight in the latency "
                "tail. Lease a padded buffer from the BufferPool "
                "(serving/pool.py) and write in place; "
                "precompile/warmup-named functions are exempt."
            ),
        )


_SLEEP_CALLS = {"time.sleep", "sleep"}


def rule_jl016(mod: ModuleInfo) -> Iterator[Finding]:
    """JL016: bare ``time.sleep()`` in a loop under
    ``speakingstyle_tpu/serving/`` — supervision/policy loops must park
    on a stop-aware wait.

    Serving-side background loops (the fleet supervisor's watchdog
    sweep, the autoscaler's policy tick, re-warm backoff) all follow one
    idiom: block on ``Event.wait(timeout)`` or ``Condition.wait(timeout)``
    so that ``close()`` can set/notify and the thread exits NOW, not up
    to a full tick later. A bare ``time.sleep`` in such a loop is
    uninterruptible — drain and shutdown inherit its latency, and a
    SIGTERM'd process misses its drain deadline because a policy thread
    was napping. One-shot sleeps outside loops (a close-path settle, an
    injected fault's deliberate stall) are not supervision cadence and
    are not flagged.
    """
    p = mod.path.replace("\\", "/")
    if "speakingstyle_tpu/serving/" not in p:
        return
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        if _dotted(node.func) not in _SLEEP_CALLS:
            continue
        if not mod.enclosing_loops(node):
            continue
        qual = mod.qualname(node)
        yield Finding(
            rule="JL016",
            path=mod.path,
            line=node.lineno,
            context=qual,
            detail="time.sleep in loop",
            message=(
                f"`time.sleep` inside a loop ({qual}): a supervision/"
                "policy loop must park on a stop-aware "
                "`Event.wait(timeout)` (or `Condition.wait`) so close()/"
                "drain interrupts it immediately — a bare sleep holds "
                "shutdown hostage for up to a full tick."
            ),
        )


# ---------------------------------------------------------------------------
# JL017 — non-atomic persistent writes to checkpoint/artifact paths
# ---------------------------------------------------------------------------


_PERSIST_SAVE_CALLS = {"np.save", "np.savez", "numpy.save", "numpy.savez"}
# path spellings that mark a durable artifact worth crash-safety
_ARTIFACT_MARKERS = (
    "ckpt", "checkpoint", "manifest", "weights", "baseline", "snapshot",
    "artifact",
)
# spellings that mark the temp half of a temp+replace pattern
_TEMP_MARKERS = ("tmp", "temp", "part")
_ATOMIC_RENAME_CALLS = {"os.replace", "os.rename"}


def _path_spelling(node: ast.AST) -> str:
    """Every lexical fragment of a path expression, lowercased: string
    constants, variable names, attribute chains, f-string parts — enough
    to recognize ``ckpt_path`` / ``f"{d}/manifest.json"`` shapes without
    evaluating anything."""
    parts: List[str] = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            parts.append(n.value)
        elif isinstance(n, ast.Name):
            parts.append(n.id)
        elif isinstance(n, ast.Attribute):
            parts.append(n.attr)
    return " ".join(parts).lower()


def _scope_has_atomic_rename(mod: "ModuleInfo", node: ast.AST) -> bool:
    """True when the enclosing function (or the module body, for
    top-level code) performs an ``os.replace``/``os.rename`` — the
    signature of the temp-file + atomic-publish idiom."""
    scope = mod.enclosing_function(node) or mod.tree
    return any(
        isinstance(n, ast.Call) and _dotted(n.func) in _ATOMIC_RENAME_CALLS
        for n in mod.walk(scope)
    )


def rule_jl017(mod: ModuleInfo) -> Iterator[Finding]:
    """JL017: non-atomic persistent writes — ``open(path, "w"/"wb")`` or
    ``np.save``/``np.savez`` on a checkpoint/artifact-shaped path with
    no temp + ``os.replace`` in the enclosing scope, under
    ``speakingstyle_tpu/training/`` or ``speakingstyle_tpu/serving/``.

    A durable artifact (checkpoint manifest, weights export, committed
    baseline, capacity snapshot) must appear ATOMICALLY: a process
    killed mid-``write()`` otherwise leaves a torn file that the next
    reader sees as corrupt — precisely the failure the checkpoint
    integrity layer (training/checkpoint.py) exists to catch, and one
    that rename-into-place eliminates for free on POSIX. Write to
    ``<name>.tmp`` in the same directory, flush+fsync, then
    ``os.replace``. Writes whose path spelling is already temp-marked
    (``tmp``/``temp``/``part``) are the first half of that idiom and
    exempt, as is any write in a scope that also calls
    ``os.replace``/``os.rename``.
    """
    p = mod.path.replace("\\", "/")
    if ("speakingstyle_tpu/training/" not in p
            and "speakingstyle_tpu/serving/" not in p):
        return
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        path_arg = None
        if callee == "open" and node.args:
            mode = ""
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                mode = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    mode = kw.value.value
            if "w" not in mode:
                continue  # reads and appends are not publishes
            path_arg = node.args[0]
        elif callee in _PERSIST_SAVE_CALLS and node.args:
            path_arg = node.args[0]
        else:
            continue
        spelling = _path_spelling(path_arg)
        if not any(m in spelling for m in _ARTIFACT_MARKERS):
            continue
        if any(m in spelling for m in _TEMP_MARKERS):
            continue  # the temp half of temp+replace
        if _scope_has_atomic_rename(mod, node):
            continue
        qual = mod.qualname(node)
        yield Finding(
            rule="JL017",
            path=mod.path,
            line=node.lineno,
            context=qual,
            detail=f"non-atomic {callee} to artifact path",
            message=(
                f"`{callee}` writes a checkpoint/artifact-shaped path "
                f"in place ({qual}): a crash mid-write leaves a torn "
                "file the next reader sees as CORRUPT. Publish "
                "atomically — write `<name>.tmp`, flush+fsync, then "
                "`os.replace` (training/checkpoint.py's manifest "
                "writer is the reference idiom)."
            ),
        )


# ---------------------------------------------------------------------------
# JL018 — XLA compilation outside the program registry
# ---------------------------------------------------------------------------


_RAW_JIT_SPELLINGS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
_JIT_IMPORT_NAMES = {"jit", "pjit"}
_REGISTRY_PATH_MARKER = "parallel/registry.py"


def _jl018_in_scope(path: str) -> bool:
    """The enforced tree: the package itself plus bench.py. Tests,
    scripts/, and anything outside the package may spell jax.jit (their
    compiles are fixtures, not production programs)."""
    p = path.replace("\\", "/")
    if _REGISTRY_PATH_MARKER in p:
        return False
    return "speakingstyle_tpu/" in p or os.path.basename(p) == "bench.py"


def rule_jl018(mod: ModuleInfo) -> Iterator[Finding]:
    """JL018: XLA compilation outside ``parallel/registry.py`` — a
    reference to ``jax.jit``/``jax.pjit`` (call, decorator,
    ``functools.partial`` argument, or bare attribute), a
    ``from jax import jit``-style import, or a ``.lower(...).compile()``
    AOT chain, anywhere under ``speakingstyle_tpu/`` or in ``bench.py``.

    The ProgramRegistry (parallel/registry.py) is the ONE guarded entry
    point where XLA programs are built: it owns the cache-key semantics
    ("did we already build this program?" has one answer), the compile
    counters, the persistent-cache hookup, and the sharding-spec card
    table behind ``GET /debug/programs``. A stray ``jax.jit`` anywhere
    else re-opens a side door the zero-steady-state-compiles invariant
    (JL008) cannot see through. Route AOT compiles through
    ``ProgramRegistry.compile`` and jit-on-first-call wrappers through
    ``jit_program``. Functions named ``precompile``/``warmup`` are
    exempt (startup fixtures); the tree baseline for this rule is zero
    and must stay zero.
    """
    if not _jl018_in_scope(mod.path):
        return

    def _exempt(node: ast.AST) -> bool:
        qual = mod.qualname(node)
        return any(m in qual.lower() for m in _COMPILE_EXEMPT_MARKERS)

    def _finding(node: ast.AST, what: str) -> Finding:
        return Finding(
            rule="JL018",
            path=mod.path,
            line=node.lineno,
            context=mod.qualname(node),
            detail=f"{what} outside registry",
            message=(
                f"`{what}` outside parallel/registry.py "
                f"({mod.qualname(node)}): the ProgramRegistry is the one "
                "compile entry point — use ProgramRegistry.compile for "
                "AOT programs or jit_program for jit-on-call wrappers "
                "so cache keys, compile counters, persistent-cache "
                "wiring, and /debug/programs cards stay complete."
            ),
        )

    for node in mod.walk():
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] == "jax":
                for alias in node.names:
                    if alias.name in _JIT_IMPORT_NAMES:
                        yield _finding(node, f"from {node.module} "
                                             f"import {alias.name}")
        elif isinstance(node, ast.Attribute):
            if _dotted(node) in _RAW_JIT_SPELLINGS and not _exempt(node):
                yield _finding(node, _dotted(node))
        elif isinstance(node, ast.Call):
            if _is_aot_compile_chain(node) and not _exempt(node):
                yield _finding(node, ".lower().compile()")


# ---------------------------------------------------------------------------
# JL019 — full-utterance accumulation (append-in-loop + concatenate)
# ---------------------------------------------------------------------------


_CONCAT_CALLS = {
    "np.concatenate", "numpy.concatenate", "jnp.concatenate",
    "jax.numpy.concatenate",
}
_ACCUM_METHODS = {"append", "extend"}


def rule_jl019(mod: ModuleInfo) -> Iterator[Finding]:
    """JL019: full-utterance accumulation under
    ``speakingstyle_tpu/serving/`` — a list ``.append``/``.extend``-ed
    inside a loop and then handed to ``np.concatenate`` /
    ``jnp.concatenate`` in the same scope.

    The bounded-memory contract for served audio is structural: the
    streaming path emits overlap-trimmed windows (serving/streaming.py)
    and the long-form path emits crossfaded seams (serving/longform.py),
    so at no point does the host hold a whole utterance — let alone a
    chapter — as one buffer.  The accumulate-then-concat shape
    (``pieces.append(wav)`` in the chunk loop, ``np.concatenate(pieces)``
    after it) silently re-materializes that buffer: memory scales with
    requested AUDIO LENGTH instead of with the in-flight window count,
    and one hour-long chapter OOMs the serving host.  Yield the pieces
    instead.  JL015 flags a ``concatenate`` *call* inside a loop or
    handler; this rule catches the spelling where the call sits after
    the loop and only the appends are inside it.  Functions named
    ``precompile``/``warmup`` are exempt (startup fixtures); the tree
    baseline for this rule is zero and must stay zero.
    """
    p = mod.path.replace("\\", "/")
    if "speakingstyle_tpu/serving/" not in p:
        return
    # scope id -> names of lists grown inside a loop in that scope
    grown: Dict[int, Set[str]] = {}
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in _ACCUM_METHODS
                and isinstance(f.value, ast.Name)):
            continue
        if not mod.enclosing_loops(node):
            continue
        scope = mod.enclosing_function(node)
        grown.setdefault(id(scope), set()).add(f.value.id)
    if not grown:
        return
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        if callee not in _CONCAT_CALLS or not node.args:
            continue
        qual = mod.qualname(node)
        if any(m in qual.lower() for m in _COMPILE_EXEMPT_MARKERS):
            continue
        scope = mod.enclosing_function(node)
        names = grown.get(id(scope), set())
        arg = node.args[0]
        arg_names = {n.id for n in ast.walk(arg) if isinstance(n, ast.Name)}
        for name in sorted(arg_names & names):
            yield Finding(
                rule="JL019",
                path=mod.path,
                line=node.lineno,
                context=qual,
                detail=f"{callee}({name}) after loop accumulation",
                message=(
                    f"`{callee}({name})` consumes a list grown inside a "
                    f"loop ({qual}): accumulate-then-concat materializes "
                    "the full utterance/chapter host-side, so memory "
                    "scales with audio length instead of the in-flight "
                    "window bound. Yield the pieces as they are produced "
                    "(streaming.stream_wav / longform.Stitcher are the "
                    "reference idioms)."
                ),
            )


# ---------------------------------------------------------------------------
# JL020–JL023 — lock-discipline rules over the class-concurrency model
# ---------------------------------------------------------------------------


def _concurrency_in_scope(mod: ModuleInfo) -> bool:
    """Package code only: bench.py and tests/ create deliberately ad-hoc
    threads and toy locks that would drown the signal."""
    p = mod.path.replace("\\", "/")
    return "speakingstyle_tpu/" in p and "tests/" not in p


def _conc_model(mod: ModuleInfo):
    from speakingstyle_tpu.analysis import concurrency

    return concurrency.module_model(mod)


def rule_jl020(mod: ModuleInfo) -> Iterator[Finding]:
    """JL020: torn-state race — an attribute accessed under a lock in
    one method and read/written lock-free in another, where the class's
    methods run on more than one thread.

    The guarded-by model (analysis/concurrency.py) classifies every
    attribute site by the ``with self._lock:`` scopes around it, widened
    by helper call-through (a private helper whose every caller holds L
    is analyzed with L at entry), and binds ``rep.state``-style local
    receivers to the class that declares the attribute. A finding needs
    all of: a guarded site, a lock-free site in a *different* method
    (``__init__`` excluded — construction happens-before), a write
    somewhere, and a thread-reachable method among the sites. Events,
    queues, obs.registry metrics, and the lock objects themselves are
    exempt (their thread-safety is internal); deliberate single-reader
    patterns get ``# jaxlint: disable=JL020 reason=...``.
    """
    if not _concurrency_in_scope(mod):
        return
    model = _conc_model(mod)
    # (owner class, attr) -> [(site, MethodModel, effective locks)]
    groups: Dict[Tuple[str, str], List] = {}
    for cls in model.classes.values():
        for mm in cls.methods.values():
            for s in mm.sites:
                if s.owner == "self":
                    owner = cls.name
                else:
                    owner = model.unique_attr_owner.get(s.attr)
                    if owner is None:
                        continue
                owner_cls = model.classes.get(owner)
                if owner_cls is None or s.attr not in owner_cls.init_attrs:
                    continue
                kind = owner_cls.attr_kinds.get(s.attr)
                if kind is not None:
                    continue  # lock/event/queue/metric: exempt kinds
                eff = s.locks | mm.entry_locks
                groups.setdefault((owner, s.attr), []).append((s, mm, eff))
    for (owner, attr), entries in sorted(groups.items()):
        guarded_methods = {mm.qualname for s, mm, eff in entries if eff}
        if not guarded_methods:
            continue
        # the write that makes a race possible must happen outside
        # __init__ — construction happens-before every thread start, so
        # an attribute assigned once and then only read is immutable
        # shared state, not a race
        if not any(s.is_write for s, mm, _ in entries
                   if mm.name != "__init__"):
            continue
        if not any(mm.thread_reachable for _, mm, _ in entries):
            continue
        locks = sorted(set().union(
            *[eff for _, _, eff in entries if eff]
        ))
        reported: Set[str] = set()
        for s, mm, eff in entries:
            if eff or mm.name == "__init__":
                continue
            other_guarded = guarded_methods - {mm.qualname}
            if not other_guarded:
                continue
            if mm.qualname in reported:
                continue
            reported.add(mm.qualname)
            kind = "write" if s.is_write else "read"
            yield Finding(
                rule="JL020",
                path=mod.path,
                line=s.lineno,
                context=mm.qualname,
                detail=f"{owner}.{attr} lock-free in {mm.qualname}",
                message=(
                    f"`{owner}.{attr}` is guarded by "
                    f"{'/'.join(locks)} in "
                    f"{'/'.join(sorted(other_guarded))} but "
                    f"{kind} lock-free in {mm.qualname} — a torn-state "
                    "race once those methods run on different threads. "
                    "Take the lock around this access, or mark a "
                    "provably benign pattern with "
                    "`# jaxlint: disable=JL020 reason=...`."
                ),
            )


def rule_jl021(mod: ModuleInfo) -> Iterator[Finding]:
    """JL021: blocking call while holding a lock — future.result,
    Event.wait, queue get/put, socket/HTTP send, subprocess, sleep, or
    a registry/XLA compile inside a ``with self._lock:`` scope (or a
    helper that inherits the lock at entry). Every other thread that
    touches the lock convoys behind the slow call; if the blocked-on
    resource needs the same lock to make progress, it is a deadlock.
    ``Condition.wait`` on the held lock releases it while parked and is
    exempt; ``SimpleQueue.put`` cannot block and is exempt. Deliberate
    holds (the registry's serialize-all-compiles lock) get
    ``# jaxlint: disable=JL021 reason=...``.
    """
    if not _concurrency_in_scope(mod):
        return
    model = _conc_model(mod)
    for cls in sorted(model.classes.values(), key=lambda c: c.lineno):
        for mm in sorted(cls.methods.values(), key=lambda m: m.lineno):
            for b in mm.blocking:
                eff = set(b.locks) | set(mm.entry_locks)
                if not eff:
                    continue
                locks = "/".join(sorted(eff))
                yield Finding(
                    rule="JL021",
                    path=mod.path,
                    line=b.lineno,
                    context=mm.qualname,
                    detail=f"{b.desc} under {locks}",
                    message=(
                        f"{mm.qualname} makes a blocking call "
                        f"({b.desc}) while holding {locks}: every "
                        "thread touching that lock convoys behind it, "
                        "and a dependency back onto the lock deadlocks. "
                        "Move the call outside the critical section, or "
                        "mark a deliberate serialization point with "
                        "`# jaxlint: disable=JL021 reason=...`."
                    ),
                )


def rule_jl022(mod: ModuleInfo) -> Iterator[Finding]:
    """JL022: lock-order cycle — nested acquisitions in source order
    (``with self._a:`` inside ``with self._b:``, helper call-through,
    and cross-class call-through on typed attributes) are edges in the
    lock-order graph; a cycle is a latent deadlock regardless of
    schedule luck. The module-local graph is checked here; the
    program-wide graph is built by ``python -m
    speakingstyle_tpu.analysis.cli lockorder --write`` into
    analysis/lockorder.json, which ``--check`` keeps fresh and the
    runtime TrackedLock witness (obs/locks.py) enforces.
    """
    if not _concurrency_in_scope(mod):
        return
    from speakingstyle_tpu.analysis import concurrency

    model = _conc_model(mod)
    edges = concurrency.lock_edges([model])
    cycle = concurrency.find_cycle(edges)
    if cycle is not None:
        first = edges.get((cycle[0], cycle[1]), ["?"])[0]
        line = 1
        if ":" in first:
            try:
                line = int(first.split(" ")[0].rsplit(":", 1)[1])
            except ValueError:
                pass
        yield Finding(
            rule="JL022",
            path=mod.path,
            line=line,
            context="<module>",
            detail="lock-order cycle " + " -> ".join(cycle),
            message=(
                "lock-order cycle within this module: "
                + " -> ".join(cycle)
                + " — two threads taking the locks in opposite orders "
                "deadlock. Break the cycle (acquire in one global "
                "order, or drop the lock before the cross call); the "
                "checked-in order lives in analysis/lockorder.json."
            ),
        )


def rule_jl023(mod: ModuleInfo) -> Iterator[Finding]:
    """JL023: unsupervised thread — ``threading.Thread(...)`` with no
    ``name=`` (anonymous in stack dumps, watchdog output, and the
    lock-witness acquisition records), or a thread-creating class with
    no shutdown path: no method that ``.join()``s a thread or sets a
    stop Event. Serving threads must be both identifiable and
    collectable — the PR 9 watchdog and every drain path assume it.
    """
    if not _concurrency_in_scope(mod):
        return
    model = _conc_model(mod)
    sites = []
    for cls in sorted(model.classes.values(), key=lambda c: c.lineno):
        sites.extend(cls.thread_sites)
    sites.extend(model.module_thread_sites)
    for lineno, has_name, target, method in sorted(sites):
        if has_name:
            continue
        tgt = f" (target {target})" if target else ""
        yield Finding(
            rule="JL023",
            path=mod.path,
            line=lineno,
            context=method,
            detail=f"unnamed thread in {method}",
            message=(
                f"threading.Thread created without name= in {method}"
                f"{tgt}: anonymous threads are invisible to watchdog "
                "stacks, the lock witness, and py-spy output — name it "
                "after its role (e.g. name=f\"replica-{i}-dispatch\")."
            ),
        )
    for cls in sorted(model.classes.values(), key=lambda c: c.lineno):
        if not cls.thread_sites:
            continue
        joins = False
        signals = False
        for mm in cls.methods.values():
            for recv, meth, _, _ in mm.local_calls:
                if meth == "join":
                    joins = True
            for attr, owner_tag, meth, _, _ in mm.attr_calls:
                if meth == "join":
                    joins = True
                if meth == "set" and owner_tag == "self" and \
                        cls.attr_kinds.get(attr) == "event":
                    signals = True
        if joins or signals:
            continue
        yield Finding(
            rule="JL023",
            path=mod.path,
            line=cls.lineno,
            context=cls.name,
            detail=f"{cls.name} never joins/stops its threads",
            message=(
                f"{cls.name} creates threads but no method joins them "
                "or sets a stop Event: the thread outlives close()/"
                "drain and is invisible to shutdown supervision. Join "
                "it (or signal a stop Event the worker loop polls) on "
                "the close()/stop() path."
            ),
        )


# ---------------------------------------------------------------------------
# JL024 — wire calls without an explicit timeout in serving code
# ---------------------------------------------------------------------------

# client constructs whose OS-default wait is unbounded (or minutes), and
# the positional index at which their signature accepts the timeout —
# a call is bounded iff it passes timeout= (or fills that slot)
_WIRE_TIMEOUT_SLOT = {
    "HTTPConnection": 2,        # (host, port, timeout=...)
    "HTTPSConnection": 2,
    "urlopen": 2,               # (url, data, timeout=...)
    "create_connection": 1,     # (address, timeout=...)
}
_REQUESTS_VERBS = {
    "get", "post", "put", "delete", "head", "patch", "options", "request",
}


def rule_jl024(mod: ModuleInfo) -> Iterator[Finding]:
    """JL024: an HTTP/socket client call with no explicit ``timeout``
    under ``speakingstyle_tpu/serving/`` — ``HTTPConnection``/
    ``HTTPSConnection``, ``urlopen``, ``requests.<verb>``, or
    ``socket.create_connection`` relying on OS defaults.

    The cluster tier made the serving tree a wire client: dispatches,
    heartbeats, registration, and adoption probes all cross a host
    boundary, and a TCP connect/read with no timeout blocks for however
    long the kernel feels like (minutes on an unroutable peer, forever
    on a silent one). Every lease, breaker, and hedge budget in the
    control plane assumes wire attempts FAIL in bounded time — one
    timeout-less call re-opens the unbounded-wait hole JL013 closed for
    futures and queues. ``socket.setdefaulttimeout`` does not satisfy
    the rule: it is process-global, invisible at the call site, and one
    import can silently reset it.
    """
    p = mod.path.replace("\\", "/")
    if "speakingstyle_tpu/serving/" not in p:
        return
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        slot = None
        if leaf in _WIRE_TIMEOUT_SLOT:
            # create_connection only as socket's (a local helper named
            # create_connection is not a wire primitive)
            if leaf == "create_connection" and not dotted.startswith(
                    ("socket.", "create_connection")):
                continue
            slot = _WIRE_TIMEOUT_SLOT[leaf]
        elif dotted.startswith("requests.") and leaf in _REQUESTS_VERBS:
            slot = None   # requests' timeout is keyword-only in practice
        else:
            continue
        if any(kw.arg == "timeout" for kw in node.keywords):
            continue
        if slot is not None and len(node.args) > slot:
            continue   # the timeout slot is filled positionally
        fn = mod.enclosing_function(node)
        qual = mod.qualname(fn or mod.tree)
        yield Finding(
            rule="JL024",
            path=mod.path,
            line=node.lineno,
            context=qual,
            detail=f"{dotted}(...) with no explicit timeout",
            message=(
                f"`{dotted}(...)` in serving code ({qual}) has no "
                "explicit timeout: a partitioned or silent peer then "
                "blocks this thread past every lease/breaker/hedge "
                "budget (the OS default is minutes to forever). Pass "
                "timeout= at the call site — derive it from the "
                "request class's deadline budget for dispatches, or "
                "cluster.connect_timeout_s for control-plane calls."
            ),
        )


_DTYPE_CTORS = frozenset((
    "float32", "bfloat16", "float16", "float64", "int8", "int4",
))


def _is_weight_tree(node) -> bool:
    """A params/variables tree by name: ``params``/``variables``, a
    ``*_params``/``*_variables`` local, or an attribute chain ending in
    one (``state.params``, ``self.variables``)."""
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    else:
        return False
    return name in ("params", "variables") or name.endswith(
        ("_params", "_variables")
    )


def rule_jl025(mod: ModuleInfo) -> Iterator[Finding]:
    """JL025: a precision cast of a weight tree outside the sanctioned
    ``cast_params`` helper — ``<tree>.astype(...)``, a
    ``jnp.float32(<tree>)``-style dtype constructor, or a
    ``tree_map(lambda x: x.astype(...), <tree>)`` over a
    params/variables tree anywhere in ``speakingstyle_tpu/`` except
    ``parallel/registry.py``.

    Precision is a lattice axis, not a local convenience: the registry's
    cache key, the ProgramCard rows, the BufferPool dtypes, and the tier
    gates all key on which precision a param tree carries. A cast done
    inline at a call site produces weights the choke point never saw —
    a program compiles and serves at a precision no canary gated and no
    card records, which is exactly the same-bucket-different-precision
    blindness the tier door exists to close. All weight-tree casts flow
    through ``parallel/registry.py``'s ``cast_params`` (bf16 cast,
    int8 per-channel quant) / ``dequant_params`` (in-program f32 read).
    """
    p = mod.path.replace("\\", "/")
    if "speakingstyle_tpu/" not in p or p.endswith("parallel/registry.py"):
        return
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        bad = None
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and _is_weight_tree(node.func.value)):
            bad = f"{_dotted(node.func.value)}.astype(...)"
        elif (leaf in _DTYPE_CTORS
                and dotted.startswith(("jnp.", "jax.numpy.", "np.", "numpy."))
                and node.args and _is_weight_tree(node.args[0])):
            bad = f"{dotted}({_dotted(node.args[0])})"
        elif leaf in ("tree_map", "map") and dotted.startswith(
                ("jax.", "tree_map", "tree.")):
            # tree_map(lambda x: x.astype(...), params): the cast hides
            # in the mapped lambda, the tree names the weights
            if not any(_is_weight_tree(a) for a in node.args[1:]):
                continue
            fn_arg = node.args[0] if node.args else None
            if not isinstance(fn_arg, ast.Lambda):
                continue
            for inner in ast.walk(fn_arg.body):
                if not isinstance(inner, ast.Call):
                    continue
                idotted = _dotted(inner.func) or ""
                ileaf = idotted.rsplit(".", 1)[-1]
                if (isinstance(inner.func, ast.Attribute)
                        and inner.func.attr == "astype") or (
                        ileaf in _DTYPE_CTORS and idotted.startswith(
                            ("jnp.", "jax.numpy.", "np.", "numpy."))):
                    bad = f"{dotted}(lambda: ...{ileaf}(...), <weights>)"
                    break
        if bad is None:
            continue
        fn = mod.enclosing_function(node)
        qual = mod.qualname(fn or mod.tree)
        yield Finding(
            rule="JL025",
            path=mod.path,
            line=node.lineno,
            context=qual,
            detail=f"out-of-band weight-tree cast: {bad}",
            message=(
                f"`{bad}` in {qual} casts a weight tree outside the "
                "sanctioned helper: the registry cache key, ProgramCards, "
                "and tier canary gates never see this precision, so a "
                "program can serve quantized/cast weights no gate "
                "approved. Route the cast through cast_params() in "
                "parallel/registry.py (dequant_params for in-program "
                "int8 reads)."
            ),
        )


# ---------------------------------------------------------------------------
# JL026 — label-cardinality bombs at metric registration sites
# ---------------------------------------------------------------------------

_JL026_METHODS = ("counter", "gauge", "histogram")

# terminal identifiers (variable / attribute / subscript-key names) that
# carry per-request identity — each distinct value mints a new series
_JL026_PER_REQUEST = (
    "req_id", "request_id", "trace_id", "span_id", "parent_span_id",
    "idempotency_key", "idem_key", "utterance_id", "session_id",
    "correlation_id", "uuid", "text", "utterance",
)


def _jl026_per_request_ident(node) -> Optional[str]:
    """The terminal identifier of an expression, when it names
    per-request identity (``req_id``, ``r.trace_id``,
    ``payload["text"]``, ...)."""
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    elif (isinstance(node, ast.Subscript)
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)):
        name = node.slice.value
    else:
        return None
    low = name.lower()
    for pat in _JL026_PER_REQUEST:
        if low == pat or low.endswith("_" + pat):
            return name
    return None


def rule_jl026(mod: ModuleInfo) -> Iterator[Finding]:
    """JL026: label-cardinality bomb — per-request identity (req_id,
    trace_id, idempotency keys, raw text, ...) flowing into a metric
    NAME or a label VALUE at a ``registry.counter/gauge/histogram``
    call site under ``speakingstyle_tpu/serving/`` or ``obs/``.

    A metric family costs memory per distinct (name, labels) identity,
    FOREVER: counters never expire, every /metrics scrape renders every
    series, and the fleet federation layer (obs/registry.merge_states)
    multiplies the page across replicas. A label whose value is
    per-request — ``labels={"req": req_id}``, a trace id interpolated
    into the metric name — therefore allocates one immortal series per
    request: memory grows linearly with traffic, scrape latency follows,
    and the observability plane becomes the outage. Per-request identity
    belongs on trace spans (bounded ring, obs/trace.py) and JSONL events
    (append-only, rotated), never on metric labels; labels stay bounded
    vocabularies (class, replica, reason, bucket). The rule keys on
    identifier NAMES flowing into the call site, so bounded dynamic
    labels (``{"class": klass}``, ``{"replica": rid}``) stay clean;
    genuinely bounded values with unfortunate names get
    ``# jaxlint: disable=JL026 reason=...``.
    """
    p = mod.path.replace("\\", "/")
    if not ("speakingstyle_tpu/serving/" in p
            or "speakingstyle_tpu/obs/" in p):
        return
    for node in mod.walk():
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _JL026_METHODS):
            continue
        # receiver must look like a metrics registry (self.registry,
        # self._registry, registry, reg) — lexical, like every rule here
        recv = (_dotted(node.func.value) or "").rsplit(".", 1)[-1]
        if "registry" not in recv.lower() and recv != "reg":
            continue
        name_expr = node.args[0] if node.args else None
        labels_expr = None
        for kw in node.keywords:
            if kw.arg == "name":
                name_expr = kw.value
            elif kw.arg == "labels":
                labels_expr = kw.value
        hits: List[Tuple[str, str]] = []
        if name_expr is not None and not isinstance(name_expr, ast.Constant):
            # dynamic name: flag when per-request identity feeds it
            # (f-string pieces, concat operands, or the variable itself)
            for sub in ast.walk(name_expr):
                ident = _jl026_per_request_ident(sub)
                if ident is not None:
                    hits.append(("the metric name", ident))
                    break
        if isinstance(labels_expr, ast.Dict):
            for key, val in zip(labels_expr.keys, labels_expr.values):
                for sub in ast.walk(val):
                    ident = _jl026_per_request_ident(sub)
                    if ident is not None:
                        label = (key.value if isinstance(key, ast.Constant)
                                 else _dotted(key) or "?")
                        hits.append((f"label {label!r}", ident))
                        break
        for where, ident in hits:
            fn = mod.enclosing_function(node)
            qual = mod.qualname(fn or mod.tree)
            yield Finding(
                rule="JL026",
                path=mod.path,
                line=node.lineno,
                context=qual,
                detail=f"per-request `{ident}` in {where}",
                message=(
                    f"`{node.func.attr}(...)` in {qual} puts per-request "
                    f"`{ident}` into {where}: each distinct value mints an "
                    "immortal time series, so the /metrics page (and every "
                    "federation merge over it) grows with traffic forever. "
                    "Put per-request identity on trace spans or JSONL "
                    "events; keep metric labels a bounded vocabulary "
                    "(class, replica, reason, bucket)."
                ),
            )


# ---------------------------------------------------------------------------
# JL027 — audio bytes leaving serving code without the quality choke point
# ---------------------------------------------------------------------------

# terminal identifiers whose ``.tobytes()`` is audio leaving the process
_JL027_AUDIO_TERMINALS = ("wav", "pcm", "audio", "chunk", "piece")

# bare-call leaves that count as validator evidence
_JL027_VALIDATORS = (
    "validate_wav", "check_wav", "check_result", "quality_check",
)


def _jl027_is_emission(node: ast.Call) -> Optional[str]:
    """What kind of audio-emission site a call is, or None.

    Three shapes: ``wav_bytes(...)`` (the RIFF container),
    ``<x>.astype(np.int16 | "int16")`` (the float->PCM conversion every
    audio path performs exactly once), and ``<audio-ish>.tobytes()``
    where the receiver's TERMINAL identifier names audio (``wav``,
    ``chunk.tobytes()`` — terminal-only, so ``np.asarray(wav,
    np.int16).tobytes()`` inside the sanctioned container helper and a
    generic ``a.tobytes()`` stay clean)."""
    func = node.func
    leaf = (func.attr if isinstance(func, ast.Attribute)
            else func.id if isinstance(func, ast.Name) else "")
    if leaf == "wav_bytes":
        return "wav_bytes(...)"
    if leaf == "astype" and node.args:
        a = node.args[0]
        if ((isinstance(a, ast.Attribute) and a.attr == "int16")
                or (isinstance(a, ast.Name) and a.id == "int16")
                or (isinstance(a, ast.Constant) and a.value == "int16")):
            return ".astype(int16)"
    if leaf == "tobytes" and isinstance(func, ast.Attribute):
        recv = func.value
        name = (recv.id if isinstance(recv, ast.Name)
                else recv.attr if isinstance(recv, ast.Attribute) else "")
        low = name.lower()
        for t in _JL027_AUDIO_TERMINALS:
            if low == t or low.endswith("_" + t) or low.startswith(t):
                return f"{name}.tobytes()"
    return None


def _jl027_is_evidence(node: ast.Call) -> bool:
    """A call that passes audio through the quality choke point:
    a dotted call through something named ``quality`` whose leaf
    checks/validates (``self.quality.check``, ``outer.quality_gate
    .check_result``, the Stitcher's ``self.quality_check(p)``), or a
    bare validator call (``validate_wav(...)``)."""
    func = node.func
    if isinstance(func, ast.Attribute):
        dotted = _dotted(func).lower()
        leaf = func.attr.lower()
        return "quality" in dotted and (
            "check" in leaf or "validate" in leaf
        )
    if isinstance(func, ast.Name):
        return func.id in _JL027_VALIDATORS
    return False


def rule_jl027(mod: ModuleInfo) -> Iterator[Finding]:
    """JL027: audio bytes leaving serving code without passing the
    quality choke point (obs/quality.py).

    The quality observability plane only works if EVERY wav crosses the
    validator exactly where it is produced or served: the engine's batch
    and streaming collect paths, the long-form stitcher, and the HTTP
    boundary all call ``QualityGate.check``/``check_result`` (or the
    stitcher's injected ``quality_check``) before bytes move on. A new
    audio path that converts to int16 PCM, wraps a RIFF container
    (``wav_bytes``), or serializes an audio buffer (``wav.tobytes()``)
    WITHOUT validator evidence in the same function ships garbage the
    whole plane — counters, quality SLO burn, pinned traces, paging —
    is blind to. The rule is lexical per enclosing function: any
    quality-check call in the function (or an enclosing one) sanctions
    its emissions; genuinely non-audio int16 conversions get
    ``# jaxlint: disable=JL027 reason=...``.
    """
    p = mod.path.replace("\\", "/")
    if "speakingstyle_tpu/serving/" not in p:
        return
    evidence_fns = set()
    for node in mod.walk():
        if isinstance(node, ast.Call) and _jl027_is_evidence(node):
            fn = mod.enclosing_function(node)
            if fn is not None:
                evidence_fns.add(fn)
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        what = _jl027_is_emission(node)
        if what is None:
            continue
        # sanctioned if this function — or any function it is nested
        # inside (a helper closure emits what the handler validated) —
        # carries validator evidence
        cur = mod.enclosing_function(node)
        sanctioned = False
        probe = cur
        while probe is not None:
            if probe in evidence_fns:
                sanctioned = True
                break
            probe = mod.enclosing_function(probe)
        if sanctioned:
            continue
        qual = mod.qualname(cur or mod.tree)
        yield Finding(
            rule="JL027",
            path=mod.path,
            line=node.lineno,
            context=qual,
            detail=f"unvalidated audio emission {what}",
            message=(
                f"`{what}` in {qual} emits audio bytes without passing "
                "the quality choke point: no "
                "`QualityGate.check/check_result`, `validate_wav`, or "
                "injected `quality_check` call in this function. Every "
                "wav must cross obs/quality.py where it is produced — "
                "otherwise the validators, the quality SLO stream, and "
                "the golden-probe drill are blind to this path. Route "
                "the buffer through the engine/server gate (or call "
                "validate_wav directly) before serializing."
            ),
        )


RULES = {
    "JL001": rule_jl001,
    "JL002": rule_jl002,
    "JL003": rule_jl003,
    "JL004": rule_jl004,
    "JL005": rule_jl005,
    "JL006": rule_jl006,
    "JL007": rule_jl007,
    "JL008": rule_jl008,
    "JL009": rule_jl009,
    "JL010": rule_jl010,
    "JL011": rule_jl011,
    "JL012": rule_jl012,
    "JL013": rule_jl013,
    "JL014": rule_jl014,
    "JL015": rule_jl015,
    "JL016": rule_jl016,
    "JL017": rule_jl017,
    "JL018": rule_jl018,
    "JL019": rule_jl019,
    "JL020": rule_jl020,
    "JL021": rule_jl021,
    "JL022": rule_jl022,
    "JL023": rule_jl023,
    "JL024": rule_jl024,
    "JL025": rule_jl025,
    "JL026": rule_jl026,
    "JL027": rule_jl027,
}
