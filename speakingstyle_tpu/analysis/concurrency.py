"""Class-concurrency model: the shared dataflow core under JL020–JL023.

One pass over a module's AST produces, per class:

  * **lock attributes** — ``self._x = threading.Lock()/RLock()/
    Condition()`` (or the ``obs.locks.make_lock(...)`` wrapper) assigned
    in ``__init__``, named ``"ClassName._x"`` everywhere downstream so
    the static model, the checked-in ``lockorder.json``, and the runtime
    ``TrackedLock`` witness all speak about the same object;
  * **attribute kinds** — Events, queues, ``obs.registry`` metrics, and
    plain state, because the first three are the JL020 exemption list
    (their thread-safety is internal to the object);
  * **thread-reachable methods** — entry points handed to
    ``threading.Thread(target=...)``, ``threading.Timer``, or an
    executor ``.submit``, closed transitively over ``self.method()``
    calls;
  * **guarded-by classification** — every attribute read/write site
    carries the set of locks lexically held around it (``with
    self._lock:`` scope tracking), widened by one level of helper-method
    call-through: a private helper whose every intra-class call site
    holds L is analyzed as if L were held at entry (the fleet's
    ``_set_state`` / ``_check_shed`` "caller must hold" idiom).

Attribute sites are also resolved through *local* receivers: inside
``FleetRouter`` methods, ``rep.state`` binds to the ``Replica`` class
when exactly one class in the module declares ``state`` in its
``__init__`` — that is how replica-lifecycle fields guarded by the
router's condition variable are modeled even though ``Replica`` itself
has no methods.

Lock-order edges (JL022 / ``lockorder.json``) come from three shapes:
lexical ``with`` nesting, self-method call-through (holding L while
calling a helper that acquires M), and cross-class call-through
(holding L while calling a method on an attribute whose class is known
— e.g. the fleet holding ``_cond`` while ``drain_rate.retry_after()``
takes the estimator's lock).  Attribute classes resolve from direct
constructor assignment (``self.x = DrainRateEstimator()``), from a
constructor call anywhere in the RHS expression, or from
constructor-argument passthrough (``self.x = param`` where some call
site passes ``ClassName(...)`` for that parameter).

``build_lockorder`` merges every module's model into one program-wide
graph and emits the total order the runtime witness enforces.
"""

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

__all__ = [
    "AccessSite",
    "Acquisition",
    "BlockingCall",
    "MethodModel",
    "ClassModel",
    "ModuleConcurrency",
    "module_model",
    "merge_models",
    "lock_edges",
    "find_cycle",
    "topological_order",
    "tree_models",
    "lockorder_artifact",
]


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Call):
        inner = _dotted(cur.func)
        parts.append(f"{inner}()" if inner else "()")
    return ".".join(reversed(parts))


# constructor spellings -> attribute kind
_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
}
_EVENT_CTORS = {"threading.Event", "Event"}
_QUEUE_CTORS = {
    "queue.Queue": "queue",
    "queue.SimpleQueue": "simplequeue",
    "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue",
    "Queue": "queue",
    "SimpleQueue": "simplequeue",
}
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
# self.X.m(...) where m mutates the container/state behind X counts as a
# write site of X (the heap/dict/deque mutation idiom)
_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popitem", "popleft", "remove", "discard",
    "clear", "push",
}
# blocking-call surface for JL021 (spellings, not types — this is AST)
_BLOCKING_DOTTED_PREFIXES = ("subprocess.", "socket.", "requests.")
_BLOCKING_DOTTED = {"urllib.request.urlopen", "urlopen", "time.sleep"}
_BLOCKING_SOCKET_METHODS = {"sendall", "recv", "connect", "accept"}


@dataclass(frozen=True)
class AccessSite:
    """One attribute read/write, resolved to the class that declares it."""

    owner: str          # declaring class name
    attr: str
    method: str         # qualname of the method containing the site
    lineno: int
    is_write: bool
    locks: FrozenSet[str]   # lock names lexically held (pre call-through)


@dataclass(frozen=True)
class Acquisition:
    """``with self._x:`` on a recognized lock attribute."""

    lock: str               # "ClassName._x"
    held: Tuple[str, ...]   # locks already held, acquisition order
    lineno: int
    method: str


@dataclass(frozen=True)
class BlockingCall:
    desc: str
    locks: Tuple[str, ...]
    lineno: int
    method: str


@dataclass
class MethodModel:
    name: str
    qualname: str
    lineno: int
    sites: List[AccessSite] = field(default_factory=list)
    acquisitions: List[Acquisition] = field(default_factory=list)
    blocking: List[BlockingCall] = field(default_factory=list)
    # (callee method name, locks held at the call, lineno)
    self_calls: List[Tuple[str, Tuple[str, ...], int]] = field(
        default_factory=list
    )
    # (receiver attr name, receiver owner class, callee, locks, lineno)
    attr_calls: List[Tuple[str, str, str, Tuple[str, ...], int]] = field(
        default_factory=list
    )
    # (local receiver name, callee, locks held, lineno) — ``t.join()``
    local_calls: List[Tuple[str, str, Tuple[str, ...], int]] = field(
        default_factory=list
    )
    entry_locks: FrozenSet[str] = frozenset()
    # locks this method holds via the explicit ``self._x.acquire()`` ...
    # ``finally: self._x.release()`` idiom (no lexical with-scope); the
    # whole method body is conservatively treated as the critical
    # section (folded into entry_locks at finalize)
    manual_locks: FrozenSet[str] = frozenset()
    thread_reachable: bool = False


@dataclass
class ClassModel:
    name: str
    lineno: int
    lock_attrs: Dict[str, str] = field(default_factory=dict)   # attr -> kind
    attr_kinds: Dict[str, str] = field(default_factory=dict)   # attr -> kind
    attr_types: Dict[str, str] = field(default_factory=dict)   # attr -> class
    init_attrs: Set[str] = field(default_factory=set)
    param_attrs: Dict[str, str] = field(default_factory=dict)  # param -> attr
    init_params: List[str] = field(default_factory=list)
    methods: Dict[str, MethodModel] = field(default_factory=dict)
    thread_entries: Set[str] = field(default_factory=set)
    # (lineno, has name= kwarg, target method name or None, method qualname)
    thread_sites: List[Tuple[int, bool, Optional[str], str]] = field(
        default_factory=list
    )

    def lock_name(self, attr: str) -> str:
        return f"{self.name}.{attr}"

    @property
    def creates_threads(self) -> bool:
        return bool(self.thread_sites)

    def effective_locks(self, site: AccessSite, method: MethodModel
                        ) -> FrozenSet[str]:
        return site.locks | method.entry_locks


@dataclass
class ModuleConcurrency:
    path: str
    classes: Dict[str, ClassModel] = field(default_factory=dict)
    # attr name -> declaring class, when exactly one class declares it
    unique_attr_owner: Dict[str, str] = field(default_factory=dict)
    # module-level Thread() calls outside any class
    module_thread_sites: List[Tuple[int, bool, Optional[str], str]] = field(
        default_factory=list
    )
    # constructor-call shapes seen anywhere in the module:
    # (ClassTail, [positional arg class tails], {kwarg: class tail})
    ctor_calls: List[Tuple[str, List[Optional[str]], Dict[str, str]]] = \
        field(default_factory=list)


def _ctor_kind(expr: ast.AST) -> Optional[Tuple[str, str]]:
    """(kind, detail) for the first recognized constructor call inside
    ``expr`` — handles ``x if cond else Lock()`` shapes by scanning the
    whole RHS expression."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        tail = callee.split(".")[-1]
        if callee in _LOCK_CTORS:
            return ("lock", _LOCK_CTORS[callee])
        if tail == "make_lock":
            kind = "lock"
            for kw in node.keywords:
                if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                    kind = str(kw.value.value)
            return ("lock", kind)
        if callee in _EVENT_CTORS:
            return ("event", "event")
        if callee in _QUEUE_CTORS:
            return ("queue", _QUEUE_CTORS[callee])
        if tail in _METRIC_FACTORIES and "." in callee:
            return ("metric", tail)
    return None


def _ctor_class(expr: ast.AST) -> Optional[str]:
    """Class name constructed anywhere in ``expr`` (``Foo()`` /
    ``pkg.Foo()``), or None.  Lock/queue/event constructors are not
    classes of interest here."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        tail = callee.split(".")[-1]
        if not tail or not tail[0].isupper():
            continue
        if callee in _LOCK_CTORS or callee in _EVENT_CTORS \
                or callee in _QUEUE_CTORS:
            continue
        return tail
    return None


def _thread_target(call: ast.Call) -> Optional[str]:
    """Method name handed to Thread(target=...) / Timer(_, fn) /
    .submit(fn, ...) — only ``self.m`` and bare-name targets resolve."""
    callee = _dotted(call.func)
    tgt: Optional[ast.AST] = None
    if callee in ("threading.Thread", "Thread"):
        for kw in call.keywords:
            if kw.arg == "target":
                tgt = kw.value
    elif callee in ("threading.Timer", "Timer"):
        if len(call.args) >= 2:
            tgt = call.args[1]
    elif isinstance(call.func, ast.Attribute) and call.func.attr == "submit":
        if call.args:
            tgt = call.args[0]
    if tgt is None:
        return None
    if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
            and tgt.value.id == "self":
        return tgt.attr
    if isinstance(tgt, ast.Name):
        return tgt.id
    return None


def _is_thread_ctor(call: ast.Call) -> bool:
    return _dotted(call.func) in ("threading.Thread", "Thread")


class _MethodScanner:
    """One lexical walk of a method body, tracking the with-held lock
    set.  Nested function/class definitions are separate scopes and are
    skipped (conservative: their sites are not attributed to the
    method's lock context)."""

    def __init__(self, cls: ClassModel, mm: MethodModel,
                 imported: Set[str],
                 ctor_calls: Optional[List] = None):
        self.cls = cls
        self.mm = mm
        self.imported = imported
        self.ctor_calls = ctor_calls if ctor_calls is not None else []
        self._call_funcs: Set[int] = set()
        # lock attrs seen in explicit self._x.acquire() / .release()
        # calls; a pair makes the lock a method-scope manual_lock
        self.manual_acq: Set[str] = set()
        self.manual_rel: Set[str] = set()

    def _self_lock(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and expr.attr in self.cls.lock_attrs:
            return self.cls.lock_name(expr.attr)
        return None

    def scan(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._visit(stmt, ())

    def _visit(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lock = self._self_lock(item.context_expr)
                if lock is not None:
                    self.mm.acquisitions.append(Acquisition(
                        lock=lock, held=held, lineno=node.lineno,
                        method=self.mm.qualname,
                    ))
                    held = held + (lock,)
                else:
                    self._visit(item.context_expr, held)
            for stmt in node.body:
                self._visit(stmt, held)
            return
        if isinstance(node, ast.Call):
            self._classify_call(node, held)
        elif isinstance(node, ast.Attribute):
            self._classify_attr(node, held)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    # -- attribute state sites ----------------------------------------------

    def _receiver(self, node: ast.Attribute) -> Optional[str]:
        """'self', a plain local name, or None for deeper chains."""
        if isinstance(node.value, ast.Name):
            name = node.value.id
            if name in self.imported:
                return None
            return name
        return None

    def _classify_attr(self, node: ast.Attribute,
                       held: Tuple[str, ...]) -> None:
        if id(node) in self._call_funcs:
            return
        recv = self._receiver(node)
        if recv is None:
            return
        if recv == "self" and node.attr in self.cls.lock_attrs:
            return  # the lock object itself, not guarded state
        is_write = isinstance(node.ctx, (ast.Store, ast.Del))
        owner = "self" if recv == "self" else f"@{node.attr}"
        self.mm.sites.append(AccessSite(
            owner=owner, attr=node.attr, method=self.mm.qualname,
            lineno=node.lineno, is_write=is_write, locks=frozenset(held),
        ))

    # -- calls ---------------------------------------------------------------

    def _classify_call(self, node: ast.Call,
                       held: Tuple[str, ...]) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            self._call_funcs.add(id(func))
        # thread creation
        tgt = _thread_target(node)
        callee = _dotted(func)
        if callee in ("threading.Thread", "Thread"):
            has_name = any(kw.arg == "name" for kw in node.keywords)
            self.cls.thread_sites.append(
                (node.lineno, has_name, tgt, self.mm.qualname)
            )
        if tgt is not None:
            self.cls.thread_entries.add(tgt)

        # constructor-shaped calls feed param-passthrough typing
        tail = callee.split(".")[-1]
        if tail and tail[0].isupper() and callee not in _LOCK_CTORS \
                and callee not in _EVENT_CTORS \
                and callee not in _QUEUE_CTORS:
            self.ctor_calls.append((
                tail,
                [_ctor_class(a) for a in node.args],
                {kw.arg: t for kw in node.keywords if kw.arg
                 for t in [_ctor_class(kw.value)] if t is not None},
            ))

        if not isinstance(func, ast.Attribute):
            # bare-name call: only module-path blocking shapes apply
            self._classify_blocking(node, None, None, callee, held)
            return

        meth = func.attr
        recv_node = func.value
        if isinstance(recv_node, ast.Name) and recv_node.id == "self":
            # self.m(...)
            self.mm.self_calls.append((meth, held, node.lineno))
            self._classify_blocking(node, "self", None, callee, held)
            return
        if isinstance(recv_node, ast.Attribute) and \
                isinstance(recv_node.value, ast.Name):
            base = recv_node.value.id
            attr = recv_node.attr
            if base == "self":
                # self.X.m(...)
                if attr in self.cls.lock_attrs and meth == "acquire":
                    self.manual_acq.add(attr)
                    self.mm.acquisitions.append(Acquisition(
                        lock=self.cls.lock_name(attr), held=held,
                        lineno=node.lineno, method=self.mm.qualname,
                    ))
                elif attr in self.cls.lock_attrs and meth == "release":
                    self.manual_rel.add(attr)
                if attr in self.cls.lock_attrs and meth in _MUTATOR_METHODS:
                    pass
                elif meth in _MUTATOR_METHODS:
                    self.mm.sites.append(AccessSite(
                        owner="self", attr=attr, method=self.mm.qualname,
                        lineno=node.lineno, is_write=True,
                        locks=frozenset(held),
                    ))
                self.mm.attr_calls.append(
                    (attr, "self", meth, held, node.lineno)
                )
            elif base not in self.imported:
                # local.X.m(...) — owner class resolves by unique attr
                if meth in _MUTATOR_METHODS:
                    self.mm.sites.append(AccessSite(
                        owner=f"@{attr}", attr=attr, method=self.mm.qualname,
                        lineno=node.lineno, is_write=True,
                        locks=frozenset(held),
                    ))
                self.mm.attr_calls.append(
                    (attr, f"@{attr}", meth, held, node.lineno)
                )
            self._classify_blocking(node, base, attr, callee, held)
            return
        if isinstance(recv_node, ast.Name):
            # local.m(...): a mutator on a bound local is a write of THAT
            # local's binding — the unique-attr pass cannot attribute it,
            # so record the call shape (JL023's join detection) and
            # classify blocking only
            self.mm.local_calls.append(
                (recv_node.id, meth, held, node.lineno)
            )
            self._classify_blocking(node, recv_node.id, None, callee, held)
            return
        self._classify_blocking(node, None, None, callee, held)

    def _classify_blocking(self, node: ast.Call, base: Optional[str],
                           attr: Optional[str], callee: str,
                           held: Tuple[str, ...]) -> None:
        # recorded regardless of the lexically-held set: a helper whose
        # entry locks are inferred later may make this blocking call
        # effectively under a lock — JL021 filters on the union
        func = node.func
        meth = func.attr if isinstance(func, ast.Attribute) else None
        desc: Optional[str] = None
        if callee.startswith(_BLOCKING_DOTTED_PREFIXES) or \
                callee in _BLOCKING_DOTTED:
            desc = f"{callee}()"
        elif meth == "result":
            desc = "future.result()"
        elif meth in _BLOCKING_SOCKET_METHODS:
            desc = f".{meth}() (socket send/recv)"
        elif meth in ("wait", "wait_for") and base == "self" and attr:
            kind = self.cls.attr_kinds.get(attr)
            if kind == "event":
                desc = f"self.{attr}.wait() (Event.wait)"
            # Condition.wait on the lock being held RELEASES it while
            # parked — the standard pattern, never a convoy: exempt
        elif meth in ("get", "put") and base == "self" and attr:
            kind = self.cls.attr_kinds.get(attr)
            if kind == "queue":
                desc = f"self.{attr}.{meth}() (queue.{meth})"
            elif kind == "simplequeue" and meth == "get":
                # SimpleQueue.put never blocks; .get does
                desc = f"self.{attr}.get() (queue.get)"
        elif meth == "compile":
            spelled = _dotted(func.value) if isinstance(func, ast.Attribute) \
                else ""
            if "registr" in spelled or "lowered" in spelled.split("."):
                desc = f"{spelled}.compile() (XLA compile)"
        if desc is not None:
            self.mm.blocking.append(BlockingCall(
                desc=desc, locks=held, lineno=node.lineno,
                method=self.mm.qualname,
            ))


def _scan_init(cls: ClassModel, init: ast.FunctionDef) -> None:
    cls.init_params = [a.arg for a in init.args.args[1:]] + \
        [a.arg for a in init.args.kwonlyargs]
    for node in ast.walk(init):
        targets: List[ast.expr] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None:
            continue
        for t in targets:
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            cls.init_attrs.add(t.attr)
            kd = _ctor_kind(value)
            if kd is not None:
                kind, detail = kd
                if kind == "lock":
                    cls.lock_attrs[t.attr] = detail
                    cls.attr_kinds[t.attr] = detail
                else:
                    cls.attr_kinds[t.attr] = kind if kind != "queue" \
                        else detail
            typed = _ctor_class(value)
            if typed is not None:
                cls.attr_types.setdefault(t.attr, typed)
            if isinstance(value, ast.Name) and \
                    value.id in cls.init_params:
                cls.param_attrs[value.id] = t.attr


def _module_imported_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                out.add(a.asname or a.name)
    return out


def build_module_model(path: str, tree: ast.Module) -> ModuleConcurrency:
    model = ModuleConcurrency(path=path)
    imported = _module_imported_names(tree)

    classdefs: List[ast.ClassDef] = [
        n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
    ]
    for cd in classdefs:
        cls = ClassModel(name=cd.name, lineno=cd.lineno)
        model.classes[cd.name] = cls
        methods = [
            n for n in cd.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        init = next((m for m in methods if m.name == "__init__"), None)
        if init is not None:
            _scan_init(cls, init)
        for m in methods:
            mm = MethodModel(
                name=m.name, qualname=f"{cd.name}.{m.name}",
                lineno=m.lineno,
            )
            cls.methods[m.name] = mm
            sc = _MethodScanner(cls, mm, imported, model.ctor_calls)
            sc.scan(m.body)
            mm.manual_locks = frozenset(
                cls.lock_name(a) for a in (sc.manual_acq & sc.manual_rel)
            )

    # module-level thread creation (functions outside classes)
    class_nodes: Set[int] = set()
    for cd in classdefs:
        for n in ast.walk(cd):
            class_nodes.add(id(n))
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_thread_ctor(node) \
                and id(node) not in class_nodes:
            has_name = any(kw.arg == "name" for kw in node.keywords)
            model.module_thread_sites.append(
                (node.lineno, has_name, _thread_target(node), "<module>")
            )

    _finalize(model)
    return model


def _finalize(model: ModuleConcurrency) -> None:
    """Resolve unique-attr owners, entry locks, param-passthrough
    types, and the thread-reachability closure."""
    # attr name -> declaring class, when unambiguous across the module
    declared: Dict[str, List[str]] = {}
    for cls in model.classes.values():
        for attr in cls.init_attrs:
            declared.setdefault(attr, []).append(cls.name)
    model.unique_attr_owner = {
        attr: owners[0] for attr, owners in declared.items()
        if len(owners) == 1
    }

    # constructor-argument passthrough: Owner(..., ClassName(...)) types
    # Owner's param-assigned attribute as ClassName
    _apply_param_passthrough(model.ctor_calls, model.classes)

    for cls in model.classes.values():
        # helper call-through: a private helper whose every intra-class
        # call site holds L is analyzed with L held at entry
        call_sites: Dict[str, List[FrozenSet[str]]] = {}
        for mm in cls.methods.values():
            for callee, held, _ in mm.self_calls:
                call_sites.setdefault(callee, []).append(frozenset(held))
        for name, mm in cls.methods.items():
            if not name.startswith("_") or name.startswith("__") \
                    or name in cls.thread_entries:
                continue
            sites = call_sites.get(name)
            if not sites:
                continue
            common = frozenset.intersection(*sites)
            if common:
                mm.entry_locks = common
        for mm in cls.methods.values():
            if mm.manual_locks:
                mm.entry_locks = mm.entry_locks | mm.manual_locks

        # thread-reachability: entries, closed over self-calls
        reachable = set(cls.thread_entries) & set(cls.methods)
        frontier = list(reachable)
        while frontier:
            m = frontier.pop()
            for callee, _, _ in cls.methods[m].self_calls:
                if callee in cls.methods and callee not in reachable:
                    reachable.add(callee)
                    frontier.append(callee)
        for name in reachable:
            cls.methods[name].thread_reachable = True


def _apply_param_passthrough(
    ctor_calls: List[Tuple[str, List[Optional[str]], Dict[str, str]]],
    registry: Dict[str, ClassModel],
) -> None:
    """``self.x = param`` + a call site ``Owner(..., ClassName(...))``
    types ``Owner.x`` as ``ClassName`` — the ``Replica(...,
    CircuitBreaker(...))`` shape, where the breaker's class is only
    visible at the router's construction site."""
    for tail, pos_tails, kw_tails in ctor_calls:
        cls = registry.get(tail)
        if cls is None or not cls.param_attrs:
            continue
        for i, arg_tail in enumerate(pos_tails):
            if arg_tail is None or i >= len(cls.init_params):
                continue
            attr = cls.param_attrs.get(cls.init_params[i])
            if attr is not None:
                cls.attr_types.setdefault(attr, arg_tail)
        for kw, arg_tail in kw_tails.items():
            attr = cls.param_attrs.get(kw)
            if attr is not None:
                cls.attr_types.setdefault(attr, arg_tail)


def module_model(mod) -> ModuleConcurrency:
    """The memoized per-ModuleInfo concurrency model (rules share it)."""
    cached = getattr(mod, "_concurrency_model", None)
    if cached is None:
        cached = build_module_model(mod.path, mod.tree)
        mod._concurrency_model = cached
    return cached


# ---------------------------------------------------------------------------
# cross-module merge + lock-order graph
# ---------------------------------------------------------------------------


def merge_models(models: List[ModuleConcurrency]) -> Dict[str, ClassModel]:
    """One registry of class models across every analyzed module.  A
    class name defined in two modules is dropped from cross-class
    resolution (ambiguous) but keeps its per-module rules."""
    seen: Dict[str, ClassModel] = {}
    dupes: Set[str] = set()
    for m in models:
        for name, cls in m.classes.items():
            if name in seen:
                dupes.add(name)
            else:
                seen[name] = cls
    for name in dupes:
        seen.pop(name, None)
    return seen


def _acquired_locks(cls: ClassModel, method: str,
                    depth: int = 1,
                    registry: Optional[Dict[str, ClassModel]] = None,
                    unique_owner: Optional[Dict[str, str]] = None
                    ) -> Set[str]:
    """Locks ``cls.method`` acquires — direct acquisitions plus one
    level of self-call-through, plus (when a class registry is given)
    one level of cross-class call-through on typed attributes: the
    ``run() -> self._compile() -> self.program_registry.compile()``
    shape, where the inner lock belongs to another class."""
    mm = cls.methods.get(method)
    if mm is None:
        return set()
    out = {a.lock for a in mm.acquisitions}
    if depth > 0:
        for callee, _, _ in mm.self_calls:
            out |= _acquired_locks(cls, callee, depth=depth - 1,
                                   registry=registry,
                                   unique_owner=unique_owner)
    if registry is not None and unique_owner is not None:
        for attr, owner_tag, callee, _, _ in mm.attr_calls:
            target = _attr_owner_class(
                cls, owner_tag, attr, registry, unique_owner
            )
            if target is not None:
                target_mm = target.methods.get(callee)
                if target_mm is not None:
                    out |= {a.lock for a in target_mm.acquisitions}
    return out


# method names too generic to identify a receiver's class (dict/list/
# primitive protocol + lifecycle verbs every class spells)
_COMMON_METHODS = {
    "get", "put", "pop", "append", "add", "remove", "clear", "update",
    "items", "keys", "values", "join", "start", "set", "is_set",
    "acquire", "release", "wait", "wait_for", "notify", "notify_all",
    "close", "stop", "run", "submit", "result", "emit", "observe",
    "inc", "read", "write", "send", "recv", "copy", "extend", "index",
}


def _unique_method_owner(registry: Dict[str, ClassModel], meth: str
                         ) -> Optional[ClassModel]:
    """The one class defining ``meth``, when the name is distinctive
    enough to identify a local receiver (``router.wait_state(...)`` →
    FleetRouter).  Generic protocol names never resolve."""
    if meth in _COMMON_METHODS or meth.startswith("__"):
        return None
    owners = [
        cls for cls in registry.values() if meth in cls.methods
    ]
    if len(owners) == 1:
        return owners[0]
    return None


def _attr_owner_class(cls: ClassModel, owner_tag: str, attr: str,
                      registry: Dict[str, ClassModel],
                      unique_attr_owner: Dict[str, str]
                      ) -> Optional[ClassModel]:
    """The ClassModel behind an attr_call receiver."""
    if owner_tag == "self":
        typed = cls.attr_types.get(attr)
        if typed is not None:
            return registry.get(typed)
        return None
    # '@attr' — a local receiver; the unique declaring class's typed
    # attribute resolves it (rep.breaker -> Replica.breaker -> its type)
    decl = unique_attr_owner.get(attr)
    if decl is None:
        return None
    decl_cls = registry.get(decl)
    if decl_cls is None:
        return None
    typed = decl_cls.attr_types.get(attr)
    if typed is not None:
        return registry.get(typed)
    return None


def lock_edges(models: List[ModuleConcurrency]
               ) -> Dict[Tuple[str, str], List[str]]:
    """Directed edges A -> B ("A is acquired before/around B") with the
    evidence sites that produced them."""
    registry = merge_models(models)
    # merge unique-attr owners across modules (drop ambiguous)
    decl: Dict[str, List[str]] = {}
    for m in models:
        for cls in m.classes.values():
            for attr in cls.init_attrs:
                decl.setdefault(attr, []).append(cls.name)
    unique_owner = {a: o[0] for a, o in decl.items() if len(o) == 1}
    # cross-module param passthrough: a ctor call in one module may type
    # an attribute of a class defined in another
    for m in models:
        _apply_param_passthrough(m.ctor_calls, registry)

    edges: Dict[Tuple[str, str], List[str]] = {}

    def add(a: str, b: str, why: str) -> None:
        if a == b:
            return
        edges.setdefault((a, b), []).append(why)

    for m in models:
        for cls in m.classes.values():
            for mm in cls.methods.values():
                entry = tuple(sorted(mm.entry_locks))
                for acq in mm.acquisitions:
                    for h in set(acq.held) | set(entry):
                        add(h, acq.lock,
                            f"{m.path}:{acq.lineno} {mm.qualname}")
                for callee, held, lineno in mm.self_calls:
                    outer = set(held) | set(entry)
                    if not outer:
                        continue
                    for inner in _acquired_locks(
                        cls, callee, registry=registry,
                        unique_owner=unique_owner,
                    ):
                        for h in outer:
                            add(h, inner,
                                f"{m.path}:{lineno} {mm.qualname} -> "
                                f"self.{callee}()")
                for attr, owner_tag, callee, held, lineno in mm.attr_calls:
                    outer = set(held) | set(entry)
                    if not outer:
                        continue
                    target = _attr_owner_class(
                        cls, owner_tag, attr, registry, unique_owner
                    )
                    if target is None:
                        continue
                    for inner in _acquired_locks(target, callee):
                        for h in outer:
                            add(h, inner,
                                f"{m.path}:{lineno} {mm.qualname} -> "
                                f".{attr}.{callee}()")
                for recv, callee, held, lineno in mm.local_calls:
                    outer = set(held) | set(entry)
                    if not outer:
                        continue
                    target = _unique_method_owner(registry, callee)
                    if target is None:
                        continue
                    for inner in _acquired_locks(target, callee):
                        for h in outer:
                            add(h, inner,
                                f"{m.path}:{lineno} {mm.qualname} -> "
                                f"{recv}.{callee}()")
    return edges


def find_cycle(edges: Dict[Tuple[str, str], List[str]]
               ) -> Optional[List[str]]:
    """A lock-order cycle as [a, b, ..., a], or None."""
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GREY
        stack.append(n)
        for nxt in sorted(adj[n]):
            if color[nxt] == GREY:
                i = stack.index(nxt)
                return stack[i:] + [nxt]
            if color[nxt] == WHITE:
                found = dfs(nxt)
                if found:
                    return found
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(adj):
        if color[n] == WHITE:
            found = dfs(n)
            if found:
                return found
    return None


def topological_order(edges: Dict[Tuple[str, str], List[str]],
                      all_locks: Set[str]) -> List[str]:
    """Kahn's algorithm with an alphabetical tiebreak: a deterministic
    total order over every known lock, consistent with the edges.
    Raises ValueError on a cycle."""
    nodes = set(all_locks)
    for a, b in edges:
        nodes.add(a)
        nodes.add(b)
    indeg: Dict[str, int] = {n: 0 for n in nodes}
    adj: Dict[str, Set[str]] = {n: set() for n in nodes}
    for a, b in edges:
        if b not in adj[a]:
            adj[a].add(b)
            indeg[b] += 1
    ready = sorted(n for n in nodes if indeg[n] == 0)
    order: List[str] = []
    while ready:
        n = ready.pop(0)
        order.append(n)
        changed = False
        for nxt in adj[n]:
            indeg[nxt] -= 1
            if indeg[nxt] == 0:
                ready.append(nxt)
                changed = True
        if changed:
            ready.sort()
    if len(order) != len(nodes):
        raise ValueError("lock-order graph has a cycle")
    return order


def all_lock_names(models: List[ModuleConcurrency]) -> Set[str]:
    out: Set[str] = set()
    for m in models:
        for cls in m.classes.values():
            for attr in cls.lock_attrs:
                out.add(cls.lock_name(attr))
    return out


# ---------------------------------------------------------------------------
# program-wide artifact (analysis/lockorder.json)
# ---------------------------------------------------------------------------


def tree_models(paths: Optional[List[str]] = None
                ) -> List[ModuleConcurrency]:
    """Concurrency models for every in-scope package module under
    ``paths`` (default: the standard lint paths, restricted to
    ``speakingstyle_tpu/`` sources the concurrency rules cover)."""
    from speakingstyle_tpu.analysis import linter

    root = linter.repo_root()
    models: List[ModuleConcurrency] = []
    for fp in linter.iter_py_files(paths or linter.default_lint_paths()):
        rel = os.path.relpath(os.path.abspath(fp), root).replace(
            os.sep, "/"
        )
        if "speakingstyle_tpu/" not in rel or "tests/" in rel:
            continue
        if rel.endswith("obs/locks.py"):
            # the witness itself: TrackedLock._inner wraps the real
            # primitives and must not appear as an app lock in the order
            continue
        try:
            with open(fp, "r", encoding="utf-8") as fh:
                src = fh.read()
        except (OSError, UnicodeDecodeError):
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        models.append(build_module_model(rel, tree))
    return models


def _evidence_key(why: str) -> str:
    """Evidence strings minus line numbers, so unrelated edits in a
    file don't churn the committed artifact (same policy as the lint
    baseline's line-free fingerprints)."""
    head, _, rest = why.partition(" ")
    return head.rsplit(":", 1)[0] + " " + rest


def lockorder_artifact(models: List[ModuleConcurrency]) -> dict:
    """The checked-in ``lockorder.json`` payload: the edge list with
    line-free evidence plus the total acquisition order the runtime
    witness (``obs.locks.TrackedLock``) enforces.

    Raises ``ValueError`` naming the cycle if the graph is cyclic.
    """
    edges = lock_edges(models)
    cycle = find_cycle(edges)
    if cycle is not None:
        raise ValueError("lock-order cycle: " + " -> ".join(cycle))
    order = topological_order(edges, all_lock_names(models))
    return {
        "comment": (
            "Static lock-acquisition order (jaxlint JL022). 'order' is "
            "the total order TrackedLock enforces at runtime under "
            "SPEAKINGSTYLE_CHECKS=1: a thread may only acquire locks in "
            "increasing order position. Regenerate with `python -m "
            "speakingstyle_tpu.analysis.cli lockorder --write`; "
            "`--check` fails if this file is stale."
        ),
        "version": 1,
        "edges": [
            {
                "before": a,
                "after": b,
                "evidence": sorted({_evidence_key(w) for w in whys}),
            }
            for (a, b), whys in sorted(edges.items())
        ],
        "order": order,
    }
