"""jaxlint command line: ``python -m speakingstyle_tpu.analysis.cli``.

Exit codes: 0 = clean modulo the committed baseline; 1 = new findings
and/or stale baseline entries (both directions fail loudly); 2 = usage
error. ``scripts/lint_jax.py`` is the repo-root wrapper for CI.

The ``lockorder`` subcommand manages the static lock-order artifact
(``analysis/lockorder.json``, rule JL022):

    python -m speakingstyle_tpu.analysis.cli lockorder           # verify
    python -m speakingstyle_tpu.analysis.cli lockorder --write   # refresh

``--check`` also fails if the committed artifact is stale, same idiom
as the lint baseline.
"""

import argparse
import json
import sys
import time

from speakingstyle_tpu.analysis import linter
from speakingstyle_tpu.analysis.rules import RULES


def _print_rules():
    for code, rule in sorted(RULES.items()):
        doc = (rule.__doc__ or "").strip().splitlines()
        head = doc[0] if doc else ""
        print(f"{code}  {head}")
        for line in doc[1:]:
            print(f"       {line.strip()}")
        print()


def _load_lockorder(path: str):
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _lockorder_stale(path=None):
    """-> (message-or-None, artifact). Rebuilds the lock-order graph
    from source and compares with the committed file; any difference —
    including a cycle — is a failure message."""
    from speakingstyle_tpu.analysis import concurrency

    path = path or linter.default_lockorder_path()
    try:
        art = concurrency.lockorder_artifact(concurrency.tree_models())
    except ValueError as e:   # cycle: the artifact cannot exist
        return str(e), None
    committed = _load_lockorder(path)
    if committed is None:
        return (
            f"lockorder artifact missing/unreadable: {path} (run "
            "`python -m speakingstyle_tpu.analysis.cli lockorder "
            "--write` and commit it)"
        ), art
    if committed != art:
        return (
            "lockorder.json is STALE: lock acquisitions changed — "
            "regenerate with `python -m speakingstyle_tpu.analysis.cli "
            "lockorder --write` and review the diff like code"
        ), art
    return None, art


def _lockorder_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m speakingstyle_tpu.analysis.cli lockorder",
        description="Build/verify the static lock-order artifact "
                    "(JL022).",
    )
    ap.add_argument(
        "--write", action="store_true",
        help="regenerate the committed artifact from source",
    )
    ap.add_argument(
        "--out", default=None,
        help=f"artifact path (default: {linter.default_lockorder_path()})",
    )
    args = ap.parse_args(argv)
    path = args.out or linter.default_lockorder_path()
    stale, art = _lockorder_stale(path)
    if art is None:   # cycle
        print(f"FAIL: {stale}", file=sys.stderr)
        return 1
    if args.write:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(art, fh, indent=2)
            fh.write("\n")
        print(
            f"lockorder written: {len(art['edges'])} edge(s), "
            f"{len(art['order'])} lock(s) -> {path}"
        )
        return 0
    if stale:
        print(f"FAIL: {stale}", file=sys.stderr)
        return 1
    print(
        f"OK: lockorder.json current ({len(art['edges'])} edge(s), "
        f"{len(art['order'])} lock(s), acyclic)"
    )
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "lockorder":
        return _lockorder_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m speakingstyle_tpu.analysis.cli",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the repo's "
             "speakingstyle_tpu/, scripts/, tests/, bench.py)",
    )
    ap.add_argument(
        "--check", action="store_true",
        help="CI mode: succeed only if clean modulo the baseline "
             "(stale baseline entries also fail)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: {linter.default_baseline_path()})",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    ap.add_argument(
        "--select", default=None,
        help="comma-separated rule codes to run (default: all)",
    )
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument(
        "--profile", action="store_true",
        help="print per-rule wall time after linting",
    )
    ap.add_argument(
        "--time-budget", type=float, default=6.0, metavar="SECONDS",
        help="with --check: fail if the full-tree lint exceeds this "
             "wall time (guards the single-pass refactor — the old "
             "flat scanner took ~7.5s; post-refactor is ~2.5s). "
             "0 disables. (default: %(default)s)",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        _print_rules()
        return 0

    select = None
    if args.select:
        select = {r.strip().upper() for r in args.select.split(",")}
        unknown = select - set(RULES)
        if unknown:
            print(f"unknown rules: {sorted(unknown)}", file=sys.stderr)
            return 2

    profile = {} if args.profile else None
    t_lint = time.perf_counter()
    findings = linter.lint_paths(
        args.paths or None, select=select, profile=profile
    )
    lint_secs = time.perf_counter() - t_lint
    if profile is not None:
        total = sum(profile.values())
        print(f"per-rule wall time ({total:.3f}s total):")
        for code, secs in sorted(
            profile.items(), key=lambda kv: -kv[1]
        ):
            print(f"  {code}  {secs * 1e3:8.1f} ms")

    if args.update_baseline:
        linter.save_baseline(findings, args.baseline)
        print(
            f"baseline updated: {len(findings)} findings -> "
            f"{args.baseline or linter.default_baseline_path()}"
        )
        return 0

    baseline = (
        linter.load_baseline(args.baseline)
        if not args.no_baseline
        else linter.findings_counter([])
    )
    new, stale = linter.compare_to_baseline(findings, baseline)

    by_fp = {}
    for f in findings:
        by_fp.setdefault(f.fingerprint, []).append(f)

    shown = 0
    for fp in sorted(new):
        for f in by_fp[fp][: new[fp]]:
            print(f"{f.path}:{f.line}: {f.rule} {f.message}")
            shown += 1
    baselined = len(findings) - shown
    if stale:
        print(
            f"\nSTALE baseline entries (fixed in code, still listed — run "
            "--update-baseline and commit the diff):",
            file=sys.stderr,
        )
        for fp in sorted(stale):
            print(f"  {fp} (x{stale[fp]})", file=sys.stderr)

    over_budget = (
        args.check and not args.paths and args.time_budget > 0
        and lint_secs > args.time_budget
    )
    if over_budget:
        print(
            f"\nlint wall time {lint_secs:.2f}s exceeds the "
            f"{args.time_budget:.1f}s budget — the single-pass walk "
            "cache may have regressed (see --profile)",
            file=sys.stderr,
        )

    lockorder_msg = None
    if args.check and not args.paths:
        # CI gate over the whole tree: the committed lock-order
        # artifact must match what the source implies (JL022)
        lockorder_msg, _ = _lockorder_stale()
        if lockorder_msg:
            print(f"\n{lockorder_msg}", file=sys.stderr)

    failed = bool(new or stale or lockorder_msg or over_budget)
    summary = (
        f"{shown} finding(s) over baseline, {baselined} baselined, "
        f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'}"
    )
    if args.check and not args.paths:
        summary += (
            ", lockorder stale" if lockorder_msg else ", lockorder current"
        )
    print(("FAIL: " if failed else "OK: ") + summary,
          file=sys.stderr if failed else sys.stdout)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
