"""jaxlint command line: ``python -m speakingstyle_tpu.analysis.cli``.

Exit codes: 0 = clean modulo the committed baseline; 1 = new findings
and/or stale baseline entries (both directions fail loudly); 2 = usage
error. ``scripts/lint_jax.py`` is the repo-root wrapper for CI.
"""

import argparse
import sys

from speakingstyle_tpu.analysis import linter
from speakingstyle_tpu.analysis.rules import RULES


def _print_rules():
    for code, rule in sorted(RULES.items()):
        doc = (rule.__doc__ or "").strip().splitlines()
        head = doc[0] if doc else ""
        print(f"{code}  {head}")
        for line in doc[1:]:
            print(f"       {line.strip()}")
        print()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m speakingstyle_tpu.analysis.cli",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the repo's "
             "speakingstyle_tpu/, scripts/, tests/, bench.py)",
    )
    ap.add_argument(
        "--check", action="store_true",
        help="CI mode: succeed only if clean modulo the baseline "
             "(stale baseline entries also fail)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: {linter.default_baseline_path()})",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    ap.add_argument(
        "--select", default=None,
        help="comma-separated rule codes to run (default: all)",
    )
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        _print_rules()
        return 0

    select = None
    if args.select:
        select = {r.strip().upper() for r in args.select.split(",")}
        unknown = select - set(RULES)
        if unknown:
            print(f"unknown rules: {sorted(unknown)}", file=sys.stderr)
            return 2

    findings = linter.lint_paths(args.paths or None, select=select)

    if args.update_baseline:
        linter.save_baseline(findings, args.baseline)
        print(
            f"baseline updated: {len(findings)} findings -> "
            f"{args.baseline or linter.default_baseline_path()}"
        )
        return 0

    baseline = (
        linter.load_baseline(args.baseline)
        if not args.no_baseline
        else linter.findings_counter([])
    )
    new, stale = linter.compare_to_baseline(findings, baseline)

    by_fp = {}
    for f in findings:
        by_fp.setdefault(f.fingerprint, []).append(f)

    shown = 0
    for fp in sorted(new):
        for f in by_fp[fp][: new[fp]]:
            print(f"{f.path}:{f.line}: {f.rule} {f.message}")
            shown += 1
    baselined = len(findings) - shown
    if stale:
        print(
            f"\nSTALE baseline entries (fixed in code, still listed — run "
            "--update-baseline and commit the diff):",
            file=sys.stderr,
        )
        for fp in sorted(stale):
            print(f"  {fp} (x{stale[fp]})", file=sys.stderr)

    summary = (
        f"{shown} finding(s) over baseline, {baselined} baselined, "
        f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'}"
    )
    print(("FAIL: " if (new or stale) else "OK: ") + summary,
          file=sys.stderr if (new or stale) else sys.stdout)
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
