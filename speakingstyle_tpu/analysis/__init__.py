"""Static analysis + runtime contracts for JAX/TPU invariants.

Two complementary layers:

* ``speakingstyle_tpu.analysis`` (jaxlint) — an AST linter enforcing the
  throughput-critical invariants no generic Python linter knows about:
  trace-unsafe control flow (JL001), numpy-on-device-arrays (JL002),
  missing donation (JL003), host syncs in training loops (JL004),
  recompilation hazards (JL005), PRNG key reuse (JL006). Run it via
  ``python scripts/lint_jax.py --check`` or
  ``python -m speakingstyle_tpu.analysis.cli``.
* ``speakingstyle_tpu.analysis.contracts`` — chex-style runtime
  shape/dtype/finiteness assertions wired into the model/training entry
  points; no-ops unless ``SPEAKINGSTYLE_CHECKS=1``.
"""

from speakingstyle_tpu.analysis.linter import (  # noqa: F401
    compare_to_baseline,
    default_baseline_path,
    default_lint_paths,
    findings_counter,
    lint_paths,
    lint_source,
    load_baseline,
    save_baseline,
)
from speakingstyle_tpu.analysis.rules import RULES, Finding  # noqa: F401
