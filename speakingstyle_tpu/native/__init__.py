"""Native (C++) runtime components, consumed via ctypes.

The reference's only native compute is third-party: pyworld's C++ WORLD
bindings for F0 (reference: preprocessor/preprocessor.py:182-187) plus the
external MFA binary. This package carries the framework's OWN native
equivalents — currently ``yin_f0.cc``, an exact C++ port of the
``data/f0.py`` YIN tracker (measured ~1.7x the vectorized numpy version,
~60x real time on one core; no FFT library needed, and agreement with the
numpy backend is near-bitwise: max |Δf0| ~1e-12 Hz).

Zero build infrastructure required: ``ensure_built()`` compiles the shared
library with ``g++ -O3`` on first use and caches it next to the source;
every consumer degrades gracefully to the numpy implementation when no
compiler is available. No pybind11 — plain C ABI through ctypes.
"""

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "yin_f0.cc")
_LIB = os.path.join(_HERE, "libyin_f0.so")
_lock = threading.Lock()
_lib_handle = None
_build_failed = False


def ensure_built(force: bool = False) -> Optional[str]:
    """Compile libyin_f0.so if missing (and g++ exists). Returns the lib
    path, or None when unavailable (no compiler / build error)."""
    global _build_failed
    with _lock:
        try:
            fresh = os.path.exists(_LIB) and os.path.getmtime(
                _LIB
            ) >= os.path.getmtime(_SRC)
        except OSError:  # source missing: use the prebuilt lib if present
            fresh = os.path.exists(_LIB)
        if not force and fresh:
            return _LIB
        if not os.path.exists(_SRC):
            return _LIB if os.path.exists(_LIB) else None
        if _build_failed and not force:
            return None
        # Compile to a process-unique temp path then os.rename (atomic on
        # POSIX): the preprocessor fans extract_f0 out over a process pool,
        # and concurrent first-use builds must never expose a half-written
        # .so to another worker's CDLL.
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        try:
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.rename(tmp, _LIB)
            return _LIB
        except (OSError, subprocess.SubprocessError):
            _build_failed = True
            return None
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass


def _load():
    global _lib_handle
    if _lib_handle is not None:
        return _lib_handle
    path = ensure_built()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        # corrupt/foreign-arch artifact: degrade to the numpy backend
        return None
    lib.yin_f0.restype = ctypes.c_long
    lib.yin_f0.argtypes = [
        ctypes.POINTER(ctypes.c_double),  # wav
        ctypes.c_long,                    # n
        ctypes.c_double,                  # sampling_rate
        ctypes.c_long,                    # hop_length
        ctypes.c_double,                  # f0_floor
        ctypes.c_double,                  # f0_ceil
        ctypes.c_double,                  # threshold
        ctypes.c_long,                    # frame_length (0 = default)
        ctypes.POINTER(ctypes.c_double),  # out
    ]
    _lib_handle = lib
    return lib


def have_native_yin() -> bool:
    return _load() is not None


def yin_f0_native(
    wav: np.ndarray,
    sampling_rate: int,
    hop_length: int,
    f0_floor: float = 71.0,
    f0_ceil: float = 800.0,
    threshold: float = 0.15,
    frame_length: int = 0,
) -> Optional[np.ndarray]:
    """C++ YIN; returns None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    wav = np.ascontiguousarray(wav, np.float64)
    n_frames = len(wav) // hop_length + 1
    out = np.empty(n_frames, np.float64)
    rc = lib.yin_f0(
        wav.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(wav),
        float(sampling_rate),
        hop_length,
        float(f0_floor),
        float(f0_ceil),
        float(threshold),
        frame_length,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    if rc != n_frames:
        return None
    return out
