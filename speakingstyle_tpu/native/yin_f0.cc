// Native YIN pitch tracker — the framework's own replacement for the
// reference's one native dependency (pyworld's C++ WORLD bindings, used
// only for F0 extraction: reference preprocessor/preprocessor.py:182-187).
//
// Algorithm and constants mirror speakingstyle_tpu/data/f0.py::yin_f0
// EXACTLY (same difference function, cumulative-mean normalization,
// first-dip-run selection, parabolic interpolation, voicing rule), in
// double precision, so the Python test suite can assert near-bitwise
// agreement between the two backends. Direct O(W·maxlag) correlation per
// frame: at 22.05 kHz (W≈620, maxlag≈312) that is ~0.2 MFLOP per 11.6 ms
// hop — orders of magnitude faster than real time without needing an FFT.
//
// Build (see speakingstyle_tpu/native/__init__.py::ensure_built):
//   g++ -O3 -march=native -shared -fPIC -o libyin_f0.so yin_f0.cc
//
// C ABI only — consumed via ctypes, no pybind11 dependency.

#include <cmath>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {

// wav[n] float64 in [-1, 1] -> out[n_frames] Hz (0 where unvoiced).
// Returns the number of frames written (n/hop + 1), or -1 on bad args.
long yin_f0(const double* wav, long n, double sampling_rate, long hop_length,
            double f0_floor, double f0_ceil, double threshold,
            long frame_length, double* out) {
  if (n <= 0 || hop_length <= 0 || f0_floor <= 0 || f0_ceil <= f0_floor)
    return -1;
  const long max_lag = (long)(sampling_rate / f0_floor) + 2;
  long min_lag = (long)(sampling_rate / f0_ceil);
  if (min_lag < 2) min_lag = 2;
  const long w = frame_length > 0 ? frame_length : 2 * max_lag;
  if (w <= max_lag) return -1;

  const long n_frames = n / hop_length + 1;
  const long pad_front = w / 2;  // matches np.pad(wav, (w//2, w))
  const long padded_len = pad_front + n + w;

  std::vector<double> padded((size_t)padded_len, 0.0);
  std::memcpy(padded.data() + pad_front, wav, sizeof(double) * (size_t)n);

  std::vector<double> frame((size_t)w);
  std::vector<double> d((size_t)max_lag);
  std::vector<double> cmnd((size_t)max_lag);

  for (long t = 0; t < n_frames; ++t) {
    const double* src = padded.data() + t * hop_length;
    double mean = 0.0;
    for (long j = 0; j < w; ++j) mean += src[j];
    mean /= (double)w;
    double energy_sq = 0.0;
    for (long j = 0; j < w; ++j) {
      frame[(size_t)j] = src[j] - mean;
      energy_sq += frame[(size_t)j] * frame[(size_t)j];
    }
    const double energy = std::sqrt(energy_sq / (double)w);

    // d(tau) = e_head(tau) + e_tail(tau) - 2*acf(tau); e_head over
    // x[0:w-tau], e_tail over x[tau:w] (same decomposition as f0.py).
    // Running the head/tail energies incrementally keeps this O(W) per
    // tau for the energies + O(W) for the correlation.
    double e_head = energy_sq;  // tau = 0: full window
    double e_tail = energy_sq;
    d[0] = 0.0;
    for (long tau = 1; tau < max_lag; ++tau) {
      e_head -= frame[(size_t)(w - tau)] * frame[(size_t)(w - tau)];
      e_tail -= frame[(size_t)(tau - 1)] * frame[(size_t)(tau - 1)];
      double acf = 0.0;
      const double* a = frame.data();
      const double* b = frame.data() + tau;
      const long m = w - tau;
      for (long j = 0; j < m; ++j) acf += a[j] * b[j];
      d[(size_t)tau] = e_head + e_tail - 2.0 * acf;
    }

    // cumulative mean normalized difference
    cmnd[0] = 1.0;
    double dsum = 0.0;
    for (long tau = 1; tau < max_lag; ++tau) {
      dsum += d[(size_t)tau];
      const double denom = dsum > 1e-12 ? dsum : 1e-12;
      cmnd[(size_t)tau] = d[(size_t)tau] * (double)tau / denom;
    }

    // first below-threshold dip: argmin over its contiguous run
    const long rlen = max_lag - min_lag;
    long first = -1;
    for (long i = 0; i < rlen; ++i) {
      if (cmnd[(size_t)(min_lag + i)] < threshold) { first = i; break; }
    }
    long best_i;
    if (first >= 0) {
      long end = first;
      while (end < rlen && cmnd[(size_t)(min_lag + end)] < threshold) ++end;
      best_i = first;
      for (long i = first; i < end; ++i)
        if (cmnd[(size_t)(min_lag + i)] < cmnd[(size_t)(min_lag + best_i)])
          best_i = i;
    } else {
      best_i = 0;
      for (long i = 1; i < rlen; ++i)
        if (cmnd[(size_t)(min_lag + i)] < cmnd[(size_t)(min_lag + best_i)])
          best_i = i;
    }
    long best = best_i + min_lag;

    // parabolic interpolation around the chosen lag
    long b_ = best;
    if (b_ < 1) b_ = 1;
    if (b_ > max_lag - 2) b_ = max_lag - 2;
    const double y0 = cmnd[(size_t)(b_ - 1)];
    const double y1 = cmnd[(size_t)b_];
    const double y2 = cmnd[(size_t)(b_ + 1)];
    const double denom2 = y0 - 2.0 * y1 + y2;
    double offset = 0.0;
    if (std::fabs(denom2) > 1e-12) {
      offset = (y0 - y2) / (2.0 * denom2);
      if (offset < -1.0) offset = -1.0;
      if (offset > 1.0) offset = 1.0;
    }
    const double lag = (double)b_ + offset;
    const double f0 = sampling_rate / (lag > 1e-6 ? lag : 1e-6);
    const double dip_depth = y1;

    const bool voiced = dip_depth < 2.0 * threshold && energy > 1e-4 &&
                        f0 >= f0_floor && f0 <= f0_ceil;
    out[t] = voiced ? f0 : 0.0;
  }
  return n_frames;
}

}  // extern "C"
