"""Synthesis utilities: vocoder loading, sample rendering, mel plots.

Reference: utils/model.py:62-115 (get_vocoder / vocoder_infer) and
utils/tools.py:128-282 (synth_one_sample / synth_samples / plot_mel).
Outputs are dict-keyed (this framework's model returns a dict, not a
12-tuple) but the rendered artifacts — wav files scaled by max_wav_value,
mel plots with pitch/energy overlays in de-normalized units — match the
reference's.
"""

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from speakingstyle_tpu.audio.tools import griffin_lim
from speakingstyle_tpu.configs.config import Config
from speakingstyle_tpu.models.hifigan import (
    Generator,
    generator_from_config,
    vocoder_infer,
)

# The pretrained LJSpeech/universal generators' hyperparameters
# (reference: hifigan/config.json — 22050 Hz, hop 256, 80 mels).
DEFAULT_HIFIGAN_CONFIG = {
    "resblock": "1",
    "upsample_rates": [8, 8, 2, 2],
    "upsample_kernel_sizes": [16, 16, 4, 4],
    "upsample_initial_channel": 512,
    "resblock_kernel_sizes": [3, 7, 11],
    "resblock_dilation_sizes": [[1, 3, 5], [1, 3, 5], [1, 3, 5]],
}


def get_vocoder(
    cfg: Config,
    ckpt_path: Optional[str] = None,
    config_path: Optional[str] = None,
    rng=None,
) -> Tuple[Generator, Dict]:
    """Build the HiFi-GAN generator and load weights.

    ``ckpt_path`` may be a PyTorch ``generator_*.pth.tar`` (converted via
    compat/torch_convert, weight norm folded) or an Orbax/msgpack params
    file from this framework's vocoder trainer. Without a checkpoint the
    generator is randomly initialized (tests / Griffin-Lim comparison).
    Reference: utils/model.py:62-94.
    """
    name = cfg.model.vocoder.model
    if name in ("MelGAN", "melgan"):
        return _get_melgan(cfg, ckpt_path, rng)
    if name not in ("HiFi-GAN", "hifigan"):
        raise NotImplementedError(
            f"vocoder {name!r}: HiFi-GAN and MelGAN are supported; "
            "use synthesize --griffin_lim for a vocoder-free fallback"
        )
    hcfg = dict(DEFAULT_HIFIGAN_CONFIG)
    if config_path:
        with open(config_path) as f:
            hcfg.update(json.load(f))
    gen = generator_from_config(hcfg)

    if ckpt_path and ckpt_path.endswith(".msgpack"):
        from flax import serialization

        import jax

        n_mels = cfg.preprocess.preprocessing.mel.n_mel_channels
        init = gen.init(
            jax.random.PRNGKey(0), np.zeros((1, 16, n_mels), np.float32)
        )["params"]
        with open(ckpt_path, "rb") as f:
            raw = f.read()
        # The vocoder trainer saves TWO artifacts: the full VocoderState
        # (gen+disc params and optimizer moments) as vocoder_*.msgpack and a
        # generator-only sidecar *.generator.msgpack. Only the latter matches
        # the generator template — detect the full-state file and say so
        # instead of failing deep inside from_bytes.
        try:
            state_dict = serialization.msgpack_restore(raw)
        except Exception:
            state_dict = None
        if isinstance(state_dict, dict) and "gen_params" in state_dict:
            raise ValueError(
                f"{ckpt_path!r} is a full VocoderState checkpoint (generator "
                "+ discriminators + optimizer state). Pass the generator-only "
                "sidecar saved next to it (*.generator.msgpack), or extract "
                "state['gen_params'] yourself."
            )
        params = serialization.from_bytes(init, raw)
    elif ckpt_path:
        from speakingstyle_tpu.compat.torch_convert import (
            convert_hifigan,
            fold_weight_norm,
            load_torch_state_dict,
        )

        sd = load_torch_state_dict(ckpt_path, key="generator")
        params = convert_hifigan(fold_weight_norm(sd))
    else:
        import jax

        rng = rng if rng is not None else jax.random.PRNGKey(0)
        n_mels = cfg.preprocess.preprocessing.mel.n_mel_channels
        params = gen.init(rng, np.zeros((1, 16, n_mels), np.float32))["params"]
    return gen, params


def _get_melgan(cfg: Config, ckpt_path: Optional[str], rng=None):
    """MelGAN generator + params (reference: utils/model.py:64-74, which
    pulls descriptinc/melgan-neurips from torch.hub at runtime).

    ``ckpt_path`` is a locally saved hub state-dict file (this framework
    never fetches the network at runtime) or a *.msgpack params file;
    without one, the generator is randomly initialized (tests /
    architecture checks).
    """
    import jax

    from speakingstyle_tpu.models.melgan import MelGANGenerator

    n_mels = cfg.preprocess.preprocessing.mel.n_mel_channels
    gen = MelGANGenerator(n_mels=n_mels)
    if ckpt_path and ckpt_path.endswith(".msgpack"):
        from flax import serialization

        init = gen.init(
            jax.random.PRNGKey(0), np.zeros((1, 16, n_mels), np.float32)
        )["params"]
        with open(ckpt_path, "rb") as f:
            params = serialization.from_bytes(init, f.read())
    elif ckpt_path:
        import torch

        from speakingstyle_tpu.compat.torch_convert import convert_melgan

        obj = torch.load(ckpt_path, map_location="cpu", weights_only=True)
        # hub checkpoints are either the raw generator state_dict or a
        # wrapper with it under a conventional key
        for key in ("model_g", "generator", "netG", "state_dict"):
            if isinstance(obj, dict) and key in obj:
                obj = obj[key]
        sd = {k: v.detach().cpu().numpy() for k, v in obj.items()}
        params = convert_melgan(sd)
    else:
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        params = gen.init(rng, np.zeros((1, 16, n_mels), np.float32))["params"]
    return gen, params


def expand(values: np.ndarray, durations: np.ndarray) -> np.ndarray:
    """Phoneme-level series -> frame-level by repeating each value
    duration[i] times (reference: utils/tools.py:118-125)."""
    return np.repeat(
        np.asarray(values), np.asarray(durations, np.int64)
    )


def _frame_level_overlay(batch_arr, lens, durations, level: str):
    """Pick the [: len] slice and expand phoneme-level series to frames."""
    if level == "phoneme_level":
        return expand(batch_arr, durations)
    return np.asarray(batch_arr)[: int(lens)]


def load_denorm_stats(cfg: Config) -> List[float]:
    """stats.json -> [p_min, p_max, p_mean, p_std, e_min, e_max]
    (reference: utils/tools.py:147-151)."""
    path = os.path.join(cfg.preprocess.path.preprocessed_path, "stats.json")
    if os.path.exists(path):
        with open(path) as f:
            stats = json.load(f)
        return list(stats["pitch"]) + list(stats["energy"][:2])
    return [-3.0, 12.0, 0.0, 1.0, -2.0, 10.0]


def plot_mel(data, stats, titles=None):
    """Stacked mel panels with F0 (left axis) and energy (right axis)
    overlays in de-normalized units (reference: utils/tools.py:233-282)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(len(data), 1, squeeze=False)
    titles = titles or [None] * len(data)
    p_min, p_max, p_mean, p_std, e_min, e_max = stats
    p_min, p_max = p_min * p_std + p_mean, p_max * p_std + p_mean

    for i, (mel, pitch, energy) in enumerate(data):
        ax = axes[i][0]
        pitch = np.asarray(pitch) * p_std + p_mean
        ax.imshow(mel, origin="lower")
        ax.set_aspect(2.5, adjustable="box")
        ax.set_ylim(0, mel.shape[0])
        ax.set_title(titles[i], fontsize="medium")
        ax.tick_params(labelsize="x-small", left=False, labelleft=False)
        ax.set_anchor("W")

        ax1 = fig.add_axes(ax.get_position(), anchor="W")
        ax1.set_facecolor("None")
        ax1.plot(pitch, color="tomato")
        ax1.set_xlim(0, mel.shape[1])
        ax1.set_ylim(0, p_max)
        ax1.set_ylabel("F0", color="tomato")
        ax1.tick_params(labelsize="x-small", colors="tomato",
                        bottom=False, labelbottom=False)

        ax2 = fig.add_axes(ax.get_position(), anchor="W")
        ax2.set_facecolor("None")
        ax2.plot(np.asarray(energy), color="darkviolet")
        ax2.set_xlim(0, mel.shape[1])
        ax2.set_ylim(e_min, e_max)
        ax2.set_ylabel("Energy", color="darkviolet")
        ax2.yaxis.set_label_position("right")
        ax2.tick_params(labelsize="x-small", colors="darkviolet",
                        bottom=False, labelbottom=False, left=False,
                        labelleft=False, right=True, labelright=True)
    return fig


def _vocode(cfg: Config, vocoder, mels, lengths=None):
    """mels [B, T, n_mels] (normalized log-mel) -> list of int16 wavs."""
    max_wav = cfg.preprocess.preprocessing.audio.max_wav_value
    if vocoder is not None:
        gen, params = vocoder
        return vocoder_infer(gen, params, mels, lengths=lengths, max_wav_value=max_wav)
    # Griffin-Lim fallback: invert log-mel via filterbank pseudo-inverse
    from speakingstyle_tpu.audio.mel import mel_filterbank

    pp = cfg.preprocess.preprocessing
    fb = mel_filterbank(pp.audio.sampling_rate, pp.stft.filter_length,
                        pp.mel.n_mel_channels, pp.mel.mel_fmin, pp.mel.mel_fmax)
    inv = np.linalg.pinv(fb)
    out = []
    for i in range(mels.shape[0]):
        T = int(lengths[i]) if lengths is not None else mels.shape[1]
        mag = np.maximum(inv @ np.exp(np.asarray(mels[i, :T])).T, 1e-8)
        wav = np.asarray(
            griffin_lim(mag[None], pp.stft.filter_length, pp.stft.hop_length,
                        pp.stft.win_length)
        )[0]
        out.append((np.clip(wav, -1, 1) * (max_wav - 1)).astype(np.int16))
    return out


def render_result(result, cfg: Config, path: str, plot: bool = False,
                  vocoder=None) -> str:
    """Write one serving ``SynthesisResult`` (serving/engine.py) to disk:
    ``<path>/<id>.wav`` (+ ``<id>.png`` with ``plot``). Returns the wav
    path.

    The engine's neural-vocoder path arrives with ``result.wav`` already
    rendered (int16, trimmed); a vocoder-less engine (``--griffin_lim``)
    arrives with ``wav=None`` and is inverted host-side here. This is the
    rendering half of the old ``synth_samples`` body, decoupled from the
    padded Batch so the CLI and the server share the engine's
    per-request results.
    """
    os.makedirs(path, exist_ok=True)
    pp = cfg.preprocess.preprocessing
    wav = result.wav
    if wav is None:
        # an untrained/degenerate prediction can be 0-1 frames long —
        # below the istft minimum (griffin_lim reflect-pads one hop);
        # write an empty (but valid) wav rather than crash the whole batch
        wav = (np.zeros(0, np.int16) if result.mel_len < 2 else
               _vocode(cfg, vocoder, result.mel[None], [result.mel_len])[0])

    if plot and result.mel_len > 0:
        pitch = _frame_level_overlay(
            result.pitch_prediction, result.mel_len, result.durations,
            pp.pitch.feature)
        energy = _frame_level_overlay(
            result.energy_prediction, result.mel_len, result.durations,
            pp.energy.feature)
        fig = plot_mel(
            [(result.mel.T, pitch, energy)], load_denorm_stats(cfg),
            ["Synthetized Spectrogram"],
        )
        fig.savefig(os.path.join(path, f"{result.id}.png"))
        import matplotlib.pyplot as plt

        plt.close(fig)

    import scipy.io.wavfile

    out = os.path.join(path, f"{result.id}.wav")
    scipy.io.wavfile.write(out, pp.audio.sampling_rate, wav)
    return out


def synth_one_sample(batch, output, vocoder, cfg: Config):
    """First batch item: (fig, wav_reconstruction, wav_prediction, basename)
    for validation logging (reference: utils/tools.py:128-180)."""
    pp = cfg.preprocess.preprocessing
    mel_len = int(np.asarray(output["mel_lens"])[0])
    src_len = int(np.asarray(batch.src_lens)[0])
    durations = np.asarray(batch.durations)[0, :src_len]
    mel_target = np.asarray(batch.mels)[0, :mel_len]
    mel_pred = np.asarray(output["mel_postnet"])[0, :mel_len]

    pitch = _frame_level_overlay(
        np.asarray(batch.pitches)[0, :src_len] if pp.pitch.feature == "phoneme_level"
        else np.asarray(batch.pitches)[0], mel_len, durations, pp.pitch.feature)
    energy = _frame_level_overlay(
        np.asarray(batch.energies)[0, :src_len] if pp.energy.feature == "phoneme_level"
        else np.asarray(batch.energies)[0], mel_len, durations, pp.energy.feature)

    fig = plot_mel(
        [(mel_pred.T, pitch, energy), (mel_target.T, pitch, energy)],
        load_denorm_stats(cfg),
        ["Synthetized Spectrogram", "Ground-Truth Spectrogram"],
    )
    wav_recon = _vocode(cfg, vocoder, mel_target[None], [mel_len])[0]
    wav_pred = _vocode(cfg, vocoder, mel_pred[None], [mel_len])[0]
    return fig, wav_recon, wav_pred, batch.ids[0]


def synth_samples(batch, output, vocoder, cfg: Config, path: str, plot: bool = False):
    """Write one wav (and optionally one plot) per batch item
    (reference: utils/tools.py:183-230). Only ``batch.n_real`` items are
    rendered — padded dummy rows are skipped."""
    os.makedirs(path, exist_ok=True)
    pp = cfg.preprocess.preprocessing
    mel_lens = np.asarray(output["mel_lens"])
    stats = load_denorm_stats(cfg)

    n = getattr(batch, "n_real", len(batch.ids))
    if plot:
        src_lens = np.asarray(batch.src_lens)
        durations = np.asarray(output["durations"])
        for i in range(n):
            mel_len, src_len = int(mel_lens[i]), int(src_lens[i])
            dur = durations[i, :src_len]
            mel_pred = np.asarray(output["mel_postnet"])[i, :mel_len]
            pitch = _frame_level_overlay(
                np.asarray(output["pitch_prediction"])[i, :src_len]
                if pp.pitch.feature == "phoneme_level"
                else np.asarray(output["pitch_prediction"])[i],
                mel_len, dur, pp.pitch.feature)
            energy = _frame_level_overlay(
                np.asarray(output["energy_prediction"])[i, :src_len]
                if pp.energy.feature == "phoneme_level"
                else np.asarray(output["energy_prediction"])[i],
                mel_len, dur, pp.energy.feature)
            fig = plot_mel([(mel_pred.T, pitch, energy)], stats,
                           ["Synthetized Spectrogram"])
            fig.savefig(os.path.join(path, f"{batch.ids[i]}.png"))
            import matplotlib.pyplot as plt

            plt.close(fig)

    wavs = _vocode(cfg, vocoder, np.asarray(output["mel_postnet"])[:n], mel_lens[:n])
    sr = pp.audio.sampling_rate
    for wav, basename in zip(wavs, batch.ids[:n]):
        import scipy.io.wavfile

        scipy.io.wavfile.write(os.path.join(path, f"{basename}.wav"), sr, wav)
    return [os.path.join(path, f"{b}.wav") for b in batch.ids[:n]]
