"""HiFi-GAN discriminators + GAN losses (vocoder training).

The reference vendors only a *partial* discriminator set — its
``MultiPeriodDiscriminator`` is imported by hifigan/train.py:17 but never
defined in hifigan/models.py, so the vendored GAN training is broken as
committed (SURVEY.md §2.3). This module implements the full HiFi-GAN V1
discriminator suite natively in Flax:

  * MultiPeriodDiscriminator — one 2-D conv stack per period (2,3,5,7,11),
    the waveform folded to [T/p, p] (reference: hifigan/train.py usage;
    architecture per the HiFi-GAN paper / reference's MSD conv pattern,
    hifigan/models.py:176-263).
  * MultiScaleDiscriminator — 3 scales of grouped 1-D convs over raw,
    ×2- and ×4-average-pooled audio (reference: hifigan/models.py:176-263).

Losses are least-squares GAN + feature matching + mel-spectrogram L1
(weights 1 / 2 / 45, reference: hifigan/train.py:122-156).

Spectral norm: torch applies spectral_norm to the first MSD scale
(weight_norm to the rest). The first scale here uses ``nn.SpectralNorm``
— power-iteration state (u, sigma) lives in the ``batch_stats``
collection, updated when the caller passes ``update_stats=True`` (the
vocoder train step does so on the discriminator pass, mirroring torch's
per-forward update). The matricization differs from torch ([k*in, out]
vs [out, in*k]) but is a transpose, so the spectral norm is identical.
The remaining weight_norm sites stay plain convs (weight norm is a
reparametrization folded at conversion; training dynamics deviation
documented in README).
"""

from typing import Dict, List, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from speakingstyle_tpu.models.hifigan import LRELU_SLOPE


class PeriodDiscriminator(nn.Module):
    """Folds wav [B, T] to [B, ceil(T/p), p] and runs strided 2-D convs."""

    period: int
    channels: Sequence[int] = (32, 128, 512, 1024, 1024)
    kernel_size: int = 5
    stride: int = 3
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
        B, T = x.shape
        p = self.period
        pad = (-T) % p
        x = jnp.pad(x, ((0, 0), (0, pad)), mode="reflect")
        x = x.reshape(B, (T + pad) // p, p, 1).astype(self.dtype)

        fmaps = []
        for i, ch in enumerate(self.channels):
            stride = self.stride if i < len(self.channels) - 1 else 1
            x = nn.Conv(
                ch,
                kernel_size=(self.kernel_size, 1),
                strides=(stride, 1),
                padding=((self.kernel_size // 2, self.kernel_size // 2), (0, 0)),
                dtype=self.dtype,
                name=f"convs_{i}",
            )(x)
            x = nn.leaky_relu(x, LRELU_SLOPE)
            fmaps.append(x)
        x = nn.Conv(
            1, kernel_size=(3, 1), padding=((1, 1), (0, 0)), dtype=self.dtype,
            name="conv_post",
        )(x)
        fmaps.append(x)
        return x.reshape(B, -1).astype(jnp.float32), fmaps


class ScaleDiscriminator(nn.Module):
    """Grouped 1-D conv stack over (possibly pooled) raw audio.

    ``use_spectral_norm`` engages nn.SpectralNorm on every conv (torch's
    first MSD scale, reference: hifigan/models.py:185); pass
    ``update_stats=True`` to run a power-iteration step (train mode)."""

    use_spectral_norm: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, update_stats: bool = False) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
        # (features, kernel, stride, groups) per layer — the reference's
        # DiscriminatorS geometry (hifigan/models.py:185-196)
        spec = [
            (128, 15, 1, 1),
            (128, 41, 2, 4),
            (256, 41, 2, 16),
            (512, 41, 4, 16),
            (1024, 41, 4, 16),
            (1024, 41, 1, 16),
            (1024, 5, 1, 1),
        ]
        B = x.shape[0]
        x = x[..., None].astype(self.dtype)

        def conv(layer, y):
            if self.use_spectral_norm:
                return nn.SpectralNorm(layer)(y, update_stats=update_stats)
            return layer(y)

        fmaps = []
        for i, (ch, k, s, g) in enumerate(spec):
            x = conv(nn.Conv(
                ch, kernel_size=(k,), strides=(s,), padding=[(k // 2, k // 2)],
                feature_group_count=g, dtype=self.dtype, name=f"convs_{i}",
            ), x)
            x = nn.leaky_relu(x, LRELU_SLOPE)
            fmaps.append(x)
        x = conv(nn.Conv(1, kernel_size=(3,), padding=[(1, 1)], dtype=self.dtype,
                         name="conv_post"), x)
        fmaps.append(x)
        return x.reshape(B, -1).astype(jnp.float32), fmaps


def _avg_pool1d(x, window: int = 4, stride: int = 2):
    """torch AvgPool1d(4, 2, padding=2) over [B, T]."""
    x = jnp.pad(x, ((0, 0), (2, 2)))
    n = (x.shape[1] - window) // stride + 1
    idx = jnp.arange(n)[:, None] * stride + jnp.arange(window)[None, :]
    return x[:, idx].mean(axis=-1)


class MultiPeriodDiscriminator(nn.Module):
    periods: Sequence[int] = (2, 3, 5, 7, 11)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, y, y_hat):
        outs_r, outs_g, fmaps_r, fmaps_g = [], [], [], []
        for i, p in enumerate(self.periods):
            d = PeriodDiscriminator(p, dtype=self.dtype, name=f"discriminators_{i}")
            o_r, f_r = d(y)
            o_g, f_g = d(y_hat)
            outs_r.append(o_r)
            outs_g.append(o_g)
            fmaps_r.append(f_r)
            fmaps_g.append(f_g)
        return outs_r, outs_g, fmaps_r, fmaps_g


class MultiScaleDiscriminator(nn.Module):
    n_scales: int = 3
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, y, y_hat, update_stats: bool = False):
        outs_r, outs_g, fmaps_r, fmaps_g = [], [], [], []
        for i in range(self.n_scales):
            # torch: spectral_norm on the first (unpooled) scale only
            d = ScaleDiscriminator(
                use_spectral_norm=(i == 0), dtype=self.dtype,
                name=f"discriminators_{i}",
            )
            o_r, f_r = d(y, update_stats=update_stats)
            o_g, f_g = d(y_hat, update_stats=update_stats)
            outs_r.append(o_r)
            outs_g.append(o_g)
            fmaps_r.append(f_r)
            fmaps_g.append(f_g)
            y, y_hat = _avg_pool1d(y), _avg_pool1d(y_hat)
        return outs_r, outs_g, fmaps_r, fmaps_g


# ---------------------------------------------------------------------------
# Losses (reference: hifigan/models.py:231-263, train.py:122-156)
# ---------------------------------------------------------------------------

def discriminator_loss(outs_real, outs_gen) -> jnp.ndarray:
    """LSGAN: mean((1 - D(y))^2) + mean(D(y_hat)^2), summed over heads."""
    loss = 0.0
    for dr, dg in zip(outs_real, outs_gen):
        loss += jnp.mean((1.0 - dr) ** 2) + jnp.mean(dg**2)
    return loss


def generator_adversarial_loss(outs_gen) -> jnp.ndarray:
    """LSGAN generator side: mean((1 - D(y_hat))^2) summed over heads."""
    loss = 0.0
    for dg in outs_gen:
        loss += jnp.mean((1.0 - dg) ** 2)
    return loss


def feature_matching_loss(fmaps_real, fmaps_gen) -> jnp.ndarray:
    """L1 between real/generated feature maps, ×2 (reference weighting)."""
    loss = 0.0
    for fr_list, fg_list in zip(fmaps_real, fmaps_gen):
        for fr, fg in zip(fr_list, fg_list):
            loss += jnp.mean(jnp.abs(fr - fg))
    return 2.0 * loss
