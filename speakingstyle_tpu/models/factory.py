"""Model factory: build FastSpeech2 from config + preprocessed-dataset stats.

Reference: utils/model.py:11-45 (get_model). Pitch/energy bin ranges come
from stats.json and the speaker count from speakers.json, both written by
the preprocessor.
"""

import json
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from speakingstyle_tpu.configs.config import Config
from speakingstyle_tpu.models.fastspeech2 import FastSpeech2


def load_dataset_stats(cfg: Config) -> Tuple[tuple, tuple, int]:
    """(pitch_min_max, energy_min_max, n_speakers) from the preprocessed dir."""
    root = cfg.preprocess.path.preprocessed_path
    pitch_stats, energy_stats, n_speakers = (-3.0, 12.0), (-2.0, 10.0), 1
    stats_path = os.path.join(root, "stats.json") if root else ""
    if stats_path and os.path.exists(stats_path):
        with open(stats_path) as f:
            stats = json.load(f)
        pitch_stats = tuple(stats["pitch"][:2])
        energy_stats = tuple(stats["energy"][:2])
    speakers_path = os.path.join(root, "speakers.json") if root else ""
    if speakers_path and os.path.exists(speakers_path):
        with open(speakers_path) as f:
            n_speakers = max(len(json.load(f)), 1)
    return pitch_stats, energy_stats, n_speakers


def reference_encoder_from_config(
    cfg: Config, n_position: Optional[int] = None, name: Optional[str] = None
):
    """The one place ReferenceEncoder kwargs are derived from config —
    shared by the model (fastspeech2.py), the analyze CLI, and the bench
    breakdown, so a constructor change can't silently diverge between
    them."""
    from speakingstyle_tpu.models.reference_encoder import ReferenceEncoder

    m = cfg.model
    ref = m.reference_encoder
    return ReferenceEncoder(
        n_conv_layers=ref.conv_layer,
        conv_filter_size=ref.conv_filter_size,
        conv_kernel_size=ref.conv_kernel_size,
        n_layers=ref.encoder_layer,
        n_head=ref.encoder_head,
        d_model=ref.encoder_hidden,
        dropout=ref.dropout,
        n_position=n_position or (m.max_seq_len + 1),
        conv_impl=m.conv_impl,
        dtype=jnp.dtype(m.compute_dtype),
        softmax_dtype=jnp.dtype(m.attention_softmax_dtype),
        attention_kernel=m.attention_kernel,
        dropout_impl=m.dropout_impl,
        **({"name": name} if name is not None else {}),
    )


def fft_stack_from_config(
    cfg: Config,
    which: str,  # "encoder" | "decoder"
    n_position: Optional[int] = None,
    seq_mesh=None,
    name: Optional[str] = None,
):
    """Encoder/Decoder construction from config (see
    reference_encoder_from_config for why this lives here)."""
    from speakingstyle_tpu.models.transformer import Decoder, Encoder

    m = cfg.model
    tf = m.transformer
    cls = {"encoder": Encoder, "decoder": Decoder}[which]
    return cls(
        n_layers=getattr(tf, f"{which}_layer"),
        d_model=getattr(tf, f"{which}_hidden"),
        n_head=getattr(tf, f"{which}_head"),
        d_inner=tf.conv_filter_size,
        kernel_sizes=tuple(tf.conv_kernel_size),
        dropout=getattr(tf, f"{which}_dropout"),
        n_position=n_position or (m.max_seq_len + 1),
        remat=cfg.train.sharding.remat,
        conv_impl=m.conv_impl,
        dtype=jnp.dtype(m.compute_dtype),
        softmax_dtype=jnp.dtype(m.attention_softmax_dtype),
        attention_kernel=m.attention_kernel,
        seq_mesh=seq_mesh,
        dropout_impl=m.dropout_impl,
        **({"name": name} if name is not None else {}),
    )


def build_model(
    cfg: Config, n_position: Optional[int] = None, seq_mesh=None
) -> FastSpeech2:
    """``seq_mesh`` (a Mesh with a "seq" axis) is required when
    cfg.model.attention_impl == "ring"; build one with
    parallel.mesh.make_seq_mesh() for long-sequence inference."""
    if cfg.model.attention_impl == "ring" and seq_mesh is None:
        raise ValueError(
            'attention_impl="ring" needs a seq mesh: '
            "build_model(cfg, seq_mesh=make_seq_mesh())"
        )
    if cfg.model.attention_impl != "ring":
        seq_mesh = None
    pitch_stats, energy_stats, n_speakers = load_dataset_stats(cfg)
    return FastSpeech2(
        config=cfg,
        pitch_stats=pitch_stats,
        energy_stats=energy_stats,
        n_speakers=n_speakers,
        n_position=n_position,
        seq_mesh=seq_mesh,
    )


def init_variables(model: FastSpeech2, cfg: Config, rng: jax.Array):
    """Initialize params/batch_stats with a minimal teacher-forced dummy batch."""
    n_mels = cfg.preprocess.preprocessing.mel.n_mel_channels
    B, L, T = 2, 8, 16
    dummy = dict(
        speakers=jnp.zeros((B,), jnp.int32),
        texts=jnp.ones((B, L), jnp.int32),
        src_lens=jnp.full((B,), L, jnp.int32),
        mels=jnp.zeros((B, T, n_mels), jnp.float32),
        mel_lens=jnp.full((B,), T, jnp.int32),
        max_mel_len=T,
        p_targets=jnp.zeros((B, L), jnp.float32),
        e_targets=jnp.zeros((B, L), jnp.float32),
        d_targets=jnp.full((B, L), T // L, jnp.int32),
    )
    rngs = {"params": rng, "dropout": rng}
    return model.init(rngs, deterministic=True, **dummy)


def count_params(params) -> int:
    """Total parameter count (reference: utils/model.py:48-51)."""
    return int(
        sum(np.prod(p.shape) for p in jax.tree_util.tree_leaves(params))
    )
