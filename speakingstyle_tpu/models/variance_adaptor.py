"""Variance adaptor: duration/pitch/energy predictors + length regulation.

Reference: model/modules.py:20-305. As-implemented quirks reproduced on
purpose (checkpoint parity — SURVEY.md §7 hard part 4):
- FiLM conditioning reaches ONLY the duration predictor; the pitch and
  energy predictor calls omit gamma/beta (reference: model/modules.py:121-131).
- Bucket boundaries are n_bins-1 values, torch.bucketize 'left' semantics.

TPU-first change: the length regulator is the padded-gather op in
``ops/length_regulator.py`` rather than a per-token Python loop.
"""

from typing import Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from speakingstyle_tpu.analysis import contracts
from speakingstyle_tpu.models.layers import FiLM, LN_EPS
from speakingstyle_tpu.ops.dropout import Dropout
from speakingstyle_tpu.ops.length_regulator import length_regulate, predicted_durations
from speakingstyle_tpu.ops.quantize import bucketize


class VariancePredictor(nn.Module):
    """2x(conv k=3 + ReLU + LN + dropout) -> optional FiLM -> linear -> scalar.

    Reference: model/modules.py:204-259.
    """

    filter_size: int = 256
    kernel_size: int = 3
    dropout: float = 0.5
    conv_impl: str = "xla"
    dtype: jnp.dtype = jnp.float32
    dropout_impl: str = "bernoulli"

    @nn.compact
    def __call__(self, x, pad_mask, gammas=None, betas=None, deterministic=True):
        from speakingstyle_tpu.ops.conv import Conv1d

        for i in (1, 2):
            x = Conv1d(
                self.filter_size,
                kernel_size=self.kernel_size,
                impl=self.conv_impl,
                activation="relu",
                dtype=self.dtype,
                name=f"conv1d_{i}",
            )(x)
            x = nn.LayerNorm(epsilon=LN_EPS, dtype=self.dtype, name=f"layer_norm_{i}")(x)
            x = Dropout(self.dropout, impl=self.dropout_impl)(
                x, deterministic=deterministic
            )
        if gammas is not None and betas is not None:
            x = FiLM(name="film")(x, gammas, betas)
        out = nn.Dense(1, dtype=self.dtype, name="linear_layer")(x)[..., 0]
        return jnp.where(pad_mask, 0.0, out.astype(jnp.float32))


class VarianceAdaptor(nn.Module):
    """Reference: model/modules.py:20-165.

    ``pitch_stats``/``energy_stats`` are (min, max) from stats.json; bins are
    baked in as compile-time constants.
    """

    pitch_stats: Tuple[float, float] = (-2.0, 10.0)
    energy_stats: Tuple[float, float] = (-2.0, 10.0)
    n_bins: int = 256
    pitch_quantization: str = "linear"
    energy_quantization: str = "linear"
    pitch_feature_level: str = "phoneme_level"
    energy_feature_level: str = "phoneme_level"
    d_model: int = 256
    filter_size: int = 256
    kernel_size: int = 3
    dropout: float = 0.5
    conv_impl: str = "xla"
    dtype: jnp.dtype = jnp.float32
    dropout_impl: str = "bernoulli"

    def _bins(self, stats, quantization):
        from speakingstyle_tpu.ops.quantize import make_bins

        return make_bins(stats[0], stats[1], self.n_bins, quantization)

    @nn.compact
    def __call__(
        self,
        x,
        src_pad_mask,
        mel_pad_mask=None,
        max_mel_len: Optional[int] = None,
        pitch_target=None,
        energy_target=None,
        duration_target=None,
        p_control: float = 1.0,
        e_control: float = 1.0,
        d_control: float = 1.0,
        gammas=None,
        betas=None,
        deterministic: bool = True,
    ):
        contracts.assert_rank(x, 3, "VarianceAdaptor.x")
        contracts.assert_shape(
            src_pad_mask, x.shape[:2], "VarianceAdaptor.src_pad_mask"
        )
        contracts.assert_dtype(
            src_pad_mask, "bool", "VarianceAdaptor.src_pad_mask"
        )
        contracts.assert_shape(
            duration_target, x.shape[:2], "VarianceAdaptor.duration_target"
        )
        mk_pred = lambda name: VariancePredictor(
            self.filter_size, self.kernel_size, self.dropout,
            conv_impl=self.conv_impl, dtype=self.dtype,
            dropout_impl=self.dropout_impl, name=name
        )
        embed = lambda name: nn.Embed(self.n_bins, self.d_model, dtype=self.dtype, name=name)

        log_d_pred = mk_pred("duration_predictor")(
            x, src_pad_mask, gammas, betas, deterministic
        )

        pitch_bins = self._bins(self.pitch_stats, self.pitch_quantization)
        energy_bins = self._bins(self.energy_stats, self.energy_quantization)
        pitch_embedding = embed("pitch_embedding")
        energy_embedding = embed("energy_embedding")

        def variance(pred_name, emb, bins, target, mask, control):
            # FiLM deliberately NOT passed (reference: model/modules.py:122-131)
            pred = mk_pred(pred_name)(x, mask, None, None, deterministic)
            if target is not None:
                e = emb(bucketize(target, bins))
            else:
                pred = pred * control
                e = emb(bucketize(pred, bins))
            return pred, e

        p_pred = e_pred = None
        if self.pitch_feature_level == "phoneme_level":
            p_pred, p_emb = variance(
                "pitch_predictor", pitch_embedding, pitch_bins,
                pitch_target, src_pad_mask, p_control,
            )
            x = x + p_emb
        if self.energy_feature_level == "phoneme_level":
            e_pred, e_emb = variance(
                "energy_predictor", energy_embedding, energy_bins,
                energy_target, src_pad_mask, e_control,
            )
            x = x + e_emb

        if duration_target is not None:
            durations = duration_target
        else:
            durations = predicted_durations(log_d_pred, src_pad_mask, d_control)
        x, mel_lens, mel_pad_mask = length_regulate(x, durations, max_mel_len)

        if self.pitch_feature_level == "frame_level":
            p_pred, p_emb = variance(
                "pitch_predictor", pitch_embedding, pitch_bins,
                pitch_target, mel_pad_mask, p_control,
            )
            x = x + p_emb
        if self.energy_feature_level == "frame_level":
            e_pred, e_emb = variance(
                "energy_predictor", energy_embedding, energy_bins,
                energy_target, mel_pad_mask, e_control,
            )
            x = x + e_emb

        return {
            "features": x,
            "pitch_prediction": p_pred,
            "energy_prediction": e_pred,
            "log_duration_prediction": log_d_pred,
            "durations": durations,
            "mel_lens": mel_lens,
            "mel_pad_mask": mel_pad_mask,
        }
