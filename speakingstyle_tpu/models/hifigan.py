"""HiFi-GAN generator in Flax (mel -> waveform vocoder).

Architecture parity with the vendored generator (reference:
hifigan/models.py:112-174, hifigan/config.json): conv_pre(512, k7) -> 4×
[transposed-conv upsample (rates 8,8,2,2 / kernels 16,16,4,4) + multi-
receptive-field fusion of 3 ResBlocks (k=3,7,11; dilations 1,3,5)] ->
conv_post -> tanh. LeakyReLU slope 0.1.

Conv semantics deliberately mirror torch's (symmetric integer padding;
transposed conv expressed as an lhs-dilated conv with a flipped kernel) so
the PyTorch->Flax weight converter (compat/) is a pure layout transpose +
weight-norm fold with bit-level parity, testable against torch on CPU.
Channels-last layout throughout so XLA maps the convs onto the MXU.
"""

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

LRELU_SLOPE = 0.1


class TorchConv1d(nn.Module):
    """Conv1d with torch padding semantics: pad = (k*d - d) // 2 per side.

    Kept separate from models/layers.py ConvNorm on purpose: this module's
    contract is bit-parity with the torch vocoder checkpoints (the two only
    diverge for even kernel sizes, but the parity tests pin THIS padding
    arithmetic, and the acoustic-model ConvNorm is free to evolve).
    """

    features: int
    kernel_size: int
    dilation: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        pad = (self.kernel_size * self.dilation - self.dilation) // 2
        return nn.Conv(
            self.features,
            kernel_size=(self.kernel_size,),
            kernel_dilation=(self.dilation,),
            padding=[(pad, pad)],
            dtype=self.dtype,
            name="conv",
        )(x)


class TorchConvTranspose1d(nn.Module):
    """ConvTranspose1d(stride=u, padding=p, output_padding=op) with exact
    torch output length (L-1)*u - 2p + k + op: an lhs-dilated conv with the
    kernel flipped in time and in/out transposed — the standard
    transpose-conv equivalence. ``padding=None`` means torch's
    HiFi-GAN-style (k-u)//2 (output length exactly L*u for even u);
    MelGAN's descript layout passes u//2 + u%2 with output_padding u%2,
    which also lands at L*u for odd upsample ratios."""

    features: int
    kernel_size: int
    stride: int
    padding: Optional[int] = None
    output_padding: int = 0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        k, u = self.kernel_size, self.stride
        p = (k - u) // 2 if self.padding is None else self.padding
        in_ch = x.shape[-1]
        # torch ConvTranspose1d weight layout: [in, out, k]
        kernel = self.param(
            "kernel",
            nn.initializers.normal(0.01),
            (in_ch, self.features, k),
            jnp.float32,
        )
        # flip time axis, reorder to [k, in, out] for lax
        w = jnp.flip(kernel, axis=-1).transpose(2, 0, 1).astype(self.dtype)
        bias = self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
        out = jax.lax.conv_general_dilated(
            x.astype(self.dtype),
            w,
            window_strides=(1,),
            # output_padding extends the high side only (torch semantics)
            padding=[(k - 1 - p, k - 1 - p + self.output_padding)],
            lhs_dilation=(u,),
            dimension_numbers=("NLC", "LIO", "NLC"),
        )
        return out + bias.astype(self.dtype)


class ResBlock(nn.Module):
    """MRF residual block (reference: hifigan/models.py:20-109, resblock '1')."""

    channels: int
    kernel_size: int = 3
    dilations: Tuple[int, ...] = (1, 3, 5)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i, d in enumerate(self.dilations):
            y = nn.leaky_relu(x, LRELU_SLOPE)
            y = TorchConv1d(
                self.channels, self.kernel_size, dilation=d, dtype=self.dtype,
                name=f"convs1_{i}",
            )(y)
            y = nn.leaky_relu(y, LRELU_SLOPE)
            y = TorchConv1d(
                self.channels, self.kernel_size, dilation=1, dtype=self.dtype,
                name=f"convs2_{i}",
            )(y)
            x = x + y
        return x


class ResBlock2(nn.Module):
    """The lighter MRF block of the HiFi-GAN V3 config (public
    hifigan models.py ``ResBlock2``; V1/V2 and every config the reference
    ships use resblock '1' — reference: hifigan/models.py:20-109): one
    conv per dilation with a residual after each, instead of ResBlock1's
    dilated+plain conv pairs."""

    channels: int
    kernel_size: int = 3
    dilations: Tuple[int, ...] = (1, 3)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i, d in enumerate(self.dilations):
            y = nn.leaky_relu(x, LRELU_SLOPE)
            y = TorchConv1d(
                self.channels, self.kernel_size, dilation=d, dtype=self.dtype,
                name=f"convs_{i}",
            )(y)
            x = x + y
        return x


class Generator(nn.Module):
    """mel [B, T, n_mels] -> wav [B, T * prod(upsample_rates)]."""

    upsample_rates: Sequence[int] = (8, 8, 2, 2)
    upsample_kernel_sizes: Sequence[int] = (16, 16, 4, 4)
    upsample_initial_channel: int = 512
    resblock_kernel_sizes: Sequence[int] = (3, 7, 11)
    resblock_dilation_sizes: Sequence[Tuple[int, ...]] = ((1, 3, 5), (1, 3, 5), (1, 3, 5))
    resblock: str = "1"  # "1" (LJSpeech/universal, V1/V2) | "2" (V3)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, mel):
        # explicit check so a typo'd/int resblock raises clearly instead
        # of silently building the wrong topology (the error would
        # otherwise surface only as a param-tree mismatch at restore, or
        # an inscrutable KeyError inside jit tracing)
        if str(self.resblock) not in ("1", "2"):
            raise ValueError(
                f"resblock must be '1' or '2', got {self.resblock!r}"
            )
        block_cls = {"1": ResBlock, "2": ResBlock2}[str(self.resblock)]
        x = TorchConv1d(
            self.upsample_initial_channel, 7, dtype=self.dtype, name="conv_pre"
        )(mel)
        num_kernels = len(self.resblock_kernel_sizes)
        for i, (u, k) in enumerate(zip(self.upsample_rates, self.upsample_kernel_sizes)):
            x = nn.leaky_relu(x, LRELU_SLOPE)
            ch = self.upsample_initial_channel // (2 ** (i + 1))
            x = TorchConvTranspose1d(
                ch, k, u, dtype=self.dtype, name=f"ups_{i}"
            )(x)
            xs = None
            for j, (rk, rd) in enumerate(
                zip(self.resblock_kernel_sizes, self.resblock_dilation_sizes)
            ):
                y = block_cls(
                    ch, rk, tuple(rd), dtype=self.dtype,
                    name=f"resblocks_{i * num_kernels + j}",
                )(x)
                xs = y if xs is None else xs + y
            x = xs / num_kernels
        x = nn.leaky_relu(x, LRELU_SLOPE)
        x = TorchConv1d(1, 7, dtype=self.dtype, name="conv_post")(x)
        return jnp.tanh(x)[..., 0].astype(jnp.float32)

    # -- uniform vocoder interface (vocoder_infer is family-agnostic) --

    @property
    def hop_factor(self) -> int:
        return int(np.prod(self.upsample_rates))

    def vocode(self, params, mels):
        """mels in the acoustic model's natural-log space -> wav."""
        return self.apply({"params": params}, mels)


def generator_from_config(config: dict, dtype=jnp.float32) -> Generator:
    """Build from a hifigan config.json dict (reference: hifigan/config.json).
    ``resblock: "1"`` (the reference's generator_{LJSpeech,universal}) and
    ``"2"`` (the public V3 config) are both supported."""
    resblock = str(config.get("resblock", "1"))
    if resblock not in ("1", "2"):
        raise ValueError(f"resblock must be '1' or '2', got {resblock!r}")
    return Generator(
        upsample_rates=tuple(config["upsample_rates"]),
        upsample_kernel_sizes=tuple(config["upsample_kernel_sizes"]),
        upsample_initial_channel=config["upsample_initial_channel"],
        resblock_kernel_sizes=tuple(config["resblock_kernel_sizes"]),
        resblock_dilation_sizes=tuple(
            tuple(d) for d in config["resblock_dilation_sizes"]
        ),
        resblock=resblock,
        dtype=dtype,
    )


def vocoder_infer(generator, params, mels, lengths=None, max_wav_value=32768.0):
    """Batch mel [B, T, n_mels] -> list of int16 wavs trimmed to true
    lengths (reference: utils/model.py:97-115, which scales by
    max_wav_value and casts to int16). Family-agnostic: every vocoder
    generator exposes ``vocode(params, mels)`` (which owns any input
    convention, e.g. MelGAN's log10 scaling) and ``hop_factor``."""
    wavs = generator.vocode(params, mels)
    hop_factor = generator.hop_factor
    wavs = np.clip(
        np.asarray(wavs) * max_wav_value, -max_wav_value, max_wav_value - 1
    ).astype(np.int16)
    out = []
    for i in range(wavs.shape[0]):
        n = wavs.shape[1] if lengths is None else int(lengths[i]) * hop_factor
        out.append(wavs[i, :n])
    return out
