"""FastSpeech2 + style reference encoder (the flagship acoustic model).

Wiring matches reference: model/fastspeech2.py:13-120 — reference-encoder
FiLM vectors condition the encoder, decoder, and duration predictor;
speaker embedding (if multi-speaker) is added to the encoder output;
variance adaptor expands phonemes to frames; decoder + mel linear + postnet
residual produce the mel pair.

All shapes are static: callers pass bucketed [B, L_src] tokens and a fixed
``max_mel_len``; teacher-forced vs free-running are two traces
distinguished by whether targets are None.
"""

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from speakingstyle_tpu.analysis import contracts
from speakingstyle_tpu.configs.config import Config
from speakingstyle_tpu.models.postnet import PostNet
from speakingstyle_tpu.models.variance_adaptor import VarianceAdaptor
from speakingstyle_tpu.ops.masking import length_to_mask


class FastSpeech2(nn.Module):
    config: Config
    pitch_stats: tuple = (-3.0, 12.0)  # (min, max) from stats.json
    energy_stats: tuple = (-2.0, 10.0)
    n_speakers: int = 1
    n_position: Optional[int] = None  # override for long-sequence inference
    # jax.sharding.Mesh with a "seq" axis: engages ring attention in the
    # encoder/decoder stacks (config.model.attention_impl == "ring")
    seq_mesh: Optional[object] = None

    @nn.compact
    def __call__(
        self,
        speakers,          # [B] int
        texts,             # [B, L_src] int
        src_lens,          # [B] int
        mels=None,         # [B, T_mel, n_mels] reference/target mel
        mel_lens=None,     # [B] int
        max_mel_len: Optional[int] = None,
        p_targets=None,
        e_targets=None,
        d_targets=None,
        p_control: float = 1.0,
        e_control: float = 1.0,
        d_control: float = 1.0,
        gammas=None,       # [B, 1, d] precomputed FiLM scale (serve path)
        betas=None,        # [B, 1, d] precomputed FiLM shift
        deterministic: bool = True,
    ):
        cfg = self.config.model
        tf = cfg.transformer
        dtype = jnp.dtype(cfg.compute_dtype)
        conv_impl = cfg.conv_impl
        n_position = self.n_position or (cfg.max_seq_len + 1)

        B, L_src = texts.shape
        contracts.assert_rank(texts, 2, "FastSpeech2.texts")
        contracts.assert_dtype(texts, "integer", "FastSpeech2.texts")
        contracts.assert_shape(speakers, (B,), "FastSpeech2.speakers")
        contracts.assert_shape(src_lens, (B,), "FastSpeech2.src_lens")
        contracts.assert_dtype(src_lens, "integer", "FastSpeech2.src_lens")
        contracts.assert_shape(
            mels,
            (B, None, self.config.preprocess.preprocessing.mel.n_mel_channels),
            "FastSpeech2.mels",
        )
        contracts.assert_shape(mel_lens, (B,), "FastSpeech2.mel_lens")
        src_pad_mask = length_to_mask(src_lens, L_src)
        mel_pad_mask = (
            length_to_mask(mel_lens, mels.shape[1])
            if mel_lens is not None and mels is not None else None
        )

        from speakingstyle_tpu.models.factory import (
            fft_stack_from_config,
            reference_encoder_from_config,
        )

        # Two ways into FiLM conditioning: the fused path runs the
        # reference encoder over a reference mel (training, and any
        # caller that still ships ``mels``); the split serve path passes
        # precomputed (gamma, beta) — the StyleService (serving/style.py)
        # ran the encoder AOT, possibly long ago, possibly cached — and
        # the synthesis program then contains no encoder at all.
        if cfg.use_reference_encoder and gammas is None:
            if mels is None:
                raise ValueError(
                    "use_reference_encoder needs a reference: pass `mels` "
                    "(fused path) or precomputed `gammas`/`betas` (style "
                    "service path)"
                )
            gammas, betas = reference_encoder_from_config(
                self.config, n_position=n_position, name="reference_encoder"
            )(mels, mel_pad_mask, deterministic=deterministic)
        elif not cfg.use_reference_encoder:
            gammas = betas = None

        x = fft_stack_from_config(
            self.config,
            "encoder",
            n_position=n_position,
            seq_mesh=self.seq_mesh,
            name="encoder",
        )(texts, src_pad_mask, gammas, betas, deterministic=deterministic)

        if cfg.multi_speaker:
            spk = nn.Embed(
                self.n_speakers, tf.encoder_hidden, dtype=dtype, name="speaker_emb"
            )(speakers)
            x = x + spk[:, None, :]

        va = VarianceAdaptor(
            pitch_stats=tuple(self.pitch_stats),
            energy_stats=tuple(self.energy_stats),
            n_bins=cfg.variance_embedding.n_bins,
            pitch_quantization=cfg.variance_embedding.pitch_quantization,
            energy_quantization=cfg.variance_embedding.energy_quantization,
            pitch_feature_level=self.config.preprocess.preprocessing.pitch.feature,
            energy_feature_level=self.config.preprocess.preprocessing.energy.feature,
            d_model=tf.encoder_hidden,
            filter_size=cfg.variance_predictor.filter_size,
            kernel_size=cfg.variance_predictor.kernel_size,
            dropout=cfg.variance_predictor.dropout,
            conv_impl=conv_impl,
            dtype=dtype,
            dropout_impl=cfg.dropout_impl,
            name="variance_adaptor",
        )(
            x,
            src_pad_mask,
            mel_pad_mask,
            max_mel_len,
            p_targets,
            e_targets,
            d_targets,
            p_control,
            e_control,
            d_control,
            gammas,
            betas,
            deterministic=deterministic,
        )

        dec = fft_stack_from_config(
            self.config,
            "decoder",
            n_position=n_position,
            seq_mesh=self.seq_mesh,
            name="decoder",
        )(va["features"], va["mel_pad_mask"], gammas, betas, deterministic=deterministic)

        mel_out = nn.Dense(
            self.config.preprocess.preprocessing.mel.n_mel_channels,
            dtype=dtype,
            name="mel_linear",
        )(dec)
        postnet_in = mel_out
        postnet_keep = None
        if d_targets is None:
            # Free-running: the reference's postnet buffer ends hard at the
            # batch-max predicted length, so every conv layer zero-pads
            # there (dynamic shape). Our static buffer extends further —
            # zero the input past that boundary AND re-zero each layer
            # (PostNet keep_mask) or bias/BatchNorm junk beyond it leaks
            # back through the 5-layer receptive field
            # (reference: model/fastspeech2.py:109, modules.py:137-144).
            global_max = jnp.max(va["mel_lens"])
            postnet_keep = jnp.arange(mel_out.shape[1]) < global_max
            postnet_in = jnp.where(postnet_keep[None, :, None], mel_out, 0.0)
        postnet_residual = PostNet(
            n_mel_channels=self.config.preprocess.preprocessing.mel.n_mel_channels,
            embedding_dim=cfg.postnet_embedding_dim,
            kernel_size=cfg.postnet_kernel_size,
            n_convolutions=cfg.postnet_layers,
            conv_impl=conv_impl,
            dtype=dtype,
            dropout_impl=cfg.dropout_impl,
            name="postnet",
        )(postnet_in, deterministic=deterministic, keep_mask=postnet_keep)
        mel_postnet = mel_out + postnet_residual

        return {
            "mel": mel_out.astype(jnp.float32),
            "mel_postnet": mel_postnet.astype(jnp.float32),
            "pitch_prediction": va["pitch_prediction"],
            "energy_prediction": va["energy_prediction"],
            "log_duration_prediction": va["log_duration_prediction"],
            "durations": va["durations"],
            "src_pad_mask": src_pad_mask,
            "mel_pad_mask": va["mel_pad_mask"],
            "src_lens": src_lens,
            "mel_lens": va["mel_lens"],
        }
