"""Core Flax layers: post-LN transformer FFT block and conv primitives.

Behavioral spec comes from the reference transformer stack
(reference: transformer/SubLayers.py:8-93, transformer/Layers.py:11-37):
post-LN residual order, scaled dot-product attention with sqrt(d_k)
temperature, conv1d position-wise FFN with kernels (9, 1), masked fills
after attention and after the FFN. TPU-first choices: batched [B, H, L, D]
einsum attention (no (n_head*B) reshape games), f32 softmax under a
bfloat16 compute dtype, additive finite mask bias instead of -inf fills.

LayerNorm epsilon is 1e-5 everywhere (torch default) for checkpoint parity.
"""

from typing import Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from speakingstyle_tpu.ops.dropout import Dropout
from speakingstyle_tpu.ops.masking import attention_bias, mask_fill

LN_EPS = 1e-5


class FiLM(nn.Module):
    """Feature-wise linear modulation with learned scalar gates.

    ``y = (s_gamma * gamma + 1) * x + s_beta * beta`` where s_gamma/s_beta are
    per-site scalars initialized to 1 and L2-regularized by the loss
    (reference: model/blocks.py:43-62, model/loss.py:84-89). Parameter names
    ``s_gamma``/``s_beta`` are load-bearing: the loss collects them by name.
    """

    @nn.compact
    def __call__(self, x, gammas, betas):
        s_gamma = self.param("s_gamma", nn.initializers.ones, (1,))
        s_beta = self.param("s_beta", nn.initializers.ones, (1,))
        g = (s_gamma * gammas).astype(x.dtype)
        b = (s_beta * betas).astype(x.dtype)
        return (g + 1.0) * x + b


class MultiHeadSelfAttention(nn.Module):
    """Post-LN multi-head self-attention (reference: transformer/SubLayers.py:8-57).

    ``seq_mesh`` switches the score computation to sequence-parallel ring
    attention (parallel/ring_attention.py) — exact, never materializing
    [L, L] per device — for inference beyond max_seq_len. L must divide
    by the mesh's ``seq`` axis.
    """

    n_head: int
    d_model: int
    dropout: float
    dtype: jnp.dtype = jnp.float32
    softmax_dtype: jnp.dtype = jnp.float32
    attention_kernel: str = "einsum"  # "einsum" | "fused" (pallas)
    seq_mesh: Optional[object] = None  # jax.sharding.Mesh with a "seq" axis
    dropout_impl: str = "bernoulli"

    @nn.compact
    def __call__(self, x, pad_mask, deterministic: bool):
        d_head = self.d_model // self.n_head
        residual = x
        dense = lambda name: nn.Dense(self.d_model, dtype=self.dtype, name=name)
        B, L, _ = x.shape
        q = dense("w_qs")(x).reshape(B, L, self.n_head, d_head)
        k = dense("w_ks")(x).reshape(B, L, self.n_head, d_head)
        v = dense("w_vs")(x).reshape(B, L, self.n_head, d_head)

        if self.seq_mesh is not None:
            from speakingstyle_tpu.parallel.ring_attention import (
                ring_self_attention,
            )

            # f32 end-to-end inside the ring (matches the dense path's f32
            # softmax); [B, L, H, D] -> [B, H, L, D]
            out = ring_self_attention(
                q.transpose(0, 2, 1, 3).astype(jnp.float32),
                k.transpose(0, 2, 1, 3).astype(jnp.float32),
                v.transpose(0, 2, 1, 3).astype(jnp.float32),
                attention_bias(pad_mask, jnp.float32),
                mesh=self.seq_mesh,
            )
            out = (
                out.transpose(0, 2, 1, 3)
                .reshape(B, L, self.d_model)
                .astype(self.dtype)
            )
        elif self.attention_kernel == "fused":
            from speakingstyle_tpu.ops.pallas_attention import fused_mha

            # softmax dtype in-kernel follows attention_softmax_dtype (bf16
            # saves ~24% of the kernel's VPU time); falls back to the
            # einsum path off-TPU or for unsupported shapes
            out = fused_mha(
                q, k, v, pad_mask,
                softmax_dtype=jnp.dtype(self.softmax_dtype),
            ).reshape(B, L, self.d_model)
        else:
            sm_dtype = jnp.dtype(self.softmax_dtype)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
                jnp.asarray(d_head, jnp.float32)
            ).astype(self.dtype)
            logits = logits.astype(sm_dtype) + attention_bias(
                pad_mask, sm_dtype
            )
            attn = nn.softmax(logits, axis=-1).astype(self.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", attn, v).reshape(
                B, L, self.d_model
            )
        out = nn.Dense(self.d_model, dtype=self.dtype, name="fc")(out)
        out = Dropout(self.dropout, impl=self.dropout_impl)(
            out, deterministic=deterministic
        )
        out = nn.LayerNorm(epsilon=LN_EPS, dtype=self.dtype, name="layer_norm")(
            out + residual
        )
        return out


class ConvFFN(nn.Module):
    """Position-wise conv feed-forward (reference: transformer/SubLayers.py:60-93)."""

    d_model: int
    d_inner: int
    kernel_sizes: Tuple[int, int]
    dropout: float
    conv_impl: str = "xla"
    dtype: jnp.dtype = jnp.float32
    dropout_impl: str = "bernoulli"

    @nn.compact
    def __call__(self, x, deterministic: bool):
        from speakingstyle_tpu.ops.conv import Conv1d

        residual = x
        h = Conv1d(
            self.d_inner,
            kernel_size=self.kernel_sizes[0],
            impl=self.conv_impl,
            activation="relu",
            dtype=self.dtype,
            name="w_1",
        )(x)
        h = Conv1d(
            self.d_model,
            kernel_size=self.kernel_sizes[1],
            impl=self.conv_impl,
            dtype=self.dtype,
            name="w_2",
        )(h)
        h = Dropout(self.dropout, impl=self.dropout_impl)(
            h, deterministic=deterministic
        )
        return nn.LayerNorm(epsilon=LN_EPS, dtype=self.dtype, name="layer_norm")(
            h + residual
        )


class FFTBlock(nn.Module):
    """Self-attention + conv FFN + optional FiLM (reference: transformer/Layers.py:11-37).

    FiLM is applied after the FFN, then padded steps are re-zeroed. The
    ``film`` flag controls whether the gate params exist at all (the
    reference encoder's blocks have none, reference: model/modules.py:380).
    """

    d_model: int
    n_head: int
    d_inner: int
    kernel_sizes: Tuple[int, int]
    dropout: float
    film: bool = True
    conv_impl: str = "xla"
    dtype: jnp.dtype = jnp.float32
    softmax_dtype: jnp.dtype = jnp.float32
    attention_kernel: str = "einsum"
    seq_mesh: Optional[object] = None
    dropout_impl: str = "bernoulli"

    @nn.compact
    def __call__(self, x, pad_mask, gammas=None, betas=None, deterministic=True):
        x = MultiHeadSelfAttention(
            self.n_head, self.d_model, self.dropout, dtype=self.dtype,
            softmax_dtype=self.softmax_dtype,
            attention_kernel=self.attention_kernel,
            seq_mesh=self.seq_mesh, dropout_impl=self.dropout_impl,
            name="slf_attn"
        )(x, pad_mask, deterministic)
        x = mask_fill(x, pad_mask)
        x = ConvFFN(
            self.d_model,
            self.d_inner,
            self.kernel_sizes,
            self.dropout,
            conv_impl=self.conv_impl,
            dtype=self.dtype,
            dropout_impl=self.dropout_impl,
            name="pos_ffn",
        )(x, deterministic)
        if self.film and gammas is not None and betas is not None:
            x = FiLM(name="film")(x, gammas, betas)
        x = mask_fill(x, pad_mask)
        return x


class ConvNorm(nn.Module):
    """1-D conv over time, channel-last (reference: transformer/Layers.py:40-74)."""

    out_channels: int
    kernel_size: int = 1
    dilation: int = 1
    conv_impl: str = "xla"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        from speakingstyle_tpu.ops.conv import Conv1d

        return Conv1d(
            self.out_channels,
            kernel_size=self.kernel_size,
            dilation=self.dilation,
            impl=self.conv_impl,
            dtype=self.dtype,
            name="conv",
        )(x)


class LinearNorm(nn.Module):
    """Bias-free xavier-initialized projection (reference: model/blocks.py:66-79)."""

    out_features: int
    use_bias: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.Dense(
            self.out_features,
            use_bias=self.use_bias,
            kernel_init=nn.initializers.xavier_uniform(),
            dtype=self.dtype,
            name="linear",
        )(x)
