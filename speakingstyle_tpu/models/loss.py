"""FastSpeech2 loss (reference: model/loss.py:5-99).

L1 on mel and postnet-mel, MSE on pitch/energy/log-duration — each averaged
over real (unmasked) elements only, reproducing the reference's
``masked_select(...).mean()`` with jit-friendly masked means — plus the
FiLM-gate L2 term ``lambda_f * sum(s_gamma^2 + s_beta^2)`` collected from
the parameter tree by name (reference: utils/model.py:53-59).
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp

from speakingstyle_tpu.ops.masking import masked_mean


def film_gate_l2(params) -> jnp.ndarray:
    """Sum of squares of every s_gamma/s_beta scalar in the tree."""
    total = jnp.zeros((), jnp.float32)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in flat:
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if any(n in ("s_gamma", "s_beta") for n in names):
            total = total + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return total


def fastspeech2_loss(
    predictions: Dict[str, Any],
    mel_targets,
    pitch_targets,
    energy_targets,
    duration_targets,
    params,
    lambda_f: float = 0.0,
    pitch_feature_level: str = "phoneme_level",
    energy_feature_level: str = "phoneme_level",
) -> Dict[str, jnp.ndarray]:
    src_keep = ~predictions["src_pad_mask"]
    mel_keep = ~predictions["mel_pad_mask"]

    log_duration_targets = jnp.log(duration_targets.astype(jnp.float32) + 1.0)

    pitch_keep = src_keep if pitch_feature_level == "phoneme_level" else mel_keep
    energy_keep = src_keep if energy_feature_level == "phoneme_level" else mel_keep

    mel_keep3 = mel_keep[..., None]
    mel_targets = mel_targets.astype(jnp.float32)
    mel_loss = masked_mean(
        jnp.abs(predictions["mel"] - mel_targets), jnp.broadcast_to(mel_keep3, mel_targets.shape)
    )
    postnet_mel_loss = masked_mean(
        jnp.abs(predictions["mel_postnet"] - mel_targets),
        jnp.broadcast_to(mel_keep3, mel_targets.shape),
    )
    pitch_loss = masked_mean(
        jnp.square(predictions["pitch_prediction"] - pitch_targets.astype(jnp.float32)),
        pitch_keep,
    )
    energy_loss = masked_mean(
        jnp.square(predictions["energy_prediction"] - energy_targets.astype(jnp.float32)),
        energy_keep,
    )
    duration_loss = masked_mean(
        jnp.square(predictions["log_duration_prediction"] - log_duration_targets),
        src_keep,
    )
    scale_reg = film_gate_l2(params)

    total = (
        mel_loss + postnet_mel_loss + duration_loss + pitch_loss + energy_loss
        + lambda_f * scale_reg
    )
    return {
        "total_loss": total,
        "mel_loss": mel_loss,
        "postnet_mel_loss": postnet_mel_loss,
        "pitch_loss": pitch_loss,
        "energy_loss": energy_loss,
        "duration_loss": duration_loss,
        "film_gate_l2": scale_reg,
    }
