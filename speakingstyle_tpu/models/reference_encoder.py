"""Style reference encoder: mel -> FiLM conditioning vectors (gamma, beta).

Reference: model/modules.py:307-406. Pipeline: 3x(conv k=3 + ReLU + LN +
dropout) over the mel, padded steps zeroed, sinusoid PE, 1024->256
projection, 4 FFT blocks (8 heads, no FiLM), time mean-pool, 256->512
affine, split into gamma/beta [B, 1, 256].

Parity note: the reference mean-pools with ``mean(dim=1)`` over the padded
length — padded frames are zeros but still count in the denominator. We
reproduce that exactly (``true_length_mean=False``); flip the flag for a
mathematically clean mean when training from scratch.
"""

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp

from speakingstyle_tpu.models.layers import ConvNorm, FFTBlock, LinearNorm, LN_EPS
from speakingstyle_tpu.ops.dropout import Dropout
from speakingstyle_tpu.ops.masking import mask_fill
from speakingstyle_tpu.ops.positional import add_position_encoding


class ReferenceEncoder(nn.Module):
    n_conv_layers: int = 3
    conv_filter_size: int = 1024
    conv_kernel_size: int = 3
    n_layers: int = 4
    n_head: int = 8
    d_model: int = 256
    dropout: float = 0.1
    n_position: int = 1001
    true_length_mean: bool = False
    conv_impl: str = "xla"
    dtype: jnp.dtype = jnp.float32
    softmax_dtype: jnp.dtype = jnp.float32
    attention_kernel: str = "einsum"
    dropout_impl: str = "bernoulli"

    @nn.compact
    def __call__(self, mel, pad_mask, deterministic=True):
        """mel: [B, T, n_mels]; pad_mask: [B, T] True at padding.

        Returns (gammas, betas), each [B, 1, d_model].
        """
        # zero padded frames up front: collate pads with zeros in the
        # reference, and the convs must not read arbitrary padding content
        x = mask_fill(mel.astype(self.dtype), pad_mask)
        for i in range(self.n_conv_layers):
            if self.conv_impl == "pallas":
                # whole conv->ReLU->LN sandwich in one fused kernel
                # (ops/pallas_conv.py); ConvParams/AffineParams create the
                # identical {conv_i/conv, ln_i} param entries the unfused
                # path below does, so the impls share checkpoints.
                from speakingstyle_tpu.ops.conv import AffineParams, ConvParams
                from speakingstyle_tpu.ops.pallas_conv import fused_conv_relu_ln

                class _Holder(nn.Module):
                    features: int
                    kernel_size: int

                    @nn.compact
                    def __call__(holder, cin):
                        return ConvParams(
                            holder.features, holder.kernel_size, name="conv"
                        )(cin)

                kernel, bias = _Holder(
                    self.conv_filter_size,
                    self.conv_kernel_size,
                    name=f"conv_{i}",
                )(x.shape[-1])
                scale, beta = AffineParams(
                    self.conv_filter_size, name=f"ln_{i}"
                )()
                kernel, bias, scale, beta = (
                    a.astype(self.dtype) for a in (kernel, bias, scale, beta)
                )
                x = fused_conv_relu_ln(x, kernel, bias, scale, beta)
            else:
                x = ConvNorm(
                    self.conv_filter_size,
                    kernel_size=self.conv_kernel_size,
                    conv_impl=self.conv_impl,
                    dtype=self.dtype,
                    name=f"conv_{i}",
                )(x)
                x = nn.relu(x)
                x = nn.LayerNorm(
                    epsilon=LN_EPS, dtype=self.dtype, name=f"ln_{i}"
                )(x)
            x = Dropout(self.dropout, impl=self.dropout_impl)(
                x, deterministic=deterministic
            )
        x = mask_fill(x, pad_mask)

        x = add_position_encoding(x, self.n_position)

        x = LinearNorm(self.d_model, dtype=self.dtype, name="fftb_linear")(x)
        for i in range(self.n_layers):
            x = FFTBlock(
                d_model=self.d_model,
                n_head=self.n_head,
                d_inner=self.conv_filter_size,
                kernel_sizes=(self.conv_kernel_size, self.conv_kernel_size),
                dropout=self.dropout,
                film=False,
                conv_impl=self.conv_impl,
                dtype=self.dtype,
                softmax_dtype=self.softmax_dtype,
                attention_kernel=self.attention_kernel,
                dropout_impl=self.dropout_impl,
                name=f"fftb_{i}",
            )(x, pad_mask, deterministic=deterministic)

        if self.true_length_mean:
            keep = (~pad_mask).astype(x.dtype)[..., None]
            pooled = (x * keep).sum(axis=1, keepdims=True) / jnp.maximum(
                keep.sum(axis=1, keepdims=True), 1.0
            )
        else:
            # reference semantics: zeros at padding, denominator = padded length
            pooled = x.mean(axis=1, keepdims=True)

        affine = LinearNorm(2 * self.d_model, dtype=self.dtype, name="feature_wise_affine")(
            pooled
        )
        gammas, betas = jnp.split(affine, 2, axis=-1)
        return gammas, betas
