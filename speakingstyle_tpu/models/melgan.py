"""MelGAN generator in Flax (the reference's alternative vocoder).

The reference loads this from torch.hub at runtime
(reference: utils/model.py:64-74 — ``descriptinc/melgan-neurips``
``load_melgan`` with the "linda_johnson" / "multi_speaker" checkpoints)
and feeds it **log10** mels: ``vocoder.inverse(mels / np.log(10))``
(reference: utils/model.py:101-102).

Architecture per the public descript implementation (MelGAN, Kumar et al.
2019; mel2wav/modules.py): reflection-padded conv k=7 → 4× [LeakyReLU(0.2)
→ weight-norm ConvTranspose1d(k=2r, stride=r) → n_residual dilated
ResnetBlocks (dilations 3^j, reflection padding, 1×1 shortcut)] →
LeakyReLU → reflection-padded conv k=7 → tanh. Hub checkpoints use
ngf=32, 3 residual layers, ratios (8,8,2,2) ⇒ 256× upsampling.

Weights load through ``compat.torch_convert.convert_melgan`` (weight norm
folded); the torch.hub download itself must happen on a machine with
network access — pass the saved state-dict file to ``get_vocoder``.
Numerical parity with a torch replica of the descript stack is pinned by
tests/test_hifigan.py::test_melgan_torch_parity.
"""

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from speakingstyle_tpu.models.hifigan import TorchConvTranspose1d

MELGAN_LRELU_SLOPE = 0.2
LOG10 = float(np.log(10.0))


class ReflectConv1d(nn.Module):
    """Reflection-padded conv1d (descript's ReflectionPad1d + WNConv1d
    pair, weight norm folded at conversion)."""

    features: int
    kernel_size: int
    dilation: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        pad = self.dilation * (self.kernel_size - 1) // 2
        if pad:
            x = jnp.pad(x, ((0, 0), (pad, pad), (0, 0)), mode="reflect")
        return nn.Conv(
            self.features,
            kernel_size=(self.kernel_size,),
            kernel_dilation=(self.dilation,),
            padding="VALID",
            dtype=self.dtype,
            name="conv",
        )(x)


class MelGANResBlock(nn.Module):
    """descript ResnetBlock: LeakyReLU → dilated k=3 conv → LeakyReLU →
    1×1 conv, plus a 1×1 shortcut."""

    dim: int
    dilation: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = nn.leaky_relu(x, MELGAN_LRELU_SLOPE)
        y = ReflectConv1d(
            self.dim, 3, dilation=self.dilation, dtype=self.dtype, name="conv1"
        )(y)
        y = nn.leaky_relu(y, MELGAN_LRELU_SLOPE)
        y = ReflectConv1d(self.dim, 1, dtype=self.dtype, name="conv2")(y)
        s = ReflectConv1d(self.dim, 1, dtype=self.dtype, name="shortcut")(x)
        return s + y


class MelGANGenerator(nn.Module):
    """log10-mel [B, T, n_mels] -> wav [B, T * prod(ratios)]."""

    n_mels: int = 80
    ngf: int = 32
    n_residual_layers: int = 3
    ratios: Sequence[int] = (8, 8, 2, 2)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, mel):
        mult = 2 ** len(self.ratios)
        x = ReflectConv1d(
            mult * self.ngf, 7, dtype=self.dtype, name="conv_pre"
        )(mel)
        for i, r in enumerate(self.ratios):
            ch = mult * self.ngf // 2
            x = nn.leaky_relu(x, MELGAN_LRELU_SLOPE)
            x = TorchConvTranspose1d(
                ch, 2 * r, r,
                # descript layout: supports odd upsample ratios too
                padding=r // 2 + r % 2,
                output_padding=r % 2,
                dtype=self.dtype,
                name=f"ups_{i}",
            )(x)
            for j in range(self.n_residual_layers):
                x = MelGANResBlock(
                    ch, 3**j, dtype=self.dtype, name=f"res_{i}_{j}"
                )(x)
            mult //= 2
        x = nn.leaky_relu(x, MELGAN_LRELU_SLOPE)
        x = ReflectConv1d(1, 7, dtype=self.dtype, name="conv_post")(x)
        return jnp.tanh(x)[..., 0].astype(jnp.float32)

    # -- uniform vocoder interface (hifigan.vocoder_infer) --

    @property
    def hop_factor(self) -> int:
        return int(np.prod(self.ratios))

    def vocode(self, params, mels):
        """The reference's calling convention: the acoustic model emits
        natural-log mels; MelGAN was trained on log10, so scale by 1/ln10
        (reference: utils/model.py:101-102)."""
        return self.apply({"params": params}, mels / LOG10)
