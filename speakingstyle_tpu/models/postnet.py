"""PostNet mel refiner: 5 conv1d(512, k=5) + BatchNorm, tanh on all but last.

Reference: transformer/Layers.py:78-148. BatchNorm note (SURVEY.md §7 hard
part 6): under jit with a batch-sharded input, the batch-mean reduction is a
global XLA collective — cross-device-synced batch stats come for free (the
reference's nn.DataParallel computed per-replica stats; synced stats are
strictly better behaved).
"""

import flax.linen as nn
import jax.numpy as jnp

from speakingstyle_tpu.ops.conv import Conv1d
from speakingstyle_tpu.ops.dropout import Dropout


class PostNet(nn.Module):
    n_mel_channels: int = 80
    embedding_dim: int = 512
    kernel_size: int = 5
    n_convolutions: int = 5
    dropout: float = 0.5
    conv_impl: str = "xla"
    dtype: jnp.dtype = jnp.float32
    dropout_impl: str = "bernoulli"

    @nn.compact
    def __call__(self, mel, deterministic=True, keep_mask=None):
        """mel: [B, T, n_mels] -> residual [B, T, n_mels].

        ``keep_mask`` ([T] or [B, T] bool, True = real frame): when given,
        every layer's output is re-zeroed at masked frames. Free-running
        inference needs this for reference parity — the reference's buffer
        ends hard at the batch-max predicted length, so each of its conv
        layers zero-pads there, while our static buffer extends further
        and intermediate bias/BatchNorm junk past the boundary would leak
        back in through the 5-layer receptive field.
        """
        x = mel.astype(self.dtype)
        if keep_mask is not None and keep_mask.ndim == 1:
            keep_mask = keep_mask[None, :]
        for i in range(self.n_convolutions):
            is_last = i == self.n_convolutions - 1
            out_ch = self.n_mel_channels if is_last else self.embedding_dim
            x = Conv1d(
                out_ch,
                kernel_size=self.kernel_size,
                impl=self.conv_impl,
                dtype=self.dtype,
                name=f"conv_{i}",
            )(x)
            x = nn.BatchNorm(
                use_running_average=deterministic,
                momentum=0.9,
                epsilon=1e-5,
                dtype=self.dtype,
                name=f"bn_{i}",
            )(x)
            if not is_last:
                x = jnp.tanh(x)
            x = Dropout(self.dropout, impl=self.dropout_impl)(
                x, deterministic=deterministic
            )
            if keep_mask is not None:
                x = jnp.where(keep_mask[..., None], x, 0.0)
        return x
