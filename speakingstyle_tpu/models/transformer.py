"""Phoneme encoder and mel decoder: sinusoid PE + FFT block stacks.

Reference: transformer/Models.py:33-170. Differences by design:
- The PE table is sized at construction (``n_position``) and baked into the
  compiled program; long-sequence inference sizes the table up instead of
  recomputing it on host per call (reference: Models.py:82-87).
- Shapes are static: callers present bucketed [B, L] inputs with pad masks;
  the decoder's train-time truncation to max_seq_len becomes a structural
  guarantee (buckets never exceed the table).
- Optional jax.checkpoint (remat) over the block stack trades FLOPs for HBM.
"""

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from speakingstyle_tpu.ops.positional import add_position_encoding
from speakingstyle_tpu.models.layers import FFTBlock
from speakingstyle_tpu.text.symbols import VOCAB_SIZE


class FFTStack(nn.Module):
    """N FiLM-modulated FFT blocks with a fixed sinusoid PE prologue."""

    n_layers: int
    d_model: int
    n_head: int
    d_inner: int
    kernel_sizes: Tuple[int, int]
    dropout: float
    n_position: int
    film: bool = True
    remat: bool = False
    conv_impl: str = "xla"
    dtype: jnp.dtype = jnp.float32
    softmax_dtype: jnp.dtype = jnp.float32
    attention_kernel: str = "einsum"
    seq_mesh: Optional[object] = None  # engages ring attention when set
    dropout_impl: str = "bernoulli"

    @nn.compact
    def __call__(self, x, pad_mask, gammas=None, betas=None, deterministic=True):
        x = add_position_encoding(x, self.n_position)
        block_cls = FFTBlock
        if self.remat:
            # flax lifts __call__(self, x, pad_mask, gammas, betas, deterministic)
            # with self at index 0 — `deterministic` is arg 5.
            block_cls = nn.remat(FFTBlock, static_argnums=(5,))
        for i in range(self.n_layers):
            x = block_cls(
                d_model=self.d_model,
                n_head=self.n_head,
                d_inner=self.d_inner,
                kernel_sizes=self.kernel_sizes,
                dropout=self.dropout,
                film=self.film,
                conv_impl=self.conv_impl,
                dtype=self.dtype,
                softmax_dtype=self.softmax_dtype,
                attention_kernel=self.attention_kernel,
                seq_mesh=self.seq_mesh,
                dropout_impl=self.dropout_impl,
                name=f"layer_{i}",
            )(x, pad_mask, gammas, betas, deterministic)
        return x


class Encoder(nn.Module):
    """Phoneme embedding + FFT stack (reference: transformer/Models.py:33-101)."""

    n_layers: int = 4
    d_model: int = 256
    n_head: int = 2
    d_inner: int = 1024
    kernel_sizes: Tuple[int, int] = (9, 1)
    dropout: float = 0.2
    n_position: int = 1001
    vocab_size: int = VOCAB_SIZE
    remat: bool = False
    conv_impl: str = "xla"
    dtype: jnp.dtype = jnp.float32
    softmax_dtype: jnp.dtype = jnp.float32
    attention_kernel: str = "einsum"
    seq_mesh: Optional[object] = None
    dropout_impl: str = "bernoulli"

    @nn.compact
    def __call__(self, token_ids, pad_mask, gammas=None, betas=None, deterministic=True):
        x = nn.Embed(
            self.vocab_size,
            self.d_model,
            dtype=self.dtype,
            name="src_word_emb",
        )(token_ids)
        return FFTStack(
            self.n_layers,
            self.d_model,
            self.n_head,
            self.d_inner,
            self.kernel_sizes,
            self.dropout,
            self.n_position,
            film=True,
            remat=self.remat,
            conv_impl=self.conv_impl,
            dtype=self.dtype,
            softmax_dtype=self.softmax_dtype,
            attention_kernel=self.attention_kernel,
            seq_mesh=self.seq_mesh,
            dropout_impl=self.dropout_impl,
            name="layer_stack",
        )(x, pad_mask, gammas, betas, deterministic)


class Decoder(nn.Module):
    """Frame-level FFT stack (reference: transformer/Models.py:104-170)."""

    n_layers: int = 6
    d_model: int = 256
    n_head: int = 2
    d_inner: int = 1024
    kernel_sizes: Tuple[int, int] = (9, 1)
    dropout: float = 0.2
    n_position: int = 1001
    remat: bool = False
    conv_impl: str = "xla"
    dtype: jnp.dtype = jnp.float32
    softmax_dtype: jnp.dtype = jnp.float32
    attention_kernel: str = "einsum"
    seq_mesh: Optional[object] = None
    dropout_impl: str = "bernoulli"

    @nn.compact
    def __call__(self, x, pad_mask, gammas=None, betas=None, deterministic=True):
        return FFTStack(
            self.n_layers,
            self.d_model,
            self.n_head,
            self.d_inner,
            self.kernel_sizes,
            self.dropout,
            self.n_position,
            film=True,
            remat=self.remat,
            conv_impl=self.conv_impl,
            dtype=self.dtype,
            softmax_dtype=self.softmax_dtype,
            attention_kernel=self.attention_kernel,
            seq_mesh=self.seq_mesh,
            dropout_impl=self.dropout_impl,
            name="layer_stack",
        )(x, pad_mask, gammas, betas, deterministic)
