"""Fine-grained prosody control: per-word / per-phone control arrays.

Reference: notebooks/control.ipynb cells 17-23 define a
``ControlledVarianceAdapter`` whose p/e/d controls are per-phone *lists*
instead of scalars. In this framework no subclass is needed: the variance
adaptor's control inputs broadcast, so a [B, L_src] array of per-phone
factors flows through the same jitted forward as a scalar
(models/variance_adaptor.py — ``pred * control`` and
``round(exp(logd)-1) * control``).

This module builds those arrays from word-level intent: G2P keeps the
word → phone-span mapping, and `expand_word_controls` turns
{word index: factor} into the per-phone array.
"""

from typing import Dict, List, Sequence, Tuple, Union

import numpy as np

from speakingstyle_tpu.text import SYMBOL_TO_ID, text_to_sequence
from speakingstyle_tpu.text.g2p import english_word_spans  # noqa: F401 (re-export)

ControlSpec = Union[float, Sequence[float], Dict[int, float]]


def _kept_phones(phones: Sequence[str]) -> List[str]:
    """Apply the text frontend's symbol filter so per-phone control arrays
    stay aligned with the token sequence: text_to_sequence silently drops
    phones outside the symbol inventory (text/__init__.py), and a control
    array built over the unfiltered phones would shift every later word's
    factor."""
    return [p for p in phones if "@" + p in SYMBOL_TO_ID]


def spans_to_sequence(
    spans: Sequence[Tuple[str, List[str]]], cleaners: Sequence[str]
) -> np.ndarray:
    phones = [p for _, ps in spans for p in ps]
    return np.asarray(
        text_to_sequence("{" + " ".join(phones) + "}", list(cleaners)), np.int32
    )


def expand_word_controls(
    spans: Sequence[Tuple[str, List[str]]],
    word_controls: ControlSpec,
    default: float = 1.0,
) -> np.ndarray:
    """Word-level factors -> per-phone [L] array.

    ``word_controls`` is a scalar (uniform), a per-word sequence (must match
    len(spans)), or {word index: factor} with `default` elsewhere.
    """
    kept = [(w, _kept_phones(ps)) for w, ps in spans]
    if np.isscalar(word_controls):
        n = sum(len(ps) for _, ps in kept)
        return np.full((n,), float(word_controls), np.float32)
    if isinstance(word_controls, dict):
        factors = [float(word_controls.get(i, default)) for i in range(len(kept))]
    else:
        factors = [float(c) for c in word_controls]
        if len(factors) != len(kept):
            raise ValueError(
                f"{len(factors)} word controls for {len(kept)} words: "
                f"{[w for w, _ in kept]}"
            )
    return np.concatenate(
        [np.full((len(ps),), f, np.float32) for f, (_, ps) in zip(factors, kept)]
    ) if kept else np.zeros((0,), np.float32)


def pad_control(control: np.ndarray, length: int, batch: int = 1) -> np.ndarray:
    """[L] per-phone control -> [batch, length] padded with 1.0 (neutral:
    padded phones have zero duration/masked predictions anyway)."""
    out = np.ones((batch, length), np.float32)
    out[:, : len(control)] = control
    return out
