"""``python -m speakingstyle_tpu <command>`` dispatcher."""

import argparse
import sys

COMMANDS = (
    "train",
    "distill",
    "evaluate",
    "synthesize",
    "preprocess",
    "prepare_align",
    "train_vocoder",
    "vocode",
    "convert",
    "analyze",
    "serve",
    "replica",
)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    parser = argparse.ArgumentParser(prog="speakingstyle-tpu")
    sub = parser.add_subparsers(dest="command", required=True)
    import importlib

    modules = {}
    for name in COMMANDS:
        mod = importlib.import_module(f"speakingstyle_tpu.cli.{name}")
        modules[name] = mod
        mod.build_parser(sub.add_parser(name, help=mod.__doc__.splitlines()[0]))
    args = parser.parse_args(argv)
    return modules[args.command].main(args)


if __name__ == "__main__":
    main()
