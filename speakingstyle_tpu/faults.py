"""Deterministic fault injection, shared by the training and serving stacks.

Every recovery path in the tree — training/resilience.py's rollback and
checkpoint drills AND serving/resilience.py's replica supervision — is
exercised end-to-end by injecting the fault it guards against at an
exact, named point.  The ``SPEAKINGSTYLE_FAULTS`` environment variable
holds a spec like

    loader_ioerror@7;nan_grads@12;sigterm@20
    replica_raise@40;style_encode_error@2

meaning each named site's counter tripping the named value fires the
fault once.  Each entry fires exactly once — a retried load, a replayed
step after rollback, or a requeued request does NOT re-trip the same
entry, which is what makes recovery observable.  Duplicate entries are
allowed (``nan_grads@3;nan_grads@3`` poisons the replay too — how the
consecutive-rollback abort is tested).

Counter semantics per kind:

  training (consumed via training/faults.py, which re-exports this plan):

  ``loader_ioerror@N``  Nth call of ``SpeechDataset._feature`` (1-based,
                        counted per dataset instance)
  ``nan_grads@N``       the batch consumed by the train step whose
                        post-increment step counter is N
  ``sigterm@N``         delivered after step N completes

  serving (serving/resilience.py; the chaos drills):

  ``replica_raise@N``       the fleet router's Nth coalesced dispatch
                            (router-global, 1-based) raises InjectedFault
                            before touching the replica engine
  ``replica_hang@N``        same counter; the dispatch stalls past the
                            hang watchdog instead of raising
  ``style_encode_error@N``  the StyleService's Nth reference-encoder
                            dispatch attempt raises before device work
  ``vocoder_raise@N``       the engine's Nth ``vocode_window`` call
                            (per engine instance) raises — a streaming
                            continuation fault
  ``longform_ring_error@N`` the LongformService's Nth ring-tier
                            synthesis attempt (per service instance,
                            1-based) raises InjectedFault before device
                            work — drives the tier-b→tier-a
                            (ring→chunked) degradation drill
  ``replica_proc_kill@N``   the fleet router's Nth coalesced dispatch
                            (the replica_raise counter) SIGKILLs the
                            target replica's *process* before the wire
                            call — the cluster tier's hard-death drill
                            (in-process routers treat it as a raise)
  ``net_partition@N``       same counter; the router↔replica link for
                            the target replica drops every packet from
                            here on (dispatches fail fast, heartbeats
                            stop renewing the lease) until the drill
                            heals it — the partition-grade chaos drill
  ``tier_poison@N``         same counter; the Nth coalesced dispatch
                            poisons the target replica engine's param
                            tree host-side (same shapes/dtypes — zero
                            compiles, no errors) so it keeps serving
                            GARBAGE audio — the quality-plane
                            degradation drill: only the validators
                            (obs/quality.py) and the golden probes
                            (serving/probes.py) can see it

  checkpoint (training/checkpoint.py; the lifecycle drills):

  ``checkpoint_corrupt@N``  the CheckpointManager's Nth restore
                            verification (per manager instance, 1-based)
                            reports the step corrupt — raises
                            CheckpointCorruptError before materializing
  ``manifest_missing@N``    same counter; the Nth verification behaves
                            as if the step's manifest.json were absent
                            (legacy-tolerant unless restoring strictly)

The plan is plain Python state constructed per run (``FaultPlan.from_env``)
and threaded explicitly into the sites — no module globals, so tests can
run many faulted loops in one process.  ``fire`` is thread-safe (serving
sites race from replica workers) and ``arm`` appends entries to a live
plan, which is how ``bench.py --chaos`` kills a replica mid-load at a
deterministic dispatch count.
"""

import dataclasses
import os
import threading
from typing import List, Sequence, Tuple
from speakingstyle_tpu.obs.locks import make_lock

ENV_VAR = "SPEAKINGSTYLE_FAULTS"

TRAINING_KINDS = ("loader_ioerror", "nan_grads", "sigterm")
SERVING_KINDS = (
    "replica_raise", "replica_hang", "style_encode_error", "vocoder_raise",
    "longform_ring_error", "replica_proc_kill", "net_partition",
    "tier_poison",
)
CHECKPOINT_KINDS = ("checkpoint_corrupt", "manifest_missing")
KINDS = TRAINING_KINDS + SERVING_KINDS + CHECKPOINT_KINDS


@dataclasses.dataclass
class _Fault:
    kind: str
    at: int
    fired: bool = False


class FaultPlan:
    """A parsed fault spec; each entry fires at most once."""

    def __init__(self, faults: Sequence[_Fault] = ()):
        self._faults: List[_Fault] = list(faults)
        self._lock = make_lock("FaultPlan._lock")

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        faults = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            kind, sep, at = part.partition("@")
            kind = kind.strip()
            if not sep or kind not in KINDS:
                raise ValueError(
                    f"bad fault spec entry {part!r}: expected <kind>@<step> "
                    f"with kind in {KINDS}"
                )
            try:
                step = int(at)  # jaxlint: disable=JL004
            except ValueError:
                raise ValueError(
                    f"bad fault spec entry {part!r}: step {at!r} is not an int"
                ) from None
            faults.append(_Fault(kind, step))
        return cls(faults)

    @classmethod
    def from_env(cls) -> "FaultPlan":
        return cls.parse(os.environ.get(ENV_VAR, ""))

    def __bool__(self) -> bool:
        return bool(self._faults)

    def arm(self, kind: str, at: int) -> None:
        """Append one entry to a live plan (bench.py --chaos arms the
        replica kill between load phases, at a dispatch count that has
        not happened yet)."""
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; kinds: {KINDS}")
        with self._lock:
            self._faults.append(_Fault(kind, int(at)))

    def fire(self, kind: str, at: int) -> bool:
        """True exactly once per matching entry when the site's counter
        hits the named value; False forever after."""
        with self._lock:
            for f in self._faults:
                if f.kind == kind and f.at == at and not f.fired:
                    f.fired = True
                    return True
        return False

    def pending(self) -> List[Tuple[str, int]]:
        with self._lock:
            return [(f.kind, f.at) for f in self._faults if not f.fired]


def dp_poison_rows(batch_rows: int, dp: int) -> int:
    """The ``nan_grads``-under-DP drill: how many leading batch rows to
    poison so the NaN lands on exactly ONE data-parallel shard.

    A ``data``-sharded batch of ``batch_rows`` rows over a ``dp``-way mesh
    gives each shard ``batch_rows // dp`` contiguous rows; poisoning just
    the first shard's slice makes the drill adversarial — the sentinel's
    ``_finite`` flag is only safe if its dp-axis all-reduce makes every
    device (and every host) see the one bad shard.  Returns the full batch
    when it cannot be split (dp <= 1 or fewer rows than shards): the
    single-chip drill poisons everything, as before.

    Pure host arithmetic (no jax) so serving-side imports of this module
    stay device-free; ``training/faults.py::poison_batch`` applies it.
    """
    if dp <= 1 or batch_rows < dp:
        return batch_rows
    return batch_rows // dp
