"""Inverse STFT, Griffin-Lim, and wav file IO.

Replaces the reference's torch ISTFT/Griffin-Lim
(reference: audio/stft.py:82-139, audio/audio_processing.py:66-82) with a
jit-compiled overlap-add implementation, and its scipy wavfile usage
(reference: utils/tools.py:173-178) with local helpers. Resampling uses
scipy polyphase filtering (librosa is not a dependency of this framework).
"""

from fractions import Fraction

import jax
import jax.numpy as jnp
import numpy as np
import scipy.io.wavfile
import scipy.signal

from speakingstyle_tpu.audio.stft import frame_signal, hann_window
from speakingstyle_tpu.parallel.registry import jit_program


@jit_program(static_argnums=(2, 3, 4))
def istft(magnitude, phase, n_fft: int, hop_length: int, win_length: int):
    """Inverse STFT via windowed overlap-add.

    magnitude/phase: [B, 1 + n_fft//2, n_frames] -> wav [B, T] with the
    n_fft//2 reflect-pad of the forward transform trimmed off.
    """
    spec = magnitude * jnp.exp(1j * phase)
    frames = jnp.fft.irfft(spec.transpose(0, 2, 1), n=n_fft, axis=-1)
    window = jnp.asarray(hann_window(win_length, n_fft))
    frames = frames * window

    B, n_frames, _ = frames.shape
    out_len = n_fft + hop_length * (n_frames - 1)
    starts = jnp.arange(n_frames) * hop_length
    idx = starts[:, None] + jnp.arange(n_fft)[None, :]  # [n_frames, n_fft]

    flat_idx = idx.reshape(-1)
    sig = jax.vmap(
        lambda f: jnp.zeros(out_len).at[flat_idx].add(f.reshape(-1))
    )(frames)
    # window sum-square normalization (reference: audio/audio_processing.py:7-63)
    wss = jnp.zeros(out_len).at[flat_idx].add(jnp.tile(window**2, (n_frames, 1)).reshape(-1))
    sig = sig / jnp.where(wss > 1e-11, wss, 1.0)

    pad = n_fft // 2
    return sig[:, pad : out_len - pad]


def _stft_phase(y, n_fft, hop_length, win_length):
    frames = frame_signal(y, n_fft, hop_length)
    window = jnp.asarray(hann_window(win_length, n_fft))
    spec = jnp.fft.rfft(frames * window, axis=-1).transpose(0, 2, 1)
    return jnp.angle(spec)


@jit_program(static_argnums=(1, 2, 3, 4))
def griffin_lim(magnitudes, n_fft: int, hop_length: int, win_length: int, n_iters: int = 30):
    """Phase reconstruction from magnitude spectrogram [B, F, T] -> wav [B, T']."""
    key = jax.random.PRNGKey(0)
    angles = jax.random.uniform(key, magnitudes.shape, minval=-np.pi, maxval=np.pi)

    def body(_, angles):
        signal = istft(magnitudes, angles, n_fft, hop_length, win_length)
        return _stft_phase(signal, n_fft, hop_length, win_length)[
            ..., : magnitudes.shape[-1]
        ]

    angles = jax.lax.fori_loop(0, n_iters, body, angles)
    return istft(magnitudes, angles, n_fft, hop_length, win_length)


def load_wav(path: str, target_sr: int = None) -> tuple:
    """Read a wav file -> (float32 array in [-1, 1], sample_rate)."""
    sr, data = scipy.io.wavfile.read(path)
    if data.dtype == np.int16:
        data = data.astype(np.float32) / 32768.0
    elif data.dtype == np.int32:
        data = data.astype(np.float32) / 2147483648.0
    elif data.dtype == np.uint8:
        data = (data.astype(np.float32) - 128.0) / 128.0
    else:
        data = data.astype(np.float32)
    if data.ndim > 1:
        data = data.mean(axis=1)
    if target_sr is not None and sr != target_sr:
        frac = Fraction(target_sr, sr).limit_denominator(1000)
        data = scipy.signal.resample_poly(data, frac.numerator, frac.denominator)
        sr = target_sr
    return data.astype(np.float32), sr


def save_wav(path: str, wav: np.ndarray, sampling_rate: int, max_wav_value: float = 32768.0):
    wav = np.asarray(wav, np.float32)
    peak = max(np.abs(wav).max(), 1e-8)
    if peak > 1.0:
        wav = wav / peak
    scipy.io.wavfile.write(path, sampling_rate, (wav * (max_wav_value - 1)).astype(np.int16))
