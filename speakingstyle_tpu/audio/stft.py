"""STFT / mel-spectrogram extraction as pure JAX functions.

Behavioral contract from the reference's conv1d-based STFT
(reference: audio/stft.py:14-178):

  * reflect-pad the signal by n_fft//2 on both sides,
  * hann window of ``win_length`` (periodic), zero-center-padded to n_fft,
  * magnitude = |rfft| per frame (frame count = T//hop + 1),
  * mel = log(clamp(mel_fb @ mag, 1e-5))   (dynamic-range compression, C=1),
  * energy = L2 norm of each magnitude frame (audio/stft.py:176).

Implemented as a strided gather + batched rfft instead of a conv against a
Fourier basis: on TPU the rfft lowers to XLA's native FFT and the windowing
fuses, so there is no materialized [n_fft, n_fft] basis matmul.
"""

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from speakingstyle_tpu.audio.mel import mel_filterbank
from speakingstyle_tpu.parallel.registry import jit_program


def hann_window(win_length: int, n_fft: int) -> np.ndarray:
    """Periodic hann of win_length, zero-center-padded to n_fft."""
    n = np.arange(win_length)
    w = 0.5 - 0.5 * np.cos(2.0 * np.pi * n / win_length)
    pad = (n_fft - win_length) // 2
    out = np.zeros(n_fft, dtype=np.float32)
    out[pad : pad + win_length] = w
    return out


def frame_signal(y: jnp.ndarray, n_fft: int, hop_length: int) -> jnp.ndarray:
    """[B, T] -> [B, n_frames, n_fft] reflect-padded overlapping frames."""
    pad = n_fft // 2
    y = jnp.pad(y, ((0, 0), (pad, pad)), mode="reflect")
    n_frames = (y.shape[1] - n_fft) // hop_length + 1
    starts = jnp.arange(n_frames) * hop_length
    idx = starts[:, None] + jnp.arange(n_fft)[None, :]
    return y[:, idx]


@jit_program(static_argnums=(1, 2, 3))
def stft_magnitude(y, n_fft: int, hop_length: int, win_length: int):
    """[B, T] float in [-1, 1] -> magnitude [B, 1 + n_fft//2, n_frames]."""
    frames = frame_signal(y, n_fft, hop_length)
    window = jnp.asarray(hann_window(win_length, n_fft))
    spec = jnp.fft.rfft(frames * window, axis=-1)
    return jnp.abs(spec).astype(jnp.float32).transpose(0, 2, 1)


def dynamic_range_compression(x, C: float = 1.0, clip_val: float = 1e-5):
    return jnp.log(jnp.clip(x, clip_val, None) * C)


def dynamic_range_decompression(x, C: float = 1.0):
    return jnp.exp(x) / C


class MelExtractor:
    """TacotronSTFT equivalent: wav -> (log-mel, energy).

    Pure-function core (``__call__`` jits); the filterbank and window are
    baked as constants at construction.
    """

    def __init__(
        self,
        filter_length: int = 1024,
        hop_length: int = 256,
        win_length: int = 1024,
        n_mel_channels: int = 80,
        sampling_rate: int = 22050,
        mel_fmin: float = 0.0,
        mel_fmax: Optional[float] = 8000.0,
    ):
        self.filter_length = filter_length
        self.hop_length = hop_length
        self.win_length = win_length
        self.n_mel_channels = n_mel_channels
        self.sampling_rate = sampling_rate
        self.mel_basis = mel_filterbank(
            sampling_rate, filter_length, n_mel_channels, mel_fmin, mel_fmax
        )

        @jit_program
        def _extract(y):
            mag = stft_magnitude(y, filter_length, hop_length, win_length)
            mel = jnp.einsum("mf,bft->bmt", jnp.asarray(self.mel_basis), mag)
            mel = dynamic_range_compression(mel)
            energy = jnp.linalg.norm(mag, axis=1)
            return mel, energy

        self._extract = _extract

    def mel_spectrogram(self, y: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """[B, T] wav in [-1, 1] -> (mel [B, n_mels, n_frames], energy [B, n_frames])."""
        return self._extract(y)

    def __call__(self, y):
        return self.mel_spectrogram(y)


def get_mel_from_wav(audio: np.ndarray, extractor: MelExtractor):
    """Single-utterance numpy convenience (reference: audio/tools.py:8-15)."""
    audio = np.clip(np.asarray(audio, np.float32), -1.0, 1.0)
    mel, energy = extractor.mel_spectrogram(jnp.asarray(audio)[None])
    return np.asarray(mel[0]), np.asarray(energy[0])
