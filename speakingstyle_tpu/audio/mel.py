"""Mel filterbank, self-contained (no librosa dependency).

Reproduces `librosa.filters.mel` with its defaults (htk=False, Slaney-style
area normalization) — the filterbank the reference builds in
audio/stft.py:145-147 — as a pure numpy function, so offline preprocessing
and on-device mel extraction share one set of constants.
"""

import numpy as np

_F_SP = 200.0 / 3  # Hz per mel below the log knee
_MIN_LOG_HZ = 1000.0
_MIN_LOG_MEL = _MIN_LOG_HZ / _F_SP
_LOGSTEP = np.log(6.4) / 27.0


def hz_to_mel(frequencies):
    """Slaney mel scale: linear below 1 kHz, log above."""
    frequencies = np.asarray(frequencies, dtype=np.float64)
    mels = frequencies / _F_SP
    log_region = frequencies >= _MIN_LOG_HZ
    mels = np.where(
        log_region,
        _MIN_LOG_MEL + np.log(np.maximum(frequencies, 1e-10) / _MIN_LOG_HZ) / _LOGSTEP,
        mels,
    )
    return mels


def mel_to_hz(mels):
    mels = np.asarray(mels, dtype=np.float64)
    freqs = mels * _F_SP
    log_region = mels >= _MIN_LOG_MEL
    return np.where(
        log_region, _MIN_LOG_HZ * np.exp(_LOGSTEP * (mels - _MIN_LOG_MEL)), freqs
    )


def mel_frequencies(n_mels, fmin, fmax):
    return mel_to_hz(np.linspace(hz_to_mel(fmin), hz_to_mel(fmax), n_mels))


def mel_filterbank(
    sampling_rate: int,
    n_fft: int,
    n_mels: int = 80,
    fmin: float = 0.0,
    fmax=None,
) -> np.ndarray:
    """[n_mels, 1 + n_fft//2] triangular filterbank, Slaney-normalized."""
    if fmax is None:
        fmax = sampling_rate / 2.0
    fft_freqs = np.linspace(0.0, sampling_rate / 2.0, 1 + n_fft // 2)
    mel_f = mel_frequencies(n_mels + 2, fmin, fmax)

    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_freqs[None, :]  # [n_mels+2, n_freq]

    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0.0, np.minimum(lower, upper))

    # Slaney area normalization: each filter integrates to ~2/bandwidth
    enorm = 2.0 / (mel_f[2 : n_mels + 2] - mel_f[:n_mels])
    weights *= enorm[:, None]
    return weights.astype(np.float32)
