"""Audio DSP: JAX STFT/mel extraction and inversion (TacotronSTFT-equivalent)."""

from speakingstyle_tpu.audio.mel import mel_filterbank
from speakingstyle_tpu.audio.stft import (
    MelExtractor,
    dynamic_range_compression,
    dynamic_range_decompression,
    get_mel_from_wav,
    stft_magnitude,
)
from speakingstyle_tpu.audio.tools import griffin_lim, istft, load_wav, save_wav

__all__ = [
    "MelExtractor",
    "mel_filterbank",
    "stft_magnitude",
    "dynamic_range_compression",
    "dynamic_range_decompression",
    "get_mel_from_wav",
    "griffin_lim",
    "istft",
    "load_wav",
    "save_wav",
]
