"""Ring attention: sequence-parallel exact attention over an ICI ring.

The long-context path the reference lacks entirely (SURVEY.md §5): queries
stay resident on their shard while key/value blocks rotate around the mesh
axis via `ppermute`; a streaming (flash-style) log-sum-exp accumulator makes
the result exactly equal to full softmax attention over the whole sequence.
Communication overlaps with compute in XLA's pipeline, and per-device memory
is O(L_local²·0 + L_local·d) — no [L, L] materialization anywhere.

Layout contract (under `shard_map` over axis ``axis_name``):
  q, k, v : [B, H, L_local, D]   (sequence axis sharded)
  bias    : [B, 1, 1, L_local]   additive key-padding bias, sharded like k

`ring_attention(...)` is the sharded kernel; `ring_self_attention(...)`
wraps it in shard_map over a mesh for direct use.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map is top-level only from 0.5.x; older releases ship it under
# jax.experimental (getattr with a default so the deprecation module
# __getattr__ can't raise at import time).
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:  # pragma: no cover - exercised on jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map


def _block_attn(q, k, v, bias, scale):
    """One q-block × kv-block pass -> (unnormalized out, row max, row sumexp)."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        logits = logits + bias
    m = jnp.max(logits, axis=-1, keepdims=True)  # [B,H,Lq,1]
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m, l


def ring_attention(q, k, v, bias=None, axis_name: str = "seq", scale: Optional[float] = None):
    """Exact attention with K/V rotating around `axis_name`.

    Call inside shard_map; every rank holds one sequence block of q/k/v.
    Returns the attention output for the local q block: [B, H, L_local, D].
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if hasattr(jax.lax, "axis_size"):
        n = jax.lax.axis_size(axis_name)
    else:  # jax <= 0.4.x: read the size off the axis environment frame
        # (axis_frame returns the bare size int on 0.4.37, a frame object
        # with .size on other 0.4.x point releases)
        n = jax.core.axis_frame(axis_name)
        n = getattr(n, "size", n)
    perm = [(i, (i + 1) % n) for i in range(n)]

    o, m, l = _block_attn(q, k, v, bias, scale)

    def body(_, carry):
        o, m, l, k, v, bias = carry
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        if bias is not None:
            bias = jax.lax.ppermute(bias, axis_name, perm)
        o_new, m_new, l_new = _block_attn(q, k, v, bias, scale)
        # streaming softmax merge
        m_tot = jnp.maximum(m, m_new)
        alpha = jnp.exp(m - m_tot)
        beta = jnp.exp(m_new - m_tot)
        o = o * alpha + o_new * beta
        l = l * alpha + l_new * beta
        return o, m_tot, l, k, v, bias

    o, m, l, _, _, _ = jax.lax.fori_loop(0, n - 1, body, (o, m, l, k, v, bias))
    return o / jnp.maximum(l, 1e-30)


def ring_self_attention(
    q, k, v, bias=None, mesh: Optional[Mesh] = None, axis_name: str = "seq"
):
    """shard_map wrapper: q/k/v [B, H, L, D] (global), bias [B, 1, 1, L].

    Shards the L axis over `axis_name`, runs the ring, returns the global
    [B, H, L, D] output (sharded the same way).
    """
    if mesh is None:
        raise ValueError("ring_self_attention requires a mesh")
    qkv_spec = P(None, None, axis_name, None)
    bias_spec = P(None, None, None, axis_name)
    in_specs = (qkv_spec, qkv_spec, qkv_spec, bias_spec if bias is not None else None)
    fn = functools.partial(ring_attention, axis_name=axis_name)

    if bias is None:
        sharded = _shard_map(
            lambda q, k, v: fn(q, k, v, None),
            mesh=mesh, in_specs=in_specs[:3], out_specs=qkv_spec,
        )
        return sharded(q, k, v)
    sharded = _shard_map(
        lambda q, k, v, b: fn(q, k, v, b),
        mesh=mesh, in_specs=in_specs, out_specs=qkv_spec,
    )
    return sharded(q, k, v, bias)
