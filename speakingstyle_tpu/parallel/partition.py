"""Tensor-parallel parameter partitioning over the mesh's ``model`` axis.

The reference has no TP at all (SURVEY.md §2.4 — nn.DataParallel is its
only strategy); this module is the TPU-native scaling path beyond pure DP:
Megatron-style column/row parallel pairs annotated as ``NamedSharding``s,
with XLA's GSPMD inserting the all-reduces over ICI.

Rule set (first regex match wins, default replicate):
  * attention q/k/v projections — column parallel (heads split across
    ``model``); the output projection ``fc`` — row parallel (psum after).
  * conv-FFN ``w_1`` — column parallel over its 1024 filters; ``w_2`` —
    row parallel back to d_model.
  * reference-encoder mel convs — output-channel parallel (the single
    most FLOPs-heavy weight stack in the model).

Everything else (LayerNorms, embeddings, FiLM gates, postnet) stays
replicated: tiny parameters where TP would only add latency.

Optimizer state inherits the layout for free: build the optax state AFTER
sharding the parameters (``tx.init(sharded_params)`` — zeros_like keeps
each leaf's sharding), so Adam moments are sharded exactly like their
parameters.
"""

import re
from typing import List, Tuple

import jax
from flax.traverse_util import flatten_dict, unflatten_dict
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec builder) — specs reference the "model" mesh axis
DEFAULT_TP_RULES: List[Tuple[str, P]] = [
    # attention: column-parallel QKV, row-parallel output projection
    (r".*slf_attn/(w_qs|w_ks|w_vs)/kernel$", P(None, "model")),
    (r".*slf_attn/(w_qs|w_ks|w_vs)/bias$", P("model")),
    (r".*slf_attn/fc/kernel$", P("model", None)),
    # conv FFN: column-parallel w_1, row-parallel w_2 (kernel [K, Cin, Cout])
    (r".*pos_ffn/w_1/kernel$", P(None, None, "model")),
    (r".*pos_ffn/w_1/bias$", P("model")),
    (r".*pos_ffn/w_2/kernel$", P(None, "model", None)),
    # reference-encoder mel conv stack: output-channel parallel
    (r".*reference_encoder/conv_\d+/conv/kernel$", P(None, None, "model")),
    (r".*reference_encoder/conv_\d+/conv/bias$", P("model")),
    (r".*reference_encoder/fftb_linear/kernel$", P("model", None)),
]


def parse_rule_overrides(overrides) -> List[Tuple[str, P]]:
    """``train.parallel.partition_rules`` -> rule list for ``tp_shardings``.

    Each override is ``[path_regex, axes]`` with ``axes`` a comma-separated
    per-dim list of mesh axis names or ``none`` (config.py validates the
    grammar at load time). Overrides are PREPENDED to ``DEFAULT_TP_RULES``
    so they win first-match; an empty/None input returns the defaults
    unchanged.
    """
    if not overrides:
        return DEFAULT_TP_RULES
    rules: List[Tuple[str, P]] = []
    for pattern, axes in overrides:
        spec = tuple(
            None if tok.strip().lower() in ("", "none") else tok.strip()
            for tok in str(axes).split(",")
        )
        rules.append((pattern, P(*spec)))
    return rules + DEFAULT_TP_RULES


def _spec_for(path: str, rules) -> P:
    for pattern, spec in rules:
        if re.match(pattern, path):
            return spec
    return P()


def tp_shardings(params, mesh: Mesh, rules=None):
    """params pytree -> matching pytree of NamedShardings per DEFAULT_TP_RULES.

    Leaves whose rule-selected axis does not divide evenly fall back to
    replicated (robust for tiny test configs)."""
    rules = DEFAULT_TP_RULES if rules is None else rules
    axis_size = mesh.shape.get("model", 1)
    flat = flatten_dict(params, sep="/")
    out = {}
    for path, leaf in flat.items():
        spec = _spec_for(path, rules)
        # validate divisibility of every sharded dim
        ok = True
        for dim, axis in enumerate(spec):
            if axis is not None and (
                dim >= leaf.ndim or leaf.shape[dim] % axis_size != 0
            ):
                ok = False
        out[path] = NamedSharding(mesh, spec if ok else P())
    return unflatten_dict(out, sep="/")


def shard_params(params, mesh: Mesh, rules=None):
    """device_put the parameter tree with TP shardings applied."""
    sh = tp_shardings(params, mesh, rules)
    return jax.tree_util.tree_map(
        jax.device_put, params, sh, is_leaf=lambda x: not isinstance(x, dict)
    )


def count_sharded(params, mesh: Mesh, rules=None) -> int:
    """How many leaves actually get a non-replicated spec (introspection)."""
    sh = flatten_dict(tp_shardings(params, mesh, rules), sep="/")
    return sum(1 for s in sh.values() if s.spec != P())


def variables_shardings(variables, mesh: Mesh, rules=None):
    """Serve-side variables pytree ({"params": ..., "batch_stats": ...})
    -> matching pytree of NamedShardings.

    With ``rules`` (``serve.parallel.partition_rules`` through
    ``parse_rule_overrides``) the params follow them over the mesh's
    ``model`` axis; without rules EVERYTHING replicates — the serve
    default, because replicated weights keep a mesh replica bit-identical
    to the single-chip one (TP's row-parallel psum reorders float sums),
    and bit-parity from one checkpoint across replica geometries is the
    serving contract the cross-mesh tests pin down.
    """
    repl = NamedSharding(mesh, P())
    out = {
        k: jax.tree_util.tree_map(lambda _: repl, v)
        for k, v in variables.items()
    }
    if rules and "params" in variables:
        out["params"] = tp_shardings(variables["params"], mesh, rules)
    return out


def opt_state_shardings(opt_state, params, param_shardings, mesh: Mesh):
    """Shardings for an optax state given the parameter shardings.

    Any subtree of ``opt_state`` structurally identical to ``params`` (Adam
    mu/nu, MultiSteps grad accumulators) gets ``param_shardings``; every
    other leaf (step counters, scalars) is replicated. Recurses through the
    tuple/namedtuple/dict nesting optax states are built from.
    """
    import jax.tree_util as jtu

    repl = NamedSharding(mesh, P())
    p_treedef = jtu.tree_structure(params)

    def rec(node):
        if jtu.tree_structure(node) == p_treedef:
            return param_shardings
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if isinstance(node, tuple):
            mapped = [rec(c) for c in node]
            # namedtuples (optax states) take positional fields; plain
            # tuples (optax.chain containers) take one iterable
            if hasattr(node, "_fields"):
                return type(node)(*mapped)
            return tuple(mapped)
        return repl

    return rec(opt_state)


def train_state_shardings(state, mesh: Mesh, rules=None):
    """TrainState pytree -> matching pytree of NamedShardings.

    params follow DEFAULT_TP_RULES over the mesh's ``model`` axis; the optax
    state mirrors them; step/batch_stats replicate. Feed the result to
    ``jax.jit(in_shardings=...)`` / ``jax.device_put``.
    """
    p_sh = tp_shardings(state.params, mesh, rules)
    repl = NamedSharding(mesh, P())
    return state.replace(
        step=repl,
        params=p_sh,
        batch_stats=jax.tree_util.tree_map(lambda _: repl, state.batch_stats),
        opt_state=opt_state_shardings(state.opt_state, state.params, p_sh, mesh),
    )


def shard_train_state(state, mesh: Mesh, rules=None):
    """device_put a TrainState with TP parameter (+ mirrored optimizer)
    shardings; the pure-DP special case (model axis size 1) reduces to full
    replication."""
    sh = train_state_shardings(state, mesh, rules)
    return jax.tree_util.tree_map(jax.device_put, state, sh)
