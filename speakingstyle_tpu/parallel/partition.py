"""Tensor-parallel parameter partitioning over the mesh's ``model`` axis.

The reference has no TP at all (SURVEY.md §2.4 — nn.DataParallel is its
only strategy); this module is the TPU-native scaling path beyond pure DP:
Megatron-style column/row parallel pairs annotated as ``NamedSharding``s,
with XLA's GSPMD inserting the all-reduces over ICI.

Rule set (first regex match wins, default replicate):
  * attention q/k/v projections — column parallel (heads split across
    ``model``); the output projection ``fc`` — row parallel (psum after).
  * conv-FFN ``w_1`` — column parallel over its 1024 filters; ``w_2`` —
    row parallel back to d_model.
  * reference-encoder mel convs — output-channel parallel (the single
    most FLOPs-heavy weight stack in the model).

Everything else (LayerNorms, embeddings, FiLM gates, postnet) stays
replicated: tiny parameters where TP would only add latency.

Optimizer state inherits the layout for free: build the optax state AFTER
sharding the parameters (``tx.init(sharded_params)`` — zeros_like keeps
each leaf's sharding), so Adam moments are sharded exactly like their
parameters.
"""

import re
from typing import List, Tuple

import jax
from flax.traverse_util import flatten_dict, unflatten_dict
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec builder) — specs reference the "model" mesh axis
DEFAULT_TP_RULES: List[Tuple[str, P]] = [
    # attention: column-parallel QKV, row-parallel output projection
    (r".*slf_attn/(w_qs|w_ks|w_vs)/kernel$", P(None, "model")),
    (r".*slf_attn/(w_qs|w_ks|w_vs)/bias$", P("model")),
    (r".*slf_attn/fc/kernel$", P("model", None)),
    # conv FFN: column-parallel w_1, row-parallel w_2 (kernel [K, Cin, Cout])
    (r".*pos_ffn/w_1/kernel$", P(None, None, "model")),
    (r".*pos_ffn/w_1/bias$", P("model")),
    (r".*pos_ffn/w_2/kernel$", P(None, "model", None)),
    # reference-encoder mel conv stack: output-channel parallel
    (r".*reference_encoder/conv_\d+/conv/kernel$", P(None, None, "model")),
    (r".*reference_encoder/conv_\d+/conv/bias$", P("model")),
    (r".*reference_encoder/fftb_linear/kernel$", P("model", None)),
]


def _spec_for(path: str, rules) -> P:
    for pattern, spec in rules:
        if re.match(pattern, path):
            return spec
    return P()


def tp_shardings(params, mesh: Mesh, rules=None):
    """params pytree -> matching pytree of NamedShardings per DEFAULT_TP_RULES.

    Leaves whose rule-selected axis does not divide evenly fall back to
    replicated (robust for tiny test configs)."""
    rules = DEFAULT_TP_RULES if rules is None else rules
    axis_size = mesh.shape.get("model", 1)
    flat = flatten_dict(params, sep="/")
    out = {}
    for path, leaf in flat.items():
        spec = _spec_for(path, rules)
        # validate divisibility of every sharded dim
        ok = True
        for dim, axis in enumerate(spec):
            if axis is not None and (
                dim >= leaf.ndim or leaf.shape[dim] % axis_size != 0
            ):
                ok = False
        out[path] = NamedSharding(mesh, spec if ok else P())
    return unflatten_dict(out, sep="/")


def shard_params(params, mesh: Mesh, rules=None):
    """device_put the parameter tree with TP shardings applied."""
    sh = tp_shardings(params, mesh, rules)
    return jax.tree_util.tree_map(
        jax.device_put, params, sh, is_leaf=lambda x: not isinstance(x, dict)
    )


def count_sharded(params, mesh: Mesh, rules=None) -> int:
    """How many leaves actually get a non-replicated spec (introspection)."""
    sh = flatten_dict(tp_shardings(params, mesh, rules), sep="/")
    return sum(1 for s in sh.values() if s.spec != P())
