"""The partitioned-program registry: ONE place where XLA compilation happens.

Before this module, four subsystems each re-invented the same compile
ritual — ``jax.jit(fn, donate_argnums=...).lower(*shapes).compile()``
under a donation-warning filter, a compile counter bump, a ProgramCard
mint, per-program gauges, and (sometimes) persistent-compile-cache
wiring: the mesh-sharded train step (training/trainer.py), the serve
lattice (serving/engine.py), the style lattice (serving/style.py), and
bench.py. ``ProgramRegistry`` extracts that ritual behind one guarded
entry point:

    (callable, mesh/sharding spec, shape bucket, donation spec)
        -> compiled executable + ProgramCard + compile governance

and jaxlint JL018 makes the guard structural: any ``jax.jit`` reference
or ``.lower().compile()`` chain outside this file is a lint error, so
the zero-steady-state-compiles invariant (JL008's concern) has exactly
one choke point instead of a convention per subsystem.

Governance the registry provides uniformly:

  * **Cache-key semantics** — ``compile()`` keys on (program name, arg
    shape/dtype signature, donation, sharding specs). A repeat request
    returns the SAME ``Compiled`` object without recompiling; the
    registry is the reason "did we already build this program?" has one
    answer instead of four dicts.
  * **Persistent compile cache** — pass ``cache_dir`` (or let a consumer
    thread ``train.obs.compilation_cache_dir`` through) and the
    registry wires jax's persistent cache before its first compile, so
    every consumer — serve replicas, style, bench, the trainer — gets
    the ~1.6 s warm restart, not just whichever CLI remembered to call
    ``enable_compilation_cache``. Hits/requests land per-registry as
    ``jax_persistent_cache_{hits,requests}_total`` in the registry's
    metrics (the ``watch_compiles`` bus bridge).
  * **Cards with shardings** — every compile mints a ProgramCard
    (obs/cost.py) and stores a JSON-ready row that ALSO records the
    mesh geometry and in/out NamedSharding specs the program was built
    against; ``GET /debug/programs`` serves these rows directly, so a
    mesh replica's programs show how they are partitioned.
  * **Sharded AOT** — ``in_shardings``/``out_shardings`` pass straight
    into ``jax.jit``, which is what lets a serve replica BE a mesh
    slice: the engine compiles every lattice point with its batch axis
    over the mesh's ``data`` axis and outputs replicated for host
    readback (serving/engine.py).

``jit_program`` is the sanctioned constructor for jit-on-first-call
wrappers (the trainer's step functions, bench micro-timers, the audio
DSP decorators): a thin alias of ``jax.jit`` that exists so JL018 can
insist the spelling ``jax.jit`` appears nowhere else in the tree.

Precision is a registry concern too: ``cast_params``/``dequant_params``
are the ONE sanctioned path for converting a weight tree between
serving precisions (``f32``/``bf16``/``int8``) — jaxlint JL025 makes
that structural the same way JL018 does for compiles, so a quantized
program's numerics are auditable in one place. ``compile`` takes a
``precision=`` tag that folds into the cache key and lands on the
ProgramCard row: two programs at the same shape bucket but different
precisions are distinct cache entries, and ``GET /debug/programs``
proves not just WHAT compiled but HOW SMALL.
"""

import contextlib
import functools
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple
from speakingstyle_tpu.obs.locks import make_lock

__all__ = [
    "PRECISIONS",
    "ProgramRegistry",
    "cast_params",
    "dequant_params",
    "jit_program",
    "quiet_donation",
]

# The serving precision axis, widest first. "f32" is the identity tier;
# "bf16" casts float leaves; "int8" stores per-channel symmetric-quantized
# weights that are dequantized to f32 on read inside the compiled program.
PRECISIONS = ("f32", "bf16", "int8")

# Marker keys of the int8 leaf representation: a plain dict holding the
# quantized tensor and its per-channel f32 scale. A dict (not a custom
# pytree node) flows through tree_map / device_put / shardings untouched.
_INT8_KEYS = frozenset(("int8_q", "int8_scale"))


def _is_int8_leaf(x: Any) -> bool:
    return isinstance(x, dict) and set(x.keys()) == set(_INT8_KEYS)


@contextlib.contextmanager
def quiet_donation():
    """CPU (and the int32 length vectors on any backend) cannot always
    honor donation; jax warns per lowering. Donation through the
    registry is best-effort by design — silence exactly that warning."""
    import warnings

    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        yield


def jit_program(fn: Optional[Callable] = None, **jit_kwargs):
    """The sanctioned ``jax.jit`` constructor (usable as a decorator).

    Compile-on-first-call wrappers are legitimate where the shape space
    is unbounded or singular (training steps riding the bucket grid,
    audio DSP over file-length signals); routing their construction
    through the registry module keeps JL018's guarantee meaningful —
    the only file that can spell ``jax.jit`` is this one.
    """
    import jax

    if fn is None:
        return functools.partial(jit_program, **jit_kwargs)
    return jax.jit(fn, **jit_kwargs)


def cast_params(variables: Any, precision: str) -> Any:
    """The sanctioned precision cast: one weight tree in, one serving
    param tree out (jaxlint JL025 forbids spelling this anywhere else).

    * ``"f32"`` — identity (the tree is already the full-precision tier).
    * ``"bf16"`` — every float leaf becomes ``bfloat16``; integer leaves
      (embedding tables' index vectors, step counters) pass through.
    * ``"int8"`` — every float matrix/tensor leaf (ndim >= 2) becomes a
      per-channel symmetric-quantized ``{"int8_q", "int8_scale"}`` pair:
      the scale is ``amax/127`` over all leading axes (one scale per
      output channel, the last axis), weights round-clip into int8, and
      ``dequant_params`` restores f32 on read inside the compiled
      program. Small leaves (biases, LayerNorm vectors, scalars) stay
      f32 — quantizing them saves nothing and costs accuracy.

    Runs on host numpy so param trees can be cast before ``device_put``
    (int8 lives in HBM; dequant happens on-chip at dispatch).
    """
    import jax
    import numpy as np

    if precision not in PRECISIONS:
        raise ValueError(
            f"precision must be one of {PRECISIONS}, got {precision!r}"
        )
    if precision == "f32":
        return variables

    if precision == "bf16":
        import jax.numpy as jnp

        def to_bf16(x):
            arr = np.asarray(x)
            if np.issubdtype(arr.dtype, np.floating):
                return jnp.asarray(arr, jnp.bfloat16)
            return x

        return jax.tree_util.tree_map(to_bf16, variables)

    def to_int8(x):
        arr = np.asarray(x)
        if not np.issubdtype(arr.dtype, np.floating) or arr.ndim < 2:
            return x
        arr = arr.astype(np.float32)
        axes = tuple(range(arr.ndim - 1))
        amax = np.max(np.abs(arr), axis=axes, keepdims=True)
        scale = (amax / 127.0).astype(np.float32)
        scale = np.where(scale == 0.0, np.float32(1.0), scale)
        q = np.clip(np.round(arr / scale), -127, 127).astype(np.int8)
        return {"int8_q": q, "int8_scale": scale}

    return jax.tree_util.tree_map(to_int8, variables)


def dequant_params(variables: Any) -> Any:
    """Restore an ``int8`` param tree to f32 — traceable, so it runs
    INSIDE the compiled program (dequant-on-read: int8 occupies device
    memory, each dispatch widens on-chip). Identity on trees without
    int8 marker leaves, so callers can apply it unconditionally.
    """
    import jax
    import jax.numpy as jnp

    def widen(x):
        if _is_int8_leaf(x):
            return x["int8_q"].astype(jnp.float32) * x["int8_scale"]
        return x

    return jax.tree_util.tree_map(widen, variables, is_leaf=_is_int8_leaf)


def _signature(tree: Any) -> str:
    """Stable hashable shape/dtype signature of an args pytree — the
    shape-bucket component of a program's cache key. Works on
    ShapeDtypeStructs, device/host arrays, and scalars alike."""
    import jax

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return f"{tuple(x.shape)}:{x.dtype}"
        return repr(x)

    return repr(jax.tree_util.tree_map(leaf, tree))


def _sharding_str(sh: Any) -> Optional[str]:
    """Human-readable spelling of a (pytree of) NamedSharding(s) for the
    card table; None passes through (single-device programs)."""
    if sh is None:
        return None
    import jax

    def leaf(s):
        spec = getattr(s, "spec", None)
        return str(spec) if spec is not None else str(s)

    leaves = jax.tree_util.tree_leaves(
        sh, is_leaf=lambda x: hasattr(x, "spec")
    )
    if not leaves:
        return None
    strs = [leaf(s) for s in leaves]
    if len(set(strs)) == 1:
        return strs[0]
    return "(" + ", ".join(strs) + ")"


def _mesh_of(sh: Any) -> Optional[str]:
    """``"2x2"``-style geometry of the first NamedSharding in a spec
    tree (all shardings of one program share the mesh)."""
    if sh is None:
        return None
    import jax

    for s in jax.tree_util.tree_leaves(sh, is_leaf=lambda x: hasattr(x, "spec")):
        mesh = getattr(s, "mesh", None)
        if mesh is not None:
            return "x".join(str(mesh.shape[a]) for a in mesh.axis_names)
    return None


class ProgramRegistry:
    """Compile governance for one consumer (an engine, a style service,
    a trainer run, a bench process).

    Each registry owns: its program + card tables, a compile counter in
    the consumer's ``MetricsRegistry`` (``counter_name`` keeps the
    historical per-subsystem names — ``serve_compiles_total``,
    ``serve_style_compiles_total`` — working), the backend-compile bus
    subscription (``watch_compiles``), and the persistent-cache hookup.
    Sharing one metrics registry across consumers (the fleet does)
    shares the bus counters; the program tables stay per-registry.
    """

    def __init__(
        self,
        metrics=None,
        *,
        cache_dir: Optional[str] = None,
        counter_name: str = "program_registry_compiles_total",
        prefix: str = "program",
    ):
        from speakingstyle_tpu.obs import MetricsRegistry, watch_compiles
        from speakingstyle_tpu.obs.jaxmon import enable_compilation_cache

        self.metrics = metrics if metrics is not None else MetricsRegistry()
        # backend-compile + persistent-cache events -> this registry's
        # metrics (jax_backend_compiles_total,
        # jax_persistent_cache_{hits,requests}_total)
        watch_compiles(self.metrics)
        self.cache_dir = (
            enable_compilation_cache(cache_dir) if cache_dir else None
        )
        self.prefix = prefix
        self._compiles = self.metrics.counter(
            counter_name,
            help="XLA programs compiled through this ProgramRegistry",
        )
        self._lock = make_lock("ProgramRegistry._lock", kind="rlock")
        self._programs: Dict[Tuple, Any] = {}
        self._by_name: Dict[str, Any] = {}
        self._cards: List[Dict] = []

    # -- introspection ------------------------------------------------------

    @property
    def compile_count(self) -> int:
        return int(self._compiles.value)

    def __len__(self) -> int:
        with self._lock:
            return len(self._programs)

    def get(self, name: str):
        """Latest compiled executable registered under ``name`` (None if
        never compiled) — the lookup consumers key their dispatch tables
        from when they don't hold the executable themselves."""
        with self._lock:
            return self._by_name.get(name)

    def programs(self) -> List[Dict]:
        """The card table: one JSON-ready row per compiled program, in
        compile order, each carrying the ProgramCard cost analysis plus
        the mesh/sharding specs it was built against (the
        ``GET /debug/programs`` payload)."""
        with self._lock:
            return [dict(row) for row in self._cards]

    # -- the single compile entry point -------------------------------------

    def compile(
        self,
        fn: Callable,
        args: Tuple,
        *,
        name: str,
        donate_argnums: Tuple[int, ...] = (),
        static_argnums=None,
        in_shardings=None,
        out_shardings=None,
        compiler_options: Optional[Dict] = None,
        labels: Optional[Dict[str, str]] = None,
        precision: str = "f32",
    ):
        """(callable, sharding spec, shape bucket, donation spec) ->
        compiled executable, with the bookkeeping done.

        ``args`` is the AOT argument tuple — ``jax.ShapeDtypeStruct``s
        or concrete arrays (concrete works because lowering only reads
        shape/dtype/sharding). The cache key is (name, args signature,
        donation, sharding specs): a repeat call returns the stored
        ``Compiled`` without recompiling, so "precompile twice" and
        "two consumers ask for the same bucket" both cost one program.

        ``fn`` may already be a jit wrapper (``jit_program`` output, the
        trainer's case) — it is lowered as-is and the jit construction
        kwargs must then be () / None.

        ``precision`` tags which tier of the precision axis this program
        serves (``f32``/``bf16``/``int8``); it folds into the cache key
        (same bucket, different precision = different program) and onto
        the card row.
        """
        import jax

        if precision not in PRECISIONS:
            raise ValueError(
                f"precision must be one of {PRECISIONS}, got {precision!r}"
            )
        key = (
            name,
            _signature(args),
            repr(donate_argnums),
            repr(static_argnums),
            _sharding_str(in_shardings),
            _sharding_str(out_shardings),
            precision,
        )
        with self._lock:
            exe = self._programs.get(key)
            if exe is not None:
                return exe
            if hasattr(fn, "lower") and not isinstance(fn, type):
                # already a jit wrapper — lower it directly
                jitted = fn
            else:
                kwargs: Dict[str, Any] = {"donate_argnums": donate_argnums}
                if static_argnums is not None:
                    kwargs["static_argnums"] = static_argnums
                if in_shardings is not None:
                    kwargs["in_shardings"] = in_shardings
                if out_shardings is not None:
                    kwargs["out_shardings"] = out_shardings
                jitted = jax.jit(fn, **kwargs)
            with quiet_donation():
                lowered = jitted.lower(*args)
                exe = (
                    # jaxlint: disable=JL021 reason=the registry lock deliberately serializes all XLA compiles; this is the one sanctioned compile entry point
                    lowered.compile(compiler_options=compiler_options)
                    if compiler_options
                    # jaxlint: disable=JL021 reason=the registry lock deliberately serializes all XLA compiles; this is the one sanctioned compile entry point
                    else lowered.compile()
                )
            self._compiles.inc()
            self._programs[key] = exe
            self._by_name[name] = exe
            self._record(exe, name, donate_argnums, in_shardings,
                         out_shardings, labels, precision)
        return exe

    def _record(self, exe, name, donate, in_sh, out_sh, labels,
                precision="f32") -> None:
        """Mint the ProgramCard, publish gauges, append the card row.
        Caller holds the lock. Card minting only reads compiler metadata
        — it can never itself compile."""
        from speakingstyle_tpu.obs.cost import (
            ProgramCard,
            publish_program_gauges,
        )

        card = ProgramCard.from_compiled(exe, name=name)
        publish_program_gauges(
            self.metrics, card, self.prefix, labels=labels or {}
        )
        row = card.as_dict()
        row["mesh"] = _mesh_of(in_sh) or _mesh_of(out_sh)
        row["in_shardings"] = _sharding_str(in_sh)
        row["out_shardings"] = _sharding_str(out_sh)
        row["donate_argnums"] = list(donate)
        row["precision"] = precision
        if labels:
            row.update({f"label_{k}": v for k, v in labels.items()})
        self._cards.append(row)

    def card(self, name: str) -> Optional[Dict]:
        """The most recent card row registered under ``name``."""
        with self._lock:
            for row in reversed(self._cards):
                if row.get("name") == name:
                    return dict(row)
        return None
