"""Device mesh construction and sharding helpers.

The TPU-native replacement for the reference's process/device plumbing
(reference: train.py:45 nn.DataParallel; hifigan/train.py:25-27 NCCL DDP):
a `jax.sharding.Mesh` with named axes and `NamedSharding` annotations — XLA
inserts the collectives (gradient psum over ICI) that NCCL provided.

Axes:
  data  — batch sharding (pure DP; the reference's only strategy)
  model — tensor parallelism degree (1 by default; reserved for scaling)
  seq   — sequence parallelism for ring attention (long-context path)
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    data: int = -1,
    model: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data, model) mesh. data=-1 consumes all remaining devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        if n % model:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(f"data*model = {data}*{model} != {n} devices")
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, ("data", "model"))


def make_seq_mesh(seq: int = -1, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh for sequence-parallel (ring attention) execution."""
    devices = list(devices if devices is not None else jax.devices())
    if seq == -1:
        seq = len(devices)
    arr = np.asarray(devices[:seq]).reshape(seq)
    return Mesh(arr, ("seq",))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis (batch) sharding over the data axis."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dispatch_sharding(mesh: Mesh, b: int) -> NamedSharding:
    """Batch-leading sharding for one serving dispatch at batch size
    ``b``: rows over ``data`` when they divide evenly, replicated
    otherwise. The compile side (``in_shardings``/``out_shardings``) and
    the dispatch side (``device_put``) must both call THIS function —
    AOT executables hard-error on mismatched input shardings, which is
    exactly the shape/sharding discipline serving wants."""
    if b % mesh.shape["data"] == 0:
        return NamedSharding(mesh, P("data"))
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Device-put every array in a pytree with its batch axis over `data`."""
    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)


class BatchShardingError(ValueError):
    """Global batch size incompatible with the mesh's ``data`` axis.

    Raised at startup (config/mesh resolution time), before any compile or
    device transfer, so a bad ``train.optimizer.batch_size`` /
    ``train.parallel.mesh`` pairing fails with the fix in the message
    instead of an opaque GSPMD shape error mid-run.
    """


def _mesh_shape_str(mesh: Mesh) -> str:
    return "x".join(str(mesh.shape[a]) for a in mesh.axis_names)


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    """Per-shard batch rows for a ``data``-sharded global batch.

    A global batch not divisible by ``dp`` has no defined sharding; the
    structured error names the batch, the mesh shape, and the two nearest
    valid batch sizes.
    """
    n_data = mesh.shape["data"]
    if global_batch % n_data:
        lo = (global_batch // n_data) * n_data
        hi = lo + n_data
        nearest = f"{lo} or {hi}" if lo > 0 else str(hi)
        raise BatchShardingError(
            f"global batch {global_batch} is not divisible by the mesh's "
            f"data axis dp={n_data} (mesh {_mesh_shape_str(mesh)} over axes "
            f"{tuple(mesh.axis_names)}); nearest valid batch sizes: {nearest}"
        )
    return global_batch // n_data


def resolve_mesh(parallel, devices: Optional[Sequence] = None) -> Optional[Mesh]:
    """``train.parallel.*`` / ``serve.parallel.*`` -> ``Mesh`` (or
    ``None`` for the single-chip path).

    ``mesh=[1,1]`` with ``seq=1`` returns ``None`` — the consumer then runs
    its unchanged single-chip path. ``dp=-1`` consumes every device not
    claimed by ``tp``. Asking for more devices than exist raises with the
    counts named (on the CPU proxy, set
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``).
    """
    if parallel.is_single():
        return None
    devices = list(devices if devices is not None else jax.devices())
    dp, tp = parallel.mesh
    if dp == -1:
        if len(devices) % tp:
            raise ValueError(
                f"parallel.mesh [-1, {tp}]: {len(devices)} devices "
                f"not divisible by tp={tp}"
            )
        dp = len(devices) // tp
    n = dp * tp
    if n > len(devices):
        raise ValueError(
            f"parallel.mesh {dp}x{tp} needs {n} devices but only "
            f"{len(devices)} are visible (CPU proxy: set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n})"
        )
    return make_mesh(data=dp, model=tp, devices=devices[:n])
