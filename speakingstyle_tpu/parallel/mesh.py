"""Device mesh construction and sharding helpers.

The TPU-native replacement for the reference's process/device plumbing
(reference: train.py:45 nn.DataParallel; hifigan/train.py:25-27 NCCL DDP):
a `jax.sharding.Mesh` with named axes and `NamedSharding` annotations — XLA
inserts the collectives (gradient psum over ICI) that NCCL provided.

Axes:
  data  — batch sharding (pure DP; the reference's only strategy)
  model — tensor parallelism degree (1 by default; reserved for scaling)
  seq   — sequence parallelism for ring attention (long-context path)
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    data: int = -1,
    model: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data, model) mesh. data=-1 consumes all remaining devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        if n % model:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(f"data*model = {data}*{model} != {n} devices")
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, ("data", "model"))


def make_seq_mesh(seq: int = -1, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh for sequence-parallel (ring attention) execution."""
    devices = list(devices if devices is not None else jax.devices())
    if seq == -1:
        seq = len(devices)
    arr = np.asarray(devices[:seq]).reshape(seq)
    return Mesh(arr, ("seq",))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis (batch) sharding over the data axis."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Device-put every array in a pytree with its batch axis over `data`."""
    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    n_data = mesh.shape["data"]
    if global_batch % n_data:
        raise ValueError(f"global batch {global_batch} not divisible by data={n_data}")
    return global_batch // n_data
