"""Parallelism: device meshes, shardings, the partitioned-program
registry, and sequence-parallel attention."""

from speakingstyle_tpu.parallel.mesh import (
    BatchShardingError,
    batch_sharding,
    dispatch_sharding,
    local_batch_size,
    make_mesh,
    make_seq_mesh,
    replicated,
    resolve_mesh,
    shard_batch,
)
from speakingstyle_tpu.parallel.registry import (
    ProgramRegistry,
    jit_program,
    quiet_donation,
)
from speakingstyle_tpu.parallel.ring_attention import ring_attention, ring_self_attention

__all__ = [
    "BatchShardingError",
    "ProgramRegistry",
    "jit_program",
    "quiet_donation",
    "make_mesh",
    "make_seq_mesh",
    "batch_sharding",
    "dispatch_sharding",
    "replicated",
    "resolve_mesh",
    "shard_batch",
    "local_batch_size",
    "ring_attention",
    "ring_self_attention",
]
