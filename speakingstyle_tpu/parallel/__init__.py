"""Parallelism: device meshes, shardings, and sequence-parallel attention."""

from speakingstyle_tpu.parallel.mesh import (
    batch_sharding,
    local_batch_size,
    make_mesh,
    make_seq_mesh,
    replicated,
    shard_batch,
)
from speakingstyle_tpu.parallel.ring_attention import ring_attention, ring_self_attention

__all__ = [
    "make_mesh",
    "make_seq_mesh",
    "batch_sharding",
    "replicated",
    "shard_batch",
    "local_batch_size",
    "ring_attention",
    "ring_self_attention",
]
