"""Parallelism: device meshes, shardings, and sequence-parallel attention."""

from speakingstyle_tpu.parallel.mesh import (
    BatchShardingError,
    batch_sharding,
    local_batch_size,
    make_mesh,
    make_seq_mesh,
    replicated,
    resolve_mesh,
    shard_batch,
)
from speakingstyle_tpu.parallel.ring_attention import ring_attention, ring_self_attention

__all__ = [
    "BatchShardingError",
    "make_mesh",
    "make_seq_mesh",
    "batch_sharding",
    "replicated",
    "resolve_mesh",
    "shard_batch",
    "local_batch_size",
    "ring_attention",
    "ring_self_attention",
]
