"""Test harness: force an 8-device virtual CPU mesh.

Must run before any JAX backend initialization. The JAX analogue of a fake
multi-device backend (the reference has no such thing — SURVEY.md §4): all
sharding/collective tests run on 8 virtual CPU devices.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

# Override any ambient accelerator plugin (e.g. a tunneled TPU registered by
# sitecustomize) — unit tests are CPU-only by design.
jax.config.update("jax_platforms", "cpu")
