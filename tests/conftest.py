"""Test harness: force an 8-device virtual CPU mesh.

Must run before any JAX backend initialization. The JAX analogue of a fake
multi-device backend (the reference has no such thing — SURVEY.md §4): all
sharding/collective tests run on 8 virtual CPU devices.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

# Override any ambient accelerator plugin (e.g. a tunneled TPU registered by
# sitecustomize) — unit tests are CPU-only by design.
jax.config.update("jax_platforms", "cpu")

import json

import numpy as np
import pytest


@pytest.fixture
def synthetic_preprocessed(tmp_path):
    """A tiny on-disk preprocessed dataset in the reference layout
    (mel/pitch/energy/duration .npy + metadata + speakers/stats json)."""
    root = tmp_path / "preprocessed"
    for kind in ("mel", "pitch", "energy", "duration"):
        (root / kind).mkdir(parents=True)
    rng = np.random.default_rng(0)
    lines = []
    n_items = 13
    for i in range(n_items):
        basename, speaker = f"utt{i:03d}", "LJSpeech"
        n_ph = int(rng.integers(5, 40))
        durations = rng.integers(1, 8, size=n_ph)
        n_frames = int(durations.sum())
        np.save(root / "mel" / f"{speaker}-mel-{basename}.npy",
                rng.standard_normal((n_frames, 80)).astype(np.float32))
        np.save(root / "pitch" / f"{speaker}-pitch-{basename}.npy",
                rng.standard_normal(n_ph).astype(np.float32))
        np.save(root / "energy" / f"{speaker}-energy-{basename}.npy",
                rng.standard_normal(n_ph).astype(np.float32))
        np.save(root / "duration" / f"{speaker}-duration-{basename}.npy",
                durations.astype(np.int64))
        phones = " ".join(rng.choice(["AH0", "K", "T", "EH1", "sp"], n_ph))
        lines.append(f"{basename}|{speaker}|{{{phones}}}|dummy text {i}")
    (root / "train.txt").write_text("\n".join(lines[:10]) + "\n")
    (root / "val.txt").write_text("\n".join(lines[10:]) + "\n")
    (root / "speakers.json").write_text(json.dumps({"LJSpeech": 0}))
    (root / "stats.json").write_text(json.dumps({
        "pitch": [-2.5, 9.0, 0.0, 1.0], "energy": [-1.5, 8.0, 0.0, 1.0],
    }))
    return str(root)
