"""Data pipeline tests: loading, sort-group collate, bucketing, prefetch."""

import dataclasses

import numpy as np

from speakingstyle_tpu.configs.config import PathConfig, load_config
from speakingstyle_tpu.data import (
    BucketedBatcher,
    DevicePrefetcher,
    SpeechDataset,
    TextBatcher,
    bucket_length,
)


def _config(root, batch_size=4):
    cfg = load_config(preset="LJSpeech")
    pp = dataclasses.replace(cfg.preprocess, path=PathConfig(preprocessed_path=root))
    opt = dataclasses.replace(cfg.train.optimizer, batch_size=batch_size)
    tr = dataclasses.replace(cfg.train, optimizer=opt)
    return dataclasses.replace(cfg, preprocess=pp, train=tr)


def test_bucket_length():
    assert bucket_length(1, 32) == 32
    assert bucket_length(32, 32) == 32
    assert bucket_length(33, 32) == 64
    assert bucket_length(999, 128, max_len=1000) == 1000


def test_dataset_items(synthetic_preprocessed):
    ds = SpeechDataset("train.txt", _config(synthetic_preprocessed))
    assert len(ds) == 10
    item = ds[0]
    assert item["mel"].shape[1] == 80
    assert item["duration"].sum() == item["mel"].shape[0]
    assert len(item["pitch"]) == len(item["text"]) == len(item["duration"])
    assert item["text"].dtype == np.int32 and (item["text"] > 0).all()


def test_batcher_static_shapes_and_sort(synthetic_preprocessed):
    cfg = _config(synthetic_preprocessed)
    ds = SpeechDataset("train.txt", cfg, sort=True, drop_last=False)
    batcher = BucketedBatcher(ds, src_bucket=32, mel_bucket=128)
    batches = list(batcher.epoch(shuffle=False))
    assert sum(len(b.ids) for b in batches) == 10
    for b in batches:
        B, L_src = b.texts.shape
        assert L_src % 32 == 0
        assert b.mels.shape[1] % 128 == 0
        assert b.mels.shape[2] == 80
        # sorted descending within each batch
        assert (np.diff(b.src_lens) <= 0).all()
        # durations sum to mel length per item
        for i in range(B):
            assert b.durations[i].sum() == b.mel_lens[i]
            # padding is zero beyond src_len
            assert (b.texts[i, b.src_lens[i]:] == 0).all()


def test_batcher_truncation_keeps_duration_sum(synthetic_preprocessed):
    cfg = _config(synthetic_preprocessed)
    ds = SpeechDataset("train.txt", cfg)
    batcher = BucketedBatcher(ds, src_bucket=16, mel_bucket=32, max_mel=32)
    for b in batcher.epoch(shuffle=False):
        assert b.mels.shape[1] <= 32
        for i in range(len(b.ids)):
            assert b.durations[i].sum() == b.mel_lens[i] <= 32


def test_src_truncation_shrinks_mel_len(synthetic_preprocessed):
    """When max_src drops phonemes, mel_len must shrink to the frames still
    covered so sum(duration) == mel_len holds for every item."""
    cfg = _config(synthetic_preprocessed)
    ds = SpeechDataset("train.txt", cfg)
    batcher = BucketedBatcher(ds, src_bucket=4, mel_bucket=16, max_src=4)
    for b in batcher.epoch(shuffle=False):
        for i in range(len(b.ids)):
            assert b.durations[i].sum() == b.mel_lens[i]
            assert b.src_lens[i] <= 4


def test_infinite_iter_reshuffles(synthetic_preprocessed):
    cfg = _config(synthetic_preprocessed)
    ds = SpeechDataset("train.txt", cfg)
    batcher = BucketedBatcher(ds, seed=7)
    it = iter(batcher)
    seen = [next(it).ids for _ in range(8)]  # > 1 epoch of 3 batches
    assert len(seen) == 8  # stream does not exhaust


def test_device_prefetcher(synthetic_preprocessed):
    cfg = _config(synthetic_preprocessed)
    ds = SpeechDataset("train.txt", cfg)
    batcher = BucketedBatcher(ds)
    pf = DevicePrefetcher(batcher.epoch(shuffle=False), mesh=None)
    batch, arrays = next(pf)
    assert set(arrays) >= {"texts", "mels", "durations"}
    assert arrays["mels"].shape[0] == len(batch.ids)
    pf.stop()


def test_text_batcher(synthetic_preprocessed, tmp_path):
    cfg = _config(synthetic_preprocessed)
    src = tmp_path / "source.txt"
    src.write_text("utt000|LJSpeech|{AH0 K T}|hello\n")
    tb = TextBatcher(str(src), cfg)
    item = tb[0]
    assert item["text"].shape == (3,)
    assert item["mel"] is not None  # found the preprocessed mel for style
