"""Fleet serving + chunked streaming (tier-1).

Four layers, mirroring the new subsystem:
  1. streaming math — receptive field, window plan (no jax);
  2. router scheduling — EDF ordering under contention, shed-vs-reject
     counter split, watermark hysteresis, drain — against fake engines
     (no jax, millisecond-fast);
  3. engine streaming — chunked reassembly equals the non-streaming wav
     bit-exactly modulo the overlap tail, over precompiled buckets only;
  4. multi-replica e2e — tiny real engines behind the router + HTTP
     server: readiness 503 -> 200, chunked /synthesize/stream, and the
     acceptance invariant that steady-state fleet serving performs ZERO
     XLA compiles on any replica.
"""

import dataclasses
import http.client
import json
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    FleetConfig,
    ModelConfig,
    ReferenceEncoderConfig,
    ServeConfig,
    StyleConfig,
    TransformerConfig,
    VarianceEmbeddingConfig,
    VariancePredictorConfig,
)
from speakingstyle_tpu.obs import MetricsRegistry
from speakingstyle_tpu.serving import streaming
from speakingstyle_tpu.serving.batcher import (
    ContinuousBatcher,
    Overloaded,
    ShutdownError,
)
from speakingstyle_tpu.serving.engine import CompileMonitor, SynthesisRequest
from speakingstyle_tpu.serving.fleet import (
    DRAINING,
    READY,
    STOPPED,
    WARMING,
    FleetRouter,
)
from speakingstyle_tpu.serving.lattice import BucketLattice, RequestTooLarge

# ---------------------------------------------------------------------------
# streaming math (no jax)
# ---------------------------------------------------------------------------


def test_receptive_field_tiny_and_flagship():
    from speakingstyle_tpu.models.hifigan import Generator

    tiny = Generator(
        upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4),
        upsample_initial_channel=16, resblock_kernel_sizes=(3,),
        resblock_dilation_sizes=((1,),),
    )
    flagship = Generator()
    rf_tiny = streaming.receptive_field_frames(tiny)
    rf_flag = streaming.receptive_field_frames(flagship)
    assert 0 < rf_tiny < rf_flag  # more stages + bigger kernels = wider
    assert rf_flag < 64           # and still far below a lattice bucket
    # resolve_overlap: explicit config wins, 0 derives
    assert streaming.resolve_overlap(5, tiny) == 5
    assert streaming.resolve_overlap(0, tiny) == rf_tiny


def test_stream_plan_covers_exactly_once():
    for mel_len, window, overlap in [(24, 8, 7), (1, 8, 3), (17, 5, 2),
                                     (40, 40, 10)]:
        spans = list(streaming.stream_plan(mel_len, window, overlap))
        # emitted spans tile [0, mel_len) without gap or overlap
        assert spans[0][0] == 0 and spans[-1][1] == mel_len
        for (s0, e0, lo, hi), (s1, _, _, _) in zip(spans, spans[1:]):
            assert e0 == s1
        for s, e, lo, hi in spans:
            assert lo <= max(0, s - overlap) or lo == 0
            assert 0 <= lo <= s < e <= hi <= mel_len
    assert list(streaming.stream_plan(0, 8, 4)) == []


# ---------------------------------------------------------------------------
# router scheduling (fake engines — no jax)
# ---------------------------------------------------------------------------


def _fleet_cfg(**fleet_kw):
    fleet = dict(queue_depth=32, stream_window=8)
    fleet.update(fleet_kw)
    return Config(serve=ServeConfig(
        batch_buckets=[1], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=5.0,
        fleet=FleetConfig(**fleet),
    ))


class FakeFleetEngine:
    """Replica stand-in: records dispatch order, optional gate."""

    def __init__(self, gate=None):
        self.dispatches = []      # request ids, in dispatch order
        self.gate = gate          # Event blocking the FIRST dispatch
        self.entered = threading.Event()
        self._first = True
        self.lock = threading.Lock()

    def precompile(self):
        return 0.0

    def run(self, requests):
        if self.gate is not None and self._first:
            self._first = False
            self.entered.set()
            self.gate.wait(timeout=10)
        with self.lock:
            self.dispatches.extend(r.id for r in requests)
        return [SimpleNamespace(id=r.id, bucket=None, mel_len=1)
                for r in requests]


def _req(i, L=8, T=4, **kw):
    return SynthesisRequest(
        id=f"r{i}", sequence=np.ones(L, np.int32),
        ref_mel=np.zeros((T, 80), np.float32), **kw,
    )


def test_router_edf_ordering_under_contention():
    """Interactive requests admitted AFTER a batch backlog still dispatch
    first: the pending heap orders by SLO deadline, not arrival."""
    gate = threading.Event()
    eng = FakeFleetEngine(gate=gate)
    router = FleetRouter(lambda reg: eng, _fleet_cfg(), replicas=1)
    assert router.wait_ready(timeout=10)
    futs = [router.submit(_req(0))]              # occupies the worker
    assert eng.entered.wait(timeout=10)
    # backlog: batch first, interactive afterwards — interactive still wins
    futs.append(router.submit(_req(1, priority="batch")))
    futs.append(router.submit(_req(2, priority="batch")))
    futs.append(router.submit(_req(3, priority="interactive")))
    futs.append(router.submit(_req(4, priority="interactive")))
    gate.set()
    for f in futs:
        f.result(timeout=10)
    router.close()
    # r0 was in flight; then EDF: interactive (earlier deadlines) before
    # batch, FIFO within a class
    assert eng.dispatches == ["r0", "r3", "r4", "r1", "r2"]


def test_router_shed_vs_reject_counters():
    """Backpressure sheds count serve_shed_total and raise Overloaded
    (429 + Retry-After); shutdown refusals count serve_rejected_total and
    raise ShutdownError — never the same counter."""
    reg = MetricsRegistry()
    gate = threading.Event()

    def factory(registry):
        gate.wait(timeout=30)   # hold the replica in WARMING: no dispatch
        return FakeFleetEngine()

    cfg = _fleet_cfg(queue_depth=4, shed_high_watermark=0.5,
                     shed_low_watermark=0.25, shed_retry_after_s=3.0)
    router = FleetRouter(factory, cfg, replicas=1, registry=reg)
    assert router.states() == {0: WARMING}
    futs, sheds = [], 0
    for i in range(6):
        try:
            futs.append(router.submit(_req(i)))
        except Overloaded as e:
            sheds += 1
            assert e.retry_after_s == 3.0
    assert sheds == 4  # depth 2 = high watermark of a 4-deep queue
    snap = reg.snapshot()["counters"]
    assert snap["serve_shed_total"] == 4
    assert snap["serve_rejected_total"] == 0
    gate.set()
    router.close(flush=False)
    with pytest.raises(ShutdownError):
        router.submit(_req(99))
    snap = reg.snapshot()["counters"]
    assert snap["serve_rejected_total"] == 1
    assert snap["serve_shed_total"] == 4  # unchanged by shutdown
    for f in futs:  # pending futures failed, not stranded
        assert isinstance(f.exception(timeout=5), ShutdownError)


def test_router_admission_validates_class_and_geometry():
    router = FleetRouter(lambda reg: FakeFleetEngine(), _fleet_cfg(),
                         replicas=1)
    with pytest.raises(ValueError, match="priority class"):
        router.submit(_req(0, priority="best-effort"))
    with pytest.raises(RequestTooLarge):
        router.submit(_req(1, L=17))  # src bucket max 16
    router.close()


def test_router_scale_to_drains_replicas():
    eng0, eng1 = FakeFleetEngine(), FakeFleetEngine()
    engines = [eng0, eng1]
    router = FleetRouter(lambda reg: engines.pop(0), _fleet_cfg(),
                         replicas=2)
    assert router.wait_ready(timeout=10, n=2)
    router.scale_to(1)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        states = router.states()
        if states[1] in (DRAINING, STOPPED) and states[0] == READY:
            break
        time.sleep(0.01)
    assert router.states()[0] == READY
    assert router.states()[1] in (DRAINING, STOPPED)
    # the surviving replica still serves
    assert router.submit(_req(5)).result(timeout=10).id == "r5"
    router.close()
    assert all(s == STOPPED for s in router.states().values())


class _FakeBatcherEngine:
    """Minimal duck-typed engine for ContinuousBatcher (gate-able)."""

    class _Cfg:
        def __init__(self, serve):
            self.serve = serve

    def __init__(self, serve, gate=None):
        self.cfg = self._Cfg(serve)
        self.lattice = BucketLattice.from_config(serve)
        self.gate = gate
        self.entered = threading.Event()
        self._first = True

    def admit(self, request):
        self.lattice.cover(1, len(request.sequence), 1)

    def run(self, requests):
        if self.gate is not None and self._first:
            self._first = False
            self.entered.set()
            self.gate.wait(timeout=10)
        return [SimpleNamespace(id=r.id, bucket=None) for r in requests]


def test_batcher_shed_split_from_shutdown_reject():
    """The single-engine batcher carries the same split: watermark sheds
    raise Overloaded + count serve_shed_total; shutdown refusals raise
    ShutdownError + count serve_rejected_total."""
    gate = threading.Event()
    serve = ServeConfig(
        batch_buckets=[1, 2, 4], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=5.0, queue_depth=4,
    )
    eng = _FakeBatcherEngine(serve, gate=gate)
    b = ContinuousBatcher(eng)
    first = b.submit(_req(0, T=1))
    assert eng.entered.wait(timeout=5)   # worker busy: queue accumulates
    sheds = 0
    for i in range(1, 6):
        try:
            b.submit(_req(i, T=1))
        except Overloaded:
            sheds += 1
    assert sheds > 0
    assert b.shed == sheds
    rejected_before = b.rejected
    gate.set()
    b.close()
    with pytest.raises(ShutdownError):
        b.submit(_req(99, T=1))
    assert b.rejected == rejected_before + 1
    assert b.shed == sheds  # shutdown does not touch the shed counter
    first.result(timeout=5)


# ---------------------------------------------------------------------------
# engine streaming + multi-replica e2e (tiny model, real jax)
# ---------------------------------------------------------------------------


def _tiny_cfg(**fleet_kw):
    fleet = dict(stream_window=8, queue_depth=32)
    fleet.update(fleet_kw)
    return Config(
        model=ModelConfig(
            transformer=TransformerConfig(
                encoder_layer=1, decoder_layer=1, encoder_hidden=16,
                decoder_hidden=16, conv_filter_size=16,
                conv_kernel_size=(3, 1),
            ),
            reference_encoder=ReferenceEncoderConfig(
                encoder_layer=1, encoder_head=2, encoder_hidden=16,
                conv_layer=1, conv_filter_size=16,
            ),
            variance_predictor=VariancePredictorConfig(filter_size=16),
            variance_embedding=VarianceEmbeddingConfig(n_bins=8),
            postnet_embedding_dim=16, postnet_layers=2,
            max_seq_len=48, compute_dtype="float32",
        ),
        serve=ServeConfig(
            batch_buckets=[1, 2], src_buckets=[16], mel_buckets=[32],
            frames_per_phoneme=2, max_wait_ms=20.0,
            fleet=FleetConfig(**fleet),
            style=StyleConfig(ref_buckets=[32]),
        ),
    )


@pytest.fixture(scope="module")
def tiny_parts():
    """Model/weights/vocoder built once; engines (which own the compiled
    programs) are constructed per test/replica from these."""
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.models.hifigan import Generator

    cfg = _tiny_cfg()
    model = build_model(cfg, n_position=49)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    bias = variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"]
    variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"] = bias + 1.1
    gen = Generator(
        upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4),
        upsample_initial_channel=16, resblock_kernel_sizes=(3,),
        resblock_dilation_sizes=((1,),),
    )
    gparams = gen.init(
        jax.random.PRNGKey(0), np.zeros((1, 8, 80), np.float32)
    )["params"]
    return cfg, model, variables, gen, gparams


@pytest.fixture(scope="module")
def tiny_fleet_engine(tiny_parts):
    """One precompiled tiny engine shared by the streaming tests."""
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg, model, variables, gen, gparams = tiny_parts
    engine = SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                             model=model)
    engine.precompile()
    return engine


def _mkreq(i, L=10, T=20, **kw):
    rng = np.random.default_rng(i)
    return SynthesisRequest(
        id=f"utt{i}",
        sequence=rng.integers(1, 300, L).astype(np.int32),
        ref_mel=rng.standard_normal((T, 80)).astype(np.float32),
        **kw,
    )


def test_stream_reassembly_bit_exact_modulo_overlap(tiny_fleet_engine):
    """Chunked windows concatenated == the non-streaming wav, bit for
    bit, up to the final overlap tail (where the full vocode sees the
    acoustic model's past-end free-run frames and the stream sees
    silence) — and the whole stream performs ZERO compiles."""
    engine = tiny_fleet_engine
    gen, _ = engine.vocoder
    hop = gen.hop_factor
    window = engine.cfg.serve.fleet.stream_window
    overlap = streaming.resolve_overlap(
        engine.cfg.serve.fleet.stream_overlap, gen
    )
    full = engine.run([_mkreq(40)])[0]
    sres = engine.run([_mkreq(40, stream=True)])[0]
    assert sres.wav is None and sres.mel_len == full.mel_len
    with CompileMonitor() as mon:
        chunks = list(streaming.stream_wav(engine, sres, window, overlap))
    assert mon.count == 0, "streaming compiled in steady state"
    assert len(chunks) == -(-full.mel_len // window)
    wav = np.concatenate(chunks)
    assert wav.dtype == np.int16 and wav.shape == (full.mel_len * hop,)
    head = (full.mel_len - overlap) * hop
    assert head > 0
    np.testing.assert_array_equal(wav[:head], full.wav[:head])


def test_vocode_window_rejects_bad_shapes(tiny_fleet_engine):
    with pytest.raises(ValueError, match="mel window"):
        tiny_fleet_engine.vocode_window(np.zeros((4, 3), np.float32))
    with pytest.raises(RequestTooLarge):
        tiny_fleet_engine.vocode_window(np.zeros((33, 80), np.float32))


def test_multi_replica_e2e_zero_steady_state_compiles(tiny_parts):
    """The acceptance invariant at fleet scale: two replicas, mixed
    stream/non-stream traffic, and after per-replica warmup the backend
    monitoring bus sees ZERO compiles — each replica serves purely from
    its own precompiled lattice."""
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg, model, variables, gen, gparams = tiny_parts
    reg = MetricsRegistry()

    def factory(registry):
        return SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                               model=model, registry=registry)

    with FleetRouter(factory, cfg, replicas=2, registry=reg) as router:
        assert router.wait_ready(timeout=300, n=2)
        engines = router.engines()
        assert len(engines) == 2
        for engine in engines:
            assert engine.is_ready
            # warmup: first-execution transfer per batch bucket (the
            # compiles all happened in precompile)
            for b in engine.lattice.batch_buckets:
                engine.run([_mkreq(800 + b * 10 + j) for j in range(b)])
        compiles_before = [len(e._acoustic) + len(e._vocoder_exe)
                           for e in engines]
        total_before = reg.value("serve_compiles_total")
        with CompileMonitor() as mon:
            futs = [router.submit(_mkreq(i, stream=(i % 2 == 0)))
                    for i in range(8)]
            results = [f.result(timeout=120) for f in futs]
            for i, r in enumerate(results):
                assert r.id == f"utt{i}"
                if i % 2 == 0:
                    t0 = time.monotonic()
                    wav = np.concatenate(
                        list(router.stream(r, arrival=t0)))
                    assert wav.shape == (r.mel_len * 4,)
                else:
                    assert r.wav is not None
        assert mon.count == 0, "the fleet compiled after warmup"
        # per replica: the program tables did not grow
        assert [len(e._acoustic) + len(e._vocoder_exe)
                for e in engines] == compiles_before
        assert reg.value("serve_compiles_total") == total_before
        # both replicas actually served work and TTFA was recorded
        snap = reg.snapshot()["counters"]
        served = [v for k, v in snap.items()
                  if k.startswith("serve_replica_requests_total")]
        assert sum(served) >= 8
        assert reg.histogram("serve_ttfa_seconds").count >= 4
    assert all(s == STOPPED for s in router.states().values())


def test_fleet_http_readiness_streaming_and_drain(tiny_parts):
    """HTTP layer over the router: /healthz is 503 with replica states
    while warming and 200 once ready; /synthesize/stream returns chunked
    audio/wav whose PCM reassembles to the batch wav; shutdown drains
    in-flight streams."""
    from speakingstyle_tpu.serving.engine import SynthesisEngine
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    cfg, model, variables, gen, gparams = tiny_parts
    gate = threading.Event()

    def factory(registry):
        gate.wait(timeout=60)
        return SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                               model=model, registry=registry)

    router = FleetRouter(factory, cfg, replicas=1,
                         registry=MetricsRegistry())
    ref = np.random.default_rng(0).standard_normal((20, 80)).astype(np.float32)
    server = SynthesisServer(
        frontend=TextFrontend(cfg, ref), host="127.0.0.1", port=0,
        router=router,
    )
    host, port = server.address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=120)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 503 and body["ready"] is False
        assert body["replicas"] == {"0": WARMING}

        gate.set()
        assert router.wait_ready(timeout=300)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200 and body["ready"] is True
        assert body["replicas"] == {"0": READY}
        assert "shed" in body and "rejected" in body

        payload = json.dumps({"text": "stream me", "priority": "batch"})
        conn.request("POST", "/synthesize", body=payload)
        resp = conn.getresponse()
        full = resp.read()
        assert resp.status == 200 and full[:4] == b"RIFF"

        conn.request("POST", "/synthesize/stream", body=payload)
        resp = conn.getresponse()
        streamed = resp.read()  # http.client reassembles the chunks
        assert resp.status == 200
        assert resp.getheader("Transfer-Encoding") == "chunked"
        assert resp.getheader("X-Request-Id")
        assert streamed[:4] == b"RIFF"
        a = np.frombuffer(full[44:], np.int16)
        b = np.frombuffer(streamed[44:], np.int16)
        assert a.shape == b.shape
        overlap = streaming.resolve_overlap(cfg.serve.fleet.stream_overlap,
                                            gen)
        head = len(a) - overlap * gen.hop_factor
        np.testing.assert_array_equal(a[:head], b[:head])
        conn.close()

        # drain: a held stream scope blocks shutdown's drain until
        # released (the SIGTERM contract)
        release = threading.Event()

        def held_stream():
            with server.stream_scope():
                release.wait(timeout=30)

        t = threading.Thread(target=held_stream, daemon=True)
        t.start()
        time.sleep(0.05)
        assert server.drain_streams(timeout=0.1) is False
        release.set()
        t.join(timeout=5)
        assert server.drain_streams(timeout=5) is True
    finally:
        release.set()
        gate.set()
        server.shutdown()


def test_fleet_config_validation():
    with pytest.raises(ValueError, match="watermarks"):
        FleetConfig(shed_high_watermark=0.3, shed_low_watermark=0.5)
    with pytest.raises(ValueError, match="replicas"):
        FleetConfig(replicas=0)
    with pytest.raises(ValueError, match="default_class"):
        FleetConfig(default_class="turbo")
    with pytest.raises(ValueError, match="class_deadline_ms"):
        FleetConfig(class_deadline_ms={"interactive": -1.0})
    with pytest.raises(ValueError, match="stream_window"):
        FleetConfig(stream_window=0)
    # the serve.fleet.* block rides train.yaml like the rest of serve.*
    cfg = FleetConfig(replicas=4, class_deadline_ms={"rt": 50.0},
                      default_class="rt")
    assert cfg.class_deadline_ms["rt"] == 50.0
