"""Tests for the concurrency-soundness engine (jaxlint v2).

Four layers:
  1. the class-concurrency model itself — guarded-by inference
     (with-scope, helper call-through, nested locks, explicit
     acquire/release pairs), thread-reachability, receiver binding;
  2. rule fixtures — JL020–JL023 positive and negative snippets;
  3. the lock-order graph — edge derivation, cycle detection, the
     deterministic total order, and the committed lockorder.json
     staleness contract;
  4. the runtime witness — TrackedLock order-inversion raise, hold /
     contention metrics export, and the make_lock gate.
"""

import json
import textwrap
import threading

import pytest

from speakingstyle_tpu.analysis import concurrency as conc
from speakingstyle_tpu.analysis import linter
from speakingstyle_tpu.obs.locks import LockOrderError, TrackedLock, make_lock
from speakingstyle_tpu.obs.registry import MetricsRegistry

_SERVING_PATH = "speakingstyle_tpu/serving/fake.py"


def _model(source):
    import ast

    return conc.build_module_model(
        _SERVING_PATH, ast.parse(textwrap.dedent(source))
    )


def _codes(source, path=_SERVING_PATH):
    return sorted({f.rule for f in linter.lint_source(
        textwrap.dedent(source), path
    )})


# ---------------------------------------------------------------------------
# the model: guarded-by inference
# ---------------------------------------------------------------------------


def test_with_scope_classifies_sites():
    m = _model("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            def peek(self):
                return self.n
    """)
    cls = m.classes["S"]
    bump = [s for s in cls.methods["bump"].sites if s.attr == "n"]
    assert bump and all("S._lock" in s.locks for s in bump)
    peek = [s for s in cls.methods["peek"].sites if s.attr == "n"]
    assert peek and all(not s.locks for s in peek)


def test_helper_call_through_one_level():
    # every call site of _apply holds the lock -> _apply's sites are
    # analyzed with the lock held at entry
    m = _model("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self._apply()

            def also(self):
                with self._lock:
                    self._apply()

            def _apply(self):
                self.n += 1
    """)
    cls = m.classes["S"]
    assert "S._lock" in cls.methods["_apply"].entry_locks


def test_helper_with_unlocked_call_site_gets_no_entry_locks():
    m = _model("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self._apply()

            def direct(self):
                self._apply()

            def _apply(self):
                self.n += 1
    """)
    assert not m.classes["S"].methods["_apply"].entry_locks


def test_nested_with_holds_both_locks():
    m = _model("""
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.n = 0

            def both(self):
                with self._a:
                    with self._b:
                        self.n += 1
    """)
    site = m.classes["S"].methods["both"].sites[-1]
    assert site.locks == frozenset({"S._a", "S._b"})


def test_explicit_acquire_release_pair_is_method_scope_lock():
    # the RolloutManager idiom: acquire(blocking=False) at the top,
    # release() in a finally — no with-scope, still a critical section
    m = _model("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def op(self):
                if not self._lock.acquire(blocking=False):
                    raise RuntimeError
                try:
                    return 1
                finally:
                    self._lock.release()
    """)
    mm = m.classes["S"].methods["op"]
    assert "S._lock" in mm.manual_locks
    assert "S._lock" in mm.entry_locks
    assert any(a.lock == "S._lock" for a in mm.acquisitions)


def test_thread_reachability_closes_over_self_calls():
    m = _model("""
        import threading

        class S:
            def __init__(self):
                self.t = threading.Thread(target=self._loop, name="w")

            def _loop(self):
                self._step()

            def _step(self):
                pass

            def outside(self):
                pass
    """)
    cls = m.classes["S"]
    assert cls.methods["_loop"].thread_reachable
    assert cls.methods["_step"].thread_reachable
    assert not cls.methods["outside"].thread_reachable


def test_local_receiver_binds_to_unique_declaring_class():
    # rep.state binds to Worker because exactly one class declares
    # ``state`` in __init__ — the fleet's Replica shape
    m = _model("""
        import threading

        class Worker:
            def __init__(self):
                self.state = "cold"

        class Boss:
            def __init__(self):
                self._lock = threading.Lock()

            def flip(self, rep):
                with self._lock:
                    rep.state = "ready"
    """)
    assert m.unique_attr_owner["state"] == "Worker"
    site = [s for s in m.classes["Boss"].methods["flip"].sites
            if s.attr == "state"][0]
    assert site.owner == "@state" and site.is_write


# ---------------------------------------------------------------------------
# JL020 — torn-state races
# ---------------------------------------------------------------------------

_JL020_POS = """
    import threading

    class S:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
            self.t = threading.Thread(target=self._loop, name="w")

        def _loop(self):
            with self._lock:
                self.n += 1

        def peek(self):
            return self.n
"""


def test_jl020_positive_guarded_write_lockfree_read():
    assert "JL020" in _codes(_JL020_POS)


def test_jl020_negative_all_sites_guarded():
    assert "JL020" not in _codes("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                self.t = threading.Thread(target=self._loop, name="w")

            def _loop(self):
                with self._lock:
                    self.n += 1

            def peek(self):
                with self._lock:
                    return self.n
    """)


def test_jl020_negative_written_only_in_init():
    # construction happens-before thread start: a field assigned only in
    # __init__ is immutable shared state, not a torn write
    assert "JL020" not in _codes("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.cfg = 7
                self.n = 0
                self.t = threading.Thread(target=self._loop, name="w")

            def _loop(self):
                with self._lock:
                    self.n += 1
                    x = self.cfg

            def peek(self):
                return self.cfg
    """)


def test_jl020_negative_no_threads():
    src = _JL020_POS.replace(
        'self.t = threading.Thread(target=self._loop, name="w")', "pass"
    )
    assert "JL020" not in _codes(src)


def test_jl020_exempts_events_and_queues():
    assert "JL020" not in _codes("""
        import queue
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._stop = threading.Event()
                self._q = queue.Queue()
                self.t = threading.Thread(target=self._loop, name="w")

            def _loop(self):
                with self._lock:
                    if self._stop.is_set():
                        return
                    self._q.put(1)

            def close(self):
                self._stop.set()
                self._q.put(None)
    """)


def test_jl020_inline_disable_with_reason():
    src = textwrap.dedent(_JL020_POS).replace(
        "return self.n",
        "return self.n  "
        "# jaxlint: disable=JL020 reason=single-reader stamp",
    )
    assert "JL020" not in sorted(
        {f.rule for f in linter.lint_source(src, _SERVING_PATH)}
    )


# ---------------------------------------------------------------------------
# JL021 — blocking under a lock
# ---------------------------------------------------------------------------


def test_jl021_positive_future_result_under_lock():
    assert "JL021" in _codes("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def op(self, fut):
                with self._lock:
                    return fut.result(timeout=5)
    """)


def test_jl021_positive_registry_compile_under_entry_lock():
    # the lock is held by the CALLER — entry-lock inference carries it
    # into the helper making the blocking call
    assert "JL021" in _codes("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.registry = None

            def run(self):
                with self._lock:
                    self._compile()

            def _compile(self):
                return self.registry.compile()
    """)


def test_jl021_negative_blocking_outside_lock():
    assert "JL021" not in _codes("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def op(self, fut):
                with self._lock:
                    n = 1
                return fut.result(timeout=5)
    """)


def test_jl021_negative_condition_wait_releases():
    # Condition.wait on the held lock RELEASES it while parked — the
    # sanctioned pattern, not a convoy
    assert "JL021" not in _codes("""
        import threading

        class S:
            def __init__(self):
                self._cond = threading.Condition()

            def op(self):
                with self._cond:
                    self._cond.wait(timeout=1)
    """)


def test_jl021_positive_event_wait_under_lock():
    assert "JL021" in _codes("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._go = threading.Event()

            def op(self):
                with self._lock:
                    self._go.wait()
    """)


# ---------------------------------------------------------------------------
# JL022 — lock-order cycles + the artifact
# ---------------------------------------------------------------------------

def test_jl022_positive_cross_class_cycle():
    # A holds _la while taking B's _lb; B holds _lb while taking A's
    # _la — the classic two-lock deadlock shape
    assert "JL022" in _codes("""
        import threading

        class A:
            def __init__(self):
                self._la = threading.Lock()
                self.b = B()

            def fwd(self):
                with self._la:
                    self.b.take()

            def grab(self):
                with self._la:
                    pass

        class B:
            def __init__(self):
                self._lb = threading.Lock()
                self.a = A()

            def take(self):
                with self._lb:
                    pass

            def back(self):
                with self._lb:
                    self.a.grab()
    """)


def test_jl022_negative_consistent_order():
    m = _model("""
        import threading

        class A:
            def __init__(self):
                self._la = threading.Lock()
                self.b = B()

            def fwd(self):
                with self._la:
                    self.b.take()

        class B:
            def __init__(self):
                self._lb = threading.Lock()

            def take(self):
                with self._lb:
                    pass
    """)
    edges = conc.lock_edges([m])
    assert ("A._la", "B._lb") in edges
    assert conc.find_cycle(edges) is None


def test_topological_order_is_total_and_deterministic():
    m = _model("""
        import threading

        class A:
            def __init__(self):
                self._la = threading.Lock()

        class B:
            def __init__(self):
                self._lb = threading.Lock()
    """)
    order = conc.topological_order({}, conc.all_lock_names([m]))
    assert order == ["A._la", "B._lb"]


def test_topological_order_raises_on_cycle():
    edges = {("x", "y"): ["e1"], ("y", "x"): ["e2"]}
    with pytest.raises(ValueError):
        conc.topological_order(edges, {"x", "y"})


def test_find_cycle_reports_loop():
    edges = {("x", "y"): ["e1"], ("y", "z"): ["e2"], ("z", "x"): ["e3"]}
    cyc = conc.find_cycle(edges)
    assert cyc is not None and cyc[0] == cyc[-1]


def test_committed_lockorder_is_current_and_acyclic():
    # same contract --check enforces in CI: rebuilding the artifact from
    # source must reproduce the committed file byte-for-byte
    art = conc.lockorder_artifact(conc.tree_models())
    with open(linter.default_lockorder_path(), "r", encoding="utf-8") as fh:
        committed = json.load(fh)
    assert committed == art
    # and the known real nestings are present
    pairs = {(e["before"], e["after"]) for e in art["edges"]}
    assert ("FleetRouter._cond", "DrainRateEstimator._lock") in pairs
    assert ("StyleService._compile_lock", "ProgramRegistry._lock") in pairs
    assert ("RolloutManager._lock", "FleetRouter._cond") in pairs
    assert ("ClusterRouter._proc_lock", "LeaseTable._lock") in pairs
    # the warming-state guard (r17) moved re-warm compiles OFF the
    # engine lock: an engine-lock -> registry-lock nesting reappearing
    # would mean compiles block dispatch again
    assert ("SynthesisEngine._lock", "ProgramRegistry._lock") not in pairs


# ---------------------------------------------------------------------------
# JL023 — unsupervised threads
# ---------------------------------------------------------------------------


def test_jl023_positive_unnamed_thread():
    assert "JL023" in _codes("""
        import threading

        class S:
            def __init__(self):
                self.t = threading.Thread(target=self._loop)
                self.t.start()

            def _loop(self):
                pass

            def close(self):
                self.t.join()
    """)


def test_jl023_positive_never_joined_or_signalled():
    assert "JL023" in _codes("""
        import threading

        class S:
            def __init__(self):
                self.t = threading.Thread(target=self._loop, name="w")
                self.t.start()

            def _loop(self):
                pass
    """)


def test_jl023_negative_named_and_joined():
    assert "JL023" not in _codes("""
        import threading

        class S:
            def __init__(self):
                self.t = threading.Thread(target=self._loop, name="w")
                self.t.start()

            def _loop(self):
                pass

            def close(self):
                self.t.join()
    """)


def test_jl023_negative_stop_event_signalled():
    assert "JL023" not in _codes("""
        import threading

        class S:
            def __init__(self):
                self._stop = threading.Event()
                self.t = threading.Thread(target=self._loop, name="w")
                self.t.start()

            def _loop(self):
                pass

            def close(self):
                self._stop.set()
    """)


# ---------------------------------------------------------------------------
# the runtime witness
# ---------------------------------------------------------------------------

_ORDER = {"A._l": 0, "B._l": 1}


def _tracked(name, kind="lock", reg=None):
    return TrackedLock(
        name, kind=kind,
        registry=reg if reg is not None else MetricsRegistry(),
        order=_ORDER,
    )


def test_trackedlock_forward_nesting_ok():
    reg = MetricsRegistry()
    a, b = _tracked("A._l", reg=reg), _tracked("B._l", reg=reg)
    with a:
        with b:
            pass


def test_trackedlock_inversion_raises_and_counts():
    reg = MetricsRegistry()
    a, b = _tracked("A._l", reg=reg), _tracked("B._l", reg=reg)
    with pytest.raises(LockOrderError):
        with b:
            with a:
                pass
    assert reg.value("lock_order_inversions_total") == 1
    # the stack unwound cleanly: the forward order still works
    with a:
        with b:
            pass


def test_trackedlock_unknown_name_unconstrained():
    reg = MetricsRegistry()
    b = _tracked("B._l", reg=reg)
    x = _tracked("X._l", reg=reg)   # not in the order: never raises
    with b:
        with x:
            pass
    with x:
        with b:
            pass


def test_trackedlock_rlock_reentry_skips_order_check():
    r = _tracked("B._l", kind="rlock")
    with r:
        with r:
            pass


def test_trackedlock_exports_hold_and_contention_metrics():
    reg = MetricsRegistry()
    a = _tracked("A._l", reg=reg)
    with a:
        pass
    hist = reg.metrics_named("lock_hold_seconds")
    assert hist and hist[0].labels == (("lock", "A._l"),)
    assert hist[0].count == 1

    # a second thread blocking on the lock counts as contention
    entered = threading.Event()
    release = threading.Event()

    def holder():
        with a:
            entered.set()
            release.wait(timeout=5)

    t = threading.Thread(target=holder, name="holder")
    t.start()
    assert entered.wait(timeout=5)
    waiter_done = threading.Event()

    def waiter():
        with a:
            waiter_done.set()

    w = threading.Thread(target=waiter, name="waiter")
    w.start()
    # give the waiter time to hit the contended non-blocking attempt
    import time as _time

    _time.sleep(0.05)
    release.set()
    assert waiter_done.wait(timeout=5)
    t.join(timeout=5)
    w.join(timeout=5)
    assert reg.value("lock_contention_total", {"lock": "A._l"}) >= 1


def test_trackedlock_condition_wait_releases_for_blocked_span():
    reg = MetricsRegistry()
    c = _tracked("A._l", kind="condition", reg=reg)
    hit = []

    def waker():
        with c:
            hit.append(1)
            c.notify_all()

    with c:
        t = threading.Thread(target=waker, name="waker")
        t.start()
        assert c.wait(timeout=5)
    t.join(timeout=5)
    assert hit == [1]


def test_make_lock_gates_on_env(monkeypatch):
    monkeypatch.delenv("SPEAKINGSTYLE_CHECKS", raising=False)
    plain = make_lock("A._l")
    assert isinstance(plain, type(threading.Lock()))
    monkeypatch.setenv("SPEAKINGSTYLE_CHECKS", "1")
    tracked = make_lock("A._l", registry=MetricsRegistry())
    assert isinstance(tracked, TrackedLock)
