"""Chaos drills for the serving resilience layer (tier-1).

Four layers, mirroring ARCHITECTURE.md "Serving resilience":
  1. circuit breaker — the pure state machine (closed/open/half-open,
     exponential backoff, cap, reset);
  2. router supervision against fake engines (no jax, millisecond-fast):
     a replica killed mid-load loses ZERO requests, the hang watchdog
     steals in-flight work exactly-once (late results discarded),
     deadlines resolve as DeadlineExceeded even with no replica alive,
     retry budgets bound transient-failure retries, and a dispatch-loop
     bookkeeping bug resolves futures as DispatchError without killing
     the worker (router and single-engine batcher both);
  3. graceful style degradation on the real tiny model: an injected
     encoder failure falls back to the default style whose output
     bit-equals an explicit default-style request, never poisons the
     content-addressed cache, and surfaces as X-Style-Degraded over
     HTTP; a vocoder fault aborts the (non-idempotent) stream;
  4. the fleet chaos acceptance drill: one real replica killed at
     steady load -> zero lost requests, both replicas READY again, and
     ZERO steady-state compiles outside the re-warm phase.
"""

import dataclasses
import http.client
import json
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    FleetConfig,
    ModelConfig,
    ReferenceEncoderConfig,
    ServeConfig,
    StyleConfig,
    TransformerConfig,
    VarianceEmbeddingConfig,
    VariancePredictorConfig,
)
from speakingstyle_tpu.faults import FaultPlan
from speakingstyle_tpu.obs import MetricsRegistry
from speakingstyle_tpu.serving.batcher import ContinuousBatcher
from speakingstyle_tpu.serving.engine import CompileMonitor, SynthesisRequest
from speakingstyle_tpu.serving.fleet import FAILED, READY, STOPPED, FleetRouter
from speakingstyle_tpu.serving.lattice import BucketLattice
from speakingstyle_tpu.serving.resilience import (
    BREAKER_CODE,
    CircuitBreaker,
    DeadlineExceeded,
    DispatchError,
    InjectedFault,
    ReplicaError,
)

# ---------------------------------------------------------------------------
# circuit breaker (pure state)
# ---------------------------------------------------------------------------


def test_circuit_breaker_lifecycle_backoff_and_cap():
    b = CircuitBreaker(0.1, 0.4)
    assert b.state == "closed" and b.code == BREAKER_CODE["closed"]
    assert b.record_failure(100.0) == pytest.approx(0.1)
    assert b.state == "open" and b.consecutive_failures == 1
    assert not b.ready_to_trial(100.05)     # backoff not elapsed
    assert b.ready_to_trial(100.1)
    b.begin_trial()
    assert b.state == "half_open" and b.code == BREAKER_CODE["half_open"]
    assert not b.ready_to_trial(500.0)      # half-open is not re-triable
    # trial failed: re-open with the backoff doubled, then capped
    assert b.record_failure(200.0) == pytest.approx(0.2)
    assert b.record_failure(300.0) == pytest.approx(0.4)
    assert b.record_failure(400.0) == pytest.approx(0.4)  # cap
    assert b.retry_at() == pytest.approx(400.4)
    b.begin_trial()
    b.record_success()                       # first good dispatch: reset
    assert b.state == "closed" and b.consecutive_failures == 0
    assert b.record_failure(500.0) == pytest.approx(0.1)  # backoff reset too
    with pytest.raises(ValueError, match="backoff"):
        CircuitBreaker(0.0, 1.0)
    with pytest.raises(ValueError, match="backoff"):
        CircuitBreaker(1.0, 0.5)


# ---------------------------------------------------------------------------
# router supervision (fake engines — no jax)
# ---------------------------------------------------------------------------


def _fleet_cfg(**fleet_kw):
    fleet = dict(
        queue_depth=64, stream_window=8,
        rewarm_backoff_s=0.05, rewarm_backoff_max_s=1.0,
        # generous budgets so only the tests that WANT expiry see it
        class_deadline_ms={"interactive": 10_000.0, "batch": 20_000.0},
    )
    fleet.update(fleet_kw)
    return Config(serve=ServeConfig(
        batch_buckets=[1], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=5.0,
        fleet=FleetConfig(**fleet),
    ))


class _Events:
    """In-memory stand-in for the JSONL event bus."""

    def __init__(self):
        self.lock = threading.Lock()
        self.records = []

    def emit(self, event, **fields):
        with self.lock:
            self.records.append((event, fields))

    def kinds(self):
        with self.lock:
            return [k for k, _ in self.records]

    def of(self, kind):
        with self.lock:
            return [dict(f) for k, f in self.records if k == kind]


class ChaosEngine:
    """Fake replica engine recording every dispatched request id; a
    ``run_hook`` takes over the return value (or raises) when set."""

    def __init__(self, run_hook=None):
        self.dispatches = []
        self.lock = threading.Lock()
        self.run_hook = run_hook

    def precompile(self):
        return 0.0

    def run(self, requests):
        with self.lock:
            self.dispatches.extend(r.id for r in requests)
        if self.run_hook is not None:
            return self.run_hook(requests)
        return [SimpleNamespace(id=r.id, bucket=None, mel_len=1)
                for r in requests]


def _factory(engines, run_hook=None):
    """Engine factory that keeps building (re-warm calls it again) and
    records every instance it produced."""

    def build(reg):
        eng = ChaosEngine(run_hook=run_hook)
        engines.append(eng)
        return eng

    return build


def _req(i, L=8, T=4, **kw):
    return SynthesisRequest(
        id=f"r{i}", sequence=np.ones(L, np.int32),
        ref_mel=np.zeros((T, 80), np.float32), **kw,
    )


def _wait_states(router, want, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if sorted(router.states().values()) == sorted(want):
            return True
        time.sleep(0.01)
    return False


def test_replica_raise_zero_lost_requests_and_rewarm():
    """The core chaos invariant against fakes: a replica killed at a
    deterministic dispatch count loses ZERO requests — its in-flight
    work requeues onto the healthy replica — and the failed replica
    circuit-breaks, re-warms, and closes its breaker on the first good
    dispatch."""
    engines = []
    plan = FaultPlan()
    events = _Events()
    reg = MetricsRegistry()
    router = FleetRouter(_factory(engines), _fleet_cfg(), replicas=2,
                         registry=reg, events=events, fault_plan=plan)
    assert router.wait_ready(timeout=10, n=2)
    # phase A: steady load
    for f in [router.submit(_req(i)) for i in range(4)]:
        assert f.result(timeout=10) is not None
    # arm the kill between phases (no dispatches flowing -> no race):
    # the NEXT dispatch, whoever pops it, raises InjectedFault
    plan.arm("replica_raise", router.dispatch_total + 1)
    futs = [router.submit(_req(100 + i)) for i in range(8)]
    results = [f.result(timeout=10) for f in futs]    # ZERO lost requests
    assert sorted(r.id for r in results) == sorted(
        f"r{100 + i}" for i in range(8)
    )
    # exactly one replica failed; its one in-flight request was requeued
    fails = [i for i in (0, 1) if reg.value(
        "serve_replica_failures_total", {"replica": str(i)}) == 1]
    assert len(fails) == 1
    assert reg.value("serve_requeued_total") == 1
    assert reg.value("serve_retries_total", {"class": "interactive"}) == 1
    rf = events.of("replica_failure")
    assert len(rf) == 1
    assert rf[0]["kind"] == "raise" and rf[0]["error"] == "InjectedFault"
    assert rf[0]["requeued"] == rf[0]["req_ids"]      # all of it came back
    # recovery: the failed replica re-warms through cold/warming back to
    # READY (a third engine build), then its breaker closes on the first
    # good dispatch it serves
    assert _wait_states(router, [READY, READY])
    assert len(engines) == 3
    idx = str(fails[0])
    deadline, n = time.monotonic() + 10, 0
    while (time.monotonic() < deadline and reg.value(
            "serve_replica_breaker_state", {"replica": idx}) != 0):
        router.submit(_req(900 + n)).result(timeout=10)
        n += 1
    assert reg.value("serve_replica_breaker_state", {"replica": idx}) == 0
    router.close()
    assert all(s == STOPPED for s in router.states().values())


def test_hang_watchdog_steals_batch_and_discards_late_results():
    """A dispatch stuck past the hang watchdog is stolen by the
    supervisor and requeued; when the hung worker eventually finishes,
    its results are discarded — each future resolves exactly once, from
    the retry."""
    engines = []
    plan = FaultPlan.parse("replica_hang@1")
    events = _Events()
    reg = MetricsRegistry()
    cfg = _fleet_cfg(hang_watchdog_s=0.15)
    router = FleetRouter(_factory(engines), cfg, replicas=1,
                         registry=reg, events=events, fault_plan=plan)
    assert router.wait_ready(timeout=10)
    fut = router.submit(_req(0))
    res = fut.result(timeout=10)          # resolved by the retry dispatch
    assert res.id == "r0"
    rf = events.of("replica_failure")
    assert len(rf) == 1 and rf[0]["kind"] == "hang"
    assert rf[0]["error"] == "TimeoutError"
    assert reg.value("serve_replica_failures_total", {"replica": "0"}) == 1
    # the hung worker wakes AFTER the retry resolved, finishes its
    # dispatch anyway, finds its claim stolen and discards the results
    deadline = time.monotonic() + 5
    while (time.monotonic() < deadline
           and "dispatch_discarded" not in events.kinds()):
        time.sleep(0.01)
    assert "dispatch_discarded" in events.kinds()
    assert sum(e.dispatches.count("r0") for e in engines) == 2
    router.close()


def test_deadline_exceeded_resolves_even_with_no_replica_alive():
    """Deadline enforcement does not depend on a healthy worker popping
    the heap: the supervisor sweeps the EDF front, so a request expires
    as a structured DeadlineExceeded while the only replica is still
    warming."""
    gate = threading.Event()

    def factory(reg):
        gate.wait(timeout=30)
        return ChaosEngine()

    reg = MetricsRegistry()
    events = _Events()
    cfg = _fleet_cfg(class_deadline_ms={"interactive": 60.0,
                                        "batch": 2000.0})
    router = FleetRouter(factory, cfg, replicas=1,
                         registry=reg, events=events)
    fut = router.submit(_req(0))
    exc = fut.exception(timeout=5)
    assert isinstance(exc, DeadlineExceeded)
    assert exc.klass == "interactive" and exc.budget_ms == 60.0
    assert "deadline" in str(exc)
    assert reg.value("serve_deadline_exceeded_total",
                     {"class": "interactive"}) == 1
    de = events.of("deadline_exceeded")
    assert de and de[0]["req_id"] == "r0"
    gate.set()
    router.close()


def test_retry_budget_exhaustion_resolves_replica_error():
    """A request burns one retry per replica failure; past the class
    budget it resolves as ReplicaError (503) instead of looping
    forever."""
    engines = []
    plan = FaultPlan.parse("replica_raise@1;replica_raise@2")
    reg = MetricsRegistry()
    cfg = _fleet_cfg(retry_budget={"interactive": 1, "batch": 2})
    router = FleetRouter(_factory(engines), cfg, replicas=1,
                         registry=reg, fault_plan=plan)
    assert router.wait_ready(timeout=10)
    exc = router.submit(_req(0)).exception(timeout=10)
    assert isinstance(exc, ReplicaError)
    assert "retry budget" in str(exc)
    assert reg.value("serve_requeued_total") == 1
    assert reg.value("serve_retries_total", {"class": "interactive"}) == 1
    assert reg.value("serve_replica_failures_total", {"replica": "0"}) == 2
    router.close()


def test_zero_retry_budget_fails_fast():
    plan = FaultPlan.parse("replica_raise@1")
    cfg = _fleet_cfg(retry_budget={"interactive": 0, "batch": 0})
    router = FleetRouter(_factory([]), cfg, replicas=1, fault_plan=plan)
    assert router.wait_ready(timeout=10)
    exc = router.submit(_req(0)).exception(timeout=10)
    assert isinstance(exc, ReplicaError)
    router.close()


def test_fleet_dispatch_bookkeeping_error_keeps_worker_alive():
    """Satellite: an unexpected exception in the dispatch loop's
    bookkeeping (the engine call itself succeeded) resolves the affected
    futures as DispatchError and the worker keeps serving."""
    calls = {"n": 0}

    def hook(requests):
        calls["n"] += 1
        if calls["n"] == 1:
            return None     # buggy engine: run "succeeded", returned junk
        return [SimpleNamespace(id=r.id, bucket=None, mel_len=1)
                for r in requests]

    reg = MetricsRegistry()
    router = FleetRouter(_factory([], run_hook=hook), _fleet_cfg(),
                         replicas=1, registry=reg)
    assert router.wait_ready(timeout=10)
    exc = router.submit(_req(0)).exception(timeout=10)
    assert isinstance(exc, DispatchError)
    assert "bookkeeping" in str(exc)
    assert reg.value("serve_dispatch_errors_total") == 1
    # NOT a replica failure: the replica stayed READY and still serves
    assert router.states()[0] == READY
    assert reg.value("serve_replica_failures_total", {"replica": "0"}) == 0
    assert router.submit(_req(1)).result(timeout=10).id == "r1"
    router.close()


def test_batcher_dispatch_bookkeeping_error_keeps_worker_alive():
    """The single-engine batcher carries the same guarantee."""
    serve = ServeConfig(
        batch_buckets=[1], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=5.0, queue_depth=8,
    )
    calls = {"n": 0}

    class Eng:
        def __init__(self):
            self.cfg = SimpleNamespace(serve=serve)
            self.lattice = BucketLattice.from_config(serve)

        def admit(self, request):
            self.lattice.cover(1, len(request.sequence), 1)

        def run(self, requests):
            calls["n"] += 1
            if calls["n"] == 1:
                return None
            return [SimpleNamespace(id=r.id, bucket=None)
                    for r in requests]

    b = ContinuousBatcher(Eng())
    exc = b.submit(_req(0, T=1)).exception(timeout=10)
    assert isinstance(exc, DispatchError)
    assert b.submit(_req(1, T=1)).result(timeout=10).id == "r1"
    assert b.registry.value("serve_dispatch_errors_total") == 1
    b.close()


def test_stream_continuation_lost_replica_is_not_retried():
    """Stream continuations are non-idempotent: a result whose replica
    failed raises ReplicaError at the next chunk instead of silently
    re-dispatching on another replica."""
    engines = []
    plan = FaultPlan()
    cfg = _fleet_cfg(retry_budget={"interactive": 0, "batch": 0},
                     rewarm_backoff_s=30.0, rewarm_backoff_max_s=60.0)
    router = FleetRouter(_factory(engines), cfg, replicas=1,
                         fault_plan=plan)
    assert router.wait_ready(timeout=10)
    res = router.submit(_req(0)).result(timeout=10)
    assert res.replica == 0
    plan.arm("replica_raise", router.dispatch_total + 1)
    exc = router.submit(_req(1)).exception(timeout=10)
    assert isinstance(exc, ReplicaError)
    assert router.states()[0] == FAILED    # 30 s backoff: stays failed
    with pytest.raises(ReplicaError, match="not retried"):
        next(router.stream(res))
    router.close()


# ---------------------------------------------------------------------------
# graceful style degradation + stream faults (tiny real model)
# ---------------------------------------------------------------------------


def _tiny_cfg():
    return Config(
        model=ModelConfig(
            transformer=TransformerConfig(
                encoder_layer=1, decoder_layer=1, encoder_hidden=16,
                decoder_hidden=16, conv_filter_size=16,
                conv_kernel_size=(3, 1),
            ),
            reference_encoder=ReferenceEncoderConfig(
                encoder_layer=1, encoder_head=2, encoder_hidden=16,
                conv_layer=1, conv_filter_size=16,
            ),
            variance_predictor=VariancePredictorConfig(filter_size=16),
            variance_embedding=VarianceEmbeddingConfig(n_bins=8),
            postnet_embedding_dim=16, postnet_layers=2,
            max_seq_len=48, compute_dtype="float32",
        ),
        serve=ServeConfig(
            batch_buckets=[1, 2], src_buckets=[16], mel_buckets=[32],
            frames_per_phoneme=2, max_wait_ms=20.0,
            fleet=FleetConfig(
                stream_window=8, queue_depth=64,
                rewarm_backoff_s=0.05, rewarm_backoff_max_s=1.0,
                class_deadline_ms={"interactive": 60_000.0,
                                   "batch": 120_000.0},
            ),
            style=StyleConfig(ref_buckets=[32]),
        ),
    )


@pytest.fixture(scope="module")
def tiny_parts():
    """Model/weights/vocoder built once; engines are constructed per
    test/replica from these (test_fleet.py's convention)."""
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.models.hifigan import Generator

    cfg = _tiny_cfg()
    model = build_model(cfg, n_position=49)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    bias = variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"]
    variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"] = bias + 1.1
    gen = Generator(
        upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4),
        upsample_initial_channel=16, resblock_kernel_sizes=(3,),
        resblock_dilation_sizes=((1,),),
    )
    gparams = gen.init(
        jax.random.PRNGKey(0), np.zeros((1, 8, 80), np.float32)
    )["params"]
    return cfg, model, variables, gen, gparams


@pytest.fixture(scope="module")
def chaos_engine(tiny_parts):
    """One precompiled tiny engine with a LIVE (initially empty) fault
    plan attached: tests arm entries at the next counter value."""
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg, model, variables, gen, gparams = tiny_parts
    plan = FaultPlan()
    engine = SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                             model=model, fault_plan=plan)
    engine.precompile()
    return engine, plan


def _mkreq(i, L=10, T=20, **kw):
    rng = np.random.default_rng(i)
    kw.setdefault(
        "ref_mel", rng.standard_normal((T, 80)).astype(np.float32)
    )
    return SynthesisRequest(
        id=f"utt{i}",
        sequence=rng.integers(1, 300, L).astype(np.int32),
        **kw,
    )


def test_style_degradation_parity_and_cache_unpoisoned(chaos_engine):
    """Acceptance: an injected encoder failure degrades to the default
    style — whose output bit-equals an explicit default-style request —
    never reaches the content-addressed cache, and the same reference
    encodes fresh (undegraded) on its next request."""
    engine, plan = chaos_engine
    style = engine.style
    n0 = len(style)
    failures0 = engine.registry.value(
        "serve_style_encode_failures_total", {"error": "InjectedFault"})
    plan.arm("style_encode_error", style.encode_attempts + 1)
    degraded = engine.run([_mkreq(50)])[0]
    assert degraded.style_degraded is True
    assert len(style) == n0                 # failed encode never cached
    assert engine.registry.value("serve_style_degraded_total") == 1
    assert engine.registry.value(
        "serve_style_encode_failures_total", {"error": "InjectedFault"}
    ) == failures0 + 1
    # parity: bit-equal to an explicit default-style request
    explicit = engine.run(
        [_mkreq(50, style=style.fallback_style(), ref_mel=None)]
    )[0]
    assert explicit.style_degraded is False
    np.testing.assert_array_equal(degraded.mel, explicit.mel)
    np.testing.assert_array_equal(degraded.wav, explicit.wav)
    # un-poisoned: the same reference encodes fresh next time, lands in
    # the cache, and produces a genuinely different (styled) output
    fresh = engine.run([_mkreq(50)])[0]
    assert fresh.style_degraded is False
    assert len(style) == n0 + 1
    sv = style.get(style.digest_mel(_mkreq(50).ref_mel))
    assert sv is not None
    assert np.any(sv.gamma != 0) or np.any(sv.beta != 0)


def test_vocoder_fault_aborts_stream(chaos_engine):
    """A vocoder fault mid-stream raises (the chunked body truncates);
    stream continuations are never transparently retried."""
    from speakingstyle_tpu.serving import streaming

    engine, plan = chaos_engine
    res = engine.run([_mkreq(60, stream=True)])[0]
    gen, _ = engine.vocoder
    window = engine.cfg.serve.fleet.stream_window
    overlap = streaming.resolve_overlap(
        engine.cfg.serve.fleet.stream_overlap, gen
    )
    plan.arm("vocoder_raise", engine.vocode_calls + 1)
    with pytest.raises(InjectedFault, match="vocoder_raise"):
        list(streaming.stream_wav(engine, res, window, overlap))
    # the fault was one-shot: the same stream replays clean after it
    chunks = list(streaming.stream_wav(engine, res, window, overlap))
    assert sum(len(c) for c in chunks) == res.mel_len * gen.hop_factor


def test_http_style_degraded_header(chaos_engine):
    """HTTP surface of degradation: the frontend's encoder failure
    produces a 200 with X-Style-Degraded: 1; the next request (cache
    still unpoisoned) encodes fine and carries no header."""
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    engine, plan = chaos_engine
    ref = np.random.default_rng(7).standard_normal((20, 80)).astype(
        np.float32)
    server = SynthesisServer(
        engine, TextFrontend(engine.cfg, ref), host="127.0.0.1", port=0,
    )
    host, port = server.address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=120)
        plan.arm("style_encode_error", engine.style.encode_attempts + 1)
        payload = json.dumps({"text": "style me"})
        conn.request("POST", "/synthesize", body=payload)
        resp = conn.getresponse()
        degraded_wav = resp.read()
        assert resp.status == 200
        assert resp.getheader("X-Style-Degraded") == "1"
        assert degraded_wav[:4] == b"RIFF"
        conn.request("POST", "/synthesize", body=payload)
        resp = conn.getresponse()
        ok_wav = resp.read()
        assert resp.status == 200
        assert resp.getheader("X-Style-Degraded") is None
        assert ok_wav[:4] == b"RIFF"
        conn.close()
    finally:
        server.shutdown()


def test_http_504_on_deadline_with_structured_body():
    """Satellite: the handler's future wait is bounded by the class
    deadline budget (+grace) and a deadline expiry maps to 504 — here
    while the only replica never finishes warming (no jax involved)."""
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    gate = threading.Event()

    def factory(reg):
        gate.wait(timeout=60)
        return ChaosEngine()

    cfg = _fleet_cfg(
        class_deadline_ms={"interactive": 100.0, "batch": 2000.0},
        deadline_grace_ms=400.0,
    )
    router = FleetRouter(factory, cfg, replicas=1,
                         registry=MetricsRegistry())
    server = SynthesisServer(
        frontend=TextFrontend(cfg, np.zeros((4, 80), np.float32)),
        host="127.0.0.1", port=0, router=router,
    )
    host, port = server.address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        t0 = time.monotonic()
        conn.request("POST", "/synthesize",
                     body=json.dumps({"text": "too late"}))
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 504
        assert "deadline" in body["error"]
        # resolved by the budget, not by the 60 s default request timeout
        assert time.monotonic() - t0 < 5.0
        conn.close()
    finally:
        gate.set()
        server.shutdown()


# ---------------------------------------------------------------------------
# fleet chaos acceptance drill (tiny real model)
# ---------------------------------------------------------------------------


def test_fleet_chaos_recovery_zero_lost_zero_steady_compiles(tiny_parts):
    """The acceptance drill at fleet scale: one of two real replicas is
    killed at a deterministic dispatch count under load — zero requests
    are lost, the fleet recovers to two READY replicas, and once the
    re-warm phase is over steady-state serving performs ZERO compiles."""
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg, model, variables, gen, gparams = tiny_parts
    reg = MetricsRegistry()
    plan = FaultPlan()

    def factory(registry):
        return SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                               model=model, registry=registry)

    with FleetRouter(factory, cfg, replicas=2, registry=reg,
                     fault_plan=plan) as router:
        assert router.wait_ready(timeout=300, n=2)
        for engine in router.engines():
            for b in engine.lattice.batch_buckets:
                engine.run([_mkreq(700 + b * 10 + j) for j in range(b)])
        # steady phase A
        for f in [router.submit(_mkreq(i)) for i in range(4)]:
            assert f.result(timeout=120).wav is not None
        # kill one replica on the next dispatch (armed between phases)
        plan.arm("replica_raise", router.dispatch_total + 1)
        futs = [router.submit(_mkreq(10 + i)) for i in range(6)]
        results = [f.result(timeout=120) for f in futs]  # ZERO lost
        assert sorted(r.id for r in results) == sorted(
            f"utt{10 + i}" for i in range(6)
        )
        fails = [i for i in (0, 1) if reg.value(
            "serve_replica_failures_total", {"replica": str(i)}) == 1]
        assert len(fails) == 1
        # recovery: the failed replica re-warms (recompiling — excluded
        # from the steady-state monitor below) back to READY
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if sorted(router.states().values()) == [READY, READY]:
                break
            time.sleep(0.05)
        assert sorted(router.states().values()) == [READY, READY]
        # re-warmed engine: first-execution transfer warmup per bucket
        for engine in router.engines():
            for b in engine.lattice.batch_buckets:
                engine.run([_mkreq(800 + b * 10 + j) for j in range(b)])
        # drive dispatches until the re-warmed replica served one (its
        # breaker closes there)
        idx, n = str(fails[0]), 0
        deadline = time.monotonic() + 120
        while (time.monotonic() < deadline and reg.value(
                "serve_replica_breaker_state", {"replica": idx}) != 0):
            router.submit(_mkreq(2000 + n)).result(timeout=120)
            n += 1
        assert reg.value(
            "serve_replica_breaker_state", {"replica": idx}) == 0
        # steady phase B: the post-recovery fleet compiles NOTHING
        with CompileMonitor() as mon:
            futs = [router.submit(_mkreq(100 + i)) for i in range(6)]
            for i, f in enumerate(futs):
                assert f.result(timeout=120).id == f"utt{100 + i}"
        assert mon.count == 0, "the fleet compiled after re-warm"
        assert reg.value("serve_requeued_total") >= 1
