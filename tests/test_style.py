"""Style service: AOT reference-encoder subsystem + embedding cache.

Four layers, mirroring serving/style.py's role in the stack:
  1. StyleLattice — pure-python (batch, ref_len) covering properties,
     including the decoupling win: a max-length reference no longer
     inflates the synthesis T_mel bucket;
  2. cache — content addressing, hit/miss/eviction counters, LRU order;
  3. engine parity — synthesis from cached (gamma, beta) is BIT-IDENTICAL
     to the ref_mel path, and a cached-style request performs zero
     reference-encoder dispatches and zero XLA compiles (the acceptance
     invariants, checked on the backend monitoring bus);
  4. HTTP — POST /styles -> style_id -> /synthesize roundtrip, ref_dir
     path confinement (``..`` escapes -> 400), per-speaker validation.
"""

import dataclasses
import http.client
import io
import json
import threading

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    ModelConfig,
    ReferenceEncoderConfig,
    ServeConfig,
    StyleConfig,
    TransformerConfig,
    VarianceEmbeddingConfig,
    VariancePredictorConfig,
)
from speakingstyle_tpu.serving.lattice import (
    BucketLattice,
    RequestTooLarge,
    StyleLattice,
)

# ---------------------------------------------------------------------------
# StyleLattice (pure python)
# ---------------------------------------------------------------------------


def test_style_lattice_cover_is_elementwise_smallest():
    lat = StyleLattice([1, 4, 8], [64, 256, 1000])
    rng = np.random.default_rng(0)
    for _ in range(200):
        n = int(rng.integers(1, 9))
        r = int(rng.integers(1, 1001))
        b, rb = lat.cover(n, r)
        assert b >= n and rb >= r
        for (pb, pr) in lat.points():
            if pb >= n and pr >= r:
                assert b <= pb and rb <= pr


def test_style_lattice_too_large_names_config_key():
    lat = StyleLattice([1], [64])
    with pytest.raises(RequestTooLarge, match="serve.style.ref_buckets"):
        lat.cover(1, 65)
    with pytest.raises(RequestTooLarge, match="serve.style.batch_buckets"):
        lat.cover(2, 10)


def test_style_lattice_rejects_bad_axes_and_inherits_batch():
    with pytest.raises(ValueError):
        StyleLattice([], [64])
    with pytest.raises(ValueError):
        StyleLattice([1], [64, 32])
    serve = ServeConfig(
        batch_buckets=[1, 2], src_buckets=[16], mel_buckets=[32],
        style=StyleConfig(ref_buckets=[32]),
    )
    lat = StyleLattice.from_config(serve)
    assert lat.batch_buckets == [1, 2]  # inherited from serve
    assert len(lat) == 2
    explicit = StyleLattice.from_config(dataclasses.replace(
        serve, style=StyleConfig(ref_buckets=[32], batch_buckets=[4])
    ))
    assert explicit.batch_buckets == [4]


def test_style_config_validation():
    with pytest.raises(ValueError, match="ref_buckets"):
        StyleConfig(ref_buckets=[])
    with pytest.raises(ValueError, match="ascending"):
        StyleConfig(ref_buckets=[64, 32])
    with pytest.raises(ValueError, match="cache_capacity"):
        StyleConfig(cache_capacity=0)


def test_ref_length_no_longer_inflates_mel_bucket():
    """The decoupling is a strict win on bucket cover: under the old
    ``required_mel = max(ref_len, est_out)`` a max-length reference
    forced the largest T_mel bucket; with references on their own axis
    the same request covers to the smallest output bucket."""
    lat = BucketLattice([1, 4, 8], [32, 64, 128], [256, 512, 1000])
    style = StyleLattice([1, 4, 8], [256, 512, 1000])
    ref_len, est_out = 1000, 120  # long reference, short utterance
    old_bucket = lat.cover(1, 10, max(ref_len, est_out))
    new_bucket = lat.cover(1, 10, est_out)
    assert old_bucket.t_mel == 1000
    assert new_bucket.t_mel == 256          # strictly smaller dispatch
    assert new_bucket.volume < old_bucket.volume
    # and the reference still admits, on its own axis
    assert style.cover(1, ref_len) == (1, 1000)


# ---------------------------------------------------------------------------
# tiny engine + service (real jax)
# ---------------------------------------------------------------------------


def _tiny_cfg(**style_kw):
    style = dict(ref_buckets=[32])
    style.update(style_kw)
    return Config(
        model=ModelConfig(
            transformer=TransformerConfig(
                encoder_layer=1, decoder_layer=1, encoder_hidden=16,
                decoder_hidden=16, conv_filter_size=16,
                conv_kernel_size=(3, 1),
            ),
            reference_encoder=ReferenceEncoderConfig(
                encoder_layer=1, encoder_head=2, encoder_hidden=16,
                conv_layer=1, conv_filter_size=16,
            ),
            variance_predictor=VariancePredictorConfig(filter_size=16),
            variance_embedding=VarianceEmbeddingConfig(n_bins=8),
            postnet_embedding_dim=16, postnet_layers=2,
            max_seq_len=48, compute_dtype="float32",
        ),
        serve=ServeConfig(
            batch_buckets=[1, 2], src_buckets=[16], mel_buckets=[32],
            frames_per_phoneme=2, max_wait_ms=20.0,
            style=StyleConfig(**style),
        ),
    )


@pytest.fixture(scope="module")
def tiny_style_engine():
    """One precompiled tiny engine (synthesis + style lattices) shared
    by the e2e tests."""
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.models.hifigan import Generator
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg = _tiny_cfg()
    model = build_model(cfg, n_position=49)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    bias = variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"]
    variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"] = bias + 1.1
    gen = Generator(
        upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4),
        upsample_initial_channel=16, resblock_kernel_sizes=(3,),
        resblock_dilation_sizes=((1,),),
    )
    gparams = gen.init(
        jax.random.PRNGKey(0), np.zeros((1, 8, 80), np.float32)
    )["params"]
    engine = SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                             model=model)
    engine.precompile()
    return engine


def _ref(i, T=20):
    rng = np.random.default_rng(1000 + i)
    return rng.standard_normal((T, 80)).astype(np.float32)


def _mkreq(i, L=10, T=20, **kw):
    rng = np.random.default_rng(i)
    from speakingstyle_tpu.serving.engine import SynthesisRequest

    kw.setdefault(
        "ref_mel", rng.standard_normal((T, 80)).astype(np.float32)
    )
    return SynthesisRequest(
        id=f"utt{i}",
        sequence=rng.integers(1, 300, L).astype(np.int32),
        **kw,
    )


def _wav_bytes(seed=0, seconds=0.15, sr=22050):
    """A small deterministic wav file as bytes (the upload body)."""
    import scipy.io.wavfile

    rng = np.random.default_rng(seed)
    t = np.arange(int(sr * seconds)) / sr
    wav = (0.3 * np.sin(2 * np.pi * (180 + 40 * seed) * t)
           + 0.01 * rng.standard_normal(t.shape)).astype(np.float32)
    buf = io.BytesIO()
    scipy.io.wavfile.write(buf, sr, (wav * 32000).astype(np.int16))
    return buf.getvalue()


def test_style_precompiled_with_program_cards(tiny_style_engine):
    style = tiny_style_engine.style
    assert style.is_ready
    assert style.compile_count == len(style.lattice) == 2  # [1,2] x [32]
    cards = style.programs()
    assert len(cards) == 2
    for c in cards:
        assert c["name"].startswith("style:")
        assert c["flops"] > 0
        json.dumps(c)
    # per-ref-bucket FLOPs gauges published into the shared registry
    text = tiny_style_engine.registry.prometheus_text()
    assert 'serve_program_flops{bucket="b1.r32",kind="style"}' in text


def test_cached_vs_fresh_embedding_bit_parity(tiny_style_engine):
    """The same reference encodes to bit-identical vectors whether it
    comes back from the cache or (hypothetically) fresh — the cache
    stores exactly what the encoder produced."""
    style = tiny_style_engine.style
    ref = _ref(1)
    first = style.encode_mel(ref)
    d0 = style.dispatch_count
    again = style.encode_mel(ref)
    assert style.dispatch_count == d0          # pure cache hit
    assert np.array_equal(first.gamma, again.gamma)
    assert np.array_equal(first.beta, again.beta)
    assert first.key == again.key == style.digest_mel(ref)


def test_synthesis_from_cached_style_bit_identical(tiny_style_engine):
    """Acceptance: synthesis driven by cached (gamma, beta) equals the
    ref_mel-carrying path bit for bit — same vectors, same compiled
    acoustic program, same audio."""
    engine = tiny_style_engine
    req = _mkreq(7)
    r_ref = engine.run([req])[0]
    cached = engine.style.encode_mel(req.ref_mel)   # cache hit
    r_cached = engine.run([_mkreq(7, style=cached, ref_mel=None)])[0]
    assert r_cached.mel_len == r_ref.mel_len
    np.testing.assert_array_equal(r_cached.mel, r_ref.mel)
    np.testing.assert_array_equal(r_cached.wav, r_ref.wav)
    np.testing.assert_array_equal(r_cached.durations, r_ref.durations)


def test_cached_request_zero_encoder_dispatches_zero_compiles(
    tiny_style_engine,
):
    """Acceptance: a cached-style request performs ZERO reference-encoder
    dispatches (hits counter moves, dispatch counter does not) and ZERO
    XLA compiles, measured on the backend monitoring bus."""
    from speakingstyle_tpu.serving.engine import CompileMonitor

    engine = tiny_style_engine
    style = engine.style
    # warm: one dispatch per batch bucket with FRESH references so both
    # encode batch shapes and both synthesis buckets have executed
    for b in engine.lattice.batch_buckets:
        engine.run([_mkreq(300 + b * 10 + j) for j in range(b)])
    req = _mkreq(42)
    engine.run([req])                    # encodes + caches this reference
    hits0 = int(style._hits.value)
    d0 = style.dispatch_count
    c0 = engine.compile_count + style.compile_count
    with CompileMonitor() as mon:
        # same reference again (ref_mel path -> cache) and an explicit
        # cached-vectors request: neither may touch the encoder
        engine.run([_mkreq(42)])
        cached = style.get(style.digest_mel(req.ref_mel))
        assert cached is not None
        engine.run([_mkreq(43, style=cached, ref_mel=None)])
    assert mon.count == 0, "the style path compiled in steady state"
    assert style.dispatch_count == d0, "cached style ran the encoder"
    assert int(style._hits.value) > hits0
    assert engine.compile_count + style.compile_count == c0


def test_fresh_styles_batch_encode_and_dedup(tiny_style_engine):
    """A coalesced dispatch with N fresh references runs ONE encoder
    dispatch; duplicates within the batch encode once."""
    engine = tiny_style_engine
    style = engine.style
    d0 = style.dispatch_count
    ref = _ref(77)
    reqs = [
        _mkreq(500, ref_mel=None, style=None),
        _mkreq(501, ref_mel=None, style=None),
    ]
    reqs[0].ref_mel = ref
    reqs[1].ref_mel = ref.copy()          # same content, distinct array
    engine.run(reqs)
    assert style.dispatch_count == d0 + 1  # one padded encode, one row


def test_cache_eviction_and_counters(tiny_style_engine):
    """Bounded LRU: capacity-2 service evicts oldest, counts evictions,
    and keeps hit/miss accounting exact."""
    from speakingstyle_tpu.serving.style import StyleService

    cfg = _tiny_cfg(cache_capacity=2)
    svc = StyleService(cfg, tiny_style_engine.variables)
    a, b, c = _ref(201), _ref(202), _ref(203)
    svc.encode_mel(a)
    svc.encode_mel(b)
    assert len(svc) == 2
    assert int(svc._misses.value) == 2 and int(svc._evictions.value) == 0
    svc.encode_mel(a)                       # refresh a's LRU position
    assert int(svc._hits.value) == 1
    svc.encode_mel(c)                       # evicts b (least recent)
    assert len(svc) == 2
    assert int(svc._evictions.value) == 1
    assert svc.get(svc.digest_mel(b)) is None
    assert svc.get(svc.digest_mel(a)) is not None
    # registration metadata for GET /styles
    ids = [e["style_id"] for e in svc.styles()]
    assert svc.digest_mel(c) in ids and len(ids) == 2


def test_digest_is_content_addressed():
    from speakingstyle_tpu.serving.style import StyleService

    data = _wav_bytes(1)
    assert StyleService.digest_bytes(data) == StyleService.digest_bytes(
        bytes(data)
    )
    assert StyleService.digest_bytes(data) != StyleService.digest_bytes(
        data + b"\x00"
    )
    mel = _ref(5)
    assert StyleService.digest_mel(mel) == StyleService.digest_mel(mel.copy())
    assert StyleService.digest_mel(mel) != StyleService.digest_mel(mel.T)


def test_admit_validates_reference_against_style_lattice(tiny_style_engine):
    engine = tiny_style_engine
    with pytest.raises(RequestTooLarge, match="serve.style.ref_buckets"):
        engine.admit(_mkreq(0, T=40))        # ref bucket max 32
    with pytest.raises(ValueError, match="style"):
        engine.admit(_mkreq(0, ref_mel=None))
    # a cached-style request admits with no reference at all
    sv = engine.style.encode_mel(_ref(9))
    engine.admit(_mkreq(1, ref_mel=None, style=sv))


def test_required_mel_ignores_reference_length(tiny_style_engine):
    engine = tiny_style_engine
    short = _mkreq(0, L=10, T=8)
    long_ref = _mkreq(1, L=10, T=32)
    assert engine.required_mel(short) == engine.required_mel(long_ref) == 20


# ---------------------------------------------------------------------------
# path confinement
# ---------------------------------------------------------------------------


def test_confined_ref_path_rejects_escapes(tmp_path):
    from speakingstyle_tpu.serving.server import confined_ref_path

    ref_dir = tmp_path / "refs"
    ref_dir.mkdir()
    (ref_dir / "ok.wav").write_bytes(_wav_bytes(3))
    (tmp_path / "secret.wav").write_bytes(b"outside")
    cfg = _tiny_cfg(ref_dir=str(ref_dir))
    assert confined_ref_path(cfg, "ok.wav") == str(ref_dir / "ok.wav")
    for bad in ("../secret.wav", "a/../../secret.wav",
                str(tmp_path / "secret.wav"), "/etc/passwd"):
        with pytest.raises(ValueError, match="escapes|disabled"):
            confined_ref_path(cfg, bad)
    with pytest.raises(ValueError, match="does not exist"):
        confined_ref_path(cfg, "missing.wav")
    # unset ref_dir disables server-side paths entirely
    with pytest.raises(ValueError, match="disabled"):
        confined_ref_path(_tiny_cfg(), "ok.wav")


# ---------------------------------------------------------------------------
# speaker registry validation
# ---------------------------------------------------------------------------


def test_frontend_speaker_registry_validation(tiny_style_engine):
    from speakingstyle_tpu.serving.server import TextFrontend

    fe = TextFrontend(tiny_style_engine.cfg, _ref(0),
                      style=tiny_style_engine.style)
    fe.speaker_map = {"mary": 0, "john": 1}
    assert fe.speaker("mary") == 0 and fe.speaker(1) == 1
    with pytest.raises(ValueError, match="unknown speaker"):
        fe.speaker("ghost")
    with pytest.raises(ValueError, match="outside the registry"):
        fe.speaker(7)

    # a style bound to a speaker drives that speaker by default and
    # refuses a conflicting explicit one
    bound = tiny_style_engine.style.encode_mel(_ref(31), speaker="john")
    req = fe.request("r1", {"text": "hi", "style_id": bound.key})
    assert req.speaker == 1 and req.style is bound
    with pytest.raises(ValueError, match="bound to speaker"):
        fe.request("r2", {"text": "hi", "style_id": bound.key,
                          "speaker_id": "mary"})


def test_per_word_controls_in_request_schema(tiny_style_engine):
    """The documented /synthesize schema accepts per-WORD control lists:
    expanded to per-phoneme arrays through the span-preserving G2P, and
    a wrong word count is a 400-shaped ValueError."""
    from speakingstyle_tpu.serving.server import TextFrontend

    fe = TextFrontend(tiny_style_engine.cfg, _ref(0),
                      style=tiny_style_engine.style)
    req = fe.request("r1", {
        "text": "hi there", "duration_control": [2.0, 1.0],
        "pitch_control": 1.2,
    })
    assert isinstance(req.d_control, np.ndarray)
    assert req.d_control.shape == req.sequence.shape
    assert req.p_control == 1.2
    # the expanded request runs through the engine like any other
    result = tiny_style_engine.run([req])[0]
    assert result.mel_len > 0
    with pytest.raises(ValueError, match="per word"):
        fe.request("r2", {"text": "hi there",
                          "duration_control": [1.0, 2.0, 3.0]})
    with pytest.raises(ValueError, match="number"):
        fe.request("r3", {"text": "hi", "pitch_control": "fast"})


# ---------------------------------------------------------------------------
# HTTP roundtrip
# ---------------------------------------------------------------------------


def test_http_styles_roundtrip(tiny_style_engine, tmp_path):
    """POST /styles (wav upload) -> style_id -> /synthesize with it;
    GET /styles lists the entry; re-upload is an idempotent cache hit;
    a ref_dir-confined JSON registration works and `..` escapes 400."""
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    ref_dir = tmp_path / "refs"
    ref_dir.mkdir()
    (ref_dir / "house.wav").write_bytes(_wav_bytes(9))
    cfg = _tiny_cfg(ref_dir=str(ref_dir))
    server = SynthesisServer(
        tiny_style_engine,
        TextFrontend(cfg, None, style=tiny_style_engine.style),
        host="127.0.0.1", port=0,
    )
    host, port = server.address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        wav = _wav_bytes(11)
        conn.request("POST", "/styles", body=wav,
                     headers={"Content-Type": "audio/wav"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        assert resp.status == 200, out
        style_id = out["style_id"]
        assert style_id and out["cached"] is False
        assert out["ref_frames"] > 0

        # idempotent: same bytes -> same id, zero encoder work
        d0 = tiny_style_engine.style.dispatch_count
        conn.request("POST", "/styles", body=wav,
                     headers={"Content-Type": "audio/wav"})
        again = json.loads(conn.getresponse().read())
        assert again["style_id"] == style_id and again["cached"] is True
        assert tiny_style_engine.style.dispatch_count == d0

        conn.request("GET", "/styles")
        listing = json.loads(conn.getresponse().read())
        assert style_id in [e["style_id"] for e in listing["styles"]]
        assert listing["capacity"] == cfg.serve.style.cache_capacity

        # synthesize with the registered style — zero encoder dispatches
        conn.request("POST", "/synthesize", body=json.dumps(
            {"text": "hi", "style_id": style_id}
        ))
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 200, body
        assert body[:4] == b"RIFF"
        assert tiny_style_engine.style.dispatch_count == d0

        # unknown style_id -> 400
        conn.request("POST", "/synthesize", body=json.dumps(
            {"text": "hi", "style_id": "f" * 64}
        ))
        resp = conn.getresponse()
        assert resp.status == 400 and b"unknown style_id" in resp.read()

        # JSON registration from the confined ref_dir
        conn.request("POST", "/styles", body=json.dumps(
            {"ref_audio": "house.wav"}
        ), headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        reg = json.loads(resp.read())
        assert resp.status == 200, reg

        # `..` escape -> 400 (the security satellite)
        conn.request("POST", "/synthesize", body=json.dumps(
            {"text": "hi", "ref_audio": "../../etc/passwd"}
        ))
        resp = conn.getresponse()
        assert resp.status == 400 and b"escapes" in resp.read()

        # /healthz surfaces the style accounting
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        assert health["style"]["entries"] >= 2
        assert health["style"]["hits"] >= 1
        conn.close()
    finally:
        server.shutdown()


def test_http_e2e_zero_compiles_with_style_path(tiny_style_engine):
    """The full acceptance loop over HTTP: after warmup, serving cached
    styles (style_id) AND fresh uploads performs ZERO XLA compiles."""
    from speakingstyle_tpu.serving.engine import CompileMonitor
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    engine = tiny_style_engine
    server = SynthesisServer(
        engine, TextFrontend(engine.cfg, _ref(90), style=engine.style),
        host="127.0.0.1", port=0,
    )
    host, port = server.address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        # warmup: default-ref request (encodes once) + an upload
        conn.request("POST", "/synthesize", body=json.dumps({"text": "hi"}))
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 200
        conn.request("POST", "/styles", body=_wav_bytes(91),
                     headers={"Content-Type": "audio/wav"})
        style_id = json.loads(conn.getresponse().read())["style_id"]
        with CompileMonitor() as mon:
            for _ in range(3):
                conn.request("POST", "/synthesize", body=json.dumps(
                    {"text": "hello there", "style_id": style_id}
                ))
                resp = conn.getresponse()
                resp.read()
                assert resp.status == 200
        assert mon.count == 0, "HTTP style serving compiled in steady state"
        conn.close()
    finally:
        server.shutdown()
