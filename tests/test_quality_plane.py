"""Audio-quality observability plane (tier-1).

Six layers, mirroring the subsystem:
  1. validator verdict matrix — ``validate_wav`` against every reason
     in the bounded vocabulary (pure numpy, no jax);
  2. gate accounting — counters, the quality SLO stream, the
     ``quality_fail`` KEEP_REASON trace pin, last-fail record, events;
  3. longform stitcher choke point — every emitted piece validated;
  4. golden probes — anchor pin/load with digest verification, drift
     math, the edge-triggered page, probe errors staying OUT of the
     quality stream (fake router, no jax);
  5. SLO quality stream — burn-rate windows and the edge-triggered
     ``slo_quality_alert`` carrying the pinned exemplar trace;
  6. probe isolation + degradation drill — probe traffic invisible to
     the autoscaler's pressure signals and the latency SLO counters;
     ``tier_poison`` wiring through the fleet fault block; and the
     end-to-end drill on a real tiny engine: poisoned params keep
     serving with ZERO compiles while the validators and the prober
     both catch the garbage.
"""

import dataclasses
import threading
import time
from concurrent.futures import Future
from types import SimpleNamespace

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    FleetConfig,
    ModelConfig,
    QualityConfig,
    ReferenceEncoderConfig,
    ServeConfig,
    SloConfig,
    StyleConfig,
    TransformerConfig,
    VarianceEmbeddingConfig,
    VariancePredictorConfig,
)
from speakingstyle_tpu.faults import FaultPlan
from speakingstyle_tpu.obs import MetricsRegistry
from speakingstyle_tpu.obs.quality import (
    QUALITY_REASONS,
    QualityGate,
    last_fail,
    validate_wav,
)
from speakingstyle_tpu.obs.slo import SloEngine
from speakingstyle_tpu.obs.trace import SpanRing, TailSampler
from speakingstyle_tpu.serving.engine import SynthesisRequest
from speakingstyle_tpu.serving.fleet import FleetRouter
from speakingstyle_tpu.serving.longform import Stitcher
from speakingstyle_tpu.serving.probes import (
    GoldenProber,
    load_anchors,
    pin_anchors,
    probe_targets,
)

SR = 22050


def _qcfg(**kw):
    return QualityConfig(**kw)


def _speechlike(n=4096, seed=0):
    """A plausible healthy wav: a pitch-ish tone under broadband noise,
    well below full scale — must pass every validator."""
    rng = np.random.default_rng(seed)
    t = np.arange(n) / SR
    x = 0.3 * np.sin(2 * np.pi * 220 * t) + 0.05 * rng.standard_normal(n)
    return (x * 8000).astype(np.int16)


class _EventSink:
    def __init__(self):
        self.records = []

    def emit(self, event, **fields):
        self.records.append(dict(fields, event=event))


# ---------------------------------------------------------------------------
# 1. validator verdict matrix (no jax)
# ---------------------------------------------------------------------------


def test_validate_wav_passes_healthy_audio():
    v = validate_wav(_speechlike(), SR, _qcfg())
    assert v.ok and v.reasons == ()
    # white noise is the adversarial healthy case for flatness: ~0.56
    # on a single periodogram, which must stay under the 0.9 bar
    rng = np.random.default_rng(1)
    noise = (rng.standard_normal(8192) * 6000).astype(np.int16)
    v = validate_wav(noise, SR, _qcfg())
    assert v.ok
    assert 0.3 < v.flatness < 0.9


def test_validate_wav_non_finite_needs_the_float_hint():
    # int16 samples cannot carry NaN: the engine's pre-conversion
    # verdict arrives via finite= and must override
    wav = _speechlike()
    v = validate_wav(wav, SR, _qcfg(), finite=False)
    assert not v.ok and "non_finite" in v.reasons
    # float input self-checks when no hint is given
    f = np.zeros(2048, np.float32)
    f[100] = np.nan
    f[200:300] = 0.1  # keep the zero-run short of the silence bar
    v = validate_wav(f + 0.01, SR, _qcfg())
    assert "non_finite" in v.reasons


def test_validate_wav_clipping_silence_dc_flatness():
    q = _qcfg(clip_fraction_max=0.5, silence_run_ms_max=100.0,
              dc_offset_max=0.5, flatness_max=0.9)
    railed = np.full(2048, 32767, np.int16)
    v = validate_wav(railed, SR, q)
    assert not v.ok
    assert "clipping" in v.reasons and v.clip_fraction == pytest.approx(1.0)
    assert "dc_offset" in v.reasons   # a rail is also pure offset
    assert "flatness" in v.reasons    # and spectrally degenerate

    dead = _speechlike(3 * SR // 4).copy()
    dead[1000:1000 + SR // 4] = 0     # 250 ms of digital silence
    v = validate_wav(dead, SR, q)
    assert not v.ok and "silence" in v.reasons
    assert v.silence_run_ms == pytest.approx(250.0, rel=0.05)

    dc = (_speechlike() * 0).astype(np.int16) + 20000
    v = validate_wav(dc, SR, q)
    assert "dc_offset" in v.reasons

    assert set(QUALITY_REASONS) >= set(v.reasons)


def test_validate_wav_short_and_empty_edges():
    q = _qcfg(flatness_min_samples=256)
    # below flatness_min_samples the spectrum check is skipped — a
    # 100-sample constant burst must not page on flatness
    short = np.full(100, 5000, np.int16)
    v = validate_wav(short, SR, q)
    assert "flatness" not in v.reasons and v.flatness == 0.0
    v = validate_wav(np.zeros(0, np.int16), SR, q)
    assert v.ok  # empty = nothing to judge


# ---------------------------------------------------------------------------
# 2. gate accounting: counters, SLO stream, trace pin, last-fail
# ---------------------------------------------------------------------------


def test_quality_fail_is_a_keep_reason():
    assert "quality_fail" in TailSampler.KEEP_REASONS


def test_gate_accounts_verdicts_and_pins_the_trace():
    reg = MetricsRegistry()
    sink = _EventSink()
    ring = SpanRing(capacity=16, keep_traces=4)
    ring.add({"span_id": "s1", "trace_id": "t-bad", "name": "serve_request"})
    gate = QualityGate(_qcfg(), SR, registry=reg, events=sink, tier="t0",
                       trace_ring=ring, tail_sampler=TailSampler(0.0))

    ok = gate.check(_speechlike(), klass="interactive", req_id="good")
    assert ok.ok
    bad = gate.check(np.full(2048, 32767, np.int16), klass="interactive",
                     trace="t-bad", req_id="r-bad")
    assert not bad.ok

    assert reg.value("serve_quality_checks_total",
                     {"class": "interactive", "tier": "t0",
                      "source": "engine"}) == 2
    # the SLO good/bad stream the burn-rate engine differentiates
    assert reg.value("serve_quality_class_total",
                     {"class": "interactive"}) == 2
    assert reg.value("serve_quality_class_fail_total",
                     {"class": "interactive"}) == 1
    for reason in bad.reasons:
        assert reg.value(
            "serve_quality_fail_total",
            {"class": "interactive", "tier": "t0", "reason": reason},
        ) == 1
    # the failing wav pinned its trace exactly like a latency incident
    assert "t-bad" in ring.kept_trace_ids()
    assert ring.last_pinned_trace_id == "t-bad"
    lf = last_fail()
    assert lf is not None and lf["req_id"] == "r-bad"
    assert lf["trace_id"] == "t-bad" and lf["tier"] == "t0"
    ev = [r for r in sink.records if r["event"] == "quality_fail"]
    assert len(ev) == 1 and ev[0]["req_id"] == "r-bad"


def test_gate_record_false_and_disabled_paths():
    reg = MetricsRegistry()
    gate = QualityGate(_qcfg(), SR, registry=reg)
    # record=False (the HTTP boundary re-check): verdict computed,
    # process tallies bumped, but NO metric/SLO/event planes touched
    v = gate.check(np.full(2048, 32767, np.int16), klass="interactive",
                   record=False)
    assert not v.ok
    assert gate.status() == {"enabled": True, "checked": 1, "failed": 1}
    assert reg.value("serve_quality_class_total",
                     {"class": "interactive"}) == 0
    # a disabled gate is a no-op that always passes
    off = QualityGate(_qcfg(enabled=False), SR, registry=reg)
    assert off.check(np.full(64, 32767, np.int16)).ok
    assert off.status()["enabled"] is False


def test_gate_check_result_reuses_the_engine_verdict():
    gate = QualityGate(_qcfg(), SR)
    sentinel = object()
    assert gate.check_result(SimpleNamespace(quality=sentinel)) is sentinel
    assert gate.check_result(
        SimpleNamespace(quality=None, wav=None)) is None  # mel-only
    v = gate.check_result(SimpleNamespace(
        quality=None, wav=np.full(2048, 32767, np.int16), priority=None,
        tier=None, trace=None, id="x"))
    assert v is not None and not v.ok


# ---------------------------------------------------------------------------
# 3. longform stitcher choke point
# ---------------------------------------------------------------------------


def test_stitcher_validates_every_emitted_piece():
    reg = MetricsRegistry()
    gate = QualityGate(_qcfg(silence_run_ms_max=10.0), SR, registry=reg)
    st = Stitcher(
        4, quality_check=lambda w: gate.check(w, klass="batch",
                                              source="longform"),
    )
    pieces = st.feed(_speechlike(1024, seed=2))
    pieces += st.feed(np.zeros(1024, np.int16))  # a dead chunk
    pieces += st.finish()
    n = reg.value("serve_quality_checks_total",
                  {"class": "batch", "tier": "default",
                   "source": "longform"})
    assert n == len(pieces) > 0
    assert reg.value("serve_quality_class_fail_total",
                     {"class": "batch"}) >= 1  # the dead chunk was caught


# ---------------------------------------------------------------------------
# 4. golden probes: anchors, drift, the edge (fake router — no jax)
# ---------------------------------------------------------------------------


def _probe_cfg(**qkw):
    q = dict(probe_mel_tolerance=0.5, probe_style_tolerance=0.5,
             probe_interval_s=0.01)
    q.update(qkw)
    return Config(serve=ServeConfig(
        batch_buckets=[1, 2, 4], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=5.0,
        style=StyleConfig(ref_buckets=[32]),
        quality=QualityConfig(**q),
    ))


class _CannedRouter:
    """Fake single-tier router: deterministic mel per request id, with
    a mutable ``scale`` (drift injection) and ``boom`` (availability
    failure injection)."""

    tier = "t0"

    def __init__(self):
        self.scale = 1.0
        self.boom = False
        self.submitted = []

    def _mel(self, req):
        rng = np.random.default_rng(abs(hash(req.id)) % 2**31)
        return (rng.standard_normal((24, 80)).astype(np.float32)
                * self.scale)

    def submit(self, req):
        self.submitted.append(req)
        fut = Future()
        if self.boom:
            fut.set_exception(RuntimeError("replica unreachable"))
        else:
            fut.set_result(SimpleNamespace(mel=self._mel(req), mel_len=24))
        return fut


class _CannedStyle:
    """Fake StyleService: encode_live only (the prober must never touch
    the cache-inserting paths)."""

    def __init__(self):
        self.scale = 1.0

    def encode_live(self, mel, speaker=None):
        base = np.asarray(mel, np.float32).mean(axis=0)[:8]
        return SimpleNamespace(gamma=base * self.scale,
                               beta=-base * self.scale)


def test_probe_targets_shapes():
    r = _CannedRouter()
    assert probe_targets(r) == [("t0", r)]
    tiered = SimpleNamespace(tiers=lambda: ["a", "b"],
                             router_for=lambda t: t + "!")
    assert probe_targets(tiered) == [("a", "a!"), ("b", "b!")]


def test_anchor_pin_load_and_digest_verification(tmp_path):
    cfg = _probe_cfg()
    router = _CannedRouter()
    style = _CannedStyle()
    d = str(tmp_path / "anchors")
    manifest = pin_anchors(router, cfg, d, style=style)
    size = cfg.serve.tiers.golden_set_size
    assert len(manifest["tiers"]["t0"]) == size
    assert len(manifest["style"]) == size
    # every probe rode the probe class, never a tenant class
    assert {r.priority for r in router.submitted} == {"probe"}

    m2, mels, styles = load_anchors(d)
    assert set(mels["t0"]) == set(manifest["tiers"]["t0"])
    assert all(g.shape == b.shape for g, b in styles.values())

    # corrupt one anchor on disk: load must refuse, not re-baseline
    gid = sorted(mels["t0"])[0]
    path = tmp_path / "anchors" / "t0" / f"{gid}.npz"
    np.savez(path, mel=np.zeros((24, 80), np.float32))
    with pytest.raises(ValueError, match="digest mismatch"):
        load_anchors(d)


def test_prober_drift_edge_and_quality_stream(tmp_path):
    cfg = _probe_cfg()
    reg = MetricsRegistry()
    sink = _EventSink()
    router = _CannedRouter()
    style = _CannedStyle()
    prober = GoldenProber(router, cfg, style=style, registry=reg,
                          events=sink, anchor_dir=str(tmp_path),
                          start=False)
    prober.pin()
    size = cfg.serve.tiers.golden_set_size

    # healthy round: drift 0, the probe class's good stream grows
    s = prober.probe_once()
    assert s["tiers"]["t0"]["mel_drift"] == pytest.approx(0.0)
    assert s["style_drift"] == pytest.approx(0.0)
    # no edge has fired yet: the alerting map carries no keys
    assert not any(prober.alerting().values())
    assert reg.value("serve_quality_class_total",
                     {"class": "probe"}) == 2 * size  # mel + style legs
    assert reg.value("serve_quality_class_fail_total",
                     {"class": "probe"}) == 0
    assert reg.value("serve_probe_total",
                     {"tier": "t0", "outcome": "ok"}) == size

    # drifted fleet: edge fires ONCE, stream counts bad, gauges move
    router.scale = 10.0
    style.scale = 10.0
    s = prober.probe_once()
    assert s["tiers"]["t0"]["mel_drift"] > cfg.serve.quality.probe_mel_tolerance
    assert prober.alerting() == {"t0": True, "style": True}
    assert reg.value("serve_probe_drift_alerts_total", {"tier": "t0"}) == 1
    assert reg.value("serve_quality_class_fail_total",
                     {"class": "probe"}) == 2 * size
    prober.probe_once()  # sustained drift: edge-triggered, no re-count
    assert reg.value("serve_probe_drift_alerts_total", {"tier": "t0"}) == 1
    assert [r["event"] for r in sink.records
            if r["event"].startswith("probe_drift")] \
        == ["probe_drift_alert", "probe_drift_alert"]  # t0 + style

    # recovery resolves the edge
    router.scale = 1.0
    style.scale = 1.0
    prober.probe_once()
    assert prober.alerting() == {"t0": False, "style": False}
    assert "probe_drift_resolved" in [r["event"] for r in sink.records]

    st = prober.status()
    assert st["pinned"] and st["rounds"] == 4
    assert st["tiers"]["t0"]["alerting"] is False
    assert st["last_unix_ts"] <= time.time()


def test_probe_errors_stay_out_of_the_quality_stream(tmp_path):
    # availability failures are the chaos plane's problem: they count
    # as probe errors, never as quality stream bad (no false page on a
    # flaky replica)
    cfg = _probe_cfg()
    reg = MetricsRegistry()
    sink = _EventSink()
    router = _CannedRouter()
    prober = GoldenProber(router, cfg, registry=reg, events=sink,
                          anchor_dir=str(tmp_path), start=False)
    prober.pin()
    before = reg.value("serve_quality_class_total", {"class": "probe"})
    router.boom = True
    s = prober.probe_once()
    assert s["tiers"]["t0"]["outcomes"]["error"] \
        == cfg.serve.tiers.golden_set_size
    assert reg.value("serve_quality_class_total",
                     {"class": "probe"}) == before
    assert reg.value("serve_quality_class_fail_total",
                     {"class": "probe"}) == 0
    assert prober.alerting().get("t0", False) is False
    assert all(r["stage"] == "result" for r in sink.records
               if r["event"] == "probe_error")


def test_prober_requires_an_anchor_dir():
    with pytest.raises(ValueError, match="anchor_dir"):
        GoldenProber(_CannedRouter(), _probe_cfg(), start=False)


# ---------------------------------------------------------------------------
# 5. SLO quality stream: burn windows + edge-triggered page
# ---------------------------------------------------------------------------


def test_slo_quality_stream_pages_and_carries_the_pinned_trace():
    reg = MetricsRegistry()
    sink = _EventSink()
    ring = SpanRing(capacity=16, keep_traces=4)
    ring.add({"span_id": "s1", "trace_id": "t-garbage"})
    ring.pin("t-garbage")  # what a failing validator just did
    scfg = SloConfig(
        objectives={"interactive": 0.999},
        quality_objectives={"interactive": 0.99, "probe": 0.99},
        fast_window_s=60.0, slow_window_s=600.0,
        fast_burn_threshold=14.4, slow_burn_threshold=6.0, tick_s=5.0,
    )
    eng = SloEngine(reg, scfg, events=sink, trace_ring=ring, start=False)
    total = reg.counter("serve_quality_class_total",
                        labels={"class": "interactive"})
    bad = reg.counter("serve_quality_class_fail_total",
                      labels={"class": "interactive"})
    t0 = 1000.0
    total.inc(1000)
    eng.step(now=t0)
    assert eng.quality_alerting() == {"interactive": False, "probe": False}

    # 300 garbage wavs over 1000: ratio 0.3 over a 1% budget = burn 30
    total.inc(1000)
    bad.inc(300)
    eng.step(now=t0 + 30.0)
    assert eng.quality_alerting()["interactive"] is True
    assert eng.quality_burn_rate("interactive", "fast") \
        == pytest.approx(30.0)
    assert reg.value("serve_slo_quality_burn_rate",
                     {"class": "interactive", "window": "fast"}) \
        == pytest.approx(30.0)
    assert reg.value("serve_slo_quality_alerts_total",
                     {"class": "interactive"}) == 1
    alert = [r for r in sink.records if r["event"] == "slo_quality_alert"]
    assert len(alert) == 1 and alert[0]["klass"] == "interactive"
    assert alert[0]["trace_id"] == "t-garbage"  # jump-to-trace handle
    # the latency stream did NOT page: quality is its own stream
    assert eng.step(now=t0 + 35.0) == {"interactive": False}
    assert len([r for r in sink.records
                if r["event"] == "slo_quality_alert"]) == 1  # edge

    # clean wavs push the bad sample past both windows: resolved
    total.inc(100_000)
    eng.step(now=t0 + 400.0)
    eng.step(now=t0 + 700.0)
    assert eng.quality_alerting()["interactive"] is False
    assert sink.records[-1]["event"] == "slo_quality_resolved"
    qs = eng.quality_status()["interactive"]
    assert qs["objective"] == 0.99 and qs["alerting"] is False


# ---------------------------------------------------------------------------
# 6. probe isolation from the autoscaler + tier_poison wiring
# ---------------------------------------------------------------------------


def _fleet_cfg(**fleet_kw):
    fleet = dict(queue_depth=32, stream_window=8)
    fleet.update(fleet_kw)
    return Config(serve=ServeConfig(
        batch_buckets=[1], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=5.0,
        fleet=FleetConfig(**fleet),
    ))


class _FakeEngine:
    """Replica stand-in; optional gate blocks the FIRST dispatch."""

    def __init__(self, gate=None):
        self.dispatches = []
        self.gate = gate
        self.entered = threading.Event()
        self._first = True
        self.poisoned = False

    def precompile(self):
        return 0.0

    def poison_params(self, precision=None, scale=1e3):
        self.poisoned = True
        return precision or "float32"

    def run(self, requests):
        if self.gate is not None and self._first:
            self._first = False
            self.entered.set()
            self.gate.wait(timeout=10)
        self.dispatches.extend(r.id for r in requests)
        return [SimpleNamespace(id=r.id, bucket=None, mel_len=1)
                for r in requests]


def _req(i, **kw):
    return SynthesisRequest(
        id=f"r{i}", sequence=np.ones(8, np.int32),
        ref_mel=np.zeros((4, 80), np.float32), **kw,
    )


def test_probe_class_is_invisible_to_autoscaler_signals():
    reg = MetricsRegistry()
    gate = threading.Event()
    eng = _FakeEngine(gate=gate)
    router = FleetRouter(lambda r: eng, _fleet_cfg(), replicas=1,
                         registry=reg)
    try:
        assert router.wait_ready(timeout=10)
        futs = [router.submit(_req(0, priority="probe"))]
        assert eng.entered.wait(timeout=10)  # probe-only in-flight claim
        futs.append(router.submit(_req(1, priority="probe")))
        futs.append(router.submit(_req(2, priority="probe")))
        futs.append(router.submit(_req(3, priority="interactive")))
        # heap holds 2 probes + 1 tenant: the autoscaler's queue signal
        # sees ONLY the tenant; a probe-only claim is not "busy"
        assert router.pending_depth() == 1
        assert router.occupancy() == 0.0
        gate.set()
        for f in futs:
            f.result(timeout=10)
        # probes on their own admission family, never the tenant's
        assert reg.value("serve_probe_requests_total") == 3
        assert reg.value("serve_class_requests_total",
                         {"class": "probe"}) == 0
        assert reg.value("serve_class_requests_total",
                         {"class": "interactive"}) == 1
    finally:
        gate.set()
        router.close()


def test_tier_poison_fault_poisons_in_place_and_keeps_serving():
    eng = _FakeEngine()
    plan = FaultPlan()
    router = FleetRouter(lambda r: eng, _fleet_cfg(), replicas=1,
                         fault_plan=plan)
    try:
        assert router.wait_ready(timeout=10)
        router.submit(_req(0)).result(timeout=10)
        assert eng.poisoned is False
        plan.arm("tier_poison", router.dispatch_total + 1)
        # the poisoning dispatch SUCCEEDS — no raise, no failover, the
        # audio is garbage only the quality plane can see
        router.submit(_req(1)).result(timeout=10)
        assert eng.poisoned is True
        assert router.states() == {0: "ready"}
        router.submit(_req(2)).result(timeout=10)  # still serving
        assert eng.dispatches == ["r0", "r1", "r2"]
    finally:
        router.close()


# ---------------------------------------------------------------------------
# 7. the degradation drill on a real tiny engine (jax, module-scoped)
# ---------------------------------------------------------------------------


def _tiny_cfg():
    return Config(
        model=ModelConfig(
            transformer=TransformerConfig(
                encoder_layer=1, decoder_layer=1, encoder_hidden=16,
                decoder_hidden=16, conv_filter_size=16,
                conv_kernel_size=(3, 1),
            ),
            reference_encoder=ReferenceEncoderConfig(
                encoder_layer=1, encoder_head=2, encoder_hidden=16,
                conv_layer=1, conv_filter_size=16,
            ),
            variance_predictor=VariancePredictorConfig(filter_size=16),
            variance_embedding=VarianceEmbeddingConfig(n_bins=8),
            postnet_embedding_dim=16, postnet_layers=2,
            max_seq_len=48, compute_dtype="float32",
        ),
        serve=ServeConfig(
            batch_buckets=[1], src_buckets=[16], mel_buckets=[32],
            frames_per_phoneme=2, max_wait_ms=5.0,
            style=StyleConfig(ref_buckets=[32]),
        ),
    )


@pytest.fixture(scope="module")
def tiny_engine():
    """(cfg, registry, engine): one precompiled tiny engine shared by
    the real-audio choke-point tests."""
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.models.hifigan import Generator
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg = _tiny_cfg()
    model = build_model(cfg, n_position=49)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    bias = variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"]
    variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"] = bias + 1.1
    gen = Generator(
        upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4),
        upsample_initial_channel=16, resblock_kernel_sizes=(3,),
        resblock_dilation_sizes=((1,),),
    )
    gparams = gen.init(
        jax.random.PRNGKey(0), np.zeros((1, 8, 80), np.float32)
    )["params"]
    registry = MetricsRegistry()
    engine = SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                             model=model, registry=registry)
    engine.precompile()
    return cfg, registry, engine


class _EngineRouter:
    """Single-tier router facade over a bare engine (what the prober
    needs: submit -> future of one SynthesisResult)."""

    tier = "tiny"

    def __init__(self, engine):
        self.engine = engine

    def submit(self, req):
        fut = Future()
        try:
            fut.set_result(self.engine.run([req])[0])
        except Exception as e:  # pragma: no cover - surfaced by tests
            fut.set_exception(e)
        return fut


def test_engine_run_choke_point_attaches_the_verdict(tiny_engine):
    cfg, reg, engine = tiny_engine
    res = engine.run([_req(10, priority="interactive")])[0]
    assert res.quality is not None and res.quality.ok
    assert reg.value("serve_quality_checks_total",
                     {"class": "interactive", "tier": "default",
                      "source": "engine"}) >= 1
    # quality_check=False is the bench's unchecked arm: no verdict,
    # no counter motion
    before = reg.value("serve_quality_class_total",
                       {"class": "interactive"})
    res = engine.run([_req(11, priority="interactive",
                           quality_check=False)])[0]
    assert res.quality is None
    assert reg.value("serve_quality_class_total",
                     {"class": "interactive"}) == before


def test_streaming_window_choke_point(tiny_engine):
    cfg, reg, engine = tiny_engine
    res = engine.run([_req(12, priority="interactive", stream=True)])[0]
    mel = np.asarray(res.mel, np.float32)[: int(res.mel_len)]
    before = reg.value("serve_quality_checks_total",
                       {"class": "interactive", "tier": "default",
                        "source": "stream"})
    handle = engine.vocode_dispatch(mel, klass="interactive")
    wav = engine.vocode_collect(handle)
    assert wav.dtype == np.int16 and wav.size > 0
    assert reg.value("serve_quality_checks_total",
                     {"class": "interactive", "tier": "default",
                      "source": "stream"}) == before + 1


def test_tier_poison_drill_validators_and_probes_catch_it(
        tiny_engine, tmp_path):
    from speakingstyle_tpu.serving.engine import CompileMonitor

    cfg, reg, engine = tiny_engine
    # a poisoned net saturates unpredictably — rails (validators catch
    # clipping) or collapses to near-silence (short wavs the per-wav
    # checks legitimately pass). The probe leg is the GUARANTEED
    # detector — any departure from the pinned anchors is drift — which
    # is why the plane carries both; anchor the drill on it with a
    # tight tolerance
    cfg = dataclasses.replace(cfg, serve=dataclasses.replace(
        cfg.serve, quality=dataclasses.replace(
            cfg.serve.quality, probe_mel_tolerance=1e-3)))
    router = _EngineRouter(engine)
    prober = GoldenProber(router, cfg, registry=reg,
                          anchor_dir=str(tmp_path), start=False)
    prober.pin()
    s = prober.probe_once()
    assert s["tiers"]["tiny"]["mel_drift"] == pytest.approx(0.0)
    assert prober.alerting().get("tiny", False) is False

    engine.poison_params()
    with CompileMonitor() as mon:
        res = engine.run([_req(13, priority="interactive")])[0]
        s = prober.probe_once()
    # same shapes, same programs: the poison costs ZERO compiles —
    # nothing but the quality plane can see it
    assert mon.count == 0
    assert res.quality is not None  # the choke point ran regardless
    drift = s["tiers"]["tiny"]["mel_drift"]
    assert drift > cfg.serve.quality.probe_mel_tolerance
    assert prober.alerting()["tiny"] is True
