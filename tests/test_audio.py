"""Audio DSP tests: STFT/mel semantics vs torch.stft, round-trips, file IO.

The reference's TacotronSTFT (reference: audio/stft.py:140-178) is the
golden semantic: reflect pad, hann window, |rfft|, Slaney mel, log-clamp
compression, L2-norm energy. torch (CPU) is available in the test env, so
we cross-check the magnitude path directly against torch.stft.
"""

import numpy as np
import pytest
import torch

from speakingstyle_tpu.audio import (
    MelExtractor,
    get_mel_from_wav,
    griffin_lim,
    istft,
    load_wav,
    mel_filterbank,
    save_wav,
    stft_magnitude,
)

SR, N_FFT, HOP, WIN = 22050, 1024, 256, 1024


def _test_wav(seconds=0.5, sr=SR):
    t = np.arange(int(seconds * sr)) / sr
    sig = 0.5 * np.sin(2 * np.pi * 220 * t) + 0.2 * np.sin(2 * np.pi * 3300 * t)
    return sig.astype(np.float32)


def test_stft_matches_torch():
    y = _test_wav()
    mag = np.asarray(stft_magnitude(y[None], N_FFT, HOP, WIN))[0]
    ref = torch.stft(
        torch.from_numpy(y),
        n_fft=N_FFT,
        hop_length=HOP,
        win_length=WIN,
        window=torch.hann_window(WIN, periodic=True),
        center=True,
        pad_mode="reflect",
        return_complex=True,
    ).abs().numpy()
    assert mag.shape == ref.shape
    np.testing.assert_allclose(mag, ref, atol=2e-3)


def test_frame_count():
    y = _test_wav()
    mag = stft_magnitude(y[None], N_FFT, HOP, WIN)
    assert mag.shape == (1, 1 + N_FFT // 2, len(y) // HOP + 1)


def test_mel_filterbank_properties():
    fb = mel_filterbank(SR, N_FFT, 80, 0.0, 8000.0)
    assert fb.shape == (80, 513)
    assert (fb >= 0).all()
    # each filter has support, filters cover low->high monotonically
    peaks = fb.argmax(axis=1)
    assert (np.diff(peaks) >= 0).all()
    assert fb.sum() > 0


def test_slaney_mel_scale_invariants():
    """Analytic invariants of the Slaney mel scale (librosa htk=False)."""
    from speakingstyle_tpu.audio.mel import hz_to_mel, mel_to_hz

    assert abs(hz_to_mel(1000.0) - 15.0) < 1e-9  # log knee at 1 kHz = mel 15
    assert abs(hz_to_mel(200.0 / 3) - 1.0) < 1e-9  # linear region: 200/3 Hz/mel
    assert abs(hz_to_mel(6400.0) - 42.0) < 1e-9  # 6400 = 1000*6.4 -> 15+27
    assert abs(mel_to_hz(15.0) - 1000.0) < 1e-6
    f = np.array([0.0, 500.0, 999.0, 1001.0, 4000.0, 8000.0])
    np.testing.assert_allclose(mel_to_hz(hz_to_mel(f)), f, rtol=1e-9, atol=1e-6)


def test_mel_filterbank_golden_values():
    """Regression pin of Slaney-normalized filterbank entries (peak + shoulder
    of filters across the band), generated from the published Slaney formulas
    that librosa.filters.mel implements (reference: audio/stft.py:145-147)."""
    fb = mel_filterbank(SR, N_FFT, 80, 0.0, 8000.0)
    golden = [
        (0, 2, 0.02265139), (0, 3, 0.00712367),
        (10, 19, 0.02649254), (10, 20, 0.01168657),
        (20, 36, 0.02192963), (20, 37, 0.01624948),
        (40, 80, 0.01489547), (40, 81, 0.01006495),
        (60, 172, 0.00663741), (60, 173, 0.00633792),
        (79, 358, 0.00326599), (79, 359, 0.00302441),
    ]
    for i, j, v in golden:
        np.testing.assert_allclose(fb[i, j], v, atol=1e-7)


def test_mel_extractor_output():
    ex = MelExtractor(N_FFT, HOP, WIN, 80, SR, 0.0, 8000.0)
    y = _test_wav()
    mel, energy = get_mel_from_wav(y, ex)
    assert mel.shape == (80, len(y) // HOP + 1)
    assert energy.shape == (len(y) // HOP + 1,)
    # log compression floor
    assert mel.min() >= np.log(1e-5) - 1e-4
    assert np.isfinite(mel).all() and (energy >= 0).all()


def test_istft_roundtrip():
    y = _test_wav(0.25)
    ynp = y[None]
    import jax.numpy as jnp

    frames = stft_magnitude(ynp, N_FFT, HOP, WIN)
    # get phase via the same framing
    import speakingstyle_tpu.audio.tools as tools

    phase = tools._stft_phase(jnp.asarray(ynp), N_FFT, HOP, WIN)
    rec = np.asarray(istft(frames, phase, N_FFT, HOP, WIN))[0]
    n = min(len(rec), len(y))
    # interior should match closely (edges lose energy to the window taper)
    np.testing.assert_allclose(rec[N_FFT : n - N_FFT], y[N_FFT : n - N_FFT], atol=1e-3)


def test_griffin_lim_reconstructs_tone():
    y = _test_wav(0.25)
    mag = stft_magnitude(y[None], N_FFT, HOP, WIN)
    rec = np.asarray(griffin_lim(mag, N_FFT, HOP, WIN, n_iters=8))[0]
    assert np.isfinite(rec).all()
    # reconstructed spectrum should concentrate at the same frequencies
    orig_f = np.abs(np.fft.rfft(y))
    rec_f = np.abs(np.fft.rfft(rec[: len(y)]))
    assert abs(orig_f.argmax() - rec_f.argmax()) <= 2


def test_wav_io_roundtrip(tmp_path):
    y = _test_wav(0.1)
    p = str(tmp_path / "x.wav")
    save_wav(p, y, SR)
    loaded, sr = load_wav(p)
    assert sr == SR
    np.testing.assert_allclose(loaded[: len(y)], y, atol=1e-3)


def test_load_wav_resample(tmp_path):
    y = _test_wav(0.1, sr=16000)
    p = str(tmp_path / "x16.wav")
    save_wav(p, y, 16000)
    loaded, sr = load_wav(p, target_sr=SR)
    assert sr == SR
    assert abs(len(loaded) - int(len(y) * SR / 16000)) <= 2
