"""ProgramRegistry + replicas that ARE mesh slices.

Four layers, mirroring ISSUE 14's acceptance bar:
  1. registry cache-key semantics — (name, shape signature, donation,
     shardings) dedupes; a repeat request returns the SAME Compiled
     without recompiling;
  2. cross-mesh serve parity — ONE set of weights behind a 1x1, 1x2,
     and 2x2 replica serves a single request BIT-identically (every
     single-request dispatch replicates per dispatch_sharding's
     divisibility rule), and a data-sharded coalesced batch agrees to
     float32 ULP;
  3. zero steady-state compiles on a MESH replica, measured on the
     backend monitoring bus (JL008's invariant, now on sharded AOT
     programs), with /debug/programs-shaped card rows recording the
     mesh geometry and sharding specs;
  4. fleet e2e — a 1x1 and a 1x2 replica behind ONE router: the router
     only sees the engine interface, so mesh slices drop in unchanged.

conftest.py forces 8 virtual CPU devices
(``--xla_force_host_platform_device_count=8``), so every geometry here
fits on the CPU proxy.
"""

import dataclasses

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    ModelConfig,
    ParallelConfig,
    ReferenceEncoderConfig,
    ServeConfig,
    StyleConfig,
    TransformerConfig,
    VarianceEmbeddingConfig,
    VariancePredictorConfig,
)
from speakingstyle_tpu.obs import MetricsRegistry
from speakingstyle_tpu.serving.engine import CompileMonitor, SynthesisRequest

# ---------------------------------------------------------------------------
# registry cache-key semantics (tiny programs, no model)
# ---------------------------------------------------------------------------


def test_registry_cache_key_dedupes_and_rebuilds():
    import jax
    import jax.numpy as jnp

    from speakingstyle_tpu.parallel import ProgramRegistry

    registry = ProgramRegistry()

    def f(x):
        return x * 2.0

    a4 = (jax.ShapeDtypeStruct((4,), jnp.float32),)
    a8 = (jax.ShapeDtypeStruct((8,), jnp.float32),)

    e1 = registry.compile(f, a4, name="double")
    assert registry.compile_count == 1 and len(registry) == 1
    # identical (name, signature, donation, shardings) -> the SAME
    # Compiled object, no recompile
    assert registry.compile(f, a4, name="double") is e1
    assert registry.compile_count == 1
    # a different shape bucket is a different program
    e2 = registry.compile(f, a8, name="double")
    assert e2 is not e1 and registry.compile_count == 2
    # donation participates in the key
    e3 = registry.compile(f, a4, name="double", donate_argnums=(0,))
    assert e3 is not e1 and registry.compile_count == 3
    # get() resolves the latest program under a name; the card table has
    # one JSON-ready row per program in compile order
    assert registry.get("double") is e3
    rows = registry.programs()
    assert [r["name"] for r in rows] == ["double"] * 3
    assert all("flops" in r and "donate_argnums" in r for r in rows)
    assert rows[2]["donate_argnums"] == [0]
    # single-device programs record no mesh
    assert rows[0]["mesh"] is None and rows[0]["in_shardings"] is None


def test_registry_sharding_specs_are_part_of_the_key():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from speakingstyle_tpu.parallel import ProgramRegistry, make_mesh

    registry = ProgramRegistry()
    mesh = make_mesh(data=2, model=1, devices=jax.devices()[:2])
    bsh = NamedSharding(mesh, P("data"))

    def f(x):
        return x + 1.0

    a4 = (jax.ShapeDtypeStruct((4,), jnp.float32),)
    plain = registry.compile(f, a4, name="inc")
    sharded = registry.compile(
        f, a4, name="inc", in_shardings=(bsh,), out_shardings=bsh
    )
    assert sharded is not plain and registry.compile_count == 2
    # and the repeat sharded request still dedupes
    assert registry.compile(
        f, a4, name="inc", in_shardings=(bsh,), out_shardings=bsh
    ) is sharded
    assert registry.compile_count == 2
    row = registry.programs()[-1]
    assert row["mesh"] == "2x1"
    assert "data" in row["in_shardings"]


def test_registry_counter_lands_in_shared_metrics():
    import jax
    import jax.numpy as jnp

    from speakingstyle_tpu.parallel import ProgramRegistry

    metrics = MetricsRegistry()
    registry = ProgramRegistry(
        metrics, counter_name="serve_compiles_total", prefix="serve"
    )
    registry.compile(
        lambda x: x, (jax.ShapeDtypeStruct((2,), jnp.float32),), name="id"
    )
    assert metrics.value("serve_compiles_total") == 1
    # the card table is queryable by name for the debug endpoints
    assert registry.card("id") is not None


def test_registry_persistent_cache_survives_late_enablement(tmp_path):
    # jax latches its persistent-cache state on the FIRST compile of the
    # process; a serve process compiles during checkpoint restore, before
    # the engine's registry exists. A registry constructed afterwards must
    # still get its writes through (the latch is reset), or warm restarts
    # silently stop hitting while the request counters keep ticking.
    import os

    import jax
    import jax.numpy as jnp

    from speakingstyle_tpu.parallel import ProgramRegistry

    cache_dir = tmp_path / "cc"
    prev_dir = jax.config.jax_compilation_cache_dir
    # latch: ensure at least one compile happened with no cache dir set
    jax.jit(lambda x: x + 1.0)(jnp.zeros((2,), jnp.float32))
    try:
        registry = ProgramRegistry(cache_dir=str(cache_dir))
        registry.compile(
            lambda x: x * 3.0,
            (jax.ShapeDtypeStruct((4,), jnp.float32),),
            name="late",
        )
        assert any(
            f.endswith("-cache") for f in os.listdir(cache_dir)
        ), "registry compile never reached the persistent cache"
    finally:
        # leave the process-global cache the way we found it
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# mesh-slice replicas (tiny model, real jax over virtual devices)
# ---------------------------------------------------------------------------


def _tiny_cfg(mesh=(1, 1)):
    return Config(
        model=ModelConfig(
            transformer=TransformerConfig(
                encoder_layer=1, decoder_layer=1, encoder_hidden=16,
                decoder_hidden=16, conv_filter_size=16,
                conv_kernel_size=(3, 1),
            ),
            reference_encoder=ReferenceEncoderConfig(
                encoder_layer=1, encoder_head=2, encoder_hidden=16,
                conv_layer=1, conv_filter_size=16,
            ),
            variance_predictor=VariancePredictorConfig(filter_size=16),
            variance_embedding=VarianceEmbeddingConfig(n_bins=8),
            postnet_embedding_dim=16, postnet_layers=2,
            max_seq_len=48, compute_dtype="float32",
        ),
        serve=ServeConfig(
            batch_buckets=[1, 2], src_buckets=[16], mel_buckets=[32],
            frames_per_phoneme=2, max_wait_ms=20.0,
            style=StyleConfig(ref_buckets=[32]),
            parallel=ParallelConfig(mesh=list(mesh)),
        ),
    )


@pytest.fixture(scope="module")
def tiny_parts():
    """Model/weights/vocoder built ONCE — the 'one checkpoint' every
    mesh geometry below consumes unchanged."""
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.models.hifigan import Generator

    cfg = _tiny_cfg()
    model = build_model(cfg, n_position=49)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    bias = variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"]
    variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"] = bias + 1.1
    gen = Generator(
        upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4),
        upsample_initial_channel=16, resblock_kernel_sizes=(3,),
        resblock_dilation_sizes=((1,),),
    )
    gparams = gen.init(
        jax.random.PRNGKey(0), np.zeros((1, 8, 80), np.float32)
    )["params"]
    return model, variables, gen, gparams


def _engine_for(mesh, parts, registry=None):
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    model, variables, gen, gparams = parts
    engine = SynthesisEngine(
        _tiny_cfg(mesh), variables, vocoder=(gen, gparams), model=model,
        registry=registry,
    )
    engine.precompile()
    return engine


@pytest.fixture(scope="module")
def engine_1x1(tiny_parts):
    return _engine_for((1, 1), tiny_parts)


@pytest.fixture(scope="module")
def engine_1x2(tiny_parts):
    return _engine_for((1, 2), tiny_parts)


@pytest.fixture(scope="module")
def engine_2x2(tiny_parts):
    return _engine_for((2, 2), tiny_parts)


def _mkreq(i, L=10, T=20):
    rng = np.random.default_rng(i)
    return SynthesisRequest(
        id=f"utt{i}",
        sequence=rng.integers(1, 300, L).astype(np.int32),
        ref_mel=rng.standard_normal((T, 80)).astype(np.float32),
    )


def test_single_request_bit_parity_across_geometries(
        engine_1x1, engine_1x2, engine_2x2):
    """THE portability contract: the same checkpoint behind a 1x2 or
    2x2 replica serves a single request bit-identically to the 1x1
    engine — a b=1 dispatch never divides by dp, so dispatch_sharding
    replicates it and every device runs the identical program."""
    base = engine_1x1.run([_mkreq(0)])[0]
    assert base.mel_len > 0 and base.wav is not None
    for engine in (engine_1x2, engine_2x2):
        res = engine.run([_mkreq(0)])[0]
        assert res.mel_len == base.mel_len
        np.testing.assert_array_equal(res.durations, base.durations)
        np.testing.assert_array_equal(res.mel, base.mel)
        np.testing.assert_array_equal(res.wav, base.wav)


def test_dp1_slice_is_bitwise_even_for_coalesced_batches(
        engine_1x1, engine_1x2):
    """On a dp=1 slice (mesh [1, 2]) NO bucket data-shards, so even the
    b=2 coalesced dispatch is bitwise equal to 1x1."""
    base = engine_1x1.run([_mkreq(1), _mkreq(2)])
    res = engine_1x2.run([_mkreq(1), _mkreq(2)])
    for rb, rr in zip(base, res):
        np.testing.assert_array_equal(rr.mel, rb.mel)
        np.testing.assert_array_equal(rr.wav, rb.wav)


def test_data_sharded_batch_agrees_to_float32_ulp(engine_1x1, engine_2x2):
    """A coalesced b=2 dispatch on dp=2 data-shards (1 row per shard):
    XLA generates a different program for the shard shape, so outputs
    agree to float32 ULP, not bitwise — the same numerics trade DP
    training makes. Durations survive bitwise (argmax-free rounding of
    ULP-close values at these magnitudes)."""
    base = engine_1x1.run([_mkreq(1), _mkreq(2)])
    res = engine_2x2.run([_mkreq(1), _mkreq(2)])
    for rb, rr in zip(base, res):
        assert rr.mel_len == rb.mel_len
        np.testing.assert_array_equal(rr.durations, rb.durations)
        np.testing.assert_allclose(rr.mel, rb.mel, rtol=0, atol=1e-4)
        assert int(np.abs(
            rr.wav.astype(np.int32) - rb.wav.astype(np.int32)
        ).max()) <= 2  # int16 rounding of ULP-close floats


def test_mesh_replica_zero_steady_state_compiles(engine_2x2):
    """JL008's acceptance invariant on a MESH replica: after per-bucket
    warmup the monitoring bus sees ZERO compiles — every sharded AOT
    program came out of precompile, and dispatch_sharding routes each
    batch onto exactly the sharding its program was built for."""
    engine = engine_2x2
    assert engine.mesh is not None and engine.compile_count == 4
    for b in engine.lattice.batch_buckets:
        engine.run([_mkreq(700 + b * 10 + j) for j in range(b)])
    with CompileMonitor() as mon:
        engine.run([_mkreq(10)])                 # replicated b=1
        engine.run([_mkreq(11), _mkreq(12)])     # data-sharded b=2
        engine.run([_mkreq(13)])
    assert mon.count == 0, "the mesh replica compiled after warmup"
    assert engine.compile_count == 4


def test_mesh_replica_cards_record_shardings(engine_2x2):
    """The /debug/programs payload: registry card rows carry the mesh
    geometry and in/out sharding specs of every compiled program."""
    rows = engine_2x2.programs()
    assert len(rows) == engine_2x2.compile_count == 4
    assert all(r["mesh"] == "2x2" for r in rows)
    acoustic_b2 = [r for r in rows if r["name"] == "acoustic:b2.s16.m32"]
    assert len(acoustic_b2) == 1
    # b=2 divides dp=2 -> batch axis over 'data'; weights replicated
    assert "data" in acoustic_b2[0]["in_shardings"]
    assert "data" in acoustic_b2[0]["out_shardings"]
    # b=1 does not divide dp=2 -> fully replicated program
    acoustic_b1 = [r for r in rows if r["name"] == "acoustic:b1.s16.m32"]
    assert "data" not in acoustic_b1[0]["out_shardings"]


def test_fleet_mixed_mesh_replicas_behind_one_router(tiny_parts):
    """A 1x1 replica and a 1x2 mesh-slice replica behind ONE router:
    FleetRouter only touches the engine interface, so a replica being a
    mesh slice is invisible to routing, and steady state stays at zero
    compiles fleet-wide."""
    from speakingstyle_tpu.serving.engine import SynthesisEngine
    from speakingstyle_tpu.serving.fleet import FleetRouter

    model, variables, gen, gparams = tiny_parts
    reg = MetricsRegistry()

    def factory_for(mesh):
        cfg = _tiny_cfg(mesh)

        def factory(registry):
            return SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                                   model=model, registry=registry)
        return factory

    with FleetRouter(factory_for((1, 1)), _tiny_cfg(), replicas=1,
                     registry=reg) as router:
        assert router.wait_ready(timeout=300, n=1)
        router.start_replica(factory=factory_for((1, 2)))
        assert router.wait_ready(timeout=300, n=2)
        engines = router.engines()
        assert len(engines) == 2
        assert engines[0].mesh is None
        assert engines[1].mesh is not None
        for engine in engines:
            for b in engine.lattice.batch_buckets:
                engine.run([_mkreq(800 + b * 10 + j) for j in range(b)])
        total_before = reg.value("serve_compiles_total")
        with CompileMonitor() as mon:
            futs = [router.submit(_mkreq(i)) for i in range(8)]
            results = [f.result(timeout=120) for f in futs]
        assert mon.count == 0, "the mixed-mesh fleet compiled after warmup"
        assert reg.value("serve_compiles_total") == total_before
        for i, r in enumerate(results):
            assert r.id == f"utt{i}"
            assert r.wav is not None and r.wav.dtype == np.int16
        # the fleet served every request
        snap = reg.snapshot()["counters"]
        served = [v for k, v in snap.items()
                  if k.startswith("serve_replica_requests_total")]
        assert sum(served) >= 8
        # and the two replicas agree bitwise on the same request
        r11 = engines[0].run([_mkreq(99)])[0]
        r12 = engines[1].run([_mkreq(99)])[0]
        np.testing.assert_array_equal(r11.wav, r12.wav)
