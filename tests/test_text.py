"""Text frontend unit tests (vocabulary parity + cleaner behavior)."""

from speakingstyle_tpu.text import (
    PAD_ID,
    SYMBOL_TO_ID,
    VOCAB_SIZE,
    sequence_to_text,
    symbols,
    text_to_sequence,
)
from speakingstyle_tpu.text.cleaners import english_cleaners
from speakingstyle_tpu.text.numbers import (
    normalize_numbers,
    number_to_words,
    ordinal_to_words,
)


def test_symbol_inventory_layout():
    # 360 symbols, vocab 361 (reference: text/symbols.py:21-29, Models.py:40)
    assert len(symbols) == 360
    assert VOCAB_SIZE == 361
    assert symbols[0] == "_" and PAD_ID == 0
    assert symbols[1] == "-"
    assert symbols[-3:] == ["@sp", "@spn", "@sil"]
    # spot-check ARPAbet block starts right after letters
    assert symbols[12:64] == list("ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz")
    assert symbols[64] == "@AA"
    assert len(set(symbols)) == 360  # no duplicates


def test_braced_phones_bypass_cleaners():
    seq = text_to_sequence("{HH AH0 L OW1}", ["english_cleaners"])
    assert seq == [SYMBOL_TO_ID[s] for s in ["@HH", "@AH0", "@L", "@OW1"]]


def test_mixed_text_roundtrip():
    seq = text_to_sequence("hi {S P IY1 CH} there", ["english_cleaners"])
    assert sequence_to_text(seq) == "hi {S P IY1 CH} there"


def test_pad_never_emitted():
    assert SYMBOL_TO_ID["_"] not in text_to_sequence("a_b", ["basic_cleaners"])


def test_english_cleaners():
    assert english_cleaners("Dr. Smith") == "doctor smith"
    assert english_cleaners("Mr.  Jones\n lives") == "mister jones lives"
    assert english_cleaners("HELLO") == "hello"


def test_number_normalization():
    # 1000 < n < 3000 reads as digit pairs (the reference's year heuristic,
    # reference: text/numbers.py:50-62)
    assert normalize_numbers("1,234") == "twelve thirty-four"
    # inflect-style group commas (reference relies on inflect's rendering)
    assert normalize_numbers("3,456") == "three thousand, four hundred fifty-six"
    assert normalize_numbers("$1.50") == "one dollar, fifty cents"
    assert normalize_numbers("$2") == "two dollars"
    assert normalize_numbers("2nd") == "second"
    assert normalize_numbers("21st") == "twenty-first"
    assert normalize_numbers("3.14") == "three point fourteen"
    assert normalize_numbers("1999") == "nineteen ninety-nine"
    assert normalize_numbers("2000") == "two thousand"
    assert normalize_numbers("2005") == "two thousand five"
    assert normalize_numbers("1906") == "nineteen oh six"
    assert normalize_numbers("£5") == "five pounds"


def test_number_words():
    assert number_to_words(0) == "zero"
    assert number_to_words(115) == "one hundred fifteen"
    assert number_to_words(1000000) == "one million"
    assert ordinal_to_words(12) == "twelfth"
    assert ordinal_to_words(30) == "thirtieth"
    assert ordinal_to_words(101) == "one hundred and first"


def test_pinyin_lexicon_generator(tmp_path):
    """The generated MFA dict must match the reference's vendored
    pinyin-lexicon-r.txt entry-for-entry (embedding-row parity), and
    read_lexicon must self-generate it when missing."""
    import os

    from speakingstyle_tpu.text.g2p import read_lexicon
    from speakingstyle_tpu.text.pinyin_lexicon import entries, write_lexicon

    all_entries = list(entries())
    assert len(all_entries) == 4120
    keys = [k for k, _ in all_entries]
    assert len(set(keys)) == 4115  # er1..er5 carry two pronunciations
    # spot checks covering every decomposition rule family
    d = {}
    for k, p in all_entries:
        d.setdefault(k, p)
    assert d["zhi1"] == ["zh", "iii1"]
    assert d["si3"] == ["s", "ii3"]
    assert d["ju2"] == ["j", "v2"]
    assert d["liu4"] == ["l", "iou4"]
    assert d["dui1"] == ["d", "uei1"]
    assert d["lun2"] == ["l", "uen2"]
    assert d["weng5"] == ["w", "uen5"]
    assert d["you3"] == ["y", "iou3"]
    assert d["yuan1"] == ["y", "van1"]
    assert d["a5"] == ["a5"]
    assert d["zuor1"] == ["z", "uo1", "rr"]
    assert d["er1"] == ["er1"]

    ref_path = "/root/reference/lexicon/pinyin-lexicon-r.txt"
    if os.path.exists(ref_path):
        ref = {tuple(l.split()) for l in open(ref_path)}
        ours = {(k, *p) for k, p in all_entries}
        assert ours == ref

    # read_lexicon self-generates a missing pinyin lexicon
    path = str(tmp_path / "lex" / "pinyin-lexicon-r.txt")
    lex = read_lexicon(path)
    assert os.path.exists(path) and lex["ni3"] == ["n", "i3"]
