"""Fleet observability plane (tier-1).

Six claims, mirroring the obs/trace.py + obs/slo.py +
obs/registry.merge_states stack and its serving integration:

  1. trace context propagates across the cluster wire: a hedged
     router→replica dispatch assembles into ONE trace whose tree holds
     router-side and replica-side spans, with a critical path;
  2. hedge legs land as sibling spans under the request's context with
     exactly one winner-marked leg;
  3. the span ring is bounded (capacity + keep-store), and tail
     sampling keeps every pressure trace while dicing healthy traffic
     deterministically;
  4. metrics federation merges histogram BUCKETS — fleet percentiles
     equal a single registry over the union of observations, counters
     sum, gauges stay replica-labeled, divergent edges degrade to a
     labeled copy instead of corrupting the merge;
  5. the SLO engine's multi-window burn-rate math against a synthetic
     miss stream, with edge-triggered alert/resolve events;
  6. the federation scraper survives a lease-expired replica — errors
     are counted, the dead replica drops from the merged view, and the
     fleet keeps serving.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    ClusterConfig,
    Config,
    FleetConfig,
    ServeConfig,
    SloConfig,
)
from speakingstyle_tpu.faults import FaultPlan
from speakingstyle_tpu.obs import MetricsRegistry
from speakingstyle_tpu.obs import trace as obstrace
from speakingstyle_tpu.obs.registry import merge_states
from speakingstyle_tpu.obs.slo import SloEngine
from speakingstyle_tpu.obs.trace import (
    Span,
    SpanRing,
    TailSampler,
    assemble_trace,
    new_context,
)
from speakingstyle_tpu.serving.cluster import ClusterRouter, ReplicaServer
from speakingstyle_tpu.serving.engine import SynthesisRequest

# ---------------------------------------------------------------------------
# harness (the test_cluster.py idiom: in-process replica "processes"
# behind the subprocess surface, real HTTP in between)
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _armed_ring():
    """Every test here runs with recording armed and a fresh ring."""
    was = obstrace.tracing_enabled()
    obstrace.set_tracing_enabled(True)
    obstrace.get_span_ring().clear()
    yield
    obstrace.get_span_ring().clear()
    obstrace.set_tracing_enabled(was)


def _req(i, L=8, T=4, **kw):
    return SynthesisRequest(
        id=f"q{i}", sequence=np.arange(1, L + 1, dtype=np.int32),
        ref_mel=np.random.default_rng(i).standard_normal(
            (T, 80)).astype(np.float32),
        **kw,
    )


class _CountingEngine:
    is_ready = True

    def __init__(self):
        self.runs = []
        self.unstall = threading.Event()
        self._lock = threading.Lock()

    def precompile(self):
        return 0.0

    def run(self, requests):
        with self._lock:
            self.runs.extend(r.id for r in requests)
        return [SimpleNamespace(id=r.id, mel_len=1) for r in requests]


class _FakeProc:
    def __init__(self, rid, router_addr, ccfg, engine=None):
        self.engine = engine if engine is not None else _CountingEngine()
        self.server = ReplicaServer(self.engine, rid, router_addr, ccfg)
        self._rc = None
        self.server.start()

    def poll(self):
        return self._rc

    def terminate(self):
        self._rc = 0
        self.engine.unstall.set()
        self.server.close()

    kill = terminate

    def wait(self, timeout=None):
        return self._rc


def _cfg(**cluster_kw):
    ckw = dict(enabled=True, heartbeat_interval_s=0.1, lease_miss_budget=3,
               spawn_grace_s=10.0, quorum=1, hedge_quantile=0.0)
    ckw.update(cluster_kw)
    return Config(serve=ServeConfig(
        batch_buckets=[1], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=5.0,
        fleet=FleetConfig(
            queue_depth=64, stream_window=8,
            rewarm_backoff_s=0.05, rewarm_backoff_max_s=0.5,
            class_deadline_ms={"interactive": 10_000.0,
                               "batch": 20_000.0},
        ),
        cluster=ClusterConfig(**ckw),
    ))


def _make_cluster(replicas, engine_factory=None, **cluster_kw):
    cfg = _cfg(**cluster_kw)
    procs = {}

    def spawn(rid, router_addr, extra):
        eng = engine_factory(rid) if engine_factory is not None else None
        p = _FakeProc(rid, router_addr, cfg.serve.cluster, engine=eng)
        procs[rid] = p
        return p

    reg = MetricsRegistry()
    router = ClusterRouter(spawn, cfg, replicas=replicas, registry=reg,
                           fault_plan=FaultPlan())
    return router, procs, reg


def _wait(pred, timeout=20.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _tree_names(view):
    names = set()

    def walk(node):
        names.add(node["name"])
        for child in node["children"]:
            walk(child)

    for root in view["roots"]:
        walk(root)
    return names


# ---------------------------------------------------------------------------
# 1. cross-process propagation
# ---------------------------------------------------------------------------


def test_trace_propagates_router_to_replica_and_assembles():
    """One traced request through the cluster: the context rides the
    wire (body + X-Trace-* headers), the replica's spans come back over
    ``GET /debug/spans``, and the assembled tree holds BOTH sides of
    the hop under one trace_id with a non-empty critical path."""
    router, procs, reg = _make_cluster(replicas=1)
    try:
        assert router.wait_ready(timeout=20, n=1)
        req = _req(1)
        with Span("serve_request", trace_id="t-prop", req_id="q1") as sp:
            req.trace = sp.ctx
            assert router.submit(req).result(timeout=10) is not None
        # leg records land on the leg threads after the response; wait
        assert _wait(lambda: any(
            s.get("name") == "replica_dispatch"
            for s in router.fetch_remote_spans("t-prop")), 10)
        spans = {s["span_id"]: s
                 for s in obstrace.get_span_ring().spans("t-prop")}
        for s in router.fetch_remote_spans("t-prop"):
            spans.setdefault(s["span_id"], s)
        assert all(s["trace_id"] == "t-prop" for s in spans.values())
        view = assemble_trace(list(spans.values()), "t-prop")
        names = _tree_names(view)
        assert {"serve_request", "serve_queue", "fleet_dispatch",
                "remote_dispatch", "replica_dispatch"} <= names
        assert view["span_count"] == len(spans)
        assert view["critical_path"], "a complete trace has a gating chain"
        # the wire hop parents correctly: remote_dispatch is a child of
        # the request context, replica_dispatch of the decoded context
        by_name = {s["name"]: s for s in spans.values()}
        assert by_name["remote_dispatch"]["parent_span_id"] \
            == sp.ctx.span_id
        assert by_name["replica_dispatch"]["parent_span_id"] \
            == sp.ctx.span_id
    finally:
        router.close()


# ---------------------------------------------------------------------------
# 2. hedge legs: siblings, exactly one winner
# ---------------------------------------------------------------------------


def test_hedge_legs_are_siblings_with_exactly_one_winner():
    stall_once = {"armed": True}
    gate = threading.Lock()

    class _SlowOnce(_CountingEngine):
        def run(self, requests):
            if any(r.id == "q500" for r in requests):
                with gate:
                    hit = stall_once["armed"]
                    stall_once["armed"] = False
                if hit:
                    self.unstall.wait(timeout=5.0)
            return super().run(requests)

    router, procs, reg = _make_cluster(
        replicas=2, engine_factory=lambda rid: _SlowOnce(),
        hedge_quantile=0.95, hedge_min_ms=50.0, hedge_max_ms=150.0,
    )
    try:
        assert router.wait_ready(timeout=20, n=2)
        req = _req(500)   # id "q500": the one dispatch the stall arms on
        req.trace = new_context("t-hedge")
        assert router.submit(req).result(timeout=10) is not None
        # release the stalled primary so its leg record can land too
        for p in procs.values():
            p.engine.unstall.set()

        def legs():
            return [s for s in obstrace.get_span_ring().spans("t-hedge")
                    if s.get("name") == "remote_dispatch"]

        assert _wait(lambda: len(legs()) == 2, 10)
        got = legs()
        # siblings: both legs are children of the SAME request context
        assert {s["parent_span_id"] for s in got} \
            == {req.trace.span_id}
        assert {s["fields"]["hedge_leg"] for s in got} \
            == {"primary", "hedge"}
        winners = [s for s in got if s["fields"].get("winner")]
        assert len(winners) == 1
        assert winners[0]["fields"]["hedge_leg"] == "hedge"
        # hedge-won is a tail-sampling keep reason: the trace is pinned
        assert "t-hedge" in obstrace.get_span_ring().kept_trace_ids()
        assert router.last_pressure_trace_id == "t-hedge"
    finally:
        for p in procs.values():
            p.engine.unstall.set()
        router.close()


# ---------------------------------------------------------------------------
# 3. ring bounds + tail-sampling keep rules
# ---------------------------------------------------------------------------


def _rec(i, tid=None):
    return {"name": "s", "trace_id": tid or f"t{i}", "span_id": f"s{i}",
            "start_ts": float(i), "duration_s": 0.0}


def test_span_ring_is_bounded_and_pin_survives_churn():
    ring = SpanRing(capacity=8, keep_traces=2)
    for i in range(20):
        ring.add(_rec(i))
    stats = ring.stats()
    assert stats["spans"] == 8 and stats["capacity"] == 8
    assert stats["evictions"] == 12
    # pin, then churn the ring far past capacity: the kept trace's
    # spans survive, and later spans of the same trace keep attaching
    ring.add(_rec(100, tid="keep"))
    ring.pin("keep")
    for i in range(200, 240):
        ring.add(_rec(i))
    ring.add(_rec(101, tid="keep"))
    assert [s["span_id"] for s in ring.spans("keep")] == ["s100", "s101"]
    assert ring.last_pinned_trace_id == "keep"
    # the keep-store is bounded too: a third pin evicts the oldest
    ring.pin("k2")
    ring.pin("k3")
    assert ring.kept_trace_ids() == ["k2", "k3"]
    ring.clear()
    assert ring.stats() == {"spans": 0, "capacity": 8, "kept_traces": 0,
                            "evictions": 0}


def test_tail_sampler_keeps_every_pressure_trace():
    s = TailSampler(sample_rate=0.0)
    for reason in TailSampler.KEEP_REASONS:
        assert s.keep(f"t-{reason}", reason=reason)
    # healthy traffic at rate 0: never kept; at rate 1: always kept
    assert not s.keep("healthy-1")
    assert TailSampler(sample_rate=1.0).keep("healthy-1")
    # the dice are deterministic per trace id, so router and replica
    # (separate sampler instances) agree on which healthy traces to pin
    ids = [f"r{i}" for i in range(200)]
    a, b = TailSampler(0.5), TailSampler(0.5)
    picks = [a.keep(t) for t in ids]
    assert picks == [b.keep(t) for t in ids]
    assert 0 < sum(picks) < len(ids)   # the rate actually subsamples
    with pytest.raises(ValueError):
        TailSampler(sample_rate=1.5)


# ---------------------------------------------------------------------------
# 4. federation: bucket merge, not percentile averaging
# ---------------------------------------------------------------------------


def test_merge_states_bucket_merge_matches_single_registry():
    edges = (0.01, 0.1, 1.0)
    a, b, single = MetricsRegistry(), MetricsRegistry(), MetricsRegistry()
    obs_a = [0.005] * 40 + [0.5] * 2
    obs_b = [0.05] * 30 + [2.0] * 8
    for reg_i, values in ((a, obs_a), (b, obs_b)):
        for v in values:
            reg_i.histogram("serve_latency_seconds", edges=edges).observe(v)
            single.histogram("serve_latency_seconds", edges=edges).observe(v)
    a.counter("serve_requests_total").inc(5)
    b.counter("serve_requests_total").inc(7)
    a.gauge("serve_inflight").set(2)
    b.gauge("serve_inflight").set(3)

    merged = merge_states([("r0", a.export_state()),
                           ("r1", b.export_state())])
    # counters: summed under one fleet_ identity
    assert merged.value("fleet_serve_requests_total") == 12
    # gauges: levels stay per-replica
    assert merged.value("fleet_serve_inflight", {"replica": "r0"}) == 2
    assert merged.value("fleet_serve_inflight", {"replica": "r1"}) == 3
    # histograms: the merged buckets answer percentiles EXACTLY as a
    # single registry over the union of observations would — the
    # never-average-percentiles invariant (averaging the two replicas'
    # p999s here would land near 1.25s; the fleet p999 is above 2s
    # because replica b's tail dominates)
    mh = merged.metrics_named("fleet_serve_latency_seconds")[0]
    sh = single.metrics_named("serve_latency_seconds")[0]
    for q in (0.5, 0.99, 0.999):
        assert mh.percentile(q) == sh.percentile(q)
    # a replica with divergent edges (config skew mid-rollout) degrades
    # to a replica-labeled copy instead of corrupting the merge
    c = MetricsRegistry()
    c.histogram("serve_latency_seconds", edges=(1.0, 2.0)).observe(1.5)
    merged2 = merge_states([("r0", a.export_state()),
                            ("rX", c.export_state())])
    labeled = [
        rec for rec in merged2.export_state()["metrics"]
        if rec["name"] == "fleet_serve_latency_seconds"
        and ["replica", "rX"] in [list(kv) for kv in rec["labels"]]
    ]
    assert labeled, "divergent-edge replica must keep a labeled copy"


# ---------------------------------------------------------------------------
# 5. SLO burn-rate window math
# ---------------------------------------------------------------------------


class _EventSink:
    def __init__(self):
        self.records = []

    def emit(self, event, **fields):
        self.records.append(dict(fields, event=event))


def test_slo_engine_burn_rate_windows_and_edge_trigger():
    reg = MetricsRegistry()
    scfg = SloConfig(
        objectives={"interactive": 0.999}, fast_window_s=60.0,
        slow_window_s=600.0, fast_burn_threshold=14.4,
        slow_burn_threshold=6.0, tick_s=5.0,
    )
    events = _EventSink()
    ring = SpanRing(capacity=16, keep_traces=4)
    ring.add(_rec(1, tid="t-bad"))
    ring.pin("t-bad")
    eng = SloEngine(reg, scfg, events=events, trace_ring=ring, start=False)
    req = reg.counter("serve_class_requests_total",
                      labels={"class": "interactive"})
    miss = reg.counter("serve_deadline_miss_total",
                       labels={"class": "interactive"})
    t0 = 1000.0
    req.inc(1000)
    assert eng.step(now=t0) == {"interactive": False}
    assert eng.burn_rate("interactive", "fast") == 0.0

    # 20 misses over 1000 requests against a 99.9% objective:
    # burn = (20/1000) / 0.001 = 20 — past both thresholds
    req.inc(1000)
    miss.inc(20)
    assert eng.step(now=t0 + 30.0) == {"interactive": True}
    assert eng.burn_rate("interactive", "fast") == pytest.approx(20.0)
    assert reg.value("serve_slo_burn_rate",
                     {"class": "interactive", "window": "fast"}) \
        == pytest.approx(20.0)
    assert reg.value("serve_slo_alerts_total",
                     {"class": "interactive"}) == 1
    alert = events.records[-1]
    assert alert["event"] == "slo_alert"
    assert alert["klass"] == "interactive"
    assert alert["fast_burn"] == pytest.approx(20.0)
    assert alert["trace_id"] == "t-bad"   # jump-to-trace handle

    # sustained burn: still alerting, but edge-triggered — no re-emit
    assert eng.step(now=t0 + 35.0) == {"interactive": True}
    assert len([r for r in events.records
                if r["event"] == "slo_alert"]) == 1

    # clean traffic pushes the bad sample past BOTH windows: resolved
    req.inc(50_000)
    eng.step(now=t0 + 400.0)
    assert eng.step(now=t0 + 700.0) == {"interactive": False}
    assert events.records[-1]["event"] == "slo_resolved"
    status = eng.status()["interactive"]
    assert status["objective"] == 0.999
    assert status["alerting"] is False
    assert status["fast_burn"] == 0.0


def test_slo_engine_shed_counts_in_numerator_and_denominator():
    # a shed request never reached serve_class_requests_total — the
    # engine must widen the denominator by the shed count, or burn
    # overshoots
    reg = MetricsRegistry()
    scfg = SloConfig(objectives={"batch": 0.99}, fast_window_s=60.0,
                     slow_window_s=600.0, fast_burn_threshold=14.4,
                     slow_burn_threshold=6.0, tick_s=5.0)
    eng = SloEngine(reg, scfg, start=False)
    eng.step(now=0.0)
    reg.counter("serve_class_requests_total",
                labels={"class": "batch"}).inc(90)
    reg.counter("serve_class_shed_total", labels={"class": "batch"}).inc(10)
    eng.step(now=30.0)
    # bad=10 over total=100 against a 1% budget -> burn 10
    assert eng.burn_rate("batch", "fast") == pytest.approx(10.0)


# ---------------------------------------------------------------------------
# 6. federation survives a lease-expired replica
# ---------------------------------------------------------------------------


def test_federation_scrape_survives_lease_expired_replica():
    router, procs, reg = _make_cluster(replicas=2)
    try:
        assert router.wait_ready(timeout=20, n=2)
        assert _wait(lambda: len(router.federated_states()) == 2, 10)
        assert router.submit(_req(7)).result(timeout=10) is not None
        text = router.federated_registry().prometheus_text()
        assert "fleet_serve_wire_dispatches_total" in text

        # silence one replica WITHOUT marking its process dead: its
        # heartbeats stop, the lease expires, and its /metrics endpoint
        # answers nothing — the scraper must neither crash nor keep the
        # frozen state in the merged view
        victim = sorted(procs)[0]
        procs[victim].engine.unstall.set()
        procs[victim].server.close()
        assert _wait(
            lambda: all(rid != victim
                        for rid, _ in router.federated_states()), 20,
        ), "expired replica must drop out of the federation cache"
        # the scrape loop is still alive and the merge still renders
        scrapes = reg.value("serve_federation_scrapes_total")
        assert _wait(
            lambda: reg.value("serve_federation_scrapes_total") > scrapes,
            10,
        )
        assert "fleet_" in router.federated_registry().prometheus_text()
        # and the fleet still serves through the survivor
        assert router.submit(_req(9)).result(timeout=15) is not None
    finally:
        router.close()
