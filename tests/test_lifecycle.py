"""Model lifecycle drills: verified checkpoints + canary-gated rollout.

Four layers, mirroring ARCHITECTURE.md "Model lifecycle":
  1. checkpoint integrity — the save-time manifest (per-leaf sha256,
     atomic via temp + os.replace) and the restore-time verification:
     tamper detection, strict mode, the injected fault kinds, and the
     newest-first walk distinguishing corrupt from absent;
  2. the canary gate against fake engines (no jax in the fleet path):
     a passing canary commits the new factory and publishes the
     version, a non-finite or out-of-tolerance canary aborts with the
     fleet untouched, and a failed verify never starts a replica;
  3. the rolling replace: zero lost requests under closed-loop load,
     READY never below the pre-roll fleet size, zero scale-down from
     the autoscaler while ``rollout_active`` holds;
  4. the HTTP surface — POST /admin/rollout validation, 409 on a
     concurrent rollout, outcome dicts as 200s, and the /healthz model
     block carrying the committed version.
"""

import json
import os
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax.numpy as jnp

from speakingstyle_tpu.configs.config import (
    AutoscaleConfig,
    Config,
    FleetConfig,
    RolloutConfig,
    ServeConfig,
)
from speakingstyle_tpu.faults import FaultPlan
from speakingstyle_tpu.obs import MetricsRegistry, weights_digest
from speakingstyle_tpu.serving.autoscale import Autoscaler
from speakingstyle_tpu.serving.batcher import ShutdownError
from speakingstyle_tpu.serving.engine import SynthesisRequest
from speakingstyle_tpu.serving.fleet import READY, STOPPED, FleetRouter
from speakingstyle_tpu.serving.lifecycle import (
    RolloutInProgress,
    RolloutManager,
    make_golden_set,
)
from speakingstyle_tpu.training.checkpoint import (
    MANIFEST_NAME,
    CheckpointCorruptError,
    CheckpointManager,
)

# ---------------------------------------------------------------------------
# 1. checkpoint integrity (real manager, toy state)
# ---------------------------------------------------------------------------


def _toy_state(value: float):
    return {
        "step": jnp.asarray(int(value), jnp.int32),
        "w": jnp.full((4,), value, jnp.float32),
    }


class _Events:
    def __init__(self):
        self.lock = threading.Lock()
        self.records = []

    def emit(self, event, **fields):
        with self.lock:
            self.records.append((event, fields))

    def kinds(self):
        with self.lock:
            return [k for k, _ in self.records]

    def of(self, kind):
        with self.lock:
            return [dict(f) for k, f in self.records if k == kind]


def test_manifest_roundtrip_and_identity(tmp_path):
    """Every save writes an atomic manifest; restore verifies it and
    records the step + weights digest for /healthz and train_start."""
    root = str(tmp_path / "ck")
    ckpt = CheckpointManager(root, config_fingerprint="cfgfp")
    ckpt.save(3, _toy_state(3.0), block=True)
    path = os.path.join(root, "3", MANIFEST_NAME)
    assert os.path.isfile(path)
    assert not os.path.exists(path + ".tmp")  # temp never lingers
    with open(path, encoding="utf-8") as fh:
        manifest = json.load(fh)
    assert manifest["step"] == 3
    assert manifest["config_fingerprint"] == "cfgfp"
    assert set(manifest["leaves"]) == {"step", "w"}
    for leaf in manifest["leaves"].values():
        assert len(leaf["sha256"]) == 64

    restored = ckpt.restore(_toy_state(0.0), step=3, strict=True)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.full(4, 3.0))
    assert ckpt.last_restored_step == 3
    assert ckpt.last_weights_digest == manifest["weights_digest"]
    assert ckpt.verify_count == 1
    ckpt.close()


def test_weights_digest_detects_changed_weights():
    a = {"w": np.ones((2, 3), np.float32), "b": np.zeros((3,), np.float32)}
    b = {"w": np.ones((2, 3), np.float32), "b": np.zeros((3,), np.float32)}
    c = {"w": np.ones((2, 3), np.float32),
         "b": np.full((3,), 1e-6, np.float32)}
    assert weights_digest(a) == weights_digest(b)
    assert weights_digest(a) != weights_digest(c)


def test_tampered_manifest_hash_raises_corrupt(tmp_path):
    root = str(tmp_path / "ck")
    ckpt = CheckpointManager(root)
    ckpt.save(1, _toy_state(1.0), block=True)
    path = os.path.join(root, "1", MANIFEST_NAME)
    with open(path, encoding="utf-8") as fh:
        manifest = json.load(fh)
    manifest["leaves"]["w"]["sha256"] = "0" * 64
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh)
    with pytest.raises(CheckpointCorruptError) as ei:
        ckpt.restore(_toy_state(0.0), step=1)
    assert ei.value.reason == "leaf_hash_mismatch" and ei.value.step == 1
    ckpt.close()


def test_malformed_manifest_is_corrupt_not_absent(tmp_path):
    root = str(tmp_path / "ck")
    ckpt = CheckpointManager(root)
    ckpt.save(1, _toy_state(1.0), block=True)
    with open(os.path.join(root, "1", MANIFEST_NAME), "w") as fh:
        fh.write("{torn mid-")
    with pytest.raises(CheckpointCorruptError) as ei:
        ckpt.restore(_toy_state(0.0), step=1)
    assert ei.value.reason == "manifest_malformed"
    ckpt.close()


def test_strict_refuses_manifestless_but_default_tolerates(tmp_path):
    """Pre-manifest checkpoints stay restorable (legacy tolerance);
    the rollout verify gate's strict mode refuses them."""
    root = str(tmp_path / "ck")
    ckpt = CheckpointManager(root)
    ckpt.save(1, _toy_state(1.0), block=True)
    os.unlink(os.path.join(root, "1", MANIFEST_NAME))
    with pytest.raises(CheckpointCorruptError) as ei:
        ckpt.restore(_toy_state(0.0), step=1, strict=True)
    assert ei.value.reason == "manifest_missing"
    restored = ckpt.restore(_toy_state(0.0), step=1)  # legacy path
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.full(4, 1.0))
    # identity still computed (not verified) for observability
    assert ckpt.last_weights_digest is not None
    ckpt.close()


def test_injected_checkpoint_fault_kinds(tmp_path):
    """``checkpoint_corrupt@N`` / ``manifest_missing@N`` drill both
    failure paths deterministically on the 1-based verify counter."""
    root = str(tmp_path / "ck")
    writer = CheckpointManager(root)
    writer.save(1, _toy_state(1.0), block=True)
    writer.close()

    plan = FaultPlan.parse("checkpoint_corrupt@1")
    ckpt = CheckpointManager(root, fault_plan=plan)
    with pytest.raises(CheckpointCorruptError) as ei:
        ckpt.restore(_toy_state(0.0), step=1)
    assert ei.value.reason == "injected"
    # fire-once: the second verification succeeds
    assert int(ckpt.restore(_toy_state(0.0), step=1)["step"]) == 1
    ckpt.close()

    plan = FaultPlan.parse("manifest_missing@1")
    ckpt = CheckpointManager(root, fault_plan=plan)
    with pytest.raises(CheckpointCorruptError) as ei:
        ckpt.restore(_toy_state(0.0), step=1, strict=True)
    assert ei.value.reason == "manifest_missing"
    ckpt.close()


def test_restore_walk_distinguishes_corrupt_from_absent(tmp_path):
    """The newest-first walk lands on the older step either way, but a
    CORRUPT (not merely absent) skip is observable: the
    ``ckpt_corrupt_skipped`` event + counter fire only for damage."""
    import shutil

    root = str(tmp_path / "ck")
    writer = CheckpointManager(root)
    writer.save(1, _toy_state(1.0), block=True)
    writer.save(2, _toy_state(2.0), block=True)
    writer.close()

    # absent: the step-2 item directory is gone entirely -> a routine
    # hole in the walk, no corruption signal
    moved = os.path.join(str(tmp_path), "stash")
    shutil.move(os.path.join(root, "2", "default"), moved)
    reg, events = MetricsRegistry(), _Events()
    ckpt = CheckpointManager(root, registry=reg, events=events)
    assert int(ckpt.restore(_toy_state(0.0))["step"]) == 1
    assert reg.value("ckpt_corrupt_skipped_total") == 0
    assert events.of("ckpt_corrupt_skipped") == []
    ckpt.close()

    # corrupt: step 2 exists but its manifest lies about the leaves
    shutil.move(moved, os.path.join(root, "2", "default"))
    mpath = os.path.join(root, "2", MANIFEST_NAME)
    with open(mpath, encoding="utf-8") as fh:
        manifest = json.load(fh)
    manifest["leaves"]["w"]["sha256"] = "f" * 64
    with open(mpath, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh)
    reg, events = MetricsRegistry(), _Events()
    ckpt = CheckpointManager(root, registry=reg, events=events)
    assert int(ckpt.restore(_toy_state(0.0))["step"]) == 1
    assert reg.value("ckpt_corrupt_skipped_total") == 1
    skipped = events.of("ckpt_corrupt_skipped")
    assert len(skipped) == 1 and skipped[0]["step"] == 2
    assert skipped[0]["reason"] == "leaf_hash_mismatch"
    # an explicitly requested corrupt step still fails loudly
    with pytest.raises(CheckpointCorruptError):
        ckpt.restore(_toy_state(0.0), step=2)
    ckpt.close()


def test_rollout_config_validation():
    with pytest.raises(ValueError, match="golden_set_size"):
        RolloutConfig(golden_set_size=0)
    with pytest.raises(ValueError, match="canary_tolerance"):
        RolloutConfig(canary_tolerance=-1.0)
    with pytest.raises(ValueError, match="replica_timeout_s"):
        RolloutConfig(replica_timeout_s=0.0)
    # rollout is an explicit operator decision, off by default
    assert ServeConfig().rollout.enabled is False


# ---------------------------------------------------------------------------
# 2+3. the canary gate and the rolling replace (fake engines — no jax)
# ---------------------------------------------------------------------------


def _fleet_cfg(**fleet_kw):
    fleet = dict(
        queue_depth=64, stream_window=8,
        rewarm_backoff_s=0.05, rewarm_backoff_max_s=1.0,
        class_deadline_ms={"interactive": 10_000.0, "batch": 20_000.0},
    )
    fleet.update(fleet_kw)
    return Config(serve=ServeConfig(
        batch_buckets=[1], src_buckets=[16], mel_buckets=[64],
        frames_per_phoneme=2, max_wait_ms=5.0,
        fleet=FleetConfig(**fleet),
    ))


class ConstMelEngine:
    """Fake replica engine whose every result carries a constant mel —
    the canary parity gate sees exactly the weight change we dial in."""

    def __init__(self, const):
        self.const = const

    def precompile(self):
        return 0.0

    def run(self, requests):
        mel = np.full((6, 8), self.const, np.float32)
        return [SimpleNamespace(id=r.id, bucket=None, mel_len=6, mel=mel)
                for r in requests]


def _vfactory(const, built):
    def build(reg):
        eng = ConstMelEngine(const)
        built.append(eng)
        return eng

    return build


def _req(i, L=8, T=4, **kw):
    return SynthesisRequest(
        id=f"r{i}", sequence=np.ones(L, np.int32),
        ref_mel=np.zeros((T, 80), np.float32), **kw,
    )


def _rcfg(**kw):
    args = dict(golden_set_size=2, canary_tolerance=0.5,
                replica_timeout_s=20.0)
    args.update(kw)
    return RolloutConfig(**args)


_GOLDEN = [_req(900), _req(901)]


def _vab(const, built, step_info=True):
    """A verify_and_build stub returning a pinned-constant factory."""

    def verify_and_build(step):
        info = {"step": step, "weights_digest": f"dig{const}"} \
            if step_info else {}
        return _vfactory(const, built), f"v{step}", info

    return verify_and_build


def test_make_golden_set_is_seeded_and_lattice_sized():
    cfg = _fleet_cfg()
    object.__setattr__(cfg.serve, "batch_buckets", [1, 4])
    a = make_golden_set(cfg, 3, seed=7)
    b = make_golden_set(cfg, 3, seed=7)
    assert [r.id for r in a] == ["golden0", "golden1", "golden2"]
    for ra, rb in zip(a, b):
        np.testing.assert_array_equal(ra.sequence, rb.sequence)
        np.testing.assert_array_equal(ra.ref_mel, rb.ref_mel)
        assert ra.sequence.shape[0] <= cfg.serve.src_buckets[0]
    c = make_golden_set(cfg, 3, seed=8)
    assert not np.array_equal(a[0].sequence, c[0].sequence)


def test_make_golden_set_clamps_to_largest_batch_bucket():
    # the set replays as ONE batch through the lattice: a size above the
    # largest batch bucket must clamp, not crash the canary gate with
    # RequestTooLarge (found live: the default golden_set_size of 4
    # against a tiny serve lattice with batch_buckets [1, 2])
    cfg = _fleet_cfg()  # batch_buckets [1]
    assert len(make_golden_set(cfg, 4, seed=7)) == 1


def test_canary_pass_commits_and_publishes_version():
    built_v1, built_v2 = [], []
    reg, events = MetricsRegistry(), _Events()
    router = FleetRouter(_vfactory(0.0, built_v1), _fleet_cfg(),
                         replicas=2, registry=reg, events=events)
    assert router.wait_ready(timeout=10, n=2)
    mgr = RolloutManager(router, _vab(0.1, built_v2), rcfg=_rcfg(),
                         golden=_GOLDEN)
    result = mgr.rollout(7)
    assert result["status"] == "committed" and result["version"] == "v7"
    assert result["replicas"] == 2
    # identity published three ways: router attrs, the gauge, the event
    assert router.model_version == "v7" and router.model_step == 7
    assert reg.value("serve_model_version") == 7
    assert reg.value("serve_rollouts_total", {"outcome": "committed"}) == 1
    assert events.kinds().count("rollout_committed") == 1
    canary = events.of("rollout_canary")
    assert len(canary) == 1 and canary[0]["passed"] is True
    # every READY replica now runs an engine built by the NEW factory
    ready = [i for i, s in router.states().items() if s == READY]
    assert len(ready) == 2
    for i in ready:
        assert router.engine_at(i) in built_v2
    # both original replicas were drain-replaced
    assert sorted(s for s in router.states().values()
                  if s == STOPPED) == [STOPPED, STOPPED]
    # future re-warms build the new version too
    assert router.engine_factory(reg) in built_v2
    router.close()


@pytest.mark.parametrize("bad_const,why", [
    (np.nan, "non-finite"),
    (10.0, "tolerance"),
])
def test_canary_failure_aborts_with_fleet_untouched(bad_const, why):
    built_v1, built_v2 = [], []
    reg, events = MetricsRegistry(), _Events()
    router = FleetRouter(_vfactory(0.0, built_v1), _fleet_cfg(),
                         replicas=2, registry=reg, events=events)
    assert router.wait_ready(timeout=10, n=2)
    factory_before = router.engine_factory
    mgr = RolloutManager(router, _vab(bad_const, built_v2), rcfg=_rcfg(),
                         golden=_GOLDEN)
    result = mgr.rollout(8)
    assert result["status"] == "aborted" and result["phase"] == "canary"
    assert why in result["reason"]
    # the fleet is untouched: original replicas READY, factory and
    # version unchanged, the canary drained away
    assert router.engine_factory is factory_before
    assert router.model_version is None
    states = router.states()
    assert [states[0], states[1]] == [READY, READY]
    assert states[2] == STOPPED  # the canary surge replica
    assert reg.value("serve_rollouts_total", {"outcome": "aborted"}) == 1
    aborted = events.of("rollout_aborted")
    assert len(aborted) == 1 and aborted[0]["phase"] == "canary"
    assert aborted[0]["partial"] is False
    assert not router.rollout_active
    router.close()


def test_canary_exception_aborts_and_drains_canary():
    """An engine that RAISES during the canary replay (vs returning bad
    mels) must abort like any failed gate — not escape rollout() as a
    500 and leak a READY canary serving uncommitted weights (found
    live: RequestTooLarge from an oversized golden set)."""
    built_v1, built_v2 = [], []
    reg, events = MetricsRegistry(), _Events()
    router = FleetRouter(_vfactory(0.0, built_v1), _fleet_cfg(),
                         replicas=2, registry=reg, events=events)
    assert router.wait_ready(timeout=10, n=2)
    factory_before = router.engine_factory

    class _BoomEngine:
        def precompile(self):
            return 0.0

        def run(self, requests):
            raise RuntimeError("boom during canary replay")

    def boom_vab(step):
        def build(reg):
            eng = _BoomEngine()
            built_v2.append(eng)
            return eng

        return build, f"v{step}", {"step": step, "weights_digest": "d"}

    mgr = RolloutManager(router, boom_vab, rcfg=_rcfg(), golden=_GOLDEN)
    result = mgr.rollout(8)
    assert result["status"] == "aborted" and result["phase"] == "canary"
    assert "RuntimeError: boom" in result["reason"]
    assert router.engine_factory is factory_before
    assert router.model_version is None
    states = router.states()
    assert [states[0], states[1]] == [READY, READY]
    assert states[2] == STOPPED  # the canary was torn down, not leaked
    assert reg.value("serve_rollouts_total", {"outcome": "aborted"}) == 1
    assert not router.rollout_active
    router.close()


def test_verify_failure_aborts_before_any_replica_exists():
    built_v1 = []
    reg, events = MetricsRegistry(), _Events()
    router = FleetRouter(_vfactory(0.0, built_v1), _fleet_cfg(),
                         replicas=2, registry=reg, events=events)
    assert router.wait_ready(timeout=10, n=2)

    def bad_vab(step):
        raise CheckpointCorruptError(step, "leaf_hash_mismatch", "drill")

    mgr = RolloutManager(router, bad_vab, rcfg=_rcfg(), golden=_GOLDEN)
    result = mgr.rollout(9)
    assert result["status"] == "aborted" and result["phase"] == "verify"
    assert "CheckpointCorruptError" in result["reason"]
    assert len(router.states()) == 2  # no canary was ever started
    assert sorted(router.states().values()) == [READY, READY]
    assert not router.rollout_active
    router.close()


def test_rolling_replace_zero_lost_under_load():
    """The acceptance invariant: a full rollout under closed-loop load
    loses ZERO requests and READY never dips below the pre-roll size
    (the canary is the +1 surge)."""
    built_v1, built_v2 = [], []
    reg = MetricsRegistry()
    router = FleetRouter(_vfactory(0.0, built_v1), _fleet_cfg(),
                         replicas=2, registry=reg)
    assert router.wait_ready(timeout=10, n=2)
    mgr = RolloutManager(router, _vab(0.1, built_v2), rcfg=_rcfg(),
                         golden=_GOLDEN)
    stop = threading.Event()
    per = [dict(ok=0, lost=[]) for _ in range(4)]
    min_ready = [99]

    def sampler():
        while not stop.is_set():
            ready = sum(1 for s in router.states().values() if s == READY)
            min_ready[0] = min(min_ready[0], ready)
            time.sleep(0.001)

    def client(cid):
        c, i = per[cid], 0
        while not stop.is_set():
            try:
                res = router.submit(_req(cid * 100_000 + i)).result(
                    timeout=10)
                assert res is not None
                c["ok"] += 1
            except Exception as e:
                c["lost"].append(f"{type(e).__name__}: {e}")
            i += 1

    threads = [threading.Thread(target=sampler, daemon=True)]
    threads += [threading.Thread(target=client, args=(c,), daemon=True)
                for c in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.05)  # load flowing before the roll begins
    result = mgr.rollout(2)
    time.sleep(0.05)  # and keeps flowing on the new fleet
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert result["status"] == "committed"
    assert [c["lost"] for c in per] == [[], [], [], []]
    assert sum(c["ok"] for c in per) > 0
    assert min_ready[0] >= 2
    assert reg.value("serve_model_version") == 2
    router.close()


def test_concurrent_rollout_raises_in_progress():
    built = []
    router = FleetRouter(_vfactory(0.0, built), _fleet_cfg(),
                         replicas=1, registry=MetricsRegistry())
    assert router.wait_ready(timeout=10, n=1)
    entered, gate = threading.Event(), threading.Event()

    def blocking_vab(step):
        entered.set()
        assert gate.wait(timeout=10)
        raise RuntimeError("released")

    mgr = RolloutManager(router, blocking_vab, rcfg=_rcfg(),
                         golden=_GOLDEN)
    first = {}
    t = threading.Thread(
        target=lambda: first.update(mgr.rollout(2)), daemon=True
    )
    t.start()
    assert entered.wait(timeout=10)
    assert router.rollout_active
    with pytest.raises(RolloutInProgress):
        mgr.rollout(3)
    gate.set()
    t.join(timeout=10)
    assert first["status"] == "aborted"
    assert not router.rollout_active
    router.close()


# ---------------------------------------------------------------------------
# 3b. autoscaler coordination: rollout_active holds scale-downs
# ---------------------------------------------------------------------------


class FakeRouter:
    """Signal-surface stand-in (as tests/test_traffic.py uses)."""

    def __init__(self, queue_depth=100, replicas=1):
        self.fleet = SimpleNamespace(queue_depth=queue_depth)
        self.registry = MetricsRegistry()
        self.events = None
        self.depth = 0
        self.occ = 0.0
        self.live = replicas
        self.warmup = None
        self.scale_calls = []
        self.rollout_active = False

    def pending_depth(self):
        return self.depth

    def live_replica_count(self):
        return self.live

    def occupancy(self):
        return self.occ

    def warmup_cost_s(self):
        return self.warmup

    def scale_to(self, n):
        self.scale_calls.append(n)
        self.live = n


def _acfg(**kw):
    args = dict(enabled=True, min_replicas=1, max_replicas=4,
                interval_s=0.1, up_queue_fraction=0.5, up_occupancy=0.9,
                up_pressure_rate=1.0, down_queue_fraction=0.05,
                down_occupancy=0.5, down_stable_s=1.0, cooldown_up_s=2.0,
                cooldown_down_s=3.0, max_step=2, assumed_warmup_s=0.5,
                warmup_cost_factor=1.0)
    args.update(kw)
    return AutoscaleConfig(**args)


def test_autoscaler_holds_calm_scaledown_during_rollout():
    router = FakeRouter(replicas=2)
    scaler = Autoscaler(router, _acfg(), start=False)
    assert scaler.step(now=100.0) is None      # calm streak starts
    router.rollout_active = True
    # calm window elapsed, but a rollout is live: hold AND restart the
    # streak so the roll's end does not inherit pre-roll calm
    assert scaler.step(now=101.5) is None
    assert router.scale_calls == []
    router.rollout_active = False
    assert scaler.step(now=102.0) is None      # streak restarted
    assert scaler.step(now=103.5) == "calm"    # full window re-served
    assert router.scale_calls == [1]


def test_autoscaler_holds_max_bound_during_rollout_surge():
    """The canary surge may sit at max_replicas + 1; the bound
    correction must not drain it mid-roll."""
    router = FakeRouter(replicas=5)            # over max_replicas=4
    scaler = Autoscaler(router, _acfg(), start=False)
    router.rollout_active = True
    assert scaler.step(now=100.0) is None
    assert router.scale_calls == []
    router.rollout_active = False
    assert scaler.step(now=101.0) == "max_bound"
    assert router.scale_calls == [4]


def test_autoscaler_still_scales_up_during_rollout():
    """An upgrade under pressure still grows: only DOWNS are held."""
    router = FakeRouter(queue_depth=100, replicas=2)
    scaler = Autoscaler(router, _acfg(), start=False)
    router.rollout_active = True
    router.depth = 50                          # at the up watermark
    assert scaler.step(now=100.0) == "queue_depth"
    assert router.scale_calls == [3]


# ---------------------------------------------------------------------------
# 4. the HTTP surface
# ---------------------------------------------------------------------------


def _start_server(router, lifecycle=None):
    from speakingstyle_tpu.serving.server import SynthesisServer, TextFrontend

    server = SynthesisServer(
        frontend=TextFrontend(router.cfg, np.zeros((4, 80), np.float32)),
        host="127.0.0.1", port=0, router=router, lifecycle=lifecycle,
    )
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def _post(host, port, path, body, timeout=30):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request("POST", path, body=body)
    resp = conn.getresponse()
    out = (resp.status, json.loads(resp.read() or b"{}"))
    conn.close()
    return out


def test_http_rollout_404_when_not_enabled():
    router = FleetRouter(_vfactory(0.0, []), _fleet_cfg(), replicas=1,
                         registry=MetricsRegistry())
    assert router.wait_ready(timeout=10, n=1)
    server = _start_server(router, lifecycle=None)
    host, port = server.address[:2]
    try:
        status, body = _post(host, port, "/admin/rollout",
                             json.dumps({"step": 2}))
        assert status == 404 and "not enabled" in body["error"]
    finally:
        server.shutdown()


def test_http_rollout_validation_conflict_and_outcomes():
    """One server, the whole admin contract: 400 on malformed input,
    409 while a rollout is in flight, 200 for both aborted and
    committed outcomes, and the committed version in /healthz."""
    import http.client

    built = []
    reg = MetricsRegistry()
    router = FleetRouter(_vfactory(0.0, built), _fleet_cfg(),
                         replicas=2, registry=reg)
    assert router.wait_ready(timeout=10, n=2)
    entered, gate = threading.Event(), threading.Event()

    def vab(step):
        if step == 2:        # the blocked-then-refused candidate
            entered.set()
            assert gate.wait(timeout=30)
            raise RuntimeError("bad checkpoint")
        return _vfactory(0.1, built), f"v{step}", \
            {"step": step, "weights_digest": "digest5"}

    lifecycle = RolloutManager(router, vab, rcfg=_rcfg(), golden=_GOLDEN)
    server = _start_server(router, lifecycle=lifecycle)
    host, port = server.address[:2]
    try:
        # -- validation
        status, body = _post(host, port, "/admin/rollout", "not json")
        assert status == 400 and "JSON" in body["error"]
        for payload in ({}, {"step": "2"}, {"step": True}):
            status, body = _post(host, port, "/admin/rollout",
                                 json.dumps(payload))
            assert status == 400 and "step" in body["error"]

        # -- 409 while a rollout holds the lock
        first = {}

        def long_post():
            first.update(dict(zip(
                ("status", "body"),
                _post(host, port, "/admin/rollout",
                      json.dumps({"step": 2}), timeout=60),
            )))

        t = threading.Thread(target=long_post, daemon=True)
        t.start()
        assert entered.wait(timeout=10)
        status, body = _post(host, port, "/admin/rollout",
                             json.dumps({"step": 3}))
        assert status == 409 and "in progress" in body["error"]
        gate.set()
        t.join(timeout=30)
        # the refused candidate still answers 200 with the outcome dict
        assert first["status"] == 200
        assert first["body"]["status"] == "aborted"
        assert first["body"]["phase"] == "verify"

        # -- a clean rollout commits over the same surface
        status, body = _post(host, port, "/admin/rollout",
                             json.dumps({"step": 5}), timeout=60)
        assert status == 200 and body["status"] == "committed"
        assert body["version"] == "v5" and body["step"] == 5

        # -- /healthz now carries the model identity block
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        health = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert health["model"] == {
            "version": "v5", "step": 5, "weights_digest": "digest5",
        }
        assert server.model_version() == "v5"
    finally:
        server.shutdown()
