"""Quality-tiered serving: precision lattice, distillation, tier routing.

Five layers, mirroring the tentpole:
  1. registry — int8 per-channel quant/dequant round-trip error bounds
     and bf16/f32 cast semantics (pure host-side, no engine);
  2. engine — bf16/int8 lattice points compile ONCE at precompile and
     dispatch with zero steady-state compiles (CompileMonitor on the
     backend's monitoring bus, the same acceptance invariant the serve
     smoke asserts);
  3. routing — class->tier mapping through TierRouter, including the
     canary-fail fallback to the teacher anchor (quality degrades in
     budget, never in availability);
  4. distillation — the data-free student smoke: loss falls against the
     frozen teacher's mels, the student is strictly smaller, and
     run_distillation lands a manifest-verified student checkpoint;
  5. e2e — a mixed-tier fleet (two precisions of one engine behind two
     FleetRouters) behind ONE TierRouter, zero compiles while serving.
"""

import dataclasses
import os

import numpy as np
import pytest

from speakingstyle_tpu.configs.config import (
    Config,
    ModelConfig,
    ReferenceEncoderConfig,
    ServeConfig,
    StyleConfig,
    TiersConfig,
    TransformerConfig,
    VarianceEmbeddingConfig,
    VariancePredictorConfig,
)
from speakingstyle_tpu.parallel.registry import (
    PRECISIONS,
    cast_params,
    dequant_params,
)
from speakingstyle_tpu.serving.engine import CompileMonitor, SynthesisRequest
from speakingstyle_tpu.serving.lattice import BucketLattice
from speakingstyle_tpu.serving.tiers import (
    TierGateResult,
    TierRouter,
    parse_tier,
    tier_gate,
)

# ---------------------------------------------------------------------------
# registry: the sanctioned precision cast
# ---------------------------------------------------------------------------


def _weight_tree(rng):
    return {
        "dense": {
            "kernel": rng.standard_normal((64, 32)).astype(np.float32),
            "bias": rng.standard_normal((32,)).astype(np.float32),
        },
        "embed": rng.standard_normal((300, 16)).astype(np.float32),
        "step": np.int32(7),
    }


def test_int8_roundtrip_error_is_bounded_per_channel():
    """Per-channel symmetric quantization: |deq - orig| <= scale/2
    elementwise (round-to-nearest), scale = per-channel amax/127."""
    rng = np.random.default_rng(0)
    tree = _weight_tree(rng)
    q = cast_params(tree, "int8")
    # matrix leaves became {int8_q, int8_scale} pairs ...
    assert set(q["dense"]["kernel"].keys()) == {"int8_q", "int8_scale"}
    assert q["dense"]["kernel"]["int8_q"].dtype == np.int8
    # ... small/non-float leaves pass through untouched
    assert q["dense"]["bias"] is tree["dense"]["bias"]
    assert q["step"] == np.int32(7)
    deq = dequant_params(q)
    for orig, wide in ((tree["dense"]["kernel"], deq["dense"]["kernel"]),
                       (tree["embed"], deq["embed"])):
        amax = np.max(np.abs(orig), axis=tuple(range(orig.ndim - 1)))
        bound = amax / 127.0 / 2.0 + 1e-7
        err = np.max(np.abs(np.asarray(wide) - orig), axis=tuple(
            range(orig.ndim - 1)))
        assert np.all(err <= bound), (err, bound)


def test_int8_zero_channel_and_idempotent_dequant():
    tree = {"w": np.zeros((8, 4), np.float32)}
    q = cast_params(tree, "int8")
    # all-zero channel: scale clamps to 1.0 instead of dividing by zero
    assert np.all(q["w"]["int8_scale"] == 1.0)
    deq = dequant_params(q)
    assert np.all(np.asarray(deq["w"]) == 0.0)
    # identity on trees without int8 marker leaves
    again = dequant_params(deq)
    assert np.all(np.asarray(again["w"]) == 0.0)


def test_bf16_and_f32_cast_semantics():
    rng = np.random.default_rng(1)
    tree = _weight_tree(rng)
    assert cast_params(tree, "f32") is tree  # identity tier
    b = cast_params(tree, "bf16")
    import jax.numpy as jnp

    assert b["dense"]["kernel"].dtype == jnp.bfloat16
    assert b["step"] == np.int32(7)  # integer leaves pass through
    # bf16 has ~8 mantissa bits: relative error under 1%
    back = np.asarray(b["dense"]["kernel"], np.float32)
    rel = np.abs(back - tree["dense"]["kernel"]) / (
        np.abs(tree["dense"]["kernel"]) + 1e-6)
    assert np.max(rel) < 0.01
    with pytest.raises(ValueError):
        cast_params(tree, "fp4")


# ---------------------------------------------------------------------------
# engine: precision lattice points compile once, dispatch compile-free
# ---------------------------------------------------------------------------


def _tiers_cfg(**tiers_kw):
    tiers = dict(
        enabled=True,
        precisions=["f32", "bf16", "int8"],
        class_tier={"interactive": "student-int8", "batch": "teacher-bf16"},
        default_tier="teacher-f32",
        tier_tolerance=0.5,
        golden_set_size=2,
    )
    tiers.update(tiers_kw)
    return Config(
        model=ModelConfig(
            transformer=TransformerConfig(
                encoder_layer=1, decoder_layer=1, encoder_hidden=16,
                decoder_hidden=16, conv_filter_size=16,
                conv_kernel_size=(3, 1),
            ),
            reference_encoder=ReferenceEncoderConfig(
                encoder_layer=1, encoder_head=2, encoder_hidden=16,
                conv_layer=1, conv_filter_size=16,
            ),
            variance_predictor=VariancePredictorConfig(filter_size=16),
            variance_embedding=VarianceEmbeddingConfig(n_bins=8),
            postnet_embedding_dim=16, postnet_layers=2,
            max_seq_len=48, compute_dtype="float32",
        ),
        serve=ServeConfig(
            batch_buckets=[1, 2], src_buckets=[16], mel_buckets=[32],
            frames_per_phoneme=2, max_wait_ms=20.0,
            style=StyleConfig(ref_buckets=[32]),
            tiers=TiersConfig(**tiers),
        ),
    )


@pytest.fixture(scope="module")
def tier_engine():
    """One precompiled engine over the full f32/bf16/int8 precision axis
    (module-scoped: the AOT precompile is the expensive part)."""
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.models.hifigan import Generator
    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg = _tiers_cfg()
    model = build_model(cfg, n_position=49)
    variables = init_variables(model, cfg, jax.random.PRNGKey(0))
    bias = variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"]
    variables["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"] = bias + 1.1
    gen = Generator(
        upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4),
        upsample_initial_channel=16, resblock_kernel_sizes=(3,),
        resblock_dilation_sizes=((1,),),
    )
    gparams = gen.init(
        jax.random.PRNGKey(0), np.zeros((1, 8, 80), np.float32)
    )["params"]
    engine = SynthesisEngine(cfg, variables, vocoder=(gen, gparams),
                             model=model)
    engine.precompile()
    return engine


def _mkreq(i, L=10, T=20, precision=None, priority=None, rng=None):
    rng = rng or np.random.default_rng(i)
    return SynthesisRequest(
        id=f"utt{i}",
        sequence=rng.integers(1, 300, L).astype(np.int32),
        ref_mel=rng.standard_normal((T, 80)).astype(np.float32),
        precision=precision,
        priority=priority,
    )


def test_precision_axis_precompiles_every_point(tier_engine):
    # 2 batch buckets x 3 precisions acoustic + 2 vocoder (b, t) pairs
    lattice = tier_engine.lattice
    assert lattice.precisions == ["f32", "bf16", "int8"]
    assert len(tier_engine._acoustic) == 6
    assert tier_engine.compile_count == 8
    # one param tree per precision, f32 is the identity tier
    assert set(tier_engine._params_by_precision) == set(PRECISIONS)


def test_every_precision_dispatches_with_zero_steady_compiles(tier_engine):
    """The acceptance invariant on the precision axis: warm bf16/int8
    dispatch recompiles nothing and the three tiers' mels stay close
    (casting weights must not change the function materially)."""
    mels = {}
    for prec in PRECISIONS:
        tier_engine.run([_mkreq(900, precision=prec)])  # warmup/transfer
        with CompileMonitor() as mon:
            r = tier_engine.run([_mkreq(7, precision=prec)])[0]
        assert mon.count == 0, f"steady dispatch at {prec} compiled"
        assert r.mel_len > 0 and np.all(np.isfinite(r.mel))
        mels[prec] = r.mel
    t = min(m.shape[0] for m in mels.values())
    for prec in ("bf16", "int8"):
        d = float(np.sqrt(np.mean(
            (mels[prec][:t] - mels["f32"][:t]) ** 2)))
        assert d < 0.5, f"{prec} drifted {d} RMS mel from f32"


def test_unknown_precision_is_rejected(tier_engine):
    with pytest.raises(ValueError, match="precision"):
        tier_engine.run([_mkreq(8, precision="f64")])


def test_program_cards_record_precision(tier_engine):
    rows = tier_engine.program_registry.programs()
    precs = {row.get("precision") for row in rows}
    assert set(PRECISIONS) <= precs
    names = [row.get("name", "") for row in rows]
    # f32 names stay byte-identical to the pre-tier engine; other
    # precisions are suffixed so /debug/programs tells them apart
    assert any(n.startswith("acoustic:") and "@" not in n for n in names)
    assert any(n.endswith("@bf16") for n in names)
    assert any(n.endswith("@int8") for n in names)


# ---------------------------------------------------------------------------
# routing: class->tier with canary-fail fallback
# ---------------------------------------------------------------------------


class _StubRouter:
    def __init__(self):
        self.submitted = []

    def submit(self, request):
        self.submitted.append(request)
        return request

    def close(self, **kw):
        pass


def _gate(tier, mel_l2, tol=0.5):
    return TierGateResult(
        tier=tier, mel_l2=mel_l2, tolerance=tol, shipped=mel_l2 <= tol,
        detail="test",
    )


def test_parse_tier_grammar():
    spec = parse_tier("student-int8")
    assert (spec.model, spec.precision) == ("student", "int8")
    for bad in ("studnt-int8", "teacher", "teacher-fp64", "x-y-z"):
        with pytest.raises(ValueError):
            parse_tier(bad)


def test_class_routing_and_canary_fail_fallback():
    cfg = _tiers_cfg()
    router = TierRouter(cfg)
    anchor, bf16, student = _StubRouter(), _StubRouter(), _StubRouter()
    router.add_tier("teacher-f32", anchor)  # ungated anchor
    router.add_tier("teacher-bf16", bf16, gate=_gate("teacher-bf16", 0.1))
    router.add_tier("student-int8", student,
                    gate=_gate("student-int8", 0.2, tol=2.0))
    assert router.tier_for("interactive") == "student-int8"
    assert router.tier_for("batch") == "teacher-bf16"
    assert router.tier_for(None) == "student-int8"  # default_class
    assert router.tier_for("unmapped") == "teacher-f32"
    # submit stamps the tier's precision and counts the dispatch
    req = _mkreq(1, priority="interactive")
    router.submit(req)
    assert student.submitted == [req] and req.precision == "int8"
    assert router.registry.counter(
        "serve_tier_dispatch_total", labels={"tier": "student-int8"}
    ).value == 1

    # now the student's canary FAILS: its classes fall back to the
    # anchor — the tier stays registered but leaves the routing table
    failed = TierRouter(cfg)
    failed.add_tier("teacher-f32", anchor)
    failed.add_tier("teacher-bf16", bf16, gate=_gate("teacher-bf16", 0.1))
    failed.add_tier("student-int8", student,
                    gate=_gate("student-int8", 3.0, tol=2.0))
    assert not failed.shipped("student-int8")
    assert failed.tier_for("interactive") == "teacher-f32"
    assert failed.routing_table()["interactive"] == "teacher-f32"
    assert failed.routing_table()["batch"] == "teacher-bf16"
    req = _mkreq(2, priority="interactive")
    failed.submit(req)
    assert anchor.submitted[-1] is req and req.precision == "f32"


def test_tier_gate_ships_recasts_and_fails_broken_tier(tier_engine):
    """The quality door on a REAL engine: the bf16/int8 recasts of the
    same weights hold under tolerance; a deliberately broken candidate
    (NaN weights) is refused with a non-finite verdict."""
    import jax

    from speakingstyle_tpu.serving.engine import SynthesisEngine

    cfg = tier_engine.cfg
    for tier in ("teacher-bf16", "teacher-int8"):
        g = tier_gate(tier_engine, tier_engine, cfg, tier)
        assert g.shipped, g.detail
        assert g.mel_l2 <= g.tolerance

    broken_vars = jax.tree_util.tree_map(
        lambda x: (np.full_like(np.asarray(x), np.nan)
                   if np.issubdtype(np.asarray(x).dtype, np.floating)
                   else x),
        tier_engine.variables,
    )
    broken = SynthesisEngine(
        cfg, broken_vars, vocoder=tier_engine.vocoder,
        lattice=BucketLattice([1, 2], [16], [32],
                              precisions=("f32", "bf16")),
        model=tier_engine.model,
    )
    g = tier_gate(broken, tier_engine, cfg, "teacher-bf16")
    assert not g.shipped
    assert g.mel_l2 == float("inf")


# ---------------------------------------------------------------------------
# distillation: the student smoke
# ---------------------------------------------------------------------------


def _distill_cfg(tmp_path):
    """Tiers cfg + train paths into tmp and the LR ramp shortened
    (train.loss.anneal_steps gates the init_lr->anneal_lr ramp; at the
    10k default a 40-step smoke never leaves init_lr and the loss
    barely moves)."""
    cfg = _tiers_cfg()
    return dataclasses.replace(
        cfg,
        train=dataclasses.replace(
            cfg.train,
            path=dataclasses.replace(
                cfg.train.path,
                ckpt_path=str(tmp_path / "ckpt"),
                log_path=str(tmp_path / "log"),
            ),
            step=dataclasses.replace(
                cfg.train.step, total_step=40, log_step=10, save_step=20,
            ),
            loss=dataclasses.replace(cfg.train.loss, anneal_steps=5),
        ),
    )


def test_student_config_halves_depth_and_keeps_film_interface():
    from speakingstyle_tpu.training.distill import student_config

    cfg = _tiers_cfg()
    big = dataclasses.replace(cfg, model=dataclasses.replace(
        cfg.model,
        transformer=dataclasses.replace(
            cfg.model.transformer, encoder_layer=4, decoder_layer=4,
            conv_filter_size=64),
        postnet_layers=4,
    ))
    s = student_config(big)
    assert s.model.transformer.encoder_layer == 2
    assert s.model.transformer.decoder_layer == 2
    assert s.model.transformer.conv_filter_size == 32
    assert s.model.postnet_layers == 2
    # the FiLM/style interface must survive halving: d_model, the ref
    # encoder, and the variance-predictor filter are the conditioning
    # surface shared with the teacher's StyleService
    assert s.model.transformer.encoder_hidden == 16
    assert s.model.reference_encoder == big.model.reference_encoder
    assert s.model.variance_predictor == big.model.variance_predictor


def test_distill_smoke_loss_falls_and_checkpoints(tmp_path):
    """40 data-free steps against a frozen (biased) teacher: the loss
    falls materially, the student is strictly smaller, its reference
    encoder is the teacher's (grafted — it gets no gradient from the
    FiLM-conditioned loop), and a manifest-verified checkpoint lands
    under the student subdir as a second model version."""
    import jax

    from speakingstyle_tpu.models.factory import build_model, init_variables
    from speakingstyle_tpu.training.distill import (
        STUDENT_SUBDIR,
        run_distillation,
    )

    cfg = _distill_cfg(tmp_path)
    teacher_model = build_model(cfg)
    t_vars = init_variables(teacher_model, cfg, jax.random.PRNGKey(0))
    bias = t_vars["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"]
    t_vars["params"]["variance_adaptor"]["duration_predictor"][
        "linear_layer"]["bias"] = bias + 1.1

    from speakingstyle_tpu.obs import MetricsRegistry

    registry = MetricsRegistry()
    state, s_cfg = run_distillation(
        cfg, teacher_variables=t_vars, max_steps=40, batch_size=4,
        src_len=8, log=False, registry=registry,
    )
    assert int(state.step) == 40
    assert registry.counter("distill_steps_total").value == 40

    def count(params):
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))

    assert count(state.params) < count(t_vars["params"])
    # the grafted style front-end: byte-identical to the teacher's
    t_ref = t_vars["params"]["reference_encoder"]
    s_ref = state.params["reference_encoder"]
    for a, b in zip(jax.tree_util.tree_leaves(t_ref),
                    jax.tree_util.tree_leaves(s_ref)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    ckpt_dir = os.path.join(cfg.train.path.ckpt_path, STUDENT_SUBDIR)
    assert os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir)

    # loss falls: re-run the identical seeded loop step-by-step and
    # compare the first logged loss against the last (run_distillation
    # doesn't expose its loss trajectory; the step fn does)
    from speakingstyle_tpu.training.distill import (
        make_distill_batch,
        make_distill_step,
        student_config,
    )
    from speakingstyle_tpu.training.optim import make_optimizer
    from speakingstyle_tpu.training.state import TrainState

    s_cfg2 = student_config(cfg)
    student_model = build_model(s_cfg2)
    s_vars = init_variables(student_model, s_cfg2, jax.random.PRNGKey(9))
    tx = make_optimizer(s_cfg2.train)
    st = TrainState.create(s_vars, tx)
    step = make_distill_step(student_model, teacher_model, t_vars, tx,
                             cfg, max_mel_len=16)
    rng = np.random.default_rng(0)
    import jax as _jax

    key = _jax.random.PRNGKey(0)
    losses = []
    for _ in range(40):
        st, l = step(st, make_distill_batch(cfg, rng, 4, 8), key)
        losses.append(float(l["total_loss"]))
    assert np.isfinite(losses).all()
    early, late = np.mean(losses[:5]), np.mean(losses[-5:])
    assert late < 0.8 * early, (early, late)


# ---------------------------------------------------------------------------
# e2e: mixed-tier fleet behind one TierRouter
# ---------------------------------------------------------------------------


def test_mixed_tier_fleet_e2e_zero_compiles(tier_engine):
    """Two precisions of one engine behind two FleetRouters behind ONE
    TierRouter: classes route to their tiers, results come back stamped
    with the producing tier, dispatch counters tally per tier, and the
    whole mixed-serve phase performs zero XLA compiles."""
    from speakingstyle_tpu.obs import MetricsRegistry
    from speakingstyle_tpu.serving.fleet import FleetRouter

    cfg = dataclasses.replace(tier_engine.cfg, serve=dataclasses.replace(
        tier_engine.cfg.serve,
        tiers=dataclasses.replace(
            tier_engine.cfg.serve.tiers,
            class_tier={"interactive": "teacher-bf16"},
        ),
    ))
    registry = MetricsRegistry()
    router = TierRouter(cfg, registry=registry)
    for name, gate in (("teacher-f32", None),
                       ("teacher-bf16", _gate("teacher-bf16", 0.1))):
        fleet = FleetRouter(lambda reg: tier_engine, cfg, replicas=1,
                            registry=registry, tier=name)
        assert fleet.wait_ready(timeout=120, n=1)
        router.add_tier(name, fleet, gate=gate)
    # warmup transfers per tier, then the measured mixed phase
    for prec in ("f32", "bf16"):
        tier_engine.run([_mkreq(950, precision=prec)])
    try:
        with CompileMonitor() as mon:
            results = []
            for i in range(8):
                prio = "interactive" if i % 2 == 0 else "batch"
                fut = router.submit(_mkreq(100 + i, priority=prio))
                results.append((prio, fut.result(timeout=60)))
        assert mon.count == 0, "mixed-tier serving compiled"
        for prio, r in results:
            want = "teacher-bf16" if prio == "interactive" else "teacher-f32"
            assert r.tier == want
            assert r.mel_len > 0
        for name, n in (("teacher-bf16", 4), ("teacher-f32", 4)):
            assert registry.counter(
                "serve_tier_dispatch_total", labels={"tier": name}
            ).value == n
    finally:
        router.close()
