"""Telemetry suite (tier-1): registry, histogram math, JSONL events, spans,
the events CLI, and the instrumented training smoke.

Layers:
  1. registry — identity/creation semantics, thread-safety under
     concurrent writers (exact totals), histogram percentiles against a
     numpy reference (error bounded by one bucket width), snapshot and
     Prometheus-text export;
  2. events — schema round-trip (every record carries ts + event),
     numpy-value coercion, size rotation, cross-rotation reads, and the
     summarize/filter CLI;
  3. spans — duration into the histogram + a joinable JSONL record;
  4. the training smoke — a supertiny run_training populates
     step-time/data-wait histograms and writes train_step events with
     the documented step/loss/step_time_s/data_wait_s fields (the
     acceptance criterion for the JSONL export layer).
"""

import io
import json
import os
import threading

import numpy as np
import pytest

from speakingstyle_tpu.obs import (
    Counter,
    Gauge,
    Histogram,
    JsonlEventLog,
    MetricsRegistry,
    Span,
    get_registry,
    read_events,
)
from speakingstyle_tpu.obs import cli as obs_cli

# ---------------------------------------------------------------------------
# 1. registry
# ---------------------------------------------------------------------------


def test_registry_creation_is_idempotent_and_typed():
    reg = MetricsRegistry()
    c1 = reg.counter("a_total", help="h")
    c2 = reg.counter("a_total")
    assert c1 is c2
    # same name, different labels -> different child of the family
    c3 = reg.counter("a_total", labels={"k": "v"})
    assert c3 is not c1
    assert {m is c1 or m is c3 for m in reg.metrics_named("a_total")} == {True}
    with pytest.raises(TypeError):
        reg.gauge("a_total")


def test_counter_inc_returns_sequence_and_rejects_negative():
    c = MetricsRegistry().counter("seq_total")
    assert [int(c.inc()) for _ in range(3)] == [1, 2, 3]
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_inc_dec():
    g = MetricsRegistry().gauge("depth")
    g.set(5)
    g.inc(2)
    g.dec()
    assert g.value == 6


def test_registry_thread_safety_exact_totals():
    """Concurrent writers on one counter, one gauge, one histogram: no
    update may be lost (the whole point of the shared registry is that
    HTTP handler threads, the dispatch thread, and scrapers race it)."""
    reg = MetricsRegistry()
    c = reg.counter("hits_total")
    h = reg.histogram("lat_seconds", edges=(0.1, 1.0, 10.0))
    n_threads, n_iter = 8, 5000

    def writer(tid):
        for i in range(n_iter):
            c.inc()
            h.observe(0.05 * (1 + (i + tid) % 3))
            # creation races too: same (name, labels) from many threads
            reg.counter("hits_by_thread_total", labels={"t": str(tid)}).inc()

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert int(c.value) == n_threads * n_iter
    assert h.count == n_threads * n_iter
    per_thread = [int(m.value) for m in reg.metrics_named("hits_by_thread_total")]
    assert per_thread == [n_iter] * n_threads


def test_histogram_percentiles_vs_numpy_reference():
    """The interpolated estimate must land within one bucket width of the
    exact numpy percentile, across distributions and quantiles."""
    rng = np.random.default_rng(0)
    edges = tuple(float(e) for e in np.geomspace(1e-4, 60.0, 24))
    for dist in (
        rng.lognormal(-4.0, 1.0, 4000),          # latency-shaped
        rng.uniform(0.001, 0.5, 4000),           # flat
        np.full(100, 0.0123),                     # degenerate: one value
    ):
        h = Histogram("x_seconds", edges=edges)
        for v in dist:
            h.observe(float(v))
        for q in (0.50, 0.95, 0.99):
            want = float(np.percentile(dist, q * 100))
            got = h.percentile(q)
            i = int(np.searchsorted(edges, want))
            lo = edges[i - 1] if i > 0 else float(dist.min())
            hi = edges[i] if i < len(edges) else float(dist.max())
            width = hi - lo
            assert abs(got - want) <= width + 1e-12, (q, got, want, width)


def test_histogram_empty_and_overflow():
    h = Histogram("x", edges=(1.0, 2.0))
    assert h.percentile(0.5) is None
    h.observe(5.0)  # overflow bin: bounded by the observed max
    assert h.percentile(0.99) == 5.0
    snap = h.snapshot()
    assert snap["count"] == 1 and snap["buckets"][2.0] == 0


def test_snapshot_and_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("req_total", help="requests").inc(3)
    reg.gauge("depth").set(7)
    reg.histogram(
        "lat_seconds", edges=(0.1, 1.0), labels={"bucket": "b1.s16.m32"}
    ).observe(0.5)
    snap = reg.snapshot()
    assert snap["counters"]["req_total"] == 3
    assert snap["gauges"]["depth"] == 7
    hist = snap["histograms"]['lat_seconds{bucket="b1.s16.m32"}']
    assert hist["count"] == 1 and hist["buckets"][1.0] == 1
    # tail keys: p999 rides every snapshot (min/max-tightened, so a
    # single observation reports itself exactly)
    assert hist["p999"] == 0.5 and hist["max"] == 0.5

    text = reg.prometheus_text()
    assert "# HELP req_total requests" in text
    assert "# TYPE req_total counter" in text
    assert "req_total 3" in text
    assert "depth 7" in text
    assert 'lat_seconds_bucket{bucket="b1.s16.m32",le="0.1"} 0' in text
    assert 'lat_seconds_bucket{bucket="b1.s16.m32",le="+Inf"} 1' in text
    assert 'lat_seconds_count{bucket="b1.s16.m32"} 1' in text
    assert 'lat_seconds_p999{bucket="b1.s16.m32"} 0.5' in text
    assert 'lat_seconds_max{bucket="b1.s16.m32"} 0.5' in text


def test_prometheus_text_skips_tail_lines_on_empty_histogram():
    reg = MetricsRegistry()
    reg.histogram("idle_seconds", edges=(0.1, 1.0))
    text = reg.prometheus_text()
    assert "idle_seconds_count 0" in text
    assert "idle_seconds_p999" not in text
    assert "idle_seconds_max" not in text


def test_default_registry_is_a_singleton():
    assert get_registry() is get_registry()


def test_retry_io_counts_retries_in_default_registry():
    """The data layer's retry-with-backoff reports into io_retries_total
    (the leading indicator of a sick filesystem on preemptible slices)."""
    from speakingstyle_tpu.training.resilience import retry_io

    before = get_registry().value("io_retries_total")
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise IOError("transient")
        return "ok"

    assert retry_io(flaky, retries=3, backoff=0.0, sleep=lambda _: None) == "ok"
    assert get_registry().value("io_retries_total") - before == 2


# ---------------------------------------------------------------------------
# 2. JSONL events
# ---------------------------------------------------------------------------


def test_event_schema_roundtrip(tmp_path):
    log = JsonlEventLog(str(tmp_path))
    log.emit("train_step", step=3, total_loss=1.25, step_time_s=0.01,
             data_wait_s=0.002)
    log.emit("rollback", step=4, rollback_n=1, restore_step=None)
    # numpy values must coerce, not crash the writer
    log.emit("val", step=np.int64(5), total_loss=np.float32(0.5),
             arr=np.asarray([1, 2]))
    log.close()
    records = list(read_events(str(tmp_path)))
    assert [r["event"] for r in records] == ["train_step", "rollback", "val"]
    for r in records:
        assert isinstance(r["ts"], float) and "event" in r
    assert records[0]["step"] == 3 and records[0]["data_wait_s"] == 0.002
    assert records[2]["step"] == 5 and records[2]["arr"] == [1, 2]
    # filtered read
    assert [r["event"] for r in read_events(str(tmp_path), event="rollback")] \
        == ["rollback"]


def test_event_rotation_keeps_order_and_bounds_files(tmp_path):
    log = JsonlEventLog(str(tmp_path), max_bytes=600, keep=2)
    for i in range(40):
        log.emit("tick", i=i)
    log.close()
    live = os.path.join(str(tmp_path), "events.jsonl")
    assert os.path.exists(live) and os.path.exists(live + ".1")
    assert not os.path.exists(live + ".3")  # keep=2 bounds the set
    assert os.path.getsize(live) <= 600
    records = list(read_events(str(tmp_path)))
    idx = [r["i"] for r in records]
    assert idx == sorted(idx)          # oldest-first across rotation
    assert idx[-1] == 39               # the newest record survives
    # a torn tail (killed writer) is skipped, not fatal
    with open(live, "a") as fh:
        fh.write('{"ts": 1.0, "event": "torn')
    assert [r["i"] for r in read_events(str(tmp_path))] == idx


def test_malformed_and_blank_lines_are_skipped(tmp_path):
    p = tmp_path / "events.jsonl"
    p.write_text('\n{"ts": 1.0, "event": "ok"}\nnot json\n')
    assert [r["event"] for r in read_events(str(p))] == ["ok"]


# ---------------------------------------------------------------------------
# 3. spans
# ---------------------------------------------------------------------------


def test_span_records_histogram_and_joinable_event(tmp_path):
    reg = MetricsRegistry()
    log = JsonlEventLog(str(tmp_path))
    with Span("serve_dispatch", registry=reg, events=log,
              labels={"bucket": "b1.s16.m32"}, req_ids=["req1", "req2"]) as sp:
        sp.note(rows=2)
    log.close()
    assert sp.duration_s is not None and sp.duration_s >= 0
    h = reg.histogram(
        "serve_dispatch_seconds", labels={"bucket": "b1.s16.m32"}
    )
    assert h.count == 1
    (rec,) = read_events(str(tmp_path))
    assert rec["event"] == "serve_dispatch"
    assert rec["req_ids"] == ["req1", "req2"] and rec["rows"] == 2
    assert rec["bucket"] == "b1.s16.m32" and rec["duration_s"] >= 0


def test_span_records_error_and_still_observes(tmp_path):
    reg = MetricsRegistry()
    log = JsonlEventLog(str(tmp_path))
    with pytest.raises(ValueError):
        with Span("op", registry=reg, events=log):
            raise ValueError("boom")
    log.close()
    (rec,) = read_events(str(tmp_path))
    assert rec["ok"] is False and rec["error"] == "ValueError"
    assert reg.histogram("op_seconds").count == 1


# ---------------------------------------------------------------------------
# events CLI
# ---------------------------------------------------------------------------


def test_events_cli_summarize_and_filter(tmp_path, capsys):
    log = JsonlEventLog(str(tmp_path))
    for s in (1, 2):
        log.emit("train_step", step=s, total_loss=2.0 / s,
                 step_time_s=0.01, data_wait_s=0.001)
    log.emit("checkpoint_save", step=2)
    log.close()

    buf = io.StringIO()
    assert obs_cli.summarize(str(tmp_path), out=buf) == 0
    text = buf.getvalue()
    assert "train_step" in text and "2" in text
    assert "step=2" in text and "total_loss" in text

    assert obs_cli.main([str(tmp_path), "--event", "checkpoint_save"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1 and json.loads(out[0])["step"] == 2

    assert obs_cli.main([str(tmp_path), "--tail", "2"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert [json.loads(ln)["event"] for ln in out] == [
        "train_step", "checkpoint_save",
    ]


# ---------------------------------------------------------------------------
# 4. ProgramCard — extraction + degradation paths (obs/cost.py)
# ---------------------------------------------------------------------------


class _GoodCompiled:
    """Backend that reports everything (list-wrapped cost dict + the
    CompiledMemoryStats attribute style — the shapes jax actually uses)."""

    class _Mem:
        argument_size_in_bytes = 100
        output_size_in_bytes = 50
        temp_size_in_bytes = 200
        alias_size_in_bytes = 25
        generated_code_size_in_bytes = 10

    def cost_analysis(self):
        return [{"flops": 1e9, "transcendentals": 1e6,
                 "bytes accessed": 5e8, "bytes accessed0{}": 1e8}]

    def memory_analysis(self):
        return self._Mem()


class _RaisingCompiled:
    def cost_analysis(self):
        raise RuntimeError("backend says no")

    def memory_analysis(self):
        raise NotImplementedError("nope")


class _NoneCompiled:
    def cost_analysis(self):
        return None

    def memory_analysis(self):
        return None


class _DictMemCompiled:
    """Dict-returning memory_analysis with the backend's own peak."""

    def cost_analysis(self):
        return {"flops": 2e9}

    def memory_analysis(self):
        return {"argument_size_in_bytes": 10, "temp_size_in_bytes": 20,
                "peak_memory_in_bytes": 999}


def test_program_card_full_extraction():
    from speakingstyle_tpu.obs import ProgramCard

    card = ProgramCard.from_compiled(_GoodCompiled(), name="p")
    assert card.flops == 1e9 and card.transcendentals == 1e6
    assert card.bytes_accessed == 5e8
    assert card.argument_bytes == 100 and card.temp_bytes == 200
    # peak estimate: args + out + temp + generated - alias
    assert card.peak_bytes == 100 + 50 + 200 + 10 - 25
    assert not card.partial and card.errors == ()
    assert card.arithmetic_intensity == 2.0
    assert card.achieved_flops_per_sec(0.5) == 2e9
    d = card.as_dict()
    assert d["name"] == "p" and d["partial"] is False
    json.dumps(d)  # JSON-ready


def test_program_card_degrades_on_raising_backend():
    from speakingstyle_tpu.obs import ProgramCard, publish_program_gauges

    card = ProgramCard.from_compiled(_RaisingCompiled(), name="p")
    assert card.partial and card.flops is None and card.peak_bytes is None
    assert any("cost_analysis" in e for e in card.errors)
    assert any("memory_analysis" in e for e in card.errors)
    assert card.achieved_flops_per_sec(1.0) is None
    json.dumps(card.as_dict())
    # publishing a fully-degraded card is a no-op, not a crash
    reg = MetricsRegistry()
    publish_program_gauges(reg, card, "serve", labels={"bucket": "b1"})
    assert reg.snapshot()["gauges"] == {}


def test_program_card_degrades_on_none_returns():
    from speakingstyle_tpu.obs import ProgramCard

    card = ProgramCard.from_compiled(_NoneCompiled(), name="p")
    assert card.partial and card.flops is None
    assert any("None" in e for e in card.errors)


def test_program_card_dict_memory_and_backend_peak():
    from speakingstyle_tpu.obs import ProgramCard, publish_program_gauges

    card = ProgramCard.from_compiled(_DictMemCompiled(), name="p")
    assert card.flops == 2e9
    assert card.peak_bytes == 999  # the backend's own peak wins
    reg = MetricsRegistry()
    publish_program_gauges(reg, card, "serve", labels={"bucket": "b1"})
    snap = reg.snapshot()
    assert snap["gauges"]['serve_program_flops{bucket="b1"}'] == 2e9
    assert snap["gauges"]['serve_program_peak_bytes{bucket="b1"}'] == 999


def test_program_card_from_real_compiled_executable():
    """The real jax surface on CPU: a compiled program yields a usable,
    non-partial card."""
    import jax
    import jax.numpy as jnp

    from speakingstyle_tpu.obs import ProgramCard

    f = jax.jit(lambda x: jnp.sin(x) @ x)
    compiled = f.lower(jax.ShapeDtypeStruct((8, 8), jnp.float32)).compile()
    card = ProgramCard.from_compiled(compiled, name="sin_matmul")
    assert card.flops and card.flops > 0
    assert card.bytes_accessed and card.bytes_accessed > 0
    assert card.peak_bytes and card.peak_bytes > 0
    assert not card.partial


def test_device_memory_watermark_falls_back_to_card():
    """Where the backend reports no memory_stats (CPU), the watermark
    comes from the card's argument+temp live set; with no card either,
    None — never a crash."""
    import jax

    from speakingstyle_tpu.obs import ProgramCard, device_memory_watermark

    card = ProgramCard.from_compiled(_GoodCompiled(), name="p")
    wm = device_memory_watermark(card)
    assert wm is not None and wm > 0
    if jax.local_devices()[0].memory_stats() is None:  # the CPU tier-1 case
        assert wm == 100.0 + 200.0  # argument + temp bytes
        none_card = ProgramCard.from_compiled(_RaisingCompiled(), name="p")
        assert device_memory_watermark(none_card) is None
        assert device_memory_watermark(None) is None


# ---------------------------------------------------------------------------
# 5. buildinfo + jaxmon cache counters
# ---------------------------------------------------------------------------


def test_build_info_identifies_the_stack():
    from speakingstyle_tpu.obs import build_info

    info = build_info()
    json.dumps(info)
    assert info["python"]
    assert info["jax"]  # jax is importable in the test env
    assert info["backend"] and info["device_count"] >= 1
    # this repo is a git checkout, so the SHA resolves here
    assert info["git_sha"] is None or len(info["git_sha"]) == 40


def test_process_rss_is_positive():
    from speakingstyle_tpu.obs import process_rss_bytes

    rss = process_rss_bytes()
    assert rss is not None and rss > 1e6  # a python process is >1 MB


def test_persistent_cache_events_count_into_watched_registries():
    """The jaxmon bridge folds the compilation-cache monitoring events
    into every watched registry, so /metrics can tell warm from cold."""
    import jax.monitoring

    from speakingstyle_tpu.obs import watch_compiles

    reg = MetricsRegistry()
    watch_compiles(reg)
    # counters export 0 before any event (scrape-friendly)
    assert reg.value("jax_persistent_cache_hits_total") == 0
    assert reg.value("jax_persistent_cache_requests_total") == 0
    jax.monitoring.record_event(
        "/jax/compilation_cache/compile_requests_use_cache"
    )
    jax.monitoring.record_event("/jax/compilation_cache/cache_hits")
    assert reg.value("jax_persistent_cache_requests_total") == 1
    assert reg.value("jax_persistent_cache_hits_total") == 1


def test_enable_compilation_cache_points_jax_at_dir(tmp_path):
    import jax

    from speakingstyle_tpu.obs import enable_compilation_cache

    before = jax.config.jax_compilation_cache_dir
    try:
        resolved = enable_compilation_cache(str(tmp_path / "cache"))
        assert os.path.isdir(resolved)
        assert jax.config.jax_compilation_cache_dir == resolved
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


# ---------------------------------------------------------------------------
# the programs CLI
# ---------------------------------------------------------------------------


def test_events_cli_programs_pretty_prints_and_rooflines(tmp_path, capsys):
    log = JsonlEventLog(str(tmp_path))
    log.emit(
        "program_card", name="train_step", flops=1.0e12,
        transcendentals=1e6, bytes_accessed=5.0e9, argument_bytes=100.0,
        output_bytes=50.0, temp_bytes=200.0, peak_bytes=350.0,
        arithmetic_intensity=200.0, partial=False,
    )
    for s in (1, 2):
        log.emit("train_step", step=s, total_loss=1.0, step_time_s=0.5,
                 data_wait_s=0.0)
    log.close()

    assert obs_cli.main(["programs", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "train_step" in out
    assert "1.00 TFLOP" in out           # card flops
    assert "2.00 TFLOP/s" in out         # 1e12 / 0.5 s mean step
    assert "intensity" in out and "200.0 FLOP/B" in out

    # --peak-flops adds the utilization row: 2e12 of 4e12 = 50%
    assert obs_cli.main(
        ["programs", str(tmp_path), "--peak-flops", "4e12"]
    ) == 0
    out = capsys.readouterr().out
    assert "50.0%" in out

    # empty log: rc 1, not a crash
    empty = tmp_path / "empty"
    empty.mkdir()
    assert obs_cli.main(["programs", str(empty)]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# 6. the instrumented training smoke (the acceptance criterion)
# ---------------------------------------------------------------------------


def test_train_smoke_populates_metrics_and_event_log(
    synthetic_preprocessed, tmp_path
):
    """A tiny run_training must (a) record step-time and data-wait into
    the registry histograms, and (b) write train_step JSONL events
    carrying the documented step/loss/step_time_s/data_wait_s fields,
    plus the checkpoint_save record for the final flush."""
    from tests.test_resilience import _train_config

    cfg = _train_config(synthetic_preprocessed, tmp_path, total=3, save=2,
                        log=1)
    reg = MetricsRegistry()
    from speakingstyle_tpu.training.trainer import run_training

    state = run_training(cfg, max_steps=3, registry=reg)
    assert int(state.step) == 3

    snap = reg.snapshot()
    assert snap["counters"]["train_steps_total"] == 3
    assert snap["counters"]["checkpoint_saves_total"] >= 1
    step_hist = snap["histograms"]["train_step_seconds"]
    wait_hist = snap["histograms"]["train_data_wait_seconds"]
    assert step_hist["count"] == 3 and step_hist["sum"] > 0
    assert wait_hist["count"] == 3 and wait_hist["p95"] is not None
    # the prefetcher reported its side of the pipeline too
    assert snap["counters"]["data_prefetch_batches_total"] >= 3
    # the ProgramCard layer: achieved FLOP/s observed once per step from
    # the card built after the first compile, and the memory watermark
    # gauge set at every log boundary (card fallback on CPU)
    flops_hist = snap["histograms"]["train_achieved_flops_per_sec"]
    assert flops_hist["count"] == 3 and flops_hist["p50"] > 0
    assert snap["gauges"]["device_memory_watermark_bytes"] > 0

    log_dir = cfg.train.path.log_path
    steps_events = list(read_events(log_dir, event="train_step"))
    assert len(steps_events) == 3  # log_step=1
    for rec in steps_events:
        assert isinstance(rec["ts"], float)
        assert rec["step"] in (1, 2, 3)
        assert np.isfinite(rec["total_loss"])
        assert rec["step_time_s"] >= 0
        assert rec["data_wait_s"] >= 0
        assert "lr" in rec
    saves = list(read_events(log_dir, event="checkpoint_save"))
    assert saves and saves[-1]["step"] == 3  # final tail-step flush
    # one train_start event identifying the stack that ran
    (start,) = read_events(log_dir, event="train_start")
    assert start["jax"] and start["backend"] and start["device_count"] >= 1
    # one program_card event: XLA's own accounting of the step program
    (card,) = read_events(log_dir, event="program_card")
    assert card["name"] == "train_step"
    assert card["flops"] > 0 and card["bytes_accessed"] > 0
    assert card["peak_bytes"] > 0 and card["partial"] is False
